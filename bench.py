"""Benchmark: the BASELINE eval grid on real hardware.

Runs every BASELINE.md eval config plus the reference's pod-count sweep
(scheduling_benchmark_test.go:51-71,180-194) and a small-instance cost-regret
measurement against the exhaustive MILP (solver/optimal.py).

Configs (BASELINE.md target table):
  1. ffd_parity_1k_x_50        — 1k homogeneous pods / 50 types
  2. selectors_taints_5k_x_500 — 5k pods with nodeSelector cohorts + provisioner taints
  3. anti_spread_10k_x_500     — HEADLINE: 10k pods, mixed anti-affinity + zonal spread
  4. repack_2k_x_300           — whole-cluster repack: 2k pods onto 300 existing nodes
  5. spot_od_multiprov_x_500   — spot/OD mixed pricing, weighted multi-provisioner

Each solve measures full Scheduler.solve wall-clock: dense encode, device
solve, verify, commit.

Prints exactly ONE JSON line (the headline config):
  {"metric": ..., "value": ..., "unit": "ms", "vs_baseline": ...,
   "configs": {...}, "pods_per_sec_sweep": {...}, "cost_regret_vs_ilp": ...}

vs_baseline is the speedup over the reference's enforced scheduler floor of
100 pods/sec (pkg/controllers/provisioning/scheduling/
scheduling_benchmark_test.go:46,173-177): 10k pods / 100 pods-per-sec =
100,000 ms baseline wall-clock.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

HEADLINE_PODS = 10_000
HEADLINE_TYPES = 500
BASELINE_PODS_PER_SEC = 100.0
HEADLINE_TRIALS = 9  # median over 9: the tunnel's dispatch RT swings 90-180ms minute to minute
SIDE_TRIALS = 3  # non-headline configs
SWEEP_PODS = (1, 50, 100, 500, 1000, 2000, 5000)  # scheduling_benchmark_test.go:51
SWEEP_TYPES = 400


PROFILE_DIR = None  # set by --profile: per-config cProfile + XLA trace artifacts


def bench_provenance(mode: str) -> dict:
    """The artifact identity block (karpenter_tpu/provenance.py): git SHA +
    ISO timestamp + a hash of the grid configuration. The r2-r5 headline
    drift stayed unbisectable because BENCH artifacts carried none of this."""
    from karpenter_tpu.provenance import provenance_block

    return provenance_block(
        {
            "mode": mode,
            "headline_pods": HEADLINE_PODS,
            "headline_types": HEADLINE_TYPES,
            "headline_trials": HEADLINE_TRIALS,
            "side_trials": SIDE_TRIALS,
            "sweep_pods": list(SWEEP_PODS),
            "sweep_types": SWEEP_TYPES,
            "baseline_pods_per_sec": BASELINE_PODS_PER_SEC,
        }
    )


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# -- bench --compare: per-config, per-phase regression diff -------------------

# the phase keys judged for regression (ms medians in the phases block) plus
# `compilations` — a steady-state compile-count increase is a regression by
# definition, not noise. Informational keys (hbm, fill routing, span trees)
# are diffed in the report but never gate.
COMPARE_PHASE_KEYS = (
    "encode", "fill", "device", "mask", "assemble", "commit", "fill_device",
    "delta_apply", "full_encode", "audit_seconds", "compilations",
)
COMPARE_DEFAULT_THRESHOLD = 10.0  # percent


def _compare_payload(doc: dict) -> dict:
    """Accept either bench.py's own emitted JSON (configs/phases at the top)
    or the committed BENCH_r0x wrapper shape ({"parsed": {...}, ...})."""
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and "configs" not in doc and "phases" not in doc:
        return parsed
    return doc


def compare_phases(old_doc: dict, new_doc: dict, threshold_pct: float = COMPARE_DEFAULT_THRESHOLD):
    """Diff two bench artifacts per config and per phase. Returns
    (report_lines, regressions): every compared value is a report line;
    a value that grew by more than `threshold_pct` percent (strictly, and
    from a nonzero base — a phase appearing from zero is reported as `new`
    but judged only when it is a counter) is also a regression."""
    old_doc, new_doc = _compare_payload(old_doc), _compare_payload(new_doc)
    lines: list = []
    regressions: list = []

    def judge(where: str, old_v, new_v, gate: bool) -> None:
        if old_v is None:
            lines.append(f"  {where}: (new) {new_v}")
            return
        if new_v is None:
            lines.append(f"  {where}: {old_v} -> (gone)")
            return
        if old_v > 0:
            pct = (new_v - old_v) / old_v * 100.0
            verdict = ""
            if gate and pct > threshold_pct:
                verdict = f"  REGRESSION (> {threshold_pct:g}%)"
                regressions.append(f"{where}: {old_v} -> {new_v} (+{pct:.1f}% > {threshold_pct:g}%)")
            lines.append(f"  {where}: {old_v} -> {new_v} ({pct:+.1f}%){verdict}")
        elif new_v > 0 and gate and where.endswith("compilations"):
            # a counter stepping off zero has no percentage; it still gates
            regressions.append(f"{where}: 0 -> {new_v} (compile churn from zero)")
            lines.append(f"  {where}: 0 -> {new_v}  REGRESSION (compile churn from zero)")
        else:
            lines.append(f"  {where}: {old_v} -> {new_v}")

    old_configs = old_doc.get("configs") or {}
    new_configs = new_doc.get("configs") or {}
    lines.append("configs (total ms):")
    for name in sorted(set(old_configs) | set(new_configs)):
        judge(name, old_configs.get(name), new_configs.get(name), gate=True)

    old_phases = old_doc.get("phases") or {}
    new_phases = new_doc.get("phases") or {}
    for name in sorted(set(old_phases) | set(new_phases)):
        lines.append(f"phases [{name}]:")
        old_block, new_block = old_phases.get(name, {}), new_phases.get(name, {})
        for key in COMPARE_PHASE_KEYS:
            if key in old_block or key in new_block:
                judge(f"{name}.{key}", old_block.get(key), new_block.get(key), gate=True)
        # informational-only numeric keys: visible in the diff, never gating
        for key in sorted(set(old_block) | set(new_block)):
            if key in COMPARE_PHASE_KEYS:
                continue
            old_v, new_v = old_block.get(key), new_block.get(key)
            if isinstance(old_v, (int, float)) or isinstance(new_v, (int, float)):
                judge(f"{name}.{key}", old_v, new_v, gate=False)
    return lines, regressions


def compare_main(argv) -> int:
    """`bench.py --compare OLD.json NEW.json [--threshold PCT]`: per-config,
    per-phase regression diff of two bench phases artifacts. Exit 0 when NEW
    is within the threshold of OLD everywhere, 1 with the regressions listed
    on stderr otherwise (the BENCH_r0x trajectory, tooled). Pure JSON — runs
    without jax, so CI can gate artifacts on any box."""
    import argparse

    parser = argparse.ArgumentParser(prog="bench.py --compare")
    parser.add_argument("old", help="baseline bench phases JSON (or BENCH_r0x wrapper)")
    parser.add_argument("new", help="candidate bench phases JSON (or BENCH_r0x wrapper)")
    parser.add_argument(
        "--threshold", type=float, default=COMPARE_DEFAULT_THRESHOLD,
        help=f"regression threshold in percent (default {COMPARE_DEFAULT_THRESHOLD:g})",
    )
    args = parser.parse_args(argv)
    if args.threshold < 0:
        parser.error("--threshold must be non-negative")
    docs = []
    for path in (args.old, args.new):
        try:
            with open(path, encoding="utf-8") as f:
                docs.append(json.load(f))
        except (OSError, json.JSONDecodeError) as err:
            print(f"bench --compare: cannot read {path}: {err}", file=sys.stderr)
            return 2
    lines, regressions = compare_phases(docs[0], docs[1], threshold_pct=args.threshold)
    print(f"bench --compare: {args.old} -> {args.new} (threshold {args.threshold:g}%)")
    for line in lines:
        print(line)
    if regressions:
        print(f"{len(regressions)} regression(s) past {args.threshold:g}%:", file=sys.stderr)
        for regression in regressions:
            print(f"  {regression}", file=sys.stderr)
        return 1
    print("no regressions")
    return 0


def profile_config(name, pods, provider, provisioners, solver, state_nodes=()):
    """Per-config profile artifacts (the scheduling_benchmark_test.go:76-108
    CPU/heap-profile grid analog): one profiled solve per config emitting
      <dir>/<name>/host.pstats    — cProfile dump (snakeviz/pstats-ready)
      <dir>/<name>/host_top.txt   — top-40 cumulative functions
      <dir>/<name>/xla_trace/     — jax.profiler trace (TensorBoard-ready),
                                    skipped if the platform can't trace
    so later rounds can chase latency-curve regressions with data."""
    import cProfile
    import io
    import os
    import pstats

    out = os.path.join(PROFILE_DIR, name)
    os.makedirs(out, exist_ok=True)
    import jax

    pr = cProfile.Profile()
    trace_ok = True
    try:
        with jax.profiler.trace(os.path.join(out, "xla_trace")):
            pr.enable()
            try:
                run_once(pods, provider, provisioners, solver, state_nodes)
            finally:
                pr.disable()  # never leave sys.setprofile installed for later configs
    except Exception as exc:
        # only the *tracer* may fail soft (platform can't trace); a solve
        # failure must surface, not silently corrupt later configs
        trace_ok = False
        log(f"  [{name}] xla trace failed ({exc}); host profile only")
        if not pr.getstats():
            pr.enable()
            try:
                run_once(pods, provider, provisioners, solver, state_nodes)
            finally:
                pr.disable()
    pr.dump_stats(os.path.join(out, "host.pstats"))
    stream = io.StringIO()
    pstats.Stats(pr, stream=stream).sort_stats("cumulative").print_stats(40)
    with open(os.path.join(out, "host_top.txt"), "w") as f:
        f.write(stream.getvalue())
    log(f"  [{name}] profile artifacts in {out}" + ("" if trace_ok else " (xla trace unavailable)"))


def build_workload(count: int, seed: int = 42):
    """The reference benchmark's mixed workload (scheduling_benchmark_test.go:
    180-194): ~4/7 generic + zonal spread + zonal self-affinity + hostname
    anti-affinity cohorts, with self-consistent selectors."""
    from karpenter_tpu.api.labels import LABEL_HOSTNAME, LABEL_TOPOLOGY_ZONE
    from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm, TopologySpreadConstraint
    from tests.helpers import make_pod

    rng = np.random.default_rng(seed)
    cpus = [0.1, 0.25, 0.5, 1.0, 1.5]
    mems = ["100Mi", "256Mi", "512Mi", "1Gi", "2Gi", "4Gi"]
    values = "abcdefg"

    def size():
        return {"cpu": cpus[rng.integers(len(cpus))], "memory": mems[rng.integers(len(mems))]}

    pods = []
    seventh = count // 7
    for i in range(seventh):  # zonal spread, 7 label cohorts
        label = {"spread": values[rng.integers(7)]}
        pods.append(
            make_pod(
                labels=label,
                requests=size(),
                topology_spread_constraints=[
                    TopologySpreadConstraint(max_skew=1, topology_key=LABEL_TOPOLOGY_ZONE, label_selector=LabelSelector(match_labels=label))
                ],
            )
        )
    for i in range(seventh):  # zonal self-affinity cohorts
        label = {"affinity": values[rng.integers(7)]}
        pods.append(
            make_pod(
                labels=label,
                requests=size(),
                pod_requirements=[PodAffinityTerm(topology_key=LABEL_TOPOLOGY_ZONE, label_selector=LabelSelector(match_labels=label))],
            )
        )
    for i in range(seventh):  # hostname anti-affinity cohorts
        label = {"anti": values[rng.integers(7)]}
        pods.append(
            make_pod(
                labels=label,
                requests=size(),
                pod_anti_requirements=[PodAffinityTerm(topology_key=LABEL_HOSTNAME, label_selector=LabelSelector(match_labels=label))],
            )
        )
    while len(pods) < count:
        pods.append(make_pod(labels={"app": values[rng.integers(7)]}, requests=size()))
    return pods


def build_selectors_taints_workload(count: int, seed: int = 7):
    """BASELINE config 2: nodeSelector cohorts over zones, all pods tolerating
    the provisioner's dedicated taint."""
    from karpenter_tpu.api.labels import LABEL_TOPOLOGY_ZONE
    from karpenter_tpu.api.objects import Toleration
    from tests.helpers import make_pod

    rng = np.random.default_rng(seed)
    cpus = [0.25, 0.5, 1.0]
    mems = ["256Mi", "512Mi", "1Gi", "2Gi"]
    zones = ["test-zone-1", "test-zone-2", "test-zone-3"]
    tol = [Toleration(key="dedicated", operator="Equal", value="batch", effect="NoSchedule")]

    pods = []
    for i in range(count):
        kwargs = dict(
            requests={"cpu": cpus[rng.integers(3)], "memory": mems[rng.integers(4)]},
            tolerations=tol,
        )
        if i % 2 == 0:  # half the pods pin a zone via nodeSelector
            kwargs["node_selector"] = {LABEL_TOPOLOGY_ZONE: zones[rng.integers(3)]}
        pods.append(make_pod(**kwargs))
    return pods


def build_repack_state(node_count: int):
    """BASELINE config 4: a warm 300-node cluster to repack onto."""
    from karpenter_tpu.api.labels import (
        LABEL_CAPACITY_TYPE,
        LABEL_INSTANCE_TYPE,
        LABEL_TOPOLOGY_ZONE,
        PROVISIONER_NAME_LABEL,
    )
    from tests.helpers import make_state_node

    zones = ["test-zone-1", "test-zone-2", "test-zone-3"]
    nodes = []
    for i in range(node_count):
        labels = {
            PROVISIONER_NAME_LABEL: "default",
            LABEL_INSTANCE_TYPE: "fake-it-15",
            LABEL_TOPOLOGY_ZONE: zones[i % 3],
            LABEL_CAPACITY_TYPE: "on-demand",
        }
        nodes.append(
            make_state_node(
                labels=labels,
                allocatable={"cpu": 16, "memory": "32Gi", "pods": 110},
            )
        )
    return nodes


def build_spot_od_types(total: int):
    """BASELINE config 5: total/2 shapes, each offered spot (cheap) and
    on-demand (pricey) as distinct types — mixed-pricing universe."""
    from karpenter_tpu.cloudprovider.fake import Offering, instance_type

    types = []
    zones = ["test-zone-1", "test-zone-2", "test-zone-3"]
    for i in range(total // 2):
        cpu = (i % 32) + 1
        mem = f"{cpu * 2}Gi"
        pods = cpu * 10
        offers = [Offering(capacity_type="on-demand", zone=z) for z in zones]
        types.append(instance_type(f"od-{i}", cpu=cpu, memory=mem, pods=pods, offerings=offers, price=0.12 * cpu))
        offers = [Offering(capacity_type="spot", zone=z) for z in zones]
        types.append(instance_type(f"spot-{i}", cpu=cpu, memory=mem, pods=pods, offerings=offers, price=0.04 * cpu))
    return types


def run_once(pods, provider, provisioners, solver, state_nodes=()):
    from karpenter_tpu.scheduler import build_scheduler
    from karpenter_tpu.solver import DenseSolveStats

    solver.stats = DenseSolveStats()
    scheduler = build_scheduler(
        provisioners, provider, pods, state_nodes=state_nodes, dense_solver=solver
    )
    t0 = time.perf_counter()
    results = scheduler.solve(pods)
    elapsed = time.perf_counter() - t0
    scheduled = sum(len(n.pods) for n in results.new_nodes) + sum(
        len(v.pods) for v in results.existing_nodes
    )
    cost = sum(n.instance_type_options[0].price() for n in results.new_nodes)
    # per-run packing stats (scheduling_benchmark_test.go:151-168)
    per_node = [len(n.pods) for n in results.new_nodes if n.pods]
    if per_node:
        stats_line = (
            f"pods/node min={min(per_node)} max={max(per_node)} "
            f"mean={np.mean(per_node):.1f} stddev={np.std(per_node):.1f}"
        )
    else:
        stats_line = "pods/node n/a (all on existing)"
    return elapsed, scheduled, len(results.new_nodes), cost, solver.stats, stats_line


# per-config phase breakdown (encode/fill/device/commit medians, warm-fill
# routing, node-guard counters), keyed by the BASELINE config name and
# emitted in the JSON line — so stage-level drift is attributable from the
# parsed artifact without rerunning by hand (VERDICT r5 hygiene ask)
PHASE_BREAKDOWN: dict = {}


def capture_span_tree():
    """The span tree of the most recently completed solve (tracing.py runs
    enabled for the whole bench): lands in the phases JSON so a headline
    drift is bisectable from the artifact — per-solve encode/device/commit
    child spans, not just aggregate medians."""
    from karpenter_tpu.tracing import TRACER

    trace_id = TRACER.last_trace_id()
    return TRACER.span_tree(trace_id) if trace_id else None


def assert_span_tree(tree, context: str) -> None:
    """Structural gate on a solve trace: non-empty, rooted at the solve span,
    and the measured encode/device/commit children sum to no more than the
    parent wall-clock (they are disjoint sub-intervals of the solve)."""
    assert tree, f"[{context}] tracing produced no span tree"
    assert tree.get("name") == "solve", f"[{context}] trace root is {tree.get('name')!r}, not the solve span"
    children = {c["name"]: c for c in tree.get("children", ())}
    for name in ("encode", "device", "commit"):
        assert name in children, f"[{context}] span tree missing dense child {name!r}: {sorted(children)}"
    child_sum = sum(children[n]["duration_ms"] for n in ("encode", "device", "commit"))
    parent = tree["duration_ms"]
    assert child_sum <= parent + 1e-3, f"[{context}] child spans sum {child_sum}ms > parent solve {parent}ms"


def run_config(name, pods, provider, provisioners, solver, state_nodes=(), trials=SIDE_TRIALS, phase_key=None):
    from karpenter_tpu import flight

    run_once(pods, provider, provisioners, solver, state_nodes)  # warmup/compile
    # compile-churn gate data (flight.py): the measured trials run the SAME
    # shapes the warmup compiled, so a nonzero count here IS steady-state
    # recompilation — the regression the flight recorder exists to attribute
    compile_base = flight.FLIGHT.compilations_total()
    compile_seconds_base = flight.COMPILE_SECONDS.value()
    times = []
    phase_trials: dict = {
        k: []
        for k in (
            "encode", "fill", "device", "mask", "assemble", "commit", "fill_device",
            "delta_apply", "full_encode", "audit_seconds",
        )
    }
    last_stats = None
    for _ in range(trials):
        elapsed, scheduled, nodes, cost, stats, packing = run_once(
            pods, provider, provisioners, solver, state_nodes
        )
        times.append(elapsed)
        last_stats = stats
        phase_trials["encode"].append(stats.encode_seconds)
        phase_trials["fill"].append(stats.fill_seconds)
        phase_trials["device"].append(stats.device_seconds)
        # host work overlapped with the device RT: splits device-link time
        # from host assembly when attributing headline drift
        # offering-availability cube reduction (device matmul at the head of
        # the device phase): subset of device time, like assemble
        phase_trials["mask"].append(stats.mask_seconds)
        phase_trials["assemble"].append(stats.assemble_seconds)
        phase_trials["commit"].append(stats.commit_seconds)
        phase_trials["fill_device"].append(stats.fill_device_seconds)
        # incremental-engine phase split (solver/incremental.py): zero on the
        # stock configs, populated by the incremental_churn config — present
        # everywhere so --compare diffs the same key set across artifacts
        phase_trials["delta_apply"].append(stats.delta_apply_seconds)
        phase_trials["full_encode"].append(stats.full_encode_seconds)
        # residency-auditor overhead (solver/audit.py): zero on the stock
        # configs (the auditor is disabled), populated when the
        # incremental_churn config runs with auditing on
        phase_trials["audit_seconds"].append(stats.audit_seconds)
        log(
            f"  [{name}] trial {elapsed*1000:.1f} ms (encode {stats.encode_seconds*1000:.0f}"
            f" fill {stats.fill_seconds*1000:.0f} device {stats.device_seconds*1000:.0f}"
            f" commit {stats.commit_seconds*1000:.0f}) scheduled={scheduled}"
            f" nodes={nodes} dense={stats.pods_committed} cost={cost:.1f} {packing}"
        )
        if scheduled < len(pods) * 0.99:
            log(f"  [{name}] WARNING: only {scheduled}/{len(pods)} pods scheduled")
    compilations = flight.FLIGHT.compilations_total() - compile_base
    if compilations:
        log(f"  [{name}] WARNING: {compilations} XLA compilations during measured trials (post-warmup)")
    if phase_key is not None and last_stats is not None:
        PHASE_BREAKDOWN[phase_key] = {
            **{k: round(float(np.median(v)) * 1000, 2) for k, v in phase_trials.items()},
            # device-runtime telemetry (flight.py): compilations across the
            # measured (post-warmup) trials, their compile seconds, and the
            # peak device memory of the final trial — per-config, so a
            # compile-churn or HBM regression is attributable from the
            # artifact exactly like a phase-time drift
            "compilations": compilations,
            "compile_seconds": round(float(flight.COMPILE_SECONDS.value() - compile_seconds_base), 3),
            "hbm_peak_bytes": int(flight.HBM_PEAK.value()),
            "fills_vectorized": last_stats.fills_vectorized,
            "fills_host": last_stats.fills_host,
            "fill_pods_vectorized": last_stats.fill_pods_vectorized,
            "fill_pods_host": last_stats.fill_pods_host,
            "nodes_opened_dense": last_stats.nodes_opened_dense,
            "nodes_opened_host_floor": last_stats.nodes_opened_host_floor,
            "node_guard_failopens": last_stats.node_guard_failopens,
            "masked_offerings": last_stats.masked_offerings,
            # the final trial's span tree (encode/device/commit children
            # under the solve root) — the bisect-from-artifacts evidence
            "span_tree": capture_span_tree(),
        }
    if PROFILE_DIR:
        profile_config(name, pods, provider, provisioners, solver, state_nodes)
    return float(np.median(times) * 1000), times


def run_incremental_churn(node_count: int, pods_per_pass: int, passes: int, phase_key=None, audit_interval: int = 0):
    """INCREMENTAL config: a large standing cluster absorbing a small
    per-pass delta — the O(delta) steady-state claim, measured and PINNED.

    A persistent DenseSolver carries the incremental engine
    (solver/incremental.py) across provision passes against a live cluster
    mirror; between passes a handful of pod binds and one node-status
    refresh flow kube -> watch -> delta journal, the production feed. The
    churn is sized to stay under the smallest dirty-pad rung (8), so the
    donated rebase kernel keeps one traced shape for the whole window.

    Asserted at measurement time (the ISSUE acceptance gates, not report
    fields): every measured pass takes the delta path, full_encode stays
    exactly zero, zero XLA recompiles across the window, and the final
    pass's placements are identical to a fresh-encode solver on the same
    snapshot and pod batch."""
    from karpenter_tpu import flight
    from karpenter_tpu.api.labels import (
        LABEL_CAPACITY_TYPE,
        LABEL_INSTANCE_TYPE,
        LABEL_TOPOLOGY_ZONE,
        PROVISIONER_NAME_LABEL,
    )
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
    from karpenter_tpu.controllers.state.cluster import Cluster
    from karpenter_tpu.kube.cluster import KubeCluster
    from karpenter_tpu.scheduler import build_scheduler
    from karpenter_tpu.solver import DenseSolveStats, DenseSolver
    from karpenter_tpu.solver.incremental import PASS_DELTA, PASS_FULL, IncrementalEngine
    from tests.helpers import make_node, make_pod, make_provisioner

    zones = ["test-zone-1", "test-zone-2", "test-zone-3"]
    provider = FakeCloudProvider(instance_types(100))
    provisioners = [make_provisioner()]
    kube = KubeCluster()
    for i in range(node_count):
        kube.create(
            make_node(
                name=f"churn-n{i:04d}",
                labels={
                    PROVISIONER_NAME_LABEL: "default",
                    LABEL_INSTANCE_TYPE: "fake-it-15",
                    LABEL_TOPOLOGY_ZONE: zones[i % 3],
                    LABEL_CAPACITY_TYPE: "on-demand",
                },
                allocatable={"cpu": 16, "memory": "32Gi", "pods": 110},
            )
        )
    cluster = Cluster(kube, None)
    engine = IncrementalEngine(cluster.delta_journal)
    solver = DenseSolver(min_batch=1, incremental=engine)

    # optional residency auditing (solver/audit.py) riding the measured
    # window: audit_interval=1 audits EVERY pass, so both audit shapes — the
    # 8-row sampled gather and the full-shadow gather on the 128 dirty-pad
    # rung — trace during the two warmup passes (audit 0 is a shadow, audit
    # 1 is sampled) and the steady-state recompile gate below covers
    # audit-induced compiles too
    audit_div_base = audit_pass_base = 0
    if audit_interval:
        from karpenter_tpu.solver import audit as solver_audit

        solver_audit.AUDITOR.reset()
        solver_audit.AUDITOR.enable(interval=audit_interval, seed=7)
        audit_div_base = solver_audit.divergences_total()
        audit_pass_base = solver_audit.audit_passes_total()

    def churn(step):
        # three pod binds + one node-status refresh: <= 4 dirty node names
        # per pass, so even with the engine's double-window the dirty pad
        # stays on its smallest rung and nothing re-traces mid-measurement
        for i in range(3):
            node = f"churn-n{(step * 3 + i) % node_count:04d}"
            kube.create(
                make_pod(
                    name=f"churn-bp{step:03d}-{i}",
                    labels={"app": "standing"},
                    requests={"cpu": 0.25, "memory": "256Mi"},
                    node_name=node,
                    phase="Running",
                    unschedulable=False,
                )
            )
        refreshed = kube.get_node(f"churn-n{(step * 7) % node_count:04d}")
        if refreshed is not None:
            kube.update(refreshed)

    def pods_for(step):
        return [
            make_pod(
                name=f"churn-p{step:03d}-{i:03d}",
                labels={"app": "delta"},
                requests={"cpu": 0.5, "memory": "512Mi"},
            )
            for i in range(pods_per_pass)
        ]

    def one_pass(run_solver, step):
        pods = pods_for(step)
        run_solver.stats = DenseSolveStats()
        scheduler = build_scheduler(
            provisioners, provider, pods, cluster=cluster,
            state_nodes=cluster.nodes_snapshot(), dense_solver=run_solver,
        )
        t0 = time.perf_counter()
        results = scheduler.solve(pods)
        elapsed = time.perf_counter() - t0
        scheduled = sum(len(n.pods) for n in results.new_nodes) + sum(
            len(v.pods) for v in results.existing_nodes
        )
        assert scheduled == len(pods), (
            f"[incremental_churn] pass {step}: {scheduled}/{len(pods)} scheduled"
        )
        return elapsed, results, run_solver.stats

    # warmup: pass 0 is the cold full encode, pass 1 the first delta pass —
    # it compiles the donated rebase kernel and the resident-head fill shape.
    # Steady state is measured strictly after both.
    one_pass(solver, 0)
    churn(0)
    one_pass(solver, 1)
    assert engine.passes[PASS_DELTA] >= 1, "[incremental_churn] warmup never reached the delta path"
    delta_base = engine.passes[PASS_DELTA]
    full_base = engine.passes[PASS_FULL]
    compile_base = flight.FLIGHT.compilations_total()

    times, delta_apply, full_encode, audit_times = [], [], [], []
    skipped = 0
    for step in range(2, passes + 2):
        churn(step)
        elapsed, _results, stats = one_pass(solver, step)
        times.append(elapsed)
        delta_apply.append(stats.delta_apply_seconds)
        full_encode.append(stats.full_encode_seconds)
        audit_times.append(stats.audit_seconds)
        skipped += stats.encode_skipped_passes
        log(
            f"  [incremental_churn] pass {step} {elapsed*1000:.1f} ms "
            f"(delta_apply {stats.delta_apply_seconds*1000:.2f} "
            f"full_encode {stats.full_encode_seconds*1000:.2f})"
        )

    compilations = flight.FLIGHT.compilations_total() - compile_base
    delta_passes = engine.passes[PASS_DELTA] - delta_base
    assert delta_passes == passes, (
        f"[incremental_churn] full re-encode leaked into steady state: "
        f"{delta_passes}/{passes} delta passes"
    )
    assert engine.passes[PASS_FULL] == full_base, "[incremental_churn] unexplained full re-encode"
    assert skipped == passes, (
        f"[incremental_churn] presolve skipped {skipped}/{passes} encodes"
    )
    assert max(full_encode) == 0.0, "[incremental_churn] full-encode time charged on a delta pass"
    assert compilations == 0, (
        f"[incremental_churn] {compilations} XLA recompile(s) across {passes} consecutive delta passes"
    )
    audit_info = {}
    if audit_interval:
        audit_divergences = solver_audit.divergences_total() - audit_div_base
        audit_passes = solver_audit.audit_passes_total() - audit_pass_base
        # the auditor rode every measured pass: a byte-equal resident state
        # under clean churn must diverge ZERO times (a nonzero here is a
        # real integrity bug, not noise), it must actually have audited,
        # and its overhead must stay bounded — note the compilations==0
        # assert above already proved the audit gathers re-traced nothing
        assert audit_divergences == 0, (
            f"[incremental_churn] auditor found {audit_divergences} divergence(s) on clean churn"
        )
        assert audit_passes >= passes, (
            f"[incremental_churn] auditor ran {audit_passes} audits across {passes} passes"
        )
        audit_ms = round(float(np.median(audit_times)) * 1000, 3)
        assert audit_ms < 50.0, f"[incremental_churn] audit overhead {audit_ms} ms/pass"
        audit_info = {
            "audit_passes": audit_passes,
            "audit_divergences": audit_divergences,
            "audit_seconds": audit_ms,
        }

    # parity coda (outside the measured window): the next delta pass must
    # place identically to a fresh-encode solver on the same snapshot + batch
    final_step = passes + 2
    churn(final_step)
    _, results_i, _ = one_pass(solver, final_step)
    _, results_f, _ = one_pass(DenseSolver(min_batch=1), final_step)

    def sig(results):
        existing = sorted(
            (v.node.name, tuple(p.name for p in v.pods)) for v in results.existing_nodes
        )
        new = sorted(tuple(sorted(p.name for p in n.pods)) for n in results.new_nodes)
        return existing, new

    assert sig(results_i) == sig(results_f), (
        "[incremental_churn] incremental placements diverge from a fresh encode"
    )
    if audit_interval:
        solver_audit.AUDITOR.disable()
        solver_audit.AUDITOR.reset()

    info = {
        "nodes": node_count,
        "pods_per_pass": pods_per_pass,
        "passes": passes,
        "delta_passes": delta_passes,
        "encode_skipped_passes": skipped,
        "delta_apply": round(float(np.median(delta_apply)) * 1000, 3),
        "full_encode": round(float(max(full_encode)) * 1000, 3),
        "compilations": compilations,
        **audit_info,
    }
    if phase_key is not None:
        PHASE_BREAKDOWN[phase_key] = {**info, "span_tree": capture_span_tree()}
    return float(np.median(times) * 1000), info


def measure_cost_regret() -> float:
    """Dense-path node-cost regret vs the exhaustive MILP on a MILP-tractable
    mixed-size instance (the <=3% BASELINE gate, measured every round)."""
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
    from karpenter_tpu.scheduler import build_scheduler
    from karpenter_tpu.scheduling.nodetemplate import NodeTemplate
    from karpenter_tpu.solver import DenseSolver
    from karpenter_tpu.solver.optimal import optimal_node_cost, problem_matrices
    from tests.helpers import make_pod, make_provisioner

    rng = np.random.default_rng(11)
    cpus = [0.25, 0.5, 1.0, 1.5]
    mems = ["256Mi", "512Mi", "1Gi", "2Gi"]
    provider = FakeCloudProvider(instance_types(8))
    provisioner = make_provisioner()
    pods = [
        make_pod(requests={"cpu": cpus[rng.integers(4)], "memory": mems[rng.integers(4)]})
        for _ in range(24)
    ]
    template = NodeTemplate.from_provisioner(provisioner)
    types = provider.get_instance_types(provisioner)
    requests, caps, prices, compat = problem_matrices(pods, types, template)
    opt = optimal_node_cost(requests, caps, prices, compat, time_limit=60.0)
    if not opt.ok:
        log(f"  [regret] MILP not optimal ({opt.status}); skipping")
        return -1.0
    solver = DenseSolver(min_batch=1)
    scheduler = build_scheduler([provisioner], provider, pods, dense_solver=solver)
    results = scheduler.solve(pods)
    placed = sum(len(n.pods) for n in results.new_nodes) + sum(
        len(v.pods) for v in results.existing_nodes
    )
    if placed != len(pods):
        # an unscheduled pod would deflate the regret (nodes priced for fewer
        # pods than the MILP packed) — report failure, not a bogus pass
        log(f"  [regret] only {placed}/{len(pods)} pods scheduled; not comparable")
        return -1.0
    cost = sum(min(it.price() for it in n.instance_type_options) for n in results.new_nodes)
    regret = (cost - opt.cost) / opt.cost
    log(f"  [regret] dense cost {cost:.4f} vs ILP {opt.cost:.4f}: {regret:.2%}")
    return round(regret, 4)


def smoke() -> dict:
    """Structural perf-path assertions on scaled-down BASELINE configs — no
    wall-clock gates, so it runs green on CPU in tier-1 (tests/
    test_bench_smoke.py) and catches perf-path breakage (dense path not
    engaging, warm fill falling back to the host loop, node-count guard
    tripping, device column gone) without timing flakes.

    Asserts per config: every pod scheduled; the dense path committed
    (cold configs) or the vectorized warm fill engaged with nonzero device
    time (repack config); the node-guard never tripped and the dense node
    count stayed within the guard ratio of the host floor."""
    from karpenter_tpu.capsule import CAPSULE
    from karpenter_tpu.flight import FLIGHT
    from karpenter_tpu.tracing import TRACER

    was_enabled = TRACER.enabled
    flight_was_enabled = FLIGHT.enabled
    capsule_was_enabled = CAPSULE.enabled
    TRACER.enable()  # smoke runs traced: an empty span tree is a tier-1 failure
    FLIGHT.enable()  # and flight-recorded: compile/HBM telemetry per config
    CAPSULE.enable()  # and capsule-armed: a healthy smoke must capture NOTHING
    try:
        return _smoke()
    finally:
        if not was_enabled:
            # smoke runs inside tier-1 (test_bench_smoke): even a failing
            # assert must not leave the process-wide tracer on for
            # unrelated tests that follow
            TRACER.disable()
        if not flight_was_enabled:
            FLIGHT.disable()
        if not capsule_was_enabled:
            CAPSULE.disable()


# smoke configs whose workloads carry NO multi-rule affinity cohorts (every
# cohort holds at most one extra integer rule — the certified vectorized
# case): their fill stream must never route a pod through the host loop.
# Today that is EVERY smoke config; a future config seeding multi-rule
# cohorts (the PR 1 deferral, ROADMAP item 5) gets added here only once the
# device-side rule kernel lands.
SMOKE_ZERO_HOST_FILL_CONFIGS = ("anti_spread", "ffd_parity", "selectors_taints", "repack", "spot_od", "ice_mask")


def _smoke() -> dict:
    from karpenter_tpu import flight
    from karpenter_tpu.api.objects import Taint
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
    from karpenter_tpu.solver import DenseSolver, faults as solver_faults
    from tests.helpers import make_pod, make_provisioner

    summary: dict = {}
    # flight records created by THIS smoke run (a shared tier-1 process may
    # hold earlier records): everything after this id is ours
    _prior = flight.FLIGHT.records()
    smoke_first_record_id = (_prior[-1].id + 1) if _prior else 0
    # solver fault-domain baseline: healthy hardware + steady traffic must
    # produce ZERO classified faults and ZERO degradation-ladder rungs
    # across the whole smoke run, and the circuit breaker must never open
    # (deltas, not absolutes — a shared tier-1 process may have run the
    # injection suites first; the breaker is RESET and any leaked FaultPlan
    # disarmed for the same reason: an aborted injection suite must not
    # leave either active under the smoke)
    solver_faults.BREAKER.reset()
    solver_faults.FAULTS.clear()
    faults_base = solver_faults.faults_total()
    degraded_base = solver_faults.degraded_total()

    def check(name, pods, provider, provisioners, state_nodes=(), repack=False):
        solver = DenseSolver(min_batch=1)
        compile_base = flight.FLIGHT.compilations_total()
        compile_seconds_base = flight.COMPILE_SECONDS.value()
        elapsed, scheduled, nodes, cost, stats, _packing = run_once(
            pods, provider, provisioners, solver, state_nodes
        )
        span_tree = capture_span_tree()
        assert_span_tree(span_tree, name)
        # flight-recorder gate: the solve was recorded, with non-negative
        # compile/HBM telemetry (counts are structural — a shared-process
        # tier-1 run may find these shapes already compiled)
        records = flight.FLIGHT.records()
        assert records, f"[{name}] flight recorder captured no solve record"
        hbm_peak, hbm_live = records[-1].hbm_peak_bytes, records[-1].hbm_live_bytes
        assert hbm_peak >= 0 and hbm_live >= 0, f"[{name}] negative HBM accounting"
        assert scheduled == len(pods), f"[{name}] scheduled {scheduled}/{len(pods)}"
        assert stats.node_guard_failopens == 0, f"[{name}] node guard tripped"
        if stats.nodes_opened_host_floor:
            ratio = stats.nodes_opened_dense / stats.nodes_opened_host_floor
            assert (
                stats.nodes_opened_dense < DenseSolver._NODE_GUARD_MIN_NODES
                or ratio <= DenseSolver._NODE_GUARD_RATIO
            ), f"[{name}] node-count ratio {ratio:.2f} over guard"
        if repack:
            assert stats.fills_vectorized >= 1, f"[{name}] warm fill fell back to host loop"
            assert stats.fill_device_seconds > 0, f"[{name}] no device work in the fill"
        else:
            assert stats.pods_committed > 0, f"[{name}] dense path never committed"
        summary[name] = {
            "pods": len(pods),
            "nodes": nodes,
            "dense_committed": stats.pods_committed,
            "fills_vectorized": stats.fills_vectorized,
            "fill_pods_vectorized": stats.fill_pods_vectorized,
            "fill_pods_host": stats.fill_pods_host,
            "nodes_opened_dense": stats.nodes_opened_dense,
            "nodes_opened_host_floor": stats.nodes_opened_host_floor,
            "masked_offerings": stats.masked_offerings,
            "mask_seconds": stats.mask_seconds,
            # device-runtime telemetry (flight.py), per config
            "compilations": flight.FLIGHT.compilations_total() - compile_base,
            "compile_seconds": round(float(flight.COMPILE_SECONDS.value() - compile_seconds_base), 6),
            "hbm_peak_bytes": hbm_peak,
            "hbm_live_bytes": hbm_live,
            "span_tree": span_tree,
        }
        # host-fallback residue gate (ROADMAP item 5): a config with no
        # multi-rule affinity cohorts must keep its whole fill stream on the
        # vectorized path — a nonzero host-routed pod count is a plan()
        # fail-open regression, not a workload property
        if name in SMOKE_ZERO_HOST_FILL_CONFIGS:
            assert stats.fill_pods_host == 0, (
                f"[{name}] {stats.fill_pods_host} pod(s) routed through the host fill loop "
                f"on a config with no multi-rule affinity cohorts"
            )
        log(
            f"  [smoke:{name}] ok ({elapsed*1000:.0f} ms, {nodes} nodes, "
            f"fill_pods_host={stats.fill_pods_host})"
        )

    log("smoke: anti_spread (headline shape, scaled)")
    check("anti_spread", build_workload(700, seed=42), FakeCloudProvider(instance_types(100)), [make_provisioner()])

    log("smoke: ffd_parity")
    check(
        "ffd_parity",
        [make_pod(requests={"cpu": 1, "memory": "1Gi"}) for _ in range(300)],
        FakeCloudProvider(instance_types(50)),
        [make_provisioner()],
    )

    log("smoke: selectors_taints")
    check(
        "selectors_taints",
        build_selectors_taints_workload(400),
        FakeCloudProvider(instance_types(100)),
        [make_provisioner(taints=[Taint(key="dedicated", value="batch", effect="NoSchedule")])],
    )

    log("smoke: repack (warm fill)")
    check(
        "repack",
        build_workload(600, seed=3),
        FakeCloudProvider(instance_types(60)),
        [make_provisioner()],
        state_nodes=build_repack_state(90),
        repack=True,
    )

    log("smoke: spot_od_multiprov")
    check(
        "spot_od",
        build_workload(500, seed=5),
        FakeCloudProvider(build_spot_od_types(100)),
        [make_provisioner(name="spot", weight=10), make_provisioner(name="on-demand", weight=1)],
    )

    # the repack shape's fill stream must be fully vectorized (the certified
    # common case, now including single-extra-rule affinity cohorts): a
    # nonzero host-routed pod count here means a plan() fail-open regressed
    assert summary["repack"]["fill_pods_vectorized"] >= 1, "[repack] no pods through the vectorized fill"

    log("smoke: ice_mask (offering-availability mask active)")
    from dataclasses import replace as _replace

    masked_types = instance_types(100)
    # quarantine every offering of the 25 cheapest types (the
    # unavailable-offerings cache shape): the dense path must schedule the
    # whole batch onto the surviving types, with the mask applied as a
    # device-side phase — never a host loop and never a masked selection
    for it in masked_types[:25]:
        it._offerings = tuple(_replace(o, available=False) for o in it._offerings)
    check("ice_mask", build_workload(500, seed=9), FakeCloudProvider(masked_types), [make_provisioner()])
    assert summary["ice_mask"]["masked_offerings"] > 0, "[ice_mask] availability mask never engaged"
    assert summary["ice_mask"]["mask_seconds"] > 0, "[ice_mask] mask phase not measured"
    device_children = {
        c["name"] for c in next(
            c for c in summary["ice_mask"]["span_tree"]["children"] if c["name"] == "device"
        ).get("children", ())
    }
    assert "mask" in device_children, f"[ice_mask] no device-side mask span: {sorted(device_children)}"

    # incremental engine steady state, scaled down but with the FULL
    # acceptance window (12 consecutive delta passes >= the 10-pass pin):
    # run_incremental_churn asserts the gates internally; the ISSUE pins are
    # re-asserted here so a softened helper can't silently pass the smoke
    log("smoke: incremental_churn (O(delta) steady state, auditor riding every pass)")
    _, inc_info = run_incremental_churn(80, 25, 12, phase_key="incremental_churn", audit_interval=1)
    assert inc_info["compilations"] == 0, (
        f"[incremental_churn] {inc_info['compilations']} recompile(s) in steady state"
    )
    # the residency auditor rode every measured pass: zero divergences on
    # clean churn, zero audit-induced recompiles (covered by the
    # compilations==0 pin above — audit gather shapes ride the pow2 ladder
    # traced in warmup), bounded overhead asserted inside the helper
    assert inc_info["audit_divergences"] == 0, (
        f"[incremental_churn] {inc_info['audit_divergences']} audit divergence(s) on clean churn"
    )
    assert inc_info["audit_passes"] >= 12, "[incremental_churn] auditor never engaged in the smoke"
    assert inc_info["encode_skipped_passes"] == inc_info["passes"], (
        "[incremental_churn] a steady-state pass re-encoded from scratch"
    )
    assert inc_info["full_encode"] == 0.0, "[incremental_churn] nonzero full-encode time"
    # the PR 17 gate gap: the O(delta) keys must land in the phases JSON
    # itself (the block --compare diffs across rounds), not only in this
    # smoke summary — a helper that stopped reporting them would have
    # silently dropped the regression surface
    churn_phase = PHASE_BREAKDOWN.get("incremental_churn") or {}
    for key in ("delta_apply", "full_encode", "encode_skipped_passes", "audit_seconds"):
        assert key in churn_phase, f"[incremental_churn] phases JSON missing {key!r}"
    summary["incremental_churn"] = inc_info

    log("smoke: interruption queue counters")
    from karpenter_tpu.cloudprovider.simulated.backend import CloudBackend
    from karpenter_tpu.utils.clock import FakeClock

    clk = FakeClock()
    backend = CloudBackend(clock=clk)
    queue = backend.notifications
    queue.send({"kind": "rebalance_recommendation", "instance_id": "i-smoke"})
    queue.send({"malformed": True})
    received = queue.receive_messages(max_messages=10)
    assert len(received) == 2, "queue must deliver both messages"
    assert queue.delete_message(received[0].receipt_handle), "fresh receipt handle must delete"
    for _ in range(backend.notifications.max_receive_count):
        clk.step(queue.visibility_timeout + 1)
        queue.receive_messages(max_messages=10)
    attrs = queue.attributes()
    assert attrs["dead_letter_depth"] == 1, "undeleted payload must dead-letter after the redrive threshold"
    assert attrs["depth"] == 0
    summary["interruption_queue"] = attrs

    # steady-state recompile gate (the flight recorder's reason to exist):
    # re-solving the already-warm anti_spread shapes must trigger ZERO new
    # XLA compilations — the property the incremental-solve work is gated on
    log("smoke: steady-state recompile gate")
    steady_base = flight.FLIGHT.compilations_total()
    run_once(
        build_workload(700, seed=42),
        FakeCloudProvider(instance_types(100)),
        [make_provisioner()],
        DenseSolver(min_batch=1),
    )
    steady = flight.FLIGHT.compilations_total() - steady_base
    assert steady == 0, f"steady-state re-solve recompiled {steady} XLA programs"
    summary["steady_state_recompiles"] = steady

    # program-contract cross-check (analysis/contracts.py): every recompile
    # the flight recorder attributed during this smoke run must be explained
    # by an axis the committed SOLVER_CONTRACTS.json declares varying for
    # that entry — a recompile on a declared-static axis is a contract
    # violation and fails here with both the static declaration and the
    # observed signature change printed
    log("smoke: recompile-axis contract cross-check")
    import os as _os

    from karpenter_tpu.analysis import contracts as _contracts

    doc = _contracts.load_committed(_os.path.dirname(_os.path.abspath(__file__)))
    smoke_records = [r for r in flight.FLIGHT.records() if r.id >= smoke_first_record_id]
    violations = _contracts.recompile_violations(smoke_records, doc)
    assert not violations, "recompile-axis contract violations:\n" + "\n".join(violations)
    summary["contract_recompile_violations"] = len(violations)

    # solver fault-domain steady-state gate (solver/faults.py): every smoke
    # solve ran on healthy hardware, so the taxonomy counters must not have
    # moved, no solve may have taken a degradation-ladder rung, the breaker
    # must still be CLOSED, and every smoke flight record must agree
    log("smoke: zero-fault steady-state gate")
    smoke_faults = solver_faults.faults_total() - faults_base
    smoke_degraded = solver_faults.degraded_total() - degraded_base
    assert smoke_faults == 0, f"smoke run classified {smoke_faults} solver fault(s) on healthy hardware"
    assert smoke_degraded == 0, f"smoke run took {smoke_degraded} degradation-ladder rung(s) on healthy hardware"
    assert solver_faults.BREAKER.state == solver_faults.STATE_CLOSED, (
        f"solver circuit breaker {solver_faults.BREAKER.state!r} after a healthy smoke run"
    )
    for record in smoke_records:
        assert not record.faults and not record.rungs, (
            f"flight record {record.id} carries faults/rungs on a healthy run: {record.faults} {record.rungs}"
        )
    summary["solver_faults_total"] = smoke_faults
    summary["degraded_solves_total"] = smoke_degraded
    summary["breaker_state"] = solver_faults.BREAKER.state

    # incident-capsule steady-state gate (capsule.py): the engine was armed
    # for the whole smoke; a healthy run must trip NO trigger — no breaker
    # opens, no host rungs, no steady-recompile contract violations, and
    # burn rates below threshold — so a final poll must capture nothing
    log("smoke: zero-capsule steady-state gate")
    from karpenter_tpu.capsule import CAPSULE as _capsule

    _capsule.poll()
    smoke_capsules = _capsule.captures_total()
    assert smoke_capsules == 0, (
        f"healthy smoke captured {smoke_capsules} incident capsule(s): {_capsule.fingerprints()}"
    )
    summary["capsules_captured"] = smoke_capsules

    summary["provenance"] = bench_provenance("smoke")
    summary["ok"] = True
    return summary


def main() -> None:
    from karpenter_tpu.api.objects import Taint
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
    from karpenter_tpu.solver import DenseSolver
    from karpenter_tpu.tracing import TRACER
    from tests.helpers import make_provisioner

    import gc

    from karpenter_tpu.flight import FLIGHT

    # the whole grid runs traced (a handful of spans per solve — noise-level
    # next to the solve itself) so the emitted phases JSON carries the span
    # tree of every config's final trial, headline included — and
    # flight-recorded, so per-config compile counts + peak HBM land in the
    # phases JSON next to the phase medians
    TRACER.enable()
    FLIGHT.enable()

    configs: dict = {}

    # one long-lived solver per catalog, as the provisioning controller holds
    # in practice (retains the uploaded device catalog between solves)

    # --- HEADLINE first, while the process is lean: accumulated object
    # graphs from the other configs otherwise stretch GC pauses into the
    # gated trials ---
    log("config anti_spread_10k_x_500 (headline)")
    provider = FakeCloudProvider(instance_types(HEADLINE_TYPES))
    pods = build_workload(HEADLINE_PODS)
    headline_ms, _ = run_config(
        "headline_10k", pods, provider, [make_provisioner()], DenseSolver(min_batch=1),
        trials=HEADLINE_TRIALS, phase_key="anti_spread_10k_x_500",
    )
    configs["anti_spread_10k_x_500"] = round(headline_ms, 1)
    del pods
    gc.collect()

    # --- FFD parity: 1k homogeneous pods / 50 types ---
    log("config ffd_parity_1k_x_50")
    from tests.helpers import make_pod

    provider = FakeCloudProvider(instance_types(50))
    pods = [make_pod(requests={"cpu": 1, "memory": "1Gi"}) for _ in range(1000)]
    ms, _ = run_config("ffd_1k", pods, provider, [make_provisioner()], DenseSolver(min_batch=1), phase_key="ffd_parity_1k_x_50")
    configs["ffd_parity_1k_x_50"] = round(ms, 1)
    del pods
    gc.collect()

    # --- 2. 5k pods with selectors + taints / 500 types ---
    log("config selectors_taints_5k_x_500")
    provider = FakeCloudProvider(instance_types(500))
    pods = build_selectors_taints_workload(5000)
    tainted = make_provisioner(taints=[Taint(key="dedicated", value="batch", effect="NoSchedule")])
    ms, _ = run_config("sel_taints_5k", pods, provider, [tainted], DenseSolver(min_batch=1), phase_key="selectors_taints_5k_x_500")
    configs["selectors_taints_5k_x_500"] = round(ms, 1)
    del pods
    gc.collect()

    # --- whole-cluster repack: 2k pods / 300 existing nodes ---
    log("config repack_2k_x_300")
    provider = FakeCloudProvider(instance_types(100))
    pods = build_workload(2000, seed=3)
    state_nodes = build_repack_state(300)
    ms, _ = run_config(
        "repack_2k", pods, provider, [make_provisioner()], DenseSolver(min_batch=1),
        state_nodes=state_nodes, phase_key="repack_2k_x_300",
    )
    configs["repack_2k_x_300"] = round(ms, 1)
    del pods, state_nodes
    gc.collect()

    # --- scaled whole-cluster repack: 16k pods / 2.4k existing nodes ---
    # (round-3 ask: the consolidation flagship's scaling story, measured.
    # The warm fill is the same exact single-pass protocol as the 2k
    # config — certificate fast paths, no scale switch.)
    log("config repack_16k_x_2400")
    provider = FakeCloudProvider(instance_types(100))
    pods = build_workload(16_000, seed=5)
    state_nodes = build_repack_state(2400)
    ms, _ = run_config(
        "repack_16k", pods, provider, [make_provisioner()], DenseSolver(min_batch=1),
        state_nodes=state_nodes, trials=SIDE_TRIALS, phase_key="repack_16k_x_2400",
    )
    configs["repack_16k_x_2400"] = round(ms, 1)
    del pods, state_nodes
    gc.collect()

    # --- incremental churn: 300 standing nodes x 50-pod deltas x 12 passes ---
    # (the O(delta) steady-state claim: full_encode pinned at zero,
    # delta_apply bounded by the delta, zero recompiles across the window,
    # final-pass placements byte-equal to a fresh encode — all asserted
    # inside the run, then reported in the phases JSON for --compare)
    log("config incremental_churn (300 nodes x 50-pod deltas x 12 passes)")
    ms, _inc = run_incremental_churn(300, 50, 12, phase_key="incremental_churn")
    configs["incremental_churn"] = round(ms, 1)
    gc.collect()

    # --- spot/OD mixed pricing, weighted multi-provisioner / 500 types ---
    log("config spot_od_multiprov_x_500")
    provider = FakeCloudProvider(build_spot_od_types(500))
    pods = build_workload(5000, seed=5)
    spot = make_provisioner(name="spot", weight=10)
    od = make_provisioner(name="on-demand", weight=1)
    ms, _ = run_config("spot_od_5k", pods, provider, [spot, od], DenseSolver(min_batch=1), phase_key="spot_od_multiprov_x_500")
    configs["spot_od_multiprov_x_500"] = round(ms, 1)
    del pods
    gc.collect()

    # --- reference pod-count sweep: 400 types x {1..5000} pods ---
    log("sweep 400 types x {1,50,100,500,1000,2000,5000} pods")
    sweep: dict = {}
    provider = FakeCloudProvider(instance_types(SWEEP_TYPES))
    # production routing: tiny batches take the exact host loop, larger ones
    # the dense device path, with the crossover MEASURED against this
    # machine's actual dispatch round trip — what a deployed Runtime does
    # (Options.dense_min_batch=0 auto-measurement)
    from karpenter_tpu.solver.dense import measure_dense_crossover

    sweep_solver = DenseSolver(min_batch=measure_dense_crossover())
    provisioners = [make_provisioner()]
    for count in SWEEP_PODS:
        pods = build_workload(count, seed=13)
        run_once(pods, provider, provisioners, sweep_solver)  # warmup this shape
        trials = []
        for _ in range(3):
            t, scheduled, nodes, _, stats, _ = run_once(pods, provider, provisioners, sweep_solver)
            trials.append(t)
        elapsed = float(np.median(trials))
        pods_per_sec = scheduled / elapsed if elapsed > 0 else 0.0
        sweep[str(count)] = round(pods_per_sec, 0)
        path = "dense" if stats.pods_committed else "host"
        log(
            f"  [sweep] {count} pods: {elapsed*1000:.1f} ms, {pods_per_sec:,.0f} pods/sec,"
            f" {nodes} nodes ({path})"
        )

    # --- cost regret vs exhaustive MILP ---
    log("cost regret vs ILP")
    try:
        regret = measure_cost_regret()
    except Exception as exc:  # scipy missing or solver failure: report, don't die
        log(f"  [regret] failed: {exc}")
        regret = -1.0

    baseline_ms = HEADLINE_PODS / BASELINE_PODS_PER_SEC * 1000
    print(
        json.dumps(
            {
                "metric": f"solve_wall_clock_{HEADLINE_PODS}_pods_x_{HEADLINE_TYPES}_types",
                "value": round(headline_ms, 1),
                "unit": "ms",
                "vs_baseline": round(baseline_ms / headline_ms, 1),
                "configs": configs,
                "pods_per_sec_sweep": sweep,
                "phases": PHASE_BREAKDOWN,
                "cost_regret_vs_ilp": regret,
                "provenance": bench_provenance("full"),
            }
        )
    )


if __name__ == "__main__":
    if "--compare" in sys.argv:
        sys.exit(compare_main(sys.argv[sys.argv.index("--compare") + 1 :]))
    if "--smoke" in sys.argv:
        print(json.dumps(smoke()))
        sys.exit(0)
    if "--profile" in sys.argv:
        i = sys.argv.index("--profile")
        PROFILE_DIR = (
            sys.argv[i + 1] if len(sys.argv) > i + 1 and not sys.argv[i + 1].startswith("-") else "bench_profiles"
        )
    main()
