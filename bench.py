"""Benchmark: the headline provisioning solve on real hardware.

Measures the full Scheduler.solve wall-clock — dense encode, device solve,
verify, commit — for the BASELINE.json headline config: 10k pending pods
against 500 instance types with a mixed constraint workload (generic sizes,
zonal topology spread, zonal self-affinity, hostname anti-affinity; the
constraint mix mirrors the reference benchmark's, with self-consistent
selectors as real deployments have).

Prints exactly ONE JSON line:
  {"metric": ..., "value": ..., "unit": "ms", "vs_baseline": ...}

vs_baseline is the speedup over the reference's enforced scheduler floor of
100 pods/sec (pkg/controllers/provisioning/scheduling/
scheduling_benchmark_test.go:46,173-177): 10k pods / 100 pods-per-sec =
100,000 ms baseline wall-clock.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

PODS = 10_000
TYPES = 500
BASELINE_PODS_PER_SEC = 100.0
TRIALS = 5  # median over 5: the tunnel's dispatch latency is jittery


def build_workload(count: int, seed: int = 42):
    from karpenter_tpu.api.labels import LABEL_HOSTNAME, LABEL_TOPOLOGY_ZONE
    from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm, TopologySpreadConstraint
    from tests.helpers import make_pod

    rng = np.random.default_rng(seed)
    cpus = [0.1, 0.25, 0.5, 1.0, 1.5]
    mems = ["100Mi", "256Mi", "512Mi", "1Gi", "2Gi", "4Gi"]
    values = "abcdefg"

    def size():
        return {"cpu": cpus[rng.integers(len(cpus))], "memory": mems[rng.integers(len(mems))]}

    pods = []
    seventh = count // 7
    # 1/7 zonal spread (self-selecting, 7 label cohorts)
    for i in range(seventh):
        label = {"spread": values[rng.integers(7)]}
        pods.append(
            make_pod(
                labels=label,
                requests=size(),
                topology_spread_constraints=[
                    TopologySpreadConstraint(max_skew=1, topology_key=LABEL_TOPOLOGY_ZONE, label_selector=LabelSelector(match_labels=label))
                ],
            )
        )
    # 1/7 zonal self-affinity cohorts
    for i in range(seventh):
        label = {"affinity": values[rng.integers(7)]}
        pods.append(
            make_pod(
                labels=label,
                requests=size(),
                pod_requirements=[PodAffinityTerm(topology_key=LABEL_TOPOLOGY_ZONE, label_selector=LabelSelector(match_labels=label))],
            )
        )
    # 1/7 hostname anti-affinity cohorts
    for i in range(seventh):
        label = {"anti": values[rng.integers(7)]}
        pods.append(
            make_pod(
                labels=label,
                requests=size(),
                pod_anti_requirements=[PodAffinityTerm(topology_key=LABEL_HOSTNAME, label_selector=LabelSelector(match_labels=label))],
            )
        )
    # remainder generic
    while len(pods) < count:
        pods.append(make_pod(labels={"app": values[rng.integers(7)]}, requests=size()))
    return pods


def run_once(pods, provider, provisioner, solver):
    from karpenter_tpu.scheduler import build_scheduler
    from karpenter_tpu.solver import DenseSolveStats

    solver.stats = DenseSolveStats()
    scheduler = build_scheduler([provisioner], provider, pods, dense_solver=solver)
    t0 = time.perf_counter()
    results = scheduler.solve(pods)
    elapsed = time.perf_counter() - t0
    scheduled = sum(len(n.pods) for n in results.new_nodes)
    cost = sum(n.instance_type_options[0].price() for n in results.new_nodes)
    return elapsed, scheduled, len(results.new_nodes), cost, solver.stats


def main() -> None:
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
    from tests.helpers import make_provisioner

    from karpenter_tpu.solver import DenseSolver

    provider = FakeCloudProvider(instance_types(TYPES))
    provisioner = make_provisioner()
    pods = build_workload(PODS)

    # one long-lived solver, as the provisioning controller holds in practice
    # (retains the uploaded device catalog between solves)
    solver = DenseSolver(min_batch=1)

    # warmup: compile + tunnel setup + catalog upload
    run_once(pods, provider, provisioner, solver)

    times = []
    scheduled = nodes = 0
    cost = 0.0
    for _ in range(TRIALS):
        elapsed, scheduled, nodes, cost, stats = run_once(pods, provider, provisioner, solver)
        times.append(elapsed)
        print(
            f"trial: {elapsed*1000:.1f} ms (encode {stats.encode_seconds*1000:.0f} device {stats.device_seconds*1000:.0f} "
            f"commit {stats.commit_seconds*1000:.0f}) scheduled={scheduled} nodes={nodes} cost={cost:.1f}",
            file=sys.stderr,
        )

    value_ms = float(np.median(times) * 1000)
    baseline_ms = PODS / BASELINE_PODS_PER_SEC * 1000
    if scheduled < PODS * 0.99:
        print(f"WARNING: only {scheduled}/{PODS} pods scheduled", file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": f"solve_wall_clock_{PODS}_pods_x_{TYPES}_types",
                "value": round(value_ms, 1),
                "unit": "ms",
                "vs_baseline": round(baseline_ms / value_ms, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
