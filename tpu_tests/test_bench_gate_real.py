"""REAL-TPU latency-gate smoke.

A single warm headline-class solve must clear the BASELINE <200 ms gate on
the actual chip (with margin for tunnel-RT variance), so a dense-path
latency regression is caught by the real tier itself rather than only by
the driver's end-of-round bench. Run explicitly:

    KARPENTER_TPU_REAL=1 python -m pytest tpu_tests/ -q
"""

from __future__ import annotations

import os
import numpy as np
import pytest

if os.environ.get("KARPENTER_TPU_REAL") != "1":
    pytest.skip("set KARPENTER_TPU_REAL=1 (and run on TPU) for real-chip coverage", allow_module_level=True)

os.environ["JAX_PLATFORMS"] = ""
import jax

if jax.default_backend() != "tpu":
    pytest.skip("no TPU backend", allow_module_level=True)


def test_headline_class_solve_under_gate():
    import bench
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
    from karpenter_tpu.solver import DenseSolver
    from tests.helpers import make_provisioner

    provider = FakeCloudProvider(instance_types(500))
    pods = bench.build_workload(10_000)
    solver = DenseSolver(min_batch=1)
    provisioners = [make_provisioner()]
    bench.run_once(pods, provider, provisioners, solver)  # warm compile + catalog
    trials = []
    for _ in range(5):
        elapsed, scheduled, _, _, stats, _ = bench.run_once(pods, provider, provisioners, solver)
        trials.append(elapsed)  # the solve-only time bench.run_config gates on
    median_ms = float(np.median(trials)) * 1000
    assert scheduled == 10_000
    assert stats.pods_committed > 9_000, "the dense path must carry the batch"
    # the 200 ms BASELINE gate + headroom for tunnel device-RT variance
    # (per-trial device time has ranged 78-178 ms across idle runs while the
    # idle-median stays 131-175 ms)
    assert median_ms < 250, f"headline-class solve took {median_ms:.1f} ms"
