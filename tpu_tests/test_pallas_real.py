"""REAL-TPU pallas compilation coverage (not run under the CPU conftest).

The runtime probe in DenseSolver._pallas_enabled compiles only the smallest
padded shape class; this suite dispatches the PRODUCTION shape classes
through real Mosaic compilation so a class that fails to compile is caught
by a test instead of a runtime retirement (ADVICE round 1). Run explicitly:

    KARPENTER_TPU_REAL=1 python -m pytest tpu_tests/ -q

with a TPU visible (it self-skips otherwise). Lives OUTSIDE tests/ so the
CPU-forcing conftest there does not apply; gate with KARPENTER_TPU_REAL=1.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

if os.environ.get("KARPENTER_TPU_REAL") != "1":
    pytest.skip("set KARPENTER_TPU_REAL=1 (and run on TPU) for real-Mosaic coverage", allow_module_level=True)

os.environ["JAX_PLATFORMS"] = ""
import jax

if jax.default_backend() != "tpu":
    pytest.skip("no TPU backend", allow_module_level=True)


# production shape classes: (buckets B, types T) pairs the bench configs hit
SHAPE_CLASSES = [(1, 50), (42, 500), (64, 500), (128, 1000), (8, 128)]


@pytest.mark.parametrize("B,T", SHAPE_CLASSES)
def test_pallas_compiles_and_matches_jnp(B, T):
    import jax.numpy as jnp

    from karpenter_tpu.ops.feasibility import bucket_type_cost_packed
    from karpenter_tpu.ops.pallas_kernels import bucket_type_cost_padded, pad_batch, pad_catalog

    rng = np.random.default_rng(B * 1000 + T)
    R = 8
    stats = np.abs(rng.normal(size=(2, B, R))).astype(np.float32)
    stats[0] = np.maximum(stats[0], stats[1])  # sum >= max
    caps = (np.abs(rng.normal(size=(T, R))) * 10).astype(np.float32)
    prices = np.abs(rng.normal(size=(T,))).astype(np.float32) + 0.01
    allowed = rng.random((B, T)) < 0.8

    caps_t, prices_p = pad_catalog(caps, prices)
    sum_p, max_p, allowed_p = pad_batch(stats, allowed)
    packed = np.asarray(
        bucket_type_cost_padded(jnp.asarray(sum_p), jnp.asarray(max_p), jnp.asarray(caps_t), jnp.asarray(prices_p), jnp.asarray(allowed_p))
    )[:, :B]
    reference = np.asarray(
        bucket_type_cost_packed(jnp.asarray(stats), jnp.asarray(caps), jnp.asarray(prices), jnp.asarray(allowed))
    )[:, :B]
    # feasibility must agree exactly; the argmin may differ only on f32 ties
    assert (packed[2] == reference[2]).all()
    tie_free = packed[0] == reference[0]
    assert tie_free.mean() > 0.9, f"argmin diverges on {100*(1-tie_free.mean()):.0f}% of buckets"
