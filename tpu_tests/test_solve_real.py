"""REAL-TPU end-to-end solve coverage.

The CPU suites pin dense-vs-host equivalence on the virtual mesh; this tier
runs the FULL production solve — encode, device dispatch (Pallas or jnp),
speculation, audit, commit — on a real chip and re-asserts the differential
invariants there, so a real-Mosaic/XLA:TPU divergence is caught by a test
rather than a production fallback. Run explicitly:

    KARPENTER_TPU_REAL=1 python -m pytest tpu_tests/ -q
"""

from __future__ import annotations

import os

import pytest

if os.environ.get("KARPENTER_TPU_REAL") != "1":
    pytest.skip("set KARPENTER_TPU_REAL=1 (and run on TPU) for real-chip coverage", allow_module_level=True)

os.environ["JAX_PLATFORMS"] = ""
import jax

if jax.default_backend() != "tpu":
    pytest.skip("no TPU backend", allow_module_level=True)

import numpy as np

from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
from karpenter_tpu.scheduler import build_scheduler
from karpenter_tpu.solver import DenseSolver
from tests.helpers import make_provisioner
from tests.test_differential_campaign import (
    _assert_invariants,
    _provisioners,
    _random_states,
    _random_workload,
    _rename,
    _scheduled_names,
)


@pytest.mark.parametrize("seed", range(3))
def test_real_chip_differential(seed):
    rng = np.random.default_rng(7000 + seed)
    provider = FakeCloudProvider(instance_types(int(rng.integers(30, 100))))
    pods_dense = _rename(_random_workload(rng, int(rng.integers(60, 120))), seed)
    states_dense = _random_states(rng)
    rng2 = np.random.default_rng(7000 + seed)
    provider2 = FakeCloudProvider(instance_types(int(rng2.integers(30, 100))))
    pods_host = _rename(_random_workload(rng2, int(rng2.integers(60, 120))), seed)
    states_host = _random_states(rng2)

    solver = DenseSolver(min_batch=1)
    dense_results = build_scheduler(
        _provisioners(), provider, pods_dense, state_nodes=states_dense, dense_solver=solver
    ).solve(pods_dense)
    host_results = build_scheduler(
        _provisioners(), provider2, pods_host, state_nodes=states_host, dense_solver=None
    ).solve(pods_host)

    assert solver.stats.batches == 1, "the dense path must actually run on the chip"
    assert _scheduled_names(dense_results) == _scheduled_names(host_results)
    _assert_invariants(dense_results, pods_dense)
    _assert_invariants(host_results, pods_host)


def test_real_chip_large_batch_commits_dense():
    from tests.helpers import make_pod

    provider = FakeCloudProvider(instance_types(200))
    pods = [make_pod(name=f"rb-{i:04d}", requests={"cpu": 0.25, "memory": "256Mi"}) for i in range(2000)]
    solver = DenseSolver(min_batch=1)
    results = build_scheduler([make_provisioner()], provider, pods, dense_solver=solver).solve(pods)
    placed = sum(len(n.pods) for n in results.new_nodes) + sum(len(v.pods) for v in results.existing_nodes)
    assert placed == 2000
    assert solver.stats.pods_committed >= 1900, "bulk of the batch must commit through the device path"
    assert solver.stats.device_seconds > 0
