"""REAL-TPU repack latency gates.

The whole-cluster repack configs are the consolidation flagship's scaling
story: 2k pods onto 300 warm nodes must clear the BASELINE <200 ms gate
(round-3 shipped 121.7 ms; the certificate-fast-path fill runs ~70 ms), and
the scaled 16k/2400 config must stay under 800 ms with NONZERO device work —
the vectorized warm fill (solver/warmfill.py) replaced the round-5 host
loop that spent 854-903 ms of the 909.7 ms median in per-pod Python, so the
gate is tightened 2.5 s → 800 ms and a silent fall-back to the host loop
now fails the gate outright. Run explicitly:

    KARPENTER_TPU_REAL=1 python -m pytest tpu_tests/ -q
"""

from __future__ import annotations

import os

import numpy as np
import pytest

if os.environ.get("KARPENTER_TPU_REAL") != "1":
    pytest.skip("set KARPENTER_TPU_REAL=1 (and run on TPU) for real-chip coverage", allow_module_level=True)

os.environ["JAX_PLATFORMS"] = ""
import jax

if jax.default_backend() != "tpu":
    pytest.skip("no TPU backend", allow_module_level=True)


def _median_repack_ms(pod_count: int, node_count: int, trials: int) -> float:
    import bench
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider, instance_types
    from karpenter_tpu.solver import DenseSolver
    from tests.helpers import make_provisioner

    provider = FakeCloudProvider(instance_types(100))
    provisioners = [make_provisioner()]
    pods = bench.build_workload(pod_count, seed=3)
    state_nodes = bench.build_repack_state(node_count)
    bench.run_once(pods, provider, provisioners, DenseSolver(min_batch=1), state_nodes)  # warm
    times = []
    for _ in range(trials):
        pods = bench.build_workload(pod_count, seed=3)
        state_nodes = bench.build_repack_state(node_count)
        elapsed, scheduled, _, _, stats, _ = bench.run_once(
            pods, provider, provisioners, DenseSolver(min_batch=1), state_nodes
        )
        assert scheduled == pod_count
        assert stats.pods_committed == pod_count, "repack must stay fully dense-committed"
        assert stats.fills_vectorized >= 1, "repack fell back to the host fill loop"
        assert stats.fill_device_seconds > 0, "repack fill did no device work"
        times.append(elapsed)
    return float(np.median(times)) * 1000


def test_repack_2k_under_gate():
    median_ms = _median_repack_ms(2_000, 300, trials=5)
    # the 200 ms BASELINE gate; the fill itself runs ~50 ms, leaving wide
    # headroom for tunnel-RT variance
    assert median_ms < 200, f"repack_2k_x_300 took {median_ms:.1f} ms"


def test_repack_16k_under_gate():
    median_ms = _median_repack_ms(16_000, 2_400, trials=3)
    # tightened from the self-set 2.5 s once the warm fill went device-side;
    # r5's host loop alone was ~870 ms, so 800 ms forces the vectorized path
    assert median_ms < 800, f"repack_16k_x_2400 took {median_ms:.1f} ms"
