"""Clock abstraction so controllers are testable without real sleeps."""

from __future__ import annotations

import threading
import time


class Clock:
    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)


class FakeClock(Clock):
    """Manually-stepped clock; sleep() advances time instead of blocking."""

    def __init__(self, start: float = 1000.0):
        self._now = start
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._now

    def sleep(self, seconds: float) -> None:
        self.step(max(0.0, seconds))

    def step(self, seconds: float) -> None:
        with self._lock:
            self._now += seconds
