"""Pod predicates (pkg/utils/pod/scheduling.go)."""

from __future__ import annotations

from ..api import labels as lbl
from ..api.objects import Pod


def is_provisionable(pod: Pod) -> bool:
    """Pending, not bound, marked unschedulable by kube-scheduler, and not
    actively preempting (pod/scheduling.go:24-31)."""
    return (
        not is_scheduled(pod)
        and not is_preempting(pod)
        and failed_to_schedule(pod)
        and not is_terminal(pod)
        and not is_terminating(pod)
    )


def is_scheduled(pod: Pod) -> bool:
    return bool(pod.spec.node_name)


def is_preempting(pod: Pod) -> bool:
    return bool(pod.status.nominated_node_name)


def failed_to_schedule(pod: Pod) -> bool:
    for condition in pod.status.conditions:
        if condition.type == "PodScheduled" and condition.status == "False" and condition.reason == "Unschedulable":
            return True
    return False


def is_terminal(pod: Pod) -> bool:
    return pod.status.phase in ("Succeeded", "Failed")


def is_terminating(pod: Pod) -> bool:
    return pod.metadata.deletion_timestamp is not None


def is_owned_by_daemonset(pod: Pod) -> bool:
    return _owned_by(pod, "DaemonSet")


def is_owned_by_node(pod: Pod) -> bool:
    return _owned_by(pod, "Node")


def is_owned(pod: Pod) -> bool:
    return bool(pod.metadata.owner_references)


def _owned_by(pod: Pod, kind: str) -> bool:
    return any(ref.kind == kind for ref in pod.metadata.owner_references)


def is_reschedulable(pod: Pod) -> bool:
    """Counts toward node emptiness / needs rescheduling on disruption.
    Daemonset pods, static (node-owned) mirror pods, and terminal pods do
    not (emptiness.go:105-110)."""
    return not is_owned_by_daemonset(pod) and not is_owned_by_node(pod) and not is_terminal(pod)


def is_node_empty(pods) -> bool:
    """The shared emptiness predicate used by the emptiness TTL and the
    consolidation empty-node fast path — one definition so they agree."""
    return not any(is_reschedulable(p) for p in pods)


def has_do_not_evict(pod: Pod) -> bool:
    return pod.metadata.annotations.get(lbl.DO_NOT_EVICT_ANNOTATION) == "true"


def has_do_not_disrupt(pod: Pod) -> bool:
    """The disruption veto, honoring both the karpenter.sh/do-not-disrupt
    spelling and the legacy karpenter.sh/do-not-evict one — a pod carrying
    either makes its node ineligible for voluntary disruption and surfaces
    as a blocked-eviction reason on involuntary drains."""
    return pod.metadata.annotations.get(lbl.DO_NOT_DISRUPT_ANNOTATION) == "true" or has_do_not_evict(pod)


def has_required_pod_affinity(pod: Pod) -> bool:
    return bool(
        pod.spec.affinity
        and pod.spec.affinity.pod_affinity
        and pod.spec.affinity.pod_affinity.required
    )


def has_pod_affinity(pod: Pod) -> bool:
    a = pod.spec.affinity
    return bool(a and a.pod_affinity and (a.pod_affinity.required or a.pod_affinity.preferred))


def has_pod_anti_affinity(pod: Pod) -> bool:
    a = pod.spec.affinity
    return bool(a and a.pod_anti_affinity and (a.pod_anti_affinity.required or a.pod_anti_affinity.preferred))


def has_required_pod_anti_affinity(pod: Pod) -> bool:
    a = pod.spec.affinity
    return bool(a and a.pod_anti_affinity and a.pod_anti_affinity.required)
