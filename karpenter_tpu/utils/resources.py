"""Resource-list arithmetic.

Mirrors the semantics of the reference's pkg/utils/resources/resources.go
(Merge/Subtract/Fits/MaxResources/Cmp and pod request aggregation) over plain
``dict[str, float]`` resource lists in canonical units.
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping

ResourceList = Dict[str, float]

CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
EPHEMERAL_STORAGE = "ephemeral-storage"
NVIDIA_GPU = "nvidia.com/gpu"
AMD_GPU = "amd.com/gpu"
AWS_NEURON = "aws.amazon.com/neuron"
AWS_POD_ENI = "vpc.amazonaws.com/pod-eni"


def merge(*resource_lists: Mapping[str, float]) -> ResourceList:
    """Element-wise sum over any number of resource lists."""
    out: ResourceList = {}
    for rl in resource_lists:
        if not rl:
            continue
        for name, value in rl.items():
            out[name] = out.get(name, 0.0) + value
    return out


def subtract(lhs: Mapping[str, float], rhs: Mapping[str, float]) -> ResourceList:
    """lhs - rhs over the union of keys (missing keys treated as zero)."""
    out: ResourceList = dict(lhs or {})
    for name, value in (rhs or {}).items():
        out[name] = out.get(name, 0.0) - value
    return out


def max_resources(*resource_lists: Mapping[str, float]) -> ResourceList:
    """Element-wise max over resource lists (used for pessimistic limit math)."""
    out: ResourceList = {}
    for rl in resource_lists:
        for name, value in (rl or {}).items():
            if name not in out or value > out[name]:
                out[name] = value
    return out


def tolerance(total):
    """Comparison tolerance for resource arithmetic: absolute for cpu-scale
    values plus relative for byte-scale values; effectively zero when the
    capacity itself is zero (a nonzero request for an absent resource never
    fits). Elementwise-safe: accepts floats or numpy arrays. Shared by
    fits(), the dense packer (pack_counts.py), and the commit audit
    (solver/dense.py) so their verdicts can never disagree."""
    if isinstance(total, (int, float)):
        return 1e-6 + 1e-9 * abs(total) if total > 0 else 1e-12
    import numpy as np

    return np.where(np.asarray(total) > 0, 1e-6 + 1e-9 * np.abs(total), 1e-12)


def fits(candidate: Mapping[str, float], total: Mapping[str, float]) -> bool:
    """True if candidate <= total for every resource named in candidate.

    Matches reference semantics (pkg/utils/resources/resources.go:Fits): a
    resource requested but absent from `total` only fits if the request is 0.
    """
    for name, value in (candidate or {}).items():
        limit = (total or {}).get(name, 0.0)
        if value > limit + tolerance(limit):
            return False
    return True


def cmp(lhs: float, rhs: float) -> int:
    if lhs < rhs:
        return -1
    if lhs > rhs:
        return 1
    return 0


def any_exceeds(lhs: Mapping[str, float], rhs: Mapping[str, float]) -> bool:
    """True if lhs[k] > rhs[k] for any key present in both (limit checks)."""
    for name, value in (lhs or {}).items():
        if name in (rhs or {}) and value > rhs[name] + 1e-9:
            return True
    return False


def is_zero(rl: Mapping[str, float]) -> bool:
    return all(abs(v) < 1e-12 for v in (rl or {}).values())


def clamp_negative_to_zero(rl: Mapping[str, float]) -> ResourceList:
    return {k: (0.0 if v < 0 else v) for k, v in (rl or {}).items()}


def requests_for_pods(*pods) -> ResourceList:
    """Aggregate effective requests over pods.

    Per-pod effective request = max(sum of container requests, max over init
    container requests) + 1 'pods' resource, following the reference's
    resources.RequestsForPods / Ceiling semantics.
    """
    out: ResourceList = {}
    for pod in pods:
        out = merge(out, pod_requests(pod))
    return out


def _effective_requests(container) -> ResourceList:
    """Per-resource, a missing request defaults to the limit — the apiserver's
    admission defaulting, which the scheduler must mirror for objects that
    never crossed a real apiserver (provisioning suite :326)."""
    return {**container.resources.limits, **container.resources.requests}


def pod_requests(pod) -> ResourceList:
    """Effective scheduling requests of a pod (containers + init peak +
    overhead + the implicit 1 pod). Memoized per (pod, resource_version) —
    the scheduler and the dense fill call this many times per pod per solve
    — so the returned mapping is SHARED and must be treated as immutable
    (every consumer merges/subtracts into fresh dicts)."""
    version = pod.metadata.resource_version
    cached = getattr(pod, "_podreq_cache", None)
    if cached is not None and cached[0] == version:
        return cached[1]
    running: ResourceList = {}
    for container in pod.spec.containers:
        running = merge(running, _effective_requests(container))
    init_peak: ResourceList = {}
    for container in pod.spec.init_containers:
        init_peak = max_resources(init_peak, _effective_requests(container))
    out = max_resources(running, init_peak)
    out[PODS] = out.get(PODS, 0.0) + 1.0
    if pod.spec.overhead:
        out = merge(out, pod.spec.overhead)
    try:
        pod._podreq_cache = (version, out)
    except AttributeError:
        pass  # slotted/frozen pod objects skip the memo
    return out


def pod_limits(pod) -> ResourceList:
    running: ResourceList = {}
    for container in pod.spec.containers:
        running = merge(running, container.resources.limits)
    init_peak: ResourceList = {}
    for container in pod.spec.init_containers:
        init_peak = max_resources(init_peak, container.resources.limits)
    return max_resources(running, init_peak)


def to_string(rl: Mapping[str, float]) -> str:
    from .quantity import format_quantity

    return ", ".join(f"{k}: {format_quantity(v)}" for k, v in sorted((rl or {}).items()))
