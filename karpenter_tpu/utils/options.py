"""Runtime options: CLI flags with environment fallback.

Equivalent of pkg/utils/options/options.go — ports, client budgets,
profiling, provider tuning — validated at boot.
"""

from __future__ import annotations

import argparse
import os
from dataclasses import dataclass, field
from typing import List, Optional

# The host-loop/device crossover, canonical for every routing site: the
# Options default below, DenseSolver's own default, and the provisioner's
# remote-sidecar gate all read this one constant. The measurement behind the
# number lives on DenseSolver.__init__ (solver/dense.py).
DENSE_MIN_BATCH_DEFAULT = 320


@dataclass
class Options:
    metrics_port: int = 8080
    health_probe_port: int = 8081
    kube_client_qps: float = 200.0
    kube_client_burst: int = 300
    enable_profiling: bool = False
    # decision tracing (tracing.py): spans per controller pass + per-pod
    # decision records, served on /debug/traces and /debug/decisions over
    # the metrics port. Off by default — disabled tracing is a true no-op
    enable_tracing: bool = False
    trace_ring_size: int = 256  # completed traces retained (bounded ring)
    # SLO accounting (slo.py): pod-pending-latency / time-to-ready summaries,
    # cluster $/hr + cost-drift gauges, churn counters, served on /debug/slo
    # over the metrics port. Off by default — disabled SLO accounting is a
    # true no-op on the watch hot path (same bar as tracing)
    enable_slo: bool = False
    # lock-order witness (analysis/witness.py): every lock created through
    # WITNESS after enabling records acquisition order, contention, and hold
    # times; cycles (potential deadlocks) surface on /debug/locks and the
    # karpenter_lockwitness_* families. Off by default — disabled means the
    # shared classes get PLAIN threading locks, zero wrapper overhead
    enable_lock_witness: bool = False
    # solver flight recorder (flight.py): per-solve shape/phase records, XLA
    # compile-churn attribution, HBM gauges, served on /debug/solver over
    # the metrics port. Off by default — disabled telemetry is a true no-op
    # on the solve path (same bar as tracing)
    enable_solver_telemetry: bool = False
    flight_ring_size: int = 128  # per-solve records retained (bounded ring)
    # lifecycle journal (journal.py): pod/node transition stream + the
    # pending-latency waterfall, served on /debug/journal and /debug/waterfall
    # over the metrics port. Off by default — a disabled journal is a true
    # no-op: no ring, no watch hooks, one attribute read per event site
    enable_journal: bool = False
    journal_ring_size: int = 8192  # lifecycle events retained (bounded ring)
    # append-only JSONL spool for the journal (the on-disk trace format the
    # replay harness consumes); empty = in-memory only. The spool is
    # size-bounded: live + one rotation never exceed journal_spool_max_bytes
    journal_spool: str = ""
    journal_spool_max_bytes: int = 16 * 2**20
    leader_elect: bool = True
    # lease-election timing (kube/leaderelection.py): how long a lost holder
    # blocks successors, and how often a candidate tries to acquire/renew —
    # the controller-runtime 15s/2s defaults; chaos harnesses shrink both so
    # a stolen lease flaps inside the scenario window
    lease_duration: float = 15.0
    lease_renew_period: float = 2.0
    # informer-coherence witness (kube/coherence.py): period of the
    # deep-compare of every registered informer cache against the
    # authoritative store. <= 0 (the default) disables the loop — the cache
    # is still registered, so harnesses can run final_check() at teardown
    coherence_interval: float = 0.0
    # invariant monitor (invariants.py): period of the leak-witness sample
    # loop (thread census stragglers, watch-subscription growth, bounded
    # ring/spool budgets, folded lock/coherence/double-launch witnesses),
    # served at /debug/invariants. <= 0 (the default) disables the loop and
    # leaves the process-wide monitor disarmed for harnesses to drive
    invariants_interval: float = 0.0
    batch_max_duration: float = 10.0
    batch_idle_duration: float = 1.0
    dense_solver_enabled: bool = True
    # below this batch size the exact host loop is faster and cheaper than a
    # device dispatch. 0 (the default) = measure the dispatch round trip at
    # startup and derive the crossover for THIS deployment's device link
    # (solver/dense.py measure_dense_crossover); a positive value pins it
    dense_min_batch: int = 0
    cluster_name: str = ""
    log_level: str = "info"
    # period of the leader-only pricing refresh loop (pricing.go:76-393 runs
    # OD and spot updaters on election; one TTL here covers both books)
    pricing_refresh_period: float = 300.0
    solver_service_address: str = ""  # host:port of the gRPC solver sidecar (empty = in-process)
    solver_service_timeout: float = 30.0
    # name of the cloud-side interruption queue (the aws.interruptionQueueName
    # settings analog). Non-empty enables the interruption controller's
    # leader-gated poll loop against the provider's notification source
    interruption_queue: str = ""
    # long-poll wait per receive; the loop re-polls immediately after a
    # non-empty batch, so this only paces the idle case
    interruption_poll_interval: float = 2.0
    # the unified disruption orchestrator (controllers/disruption): owns all
    # voluntary disruption — emptiness, expiration, drift, consolidation —
    # behind per-provisioner budgets and a validated command queue. Disabling
    # falls back to the legacy per-controller paths (consolidation loop +
    # node-controller TTL deletes) with no budgets or drift detection
    disruption_enabled: bool = True
    # URL of a Kubernetes apiserver (http://host:port). Empty = the in-memory
    # simulation backend; set (or KUBERNETES_APISERVER_URL) = the real-protocol
    # HTTP client (kube/client.py) with the QPS/burst budget above
    apiserver_url: str = ""
    # period of the GC reconciliation sweep (controllers/gc): cloud instances
    # vs node objects, both directions; the first sweep runs at startup so a
    # restarted controller reconciles crash leftovers before provisioning
    # resumes. <= 0 disables the loop (the startup sweep still runs)
    gc_interval: float = 15.0
    # how long a launched instance may exist unregistered before the sweep
    # treats it as an orphan (the legitimate launch->register window)
    gc_registration_grace: float = 30.0
    # capacity-failure escalation (controllers/provisioning): how long a pod
    # whose every launch/re-solve attempt hit insufficient capacity sits out
    # of the batch before re-probing — below the unavailable-offering TTL so
    # recovery is noticed, above the batch window so a total crunch cannot
    # hot-loop the solver into the wall
    ice_backoff_seconds: float = 10.0
    # solver fault domain (solver/faults.py): pre-solve HBM-pressure budget —
    # when the flight recorder's HBM-peak gauge exceeds this many bytes the
    # dense dispatch chunks pre-emptively instead of building the full
    # [B, T] surface (0 = no budget; requires --enable-solver-telemetry for
    # the gauge to be live)
    solver_hbm_budget_bytes: int = 0
    # the solver circuit breaker: this many CONSECUTIVE classified device
    # faults short-circuit the device attempt entirely (the exact host loop
    # owns every batch), and after the backoff the next real solve runs a
    # half-open recovery probe that re-admits the fast path on success
    solver_breaker_threshold: int = 3
    solver_breaker_backoff: float = 30.0
    # incremental solve engine (solver/incremental.py): keep the warm-view
    # encoding + device headroom surface resident across provision passes
    # and apply the cluster state journal's delta instead of re-encoding —
    # O(changes) steady state with byte-equal fallback to the fresh-encode
    # path on catalog changes, journal gaps, and fault invalidations
    solver_incremental: bool = False
    # residency auditor (solver/audit.py): every Nth incremental provision
    # pass re-encodes a seeded sample of view rows (plus a periodic full
    # shadow under a byte budget) from cluster truth and compares host
    # mirror, device-buffer rows, and the availability cube against the
    # engine's resident state; divergence triggers a residency-divergence
    # capsule and auto-heals by forcing the fresh full re-encode path.
    # 0 (the default) disables the auditor entirely
    residency_audit_interval: int = 0
    # incident capsules (capsule.py): triggered cross-subsystem evidence
    # capture — breaker opens, host-rung falls, conservation violations,
    # steady-state recompiles, lock cycles, invariant breaches, and the
    # multi-window SLO burn-rate monitor each freeze every telemetry ring
    # into one CAPSULE_<trigger>_<seq>.json bundle at /debug/capsules;
    # capsule_spool lands them on disk under a byte budget (the journal's
    # rotation discipline), capsule_debounce_seconds rate-limits per
    # trigger kind
    enable_capsules: bool = False
    capsule_spool: str = ""
    capsule_spool_max_bytes: int = 32 * 2**20
    capsule_debounce_seconds: float = 30.0

    def validate(self) -> List[str]:
        errs = []
        if not (0 < self.metrics_port < 65536):
            errs.append(f"invalid metrics port {self.metrics_port}")
        if not (0 < self.health_probe_port < 65536):
            errs.append(f"invalid health probe port {self.health_probe_port}")
        if self.kube_client_qps <= 0:
            errs.append("kube client qps must be positive")
        if self.batch_idle_duration <= 0 or self.batch_max_duration < self.batch_idle_duration:
            errs.append("batch durations must satisfy 0 < idle <= max")
        if self.pricing_refresh_period <= 0:
            errs.append("pricing refresh period must be positive")
        if self.lease_duration <= 0 or self.lease_renew_period <= 0:
            errs.append("lease duration and renew period must be positive")
        if self.lease_renew_period >= self.lease_duration:
            errs.append("lease renew period must be shorter than the lease duration")
        if self.interruption_poll_interval <= 0:
            errs.append("interruption poll interval must be positive")
        if self.gc_registration_grace < 0:
            errs.append("gc registration grace must be non-negative")
        if self.ice_backoff_seconds <= 0:
            errs.append("ice backoff must be positive")
        if self.solver_hbm_budget_bytes < 0:
            errs.append("solver hbm budget must be non-negative")
        if self.solver_breaker_threshold < 1:
            errs.append("solver breaker threshold must be >= 1")
        if self.solver_breaker_backoff <= 0:
            errs.append("solver breaker backoff must be positive")
        if self.residency_audit_interval < 0:
            errs.append("residency audit interval must be non-negative")
        if self.trace_ring_size <= 0:
            errs.append("trace ring size must be positive")
        if self.flight_ring_size <= 0:
            errs.append("flight ring size must be positive")
        if self.journal_ring_size <= 0:
            errs.append("journal ring size must be positive")
        if self.journal_spool_max_bytes <= 0:
            errs.append("journal spool max bytes must be positive")
        if self.capsule_spool_max_bytes <= 0:
            errs.append("capsule spool max bytes must be positive")
        if self.capsule_debounce_seconds < 0:
            errs.append("capsule debounce must be non-negative")
        from ..logsetup import is_valid_level

        if not is_valid_level(self.log_level):
            errs.append(f"invalid log level {self.log_level!r}")
        return errs


def _env(name: str, default):
    value = os.environ.get(name)
    if value is None:
        return default
    if isinstance(default, bool):
        return value.lower() in ("1", "true", "yes")
    try:
        return type(default)(value)
    except ValueError:
        raise SystemExit(f"karpenter-tpu: error: invalid value for ${name}: {value!r}")


def parse(argv: Optional[List[str]] = None) -> Options:
    defaults = Options()
    parser = argparse.ArgumentParser(prog="karpenter-tpu")
    parser.add_argument("--metrics-port", type=int, default=_env("METRICS_PORT", defaults.metrics_port))
    parser.add_argument("--health-probe-port", type=int, default=_env("HEALTH_PROBE_PORT", defaults.health_probe_port))
    parser.add_argument("--kube-client-qps", type=float, default=_env("KUBE_CLIENT_QPS", defaults.kube_client_qps))
    parser.add_argument("--kube-client-burst", type=int, default=_env("KUBE_CLIENT_BURST", defaults.kube_client_burst))
    parser.add_argument("--enable-profiling", action="store_true", default=_env("ENABLE_PROFILING", defaults.enable_profiling))
    parser.add_argument("--enable-tracing", action="store_true", default=_env("ENABLE_TRACING", defaults.enable_tracing))
    parser.add_argument("--enable-slo", action="store_true", default=_env("ENABLE_SLO", defaults.enable_slo))
    parser.add_argument("--enable-lock-witness", action="store_true", default=_env("ENABLE_LOCK_WITNESS", defaults.enable_lock_witness))
    parser.add_argument("--enable-solver-telemetry", action="store_true", default=_env("ENABLE_SOLVER_TELEMETRY", defaults.enable_solver_telemetry))
    parser.add_argument("--enable-journal", action="store_true", default=_env("ENABLE_JOURNAL", defaults.enable_journal))
    parser.add_argument("--trace-ring-size", type=int, default=_env("TRACE_RING_SIZE", defaults.trace_ring_size))
    parser.add_argument("--flight-ring-size", type=int, default=_env("FLIGHT_RING_SIZE", defaults.flight_ring_size))
    parser.add_argument("--journal-ring-size", type=int, default=_env("JOURNAL_RING_SIZE", defaults.journal_ring_size))
    parser.add_argument("--journal-spool", default=_env("JOURNAL_SPOOL", defaults.journal_spool))
    parser.add_argument("--journal-spool-max-bytes", type=int, default=_env("JOURNAL_SPOOL_MAX_BYTES", defaults.journal_spool_max_bytes))
    parser.add_argument("--enable-capsules", action="store_true", default=_env("ENABLE_CAPSULES", defaults.enable_capsules))
    parser.add_argument("--capsule-spool", default=_env("CAPSULE_SPOOL", defaults.capsule_spool))
    parser.add_argument("--capsule-spool-max-bytes", type=int, default=_env("CAPSULE_SPOOL_MAX_BYTES", defaults.capsule_spool_max_bytes))
    parser.add_argument("--capsule-debounce-seconds", type=float, default=_env("CAPSULE_DEBOUNCE_SECONDS", defaults.capsule_debounce_seconds))
    parser.add_argument("--no-leader-elect", dest="leader_elect", action="store_false", default=_env("LEADER_ELECT", defaults.leader_elect))
    parser.add_argument("--lease-duration", type=float, default=_env("LEASE_DURATION", defaults.lease_duration))
    parser.add_argument("--lease-renew-period", type=float, default=_env("LEASE_RENEW_PERIOD", defaults.lease_renew_period))
    parser.add_argument("--coherence-interval", type=float, default=_env("COHERENCE_INTERVAL", defaults.coherence_interval))
    parser.add_argument("--invariants-interval", type=float, default=_env("INVARIANTS_INTERVAL", defaults.invariants_interval))
    parser.add_argument("--batch-max-duration", type=float, default=_env("BATCH_MAX_DURATION", defaults.batch_max_duration))
    parser.add_argument("--batch-idle-duration", type=float, default=_env("BATCH_IDLE_DURATION", defaults.batch_idle_duration))
    parser.add_argument("--disable-dense-solver", dest="dense_solver_enabled", action="store_false", default=_env("DENSE_SOLVER_ENABLED", defaults.dense_solver_enabled))
    parser.add_argument("--dense-min-batch", type=int, default=_env("DENSE_MIN_BATCH", defaults.dense_min_batch))
    parser.add_argument("--cluster-name", default=_env("CLUSTER_NAME", defaults.cluster_name))
    parser.add_argument("--log-level", default=_env("LOG_LEVEL", defaults.log_level))
    parser.add_argument("--solver-service-address", default=_env("SOLVER_SERVICE_ADDRESS", defaults.solver_service_address))
    parser.add_argument("--solver-service-timeout", type=float, default=_env("SOLVER_SERVICE_TIMEOUT", defaults.solver_service_timeout))
    parser.add_argument("--pricing-refresh-period", type=float, default=_env("PRICING_REFRESH_PERIOD", defaults.pricing_refresh_period))
    parser.add_argument("--interruption-queue", dest="interruption_queue", default=_env("INTERRUPTION_QUEUE", defaults.interruption_queue))
    parser.add_argument("--interruption-poll-interval", type=float, default=_env("INTERRUPTION_POLL_INTERVAL", defaults.interruption_poll_interval))
    parser.add_argument("--ice-backoff-seconds", type=float, default=_env("ICE_BACKOFF_SECONDS", defaults.ice_backoff_seconds))
    parser.add_argument("--solver-hbm-budget", dest="solver_hbm_budget_bytes", type=int, default=_env("SOLVER_HBM_BUDGET", defaults.solver_hbm_budget_bytes))
    parser.add_argument("--solver-breaker-threshold", type=int, default=_env("SOLVER_BREAKER_THRESHOLD", defaults.solver_breaker_threshold))
    parser.add_argument("--solver-breaker-backoff", type=float, default=_env("SOLVER_BREAKER_BACKOFF", defaults.solver_breaker_backoff))
    parser.add_argument("--solver-incremental", dest="solver_incremental", action="store_true", default=_env("SOLVER_INCREMENTAL", defaults.solver_incremental))
    parser.add_argument("--residency-audit-interval", type=int, default=_env("RESIDENCY_AUDIT_INTERVAL", defaults.residency_audit_interval))
    parser.add_argument("--disable-disruption", dest="disruption_enabled", action="store_false", default=_env("DISRUPTION_ENABLED", defaults.disruption_enabled))
    parser.add_argument("--apiserver-url", default=_env("KUBERNETES_APISERVER_URL", defaults.apiserver_url))
    parser.add_argument("--gc-interval", type=float, default=_env("GC_INTERVAL", defaults.gc_interval))
    parser.add_argument("--gc-registration-grace", type=float, default=_env("GC_REGISTRATION_GRACE", defaults.gc_registration_grace))
    namespace = parser.parse_args(argv)
    options = Options(**vars(namespace))
    errs = options.validate()
    if errs:
        parser.error("; ".join(errs))
    return options
