"""Kubernetes-style resource quantity parsing.

The reference manipulates `resource.Quantity` values everywhere (requests,
capacities, limits). We normalize quantities to floats in canonical units at
the edge of the system — CPU in cores, memory/storage in bytes — because the
dense TPU solver operates on float32/bfloat16 matrices anyway and exact
arithmetic only needs to survive until encoding.
"""

from __future__ import annotations

import math
import re

# Binary and decimal suffixes per the Kubernetes quantity grammar.
_BINARY = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}
_DECIMAL = {"n": 1e-9, "u": 1e-6, "m": 1e-3, "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18}

_QUANTITY_RE = re.compile(r"^([+-]?[0-9.]+(?:[eE][+-]?[0-9]+)?)(Ki|Mi|Gi|Ti|Pi|Ei|n|u|m|k|M|G|T|P|E)?$")


def parse_quantity(value) -> float:
    """Parse a quantity ('100m', '4Gi', '2', 1.5) into a float in base units."""
    if isinstance(value, (int, float)):
        return float(value)
    value = value.strip()
    match = _QUANTITY_RE.match(value)
    if match is None:
        raise ValueError(f"cannot parse quantity {value!r}")
    number, suffix = match.groups()
    scale = 1.0
    if suffix:
        scale = _BINARY.get(suffix) or _DECIMAL[suffix]
    return float(number) * scale


def format_quantity(value: float) -> str:
    """Render a float quantity compactly (inverse of parse for common cases)."""
    if value == 0:
        return "0"
    if value == math.floor(value):
        intval = int(value)
        for suffix, scale in (("Gi", 2**30), ("Mi", 2**20), ("Ki", 2**10)):
            if intval % scale == 0 and intval >= scale:
                return f"{intval // scale}{suffix}"
        return str(intval)
    # sub-unit values render in millis when exact (the common CPU case)
    millis = value * 1000
    if abs(millis - round(millis)) < 1e-9:
        return f"{int(round(millis))}m"
    return repr(value)
