"""Deterministic seed fan-out: ONE master seed per scenario, every consumer derived.

The three fault seams (solver `FaultPlan`, kube `KubeFaultPlan`, and the
chaos orchestrator's schedule) plus the workload stand-in's jitter each take
a seed. Keeping them as independent knobs invites silent drift: a scenario
that pins `fault_seed` but forgets `kube_fault_seed` is only half
reproducible, and nobody can tell from the artifact. `split_seed` is the
splitmix64-style fan-out that makes one `Scenario.seed` the single
reproducibility handle: every derived seed is a pure function of
(master, label), recorded in provenance, so two runs of any scenario are
replayable from one number.

splitmix64 is the standard seed-expansion mixer (Steele et al., "Fast
splittable pseudorandom number generators"): one round of add-and-mix whose
outputs are statistically independent across labels even for adjacent
masters (0, 1, 2, ...) — exactly the property a campaign sweeping master
seeds needs.
"""

from __future__ import annotations

import hashlib

_MASK = (1 << 64) - 1
_GOLDEN = 0x9E3779B97F4A7C15


def _mix(x: int) -> int:
    """One splitmix64 output round."""
    z = (x + _GOLDEN) & _MASK
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK
    return (z ^ (z >> 31)) & _MASK


def split_seed(master: int, label: str) -> int:
    """Derive the seed for one named consumer from the master seed.

    Pure, stable across processes and platforms (the label hashes through
    sha256, never Python's randomized `hash()`), and clamped to a positive
    63-bit int so every RNG constructor accepts it."""
    label_key = int.from_bytes(hashlib.sha256(label.encode("utf-8")).digest()[:8], "big")
    return _mix((int(master) & _MASK) ^ label_key) & 0x7FFFFFFFFFFFFFFF
