"""Minimal 5-field cron expressions for disruption-budget windows.

The budget `schedule` field uses the standard crontab shape
(`minute hour day-of-month month day-of-week`, UTC) with the field syntax
subset the reference's disruption budgets accept: `*`, single values,
ranges (`a-b`), steps (`*/n`, `a-b/n`), and comma lists. A budget window is
"active" when any cron fire time within the trailing `duration` matches.
"""

from __future__ import annotations

from datetime import datetime, timedelta, timezone
from typing import List, Optional, Set, Tuple

# (min, max) per field, in crontab order
_FIELD_RANGES: Tuple[Tuple[int, int], ...] = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 6))
_FIELD_NAMES = ("minute", "hour", "day-of-month", "month", "day-of-week")

# how far back an active-window probe will scan; a longer duration is legal
# but only the trailing week of fire times is considered
MAX_WINDOW_SCAN_MINUTES = 7 * 24 * 60


def _parse_field(spec: str, lo: int, hi: int) -> Optional[Set[int]]:
    """One cron field -> the set of matching values, or None when malformed."""
    out: Set[int] = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, _, step_s = part.partition("/")
            if not step_s.isdigit() or int(step_s) < 1:
                return None
            step = int(step_s)
        if part == "*":
            lo_p, hi_p = lo, hi
        elif "-" in part:
            a, _, b = part.partition("-")
            if not (a.isdigit() and b.isdigit()):
                return None
            lo_p, hi_p = int(a), int(b)
        elif part.isdigit():
            lo_p = hi_p = int(part)
        else:
            return None
        if lo_p < lo or hi_p > hi or lo_p > hi_p:
            return None
        out.update(range(lo_p, hi_p + 1, step))
    return out


def cron_errors(expr: str) -> List[str]:
    """Human-readable syntax violations for a cron expression (empty == valid)."""
    fields = expr.split()
    if len(fields) != 5:
        return [f"schedule {expr!r} must have 5 fields (minute hour day-of-month month day-of-week), got {len(fields)}"]
    errs: List[str] = []
    for spec, (lo, hi), name in zip(fields, _FIELD_RANGES, _FIELD_NAMES):
        if _parse_field(spec, lo, hi) is None:
            errs.append(f"schedule {expr!r}: invalid {name} field {spec!r} (allowed: *, n, a-b, */s, lists; range {lo}-{hi})")
    return errs


def matches(expr: str, when: datetime) -> bool:
    """True when `when` (minute precision) is a fire time of `expr`.

    Standard (vixie) cron semantics: when BOTH day-of-month and day-of-week
    are restricted (neither is `*`), the date matches if EITHER does —
    `0 0 15 * 1` fires on the 15th OR on Mondays, not only on Mondays that
    fall on the 15th."""
    fields = expr.split()
    # crontab day-of-week: 0=Sunday..6=Saturday; datetime.weekday(): 0=Monday
    dow = (when.weekday() + 1) % 7
    values = (when.minute, when.hour, when.day, when.month, dow)
    parsed = [_parse_field(spec, lo, hi) for spec, (lo, hi) in zip(fields, _FIELD_RANGES)]
    if any(p is None for p in parsed):
        return False
    minute_ok, hour_ok, month_ok = values[0] in parsed[0], values[1] in parsed[1], values[3] in parsed[3]
    dom_restricted, dow_restricted = fields[2] != "*", fields[4] != "*"
    dom_ok, dow_ok = values[2] in parsed[2], values[4] in parsed[4]
    if dom_restricted and dow_restricted:
        day_ok = dom_ok or dow_ok
    else:
        day_ok = dom_ok and dow_ok
    return minute_ok and hour_ok and month_ok and day_ok


def window_active(expr: str, duration_seconds: float, now_epoch: float) -> bool:
    """True when `now` falls inside [fire, fire + duration] for some fire
    time of `expr`. Scans trailing minutes (bounded at one week)."""
    minutes = min(int(duration_seconds // 60) + 1, MAX_WINDOW_SCAN_MINUTES)
    now = datetime.fromtimestamp(now_epoch, tz=timezone.utc).replace(second=0, microsecond=0)
    for back in range(minutes):
        probe = now - timedelta(minutes=back)
        if matches(expr, probe):
            # fire at `probe`; active until probe + duration
            fired = probe.timestamp()
            if now_epoch < fired + duration_seconds:
                return True
    return False
