from .requirement import Requirement
from .requirements import Requirements
from .taints import Taints
from .nodetemplate import NodeTemplate
from .hostports import HostPortUsage
from .volumelimits import VolumeLimits, VolumeCount

__all__ = [
    "Requirement",
    "Requirements",
    "Taints",
    "NodeTemplate",
    "HostPortUsage",
    "VolumeLimits",
    "VolumeCount",
]
