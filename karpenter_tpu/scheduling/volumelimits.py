"""VolumeLimits: per-node CSI-driver mounted-volume counting.

Mirrors pkg/scheduling/volumelimits.go:33-236 — resolves each pod PVC through
its StorageClass/PV to a CSI driver name, counts unique mounted volumes per
driver, and compares against the node's CSINode allocatable limits.
"""

from __future__ import annotations

from typing import Dict, Optional, Set

from ..api.objects import CSINode, Pod


class VolumeCount(dict):
    """driver name -> number of unique volumes."""

    def exceeds(self, limits: "VolumeCount") -> bool:
        for driver, count in self.items():
            if driver in limits and count > limits[driver]:
                return True
        return False


class VolumeLimits:
    """Tracks which volumes are mounted per CSI driver on one node.

    The kube client is any object exposing get_persistent_volume_claim /
    get_persistent_volume / get_storage_class lookups (see kube.Client).
    """

    def __init__(self, kube_client=None):
        self._kube = kube_client
        self._volumes: Dict[str, Set[str]] = {}  # driver -> volume ids
        self._pod_volumes: Dict[str, Dict[str, Set[str]]] = {}  # pod uid -> driver -> ids

    def _resolve_driver(self, namespace: str, claim_name: str) -> Optional[str]:
        if self._kube is None:
            return None
        pvc = self._kube.get_persistent_volume_claim(namespace, claim_name)
        if pvc is None:
            return None
        if pvc.volume_name:
            pv = self._kube.get_persistent_volume(pvc.volume_name)
            if pv is not None and pv.csi_driver:
                return pv.csi_driver
        if pvc.storage_class_name:
            sc = self._kube.get_storage_class(pvc.storage_class_name)
            if sc is not None and sc.provisioner:
                return sc.provisioner
        return None

    def _volumes_for_pod(self, pod: Pod) -> Dict[str, Set[str]]:
        result: Dict[str, Set[str]] = {}
        for volume in pod.spec.volumes:
            if volume.persistent_volume_claim is None:
                continue
            claim = volume.persistent_volume_claim.claim_name
            driver = self._resolve_driver(pod.namespace, claim)
            if driver is None:
                continue
            result.setdefault(driver, set()).add(f"{pod.namespace}/{claim}")
        return result

    def validate(self, pod: Pod) -> VolumeCount:
        """Counts volumes mounted if the pod schedules (existing + new)."""
        result = VolumeCount()
        new = self._volumes_for_pod(pod)
        for driver, existing in self._volumes.items():
            result[driver] = len(existing | new.get(driver, set()))
        for driver, ids in new.items():
            if driver not in result:
                result[driver] = len(ids)
        return result

    def add(self, pod: Pod) -> None:
        per_pod = self._volumes_for_pod(pod)
        self._pod_volumes[pod.uid] = per_pod
        for driver, ids in per_pod.items():
            self._volumes.setdefault(driver, set()).update(ids)

    def delete_pod(self, uid: str) -> None:
        per_pod = self._pod_volumes.pop(uid, None)
        if not per_pod:
            return
        # rebuild driver sets from remaining pods (volumes may be shared)
        self._volumes = {}
        for volumes in self._pod_volumes.values():
            for driver, ids in volumes.items():
                self._volumes.setdefault(driver, set()).update(ids)

    def copy(self) -> "VolumeLimits":
        out = VolumeLimits(self._kube)
        out._volumes = {d: set(v) for d, v in self._volumes.items()}
        out._pod_volumes = {u: {d: set(v) for d, v in pv.items()} for u, pv in self._pod_volumes.items()}
        return out

    def to_wire(self) -> tuple:
        """Detached plain-data form for the solver-service wire (service/)."""
        return (
            {d: sorted(v) for d, v in self._volumes.items()},
            {u: {d: sorted(v) for d, v in pv.items()} for u, pv in self._pod_volumes.items()},
        )

    @classmethod
    def from_wire(cls, data: tuple, kube_client=None) -> "VolumeLimits":
        out = cls(kube_client)
        volumes, pod_volumes = data
        out._volumes = {d: set(v) for d, v in volumes.items()}
        out._pod_volumes = {u: {d: set(v) for d, v in pv.items()} for u, pv in pod_volumes.items()}
        return out


def limits_from_csi_node(csi_node: Optional[CSINode]) -> VolumeCount:
    limits = VolumeCount()
    if csi_node is not None:
        for driver in csi_node.drivers:
            if driver.allocatable_count is not None:
                limits[driver.name] = driver.allocatable_count
    return limits
