"""Requirement: an efficient node-selector requirement as a value set.

The key trick carried over from the reference (pkg/scheduling/requirement.go:35-41)
is the *complement* representation: `NotIn{a,b}` and `Exists` are stored as the
complement of a finite set, so every operator becomes closed under
intersection without enumerating an open world of values. Gt/Lt keep integer
bounds alongside. This same representation is what the dense IR encodes as
(mask, complement-flag) pairs over the interned label vocabulary
(ir/encode.py), so host algebra and device masks stay in exact correspondence.

Deviation from the reference: `any_value()` is deterministic (the reference
picks randomly, requirement.go:106-122); determinism is load-bearing for
differential testing of the TPU solver against the host oracle.
"""

from __future__ import annotations

from typing import FrozenSet, Iterable, Optional, Set

from ..api.labels import normalize_label
from ..api.objects import OP_DOES_NOT_EXIST, OP_EXISTS, OP_GT, OP_IN, OP_LT, OP_NOT_IN

# Stand-in for "infinity" when reporting the size of complement sets.
INF = 1 << 62


def _within(value: str, greater_than: Optional[int], less_than: Optional[int]) -> bool:
    if greater_than is None and less_than is None:
        return True
    try:
        as_int = int(value)
    except ValueError:
        return False  # non-integer values are invalid once bounds exist
    if greater_than is not None and as_int <= greater_than:
        return False
    if less_than is not None and as_int >= less_than:
        return False
    return True


class Requirement:
    __slots__ = ("key", "complement", "values", "greater_than", "less_than")

    def __init__(self, key: str, operator: str, *values: str):
        self.key = normalize_label(key)
        self.values: Set[str] = set()
        self.complement = operator not in (OP_IN, OP_DOES_NOT_EXIST)
        self.greater_than: Optional[int] = None
        self.less_than: Optional[int] = None
        if operator in (OP_IN, OP_NOT_IN):
            self.values.update(str(v) for v in values)
        elif operator == OP_GT:
            self.greater_than = int(values[0])
        elif operator == OP_LT:
            self.less_than = int(values[0])
        elif operator not in (OP_EXISTS, OP_DOES_NOT_EXIST):
            raise ValueError(f"invalid operator {operator!r}")

    @classmethod
    def _raw(cls, key: str, complement: bool, values: Set[str], greater_than=None, less_than=None) -> "Requirement":
        r = cls(key, OP_EXISTS)
        r.complement = complement
        r.values = values
        r.greater_than = greater_than
        r.less_than = less_than
        return r

    # -- set algebra --------------------------------------------------------

    def intersection(self, other: "Requirement") -> "Requirement":
        """Closed-form intersection over all operator combinations.

        Mirrors requirement.go:71-104: union/difference/intersection of the
        finite parts depending on complement flags, bound tightening, and
        collapse to DoesNotExist on empty integer ranges.
        """
        complement = self.complement and other.complement
        greater_than = _max_opt(self.greater_than, other.greater_than)
        less_than = _min_opt(self.less_than, other.less_than)
        if greater_than is not None and less_than is not None and greater_than >= less_than:
            return Requirement(self.key, OP_DOES_NOT_EXIST)

        if self.complement and other.complement:
            values = self.values | other.values
        elif self.complement and not other.complement:
            values = other.values - self.values
        elif not self.complement and other.complement:
            values = self.values - other.values
        else:
            values = self.values & other.values
        values = {v for v in values if _within(v, greater_than, less_than)}
        if not complement:
            greater_than, less_than = None, None
        return Requirement._raw(self.key, complement, values, greater_than, less_than)

    def has(self, value: str) -> bool:
        if self.complement:
            return value not in self.values and _within(value, self.greater_than, self.less_than)
        return value in self.values and _within(value, self.greater_than, self.less_than)

    def insert(self, *values: str) -> None:
        self.values.update(values)

    def operator(self) -> str:
        if self.complement:
            return OP_NOT_IN if self.values else OP_EXISTS
        return OP_IN if self.values else OP_DOES_NOT_EXIST

    def __len__(self) -> int:
        if self.complement:
            return INF - len(self.values)
        return len(self.values)

    def allowed_values(self) -> FrozenSet[str]:
        """The finite allowed set; only meaningful when not complement."""
        return frozenset(self.values)

    def any_value(self) -> str:
        """A deterministic representative allowed value ('' if none expressible)."""
        op = self.operator()
        if op == OP_IN:
            return min(self.values)
        if op in (OP_NOT_IN, OP_EXISTS):
            low = 0 if self.greater_than is None else self.greater_than + 1
            high = (1 << 31) if self.less_than is None else self.less_than
            for candidate in range(low, high):
                if str(candidate) not in self.values:
                    return str(candidate)
        return ""

    def __repr__(self) -> str:
        op = self.operator()
        if op in (OP_EXISTS, OP_DOES_NOT_EXIST):
            s = f"{self.key} {op}"
        else:
            shown = sorted(self.values)
            if len(shown) > 5:
                shown = shown[:5] + [f"and {len(self.values) - 5} others"]
            s = f"{self.key} {op} {shown}"
        if self.greater_than is not None:
            s += f" >{self.greater_than}"
        if self.less_than is not None:
            s += f" <{self.less_than}"
        return s


def _min_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)


def _max_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)
