"""Taint toleration checking (pkg/scheduling/taints.go:28-40).

Note: like the reference, PreferNoSchedule taints also require a toleration
here — the preference-relaxation pass adds a blanket PreferNoSchedule
toleration when a provisioner carries such a taint (preferences.go:133-147),
which is what restores the kube soft-preference semantics end to end.
"""

from __future__ import annotations

from typing import Optional

from ..api.objects import Pod


class Taints(list):
    """A list of taints with a pod toleration check."""

    def tolerates(self, pod: Pod) -> Optional[str]:
        """Returns an error string if the pod doesn't tolerate every taint."""
        for taint in self:
            if not any(toleration.tolerates(taint) for toleration in pod.spec.tolerations):
                return f"did not tolerate {taint.key}={taint.value}:{taint.effect}"
        return None
