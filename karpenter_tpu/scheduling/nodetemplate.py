"""NodeTemplate: a provisioner rendered into a launchable-node template.

Mirrors pkg/scheduling/nodetemplate.go:29-67 — the provisioner's labels,
taints, startup taints, requirements, and kubelet config rolled into the
object the scheduler opens new virtual nodes from, plus `to_node()` which
emits the cluster Node object carrying the termination finalizer.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

from ..api import labels as lbl
from ..api.objects import Node, NodeSpec, NodeStatus, ObjectMeta, OP_IN
from ..api.provisioner import KubeletConfiguration, Provisioner
from .requirement import Requirement
from .requirements import Requirements
from .taints import Taints


@dataclass
class NodeTemplate:
    provisioner_name: str
    provider: Optional[dict] = None
    provider_ref: Optional[str] = None
    labels: Dict[str, str] = field(default_factory=dict)
    taints: Taints = field(default_factory=Taints)
    startup_taints: Taints = field(default_factory=Taints)
    requirements: Requirements = field(default_factory=Requirements)
    kubelet_configuration: Optional[KubeletConfiguration] = None
    # the base provisioner template's digest, pinned at from_provisioner()
    # time: the scheduler tightens per-node COPIES of the requirements, so
    # hashing the launch-time template would make every node look drifted
    stamped_hash: Optional[str] = None

    @classmethod
    def from_provisioner(cls, provisioner: Provisioner) -> "NodeTemplate":
        requirements = Requirements()
        requirements.add(*Requirements.from_node_selector_requirements(provisioner.spec.requirements).values())
        requirements.add(*Requirements.from_labels(provisioner.spec.labels).values())
        requirements.add(Requirement(lbl.PROVISIONER_NAME_LABEL, OP_IN, provisioner.name))
        template = cls(
            provisioner_name=provisioner.name,
            provider=provisioner.spec.provider,
            provider_ref=provisioner.spec.provider_ref,
            labels=dict(provisioner.spec.labels),
            taints=Taints(provisioner.spec.taints),
            startup_taints=Taints(provisioner.spec.startup_taints),
            requirements=requirements,
            kubelet_configuration=provisioner.spec.kubelet_configuration,
        )
        template.stamped_hash = template.spec_hash()
        return template

    def spec_hash(self) -> str:
        """Deterministic digest of everything that shapes a launched node:
        labels, taints, requirements, kubelet config, and provider config.
        Providers stamp it onto nodes at launch (the
        karpenter.sh/provisioner-hash annotation); the disruption
        controller's drift method compares it against the CURRENT
        provisioner's template — a mismatch flags the node drifted.

        Returns the digest pinned by from_provisioner() when present (the
        base template, surviving per-node requirement tightening)."""
        if self.stamped_hash is not None:
            return self.stamped_hash
        import hashlib
        import json

        def _taints(taints) -> list:
            return sorted((t.key, t.value, t.effect) for t in taints)

        requirements = sorted(
            (r.key, r.operator(), sorted(str(v) for v in r.values), r.greater_than, r.less_than)
            for r in self.requirements
        )
        kubelet = None
        if self.kubelet_configuration is not None:
            kc = self.kubelet_configuration
            kubelet = [
                list(kc.cluster_dns), kc.max_pods, kc.pods_per_core,
                sorted(kc.system_reserved.items()), sorted(kc.kube_reserved.items()),
            ]
        payload = {
            "labels": sorted(self.labels.items()),
            "taints": _taints(self.taints),
            "startup_taints": _taints(self.startup_taints),
            "requirements": requirements,
            "kubelet": kubelet,
            "provider": self.provider,
            "provider_ref": self.provider_ref,
        }
        return hashlib.sha256(json.dumps(payload, sort_keys=True, default=str).encode()).hexdigest()[:16]

    def copy(self) -> "NodeTemplate":
        return NodeTemplate(
            provisioner_name=self.provisioner_name,
            provider=self.provider,
            provider_ref=self.provider_ref,
            labels=dict(self.labels),
            taints=Taints(self.taints),
            startup_taints=Taints(self.startup_taints),
            requirements=self.requirements.copy(),
            kubelet_configuration=self.kubelet_configuration,
            stamped_hash=self.stamped_hash,
        )

    def to_node(self) -> Node:
        """Emit the Node object for launch (nodetemplate.go:57-67)."""
        labels = dict(self.labels)
        labels.update(self.requirements.labels())
        labels[lbl.PROVISIONER_NAME_LABEL] = self.provisioner_name
        return Node(
            metadata=ObjectMeta(
                name="",
                namespace="",
                labels=labels,
                annotations={lbl.PROVISIONER_HASH_ANNOTATION: self.spec_hash()},
                finalizers=[lbl.TERMINATION_FINALIZER],
            ),
            spec=NodeSpec(taints=list(self.taints) + list(self.startup_taints)),
            status=NodeStatus(),
        )
