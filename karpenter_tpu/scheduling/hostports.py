"""HostPortUsage: per-node host-port uniqueness tracking.

Mirrors pkg/scheduling/hostportusage.go:31-149 — (ip, port, protocol) entries
with wildcard-IP awareness: 0.0.0.0 conflicts with every IP on the same
(port, protocol) and vice versa.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..api.objects import Pod

WILDCARD_IP = "0.0.0.0"


@dataclass(frozen=True)
class HostPortEntry:
    ip: str
    port: int
    protocol: str

    def matches(self, other: "HostPortEntry") -> bool:
        if self.port != other.port or self.protocol != other.protocol:
            return False
        if self.ip == WILDCARD_IP or other.ip == WILDCARD_IP:
            return True
        return self.ip == other.ip


def _entries_for_pod(pod: Pod) -> List[HostPortEntry]:
    entries = []
    for container in list(pod.spec.containers) + list(pod.spec.init_containers):
        for port in container.ports:
            if port.host_port:
                ip = port.host_ip or WILDCARD_IP
                entries.append(HostPortEntry(ip=ip, port=port.host_port, protocol=port.protocol or "TCP"))
    return entries


class HostPortUsage:
    def __init__(self):
        self._reserved: Dict[str, List[HostPortEntry]] = {}  # pod uid -> entries

    def validate(self, pod: Pod) -> Optional[str]:
        """Returns an error string if the pod's host ports conflict."""
        for entry in _entries_for_pod(pod):
            for owner_uid, entries in self._reserved.items():
                if owner_uid == pod.uid:
                    continue
                for existing in entries:
                    if entry.matches(existing):
                        return f"host port {entry.ip}:{entry.port}/{entry.protocol} is already in use"
        return None

    def add(self, pod: Pod) -> None:
        entries = _entries_for_pod(pod)
        if entries:
            self._reserved[pod.uid] = entries

    def delete_pod(self, uid: str) -> None:
        self._reserved.pop(uid, None)

    def copy(self) -> "HostPortUsage":
        out = HostPortUsage()
        out._reserved = {uid: list(entries) for uid, entries in self._reserved.items()}
        return out

    def to_wire(self) -> Dict[str, List[tuple]]:
        """Detached plain-data form for the solver-service wire (service/)."""
        return {uid: [(e.ip, e.port, e.protocol) for e in entries] for uid, entries in self._reserved.items()}

    @classmethod
    def from_wire(cls, data: Dict[str, List[tuple]]) -> "HostPortUsage":
        out = cls()
        out._reserved = {uid: [HostPortEntry(*entry) for entry in entries] for uid, entries in data.items()}
        return out
