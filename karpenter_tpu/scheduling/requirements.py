"""Requirements: a keyed set of Requirement values with intersection-on-add.

Mirrors pkg/scheduling/requirements.go:32-164 — including the asymmetric
`compatible` rule (custom labels must be *known* by the node side; well-known
labels are open-world) and the NotIn/DoesNotExist escape hatch in
`intersects`.
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, List, Optional

from ..api import labels as lbl
from ..api.objects import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_IN,
    OP_NOT_IN,
    NodeSelectorRequirement,
    Pod,
)
from .requirement import Requirement


class Requirements:
    __slots__ = ("_by_key",)

    def __init__(self, *requirements: Requirement):
        self._by_key: Dict[str, Requirement] = {}
        self.add(*requirements)

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_node_selector_requirements(cls, reqs: Iterable[NodeSelectorRequirement]) -> "Requirements":
        return cls(*[Requirement(r.key, r.operator, *r.values) for r in reqs])

    @classmethod
    def from_labels(cls, labels: Dict[str, str]) -> "Requirements":
        return cls(*[Requirement(k, OP_IN, v) for k, v in labels.items()])

    @classmethod
    def from_pod(cls, pod: Pod) -> "Requirements":
        """Pod scheduling requirements: nodeSelector, the heaviest preferred
        node-affinity term, and the *first* required node-affinity term (OR
        semantics are handled by preference relaxation, see
        core/scheduler/preferences.py). Mirrors requirements.go:61-78.

        Memoized per (pod, resource_version): the host loop calls this for
        every candidate node it scans, and the result is treated as
        IMMUTABLE by every consumer (compatible/intersects/add never mutate
        their operands; relaxation copies drop the memo — preferences.py).
        """
        version = pod.metadata.resource_version
        cached = getattr(pod, "_reqs_cache", None)
        if cached is not None and cached[0] == version:
            return cached[1]
        requirements = cls.from_labels(pod.spec.node_selector)
        affinity = pod.spec.affinity
        if affinity is not None and affinity.node_affinity is not None:
            preferred = affinity.node_affinity.preferred
            if preferred:
                heaviest = max(preferred, key=lambda term: term.weight)
                requirements.add(*cls.from_node_selector_requirements(heaviest.preference.match_expressions).values())
            required = affinity.node_affinity.required
            if required:
                requirements.add(*cls.from_node_selector_requirements(required[0].match_expressions).values())
        try:
            pod._reqs_cache = (version, requirements)
        except AttributeError:
            pass  # slotted/frozen pod objects skip the memo
        return requirements

    # -- collection protocol ------------------------------------------------

    def same_as(self, other: "Requirements") -> bool:
        """Content equality over every key's full constraint state — the
        requirements-epoch guard of ExistingNodeView's cohort certificates
        (existingnode.py) relies on this detecting ANY semantic change."""
        if len(self._by_key) != len(other._by_key):
            return False
        for key, r in self._by_key.items():
            o = other._by_key.get(key)
            if (
                o is None
                or r.complement != o.complement
                or r.values != o.values
                or r.greater_than != o.greater_than
                or r.less_than != o.less_than
            ):
                return False
        return True

    def add(self, *requirements: Requirement) -> None:
        for requirement in requirements:
            existing = self._by_key.get(requirement.key)
            if existing is not None:
                requirement = requirement.intersection(existing)
            self._by_key[requirement.key] = requirement

    def keys(self) -> set:
        return set(self._by_key)

    def values(self) -> List[Requirement]:
        return list(self._by_key.values())

    def has(self, key: str) -> bool:
        return key in self._by_key

    def get(self, key: str) -> Requirement:
        if key not in self._by_key:
            return Requirement(key, OP_EXISTS)  # undefined keys allow anything
        return self._by_key[key]

    def copy(self) -> "Requirements":
        return Requirements(*self.values())

    def delete(self, key: str) -> None:
        self._by_key.pop(key, None)

    def __iter__(self) -> Iterator[Requirement]:
        return iter(self._by_key.values())

    def __len__(self) -> int:
        return len(self._by_key)

    # -- compatibility rules -------------------------------------------------

    def compatible(self, incoming: "Requirements") -> Optional[str]:
        """Can a node constrained by `self` satisfy `incoming`? Returns an
        error string or None. Custom (non-well-known) incoming keys must be
        defined on the node side unless the incoming operator is negative."""
        for key in incoming.keys() - lbl.WELL_KNOWN_LABELS:
            operator = incoming.get(key).operator()
            if self.has(key) or operator in (OP_NOT_IN, OP_DOES_NOT_EXIST):
                continue
            return f"key {key} does not have known values"
        return self.intersects(incoming)

    def intersects(self, incoming: "Requirements") -> Optional[str]:
        """Symmetric overlap check on shared keys; NotIn/DoesNotExist pairs
        are allowed to have empty intersections (requirements.go:130-147)."""
        for key in self.keys() & incoming.keys():
            existing = self.get(key)
            inc = incoming.get(key)
            if len(existing.intersection(inc)) == 0:
                if inc.operator() in (OP_NOT_IN, OP_DOES_NOT_EXIST) and existing.operator() in (OP_NOT_IN, OP_DOES_NOT_EXIST):
                    continue
                return f"key {key}, {inc!r} not in {existing!r}"
        return None

    def labels(self) -> Dict[str, str]:
        """Materialize concrete node labels from the requirements.

        Well-known / restricted node labels are excluded — those are injected
        by the cloud provider on the launched node (requirements.go:149-159).
        """
        out: Dict[str, str] = {}
        for key, requirement in self._by_key.items():
            if not lbl.is_restricted_node_label(key):
                value = requirement.any_value()
                if value:
                    out[key] = value
        return out

    def __repr__(self) -> str:
        shown = [r for r in self.values() if r.key not in lbl.RESTRICTED_LABELS]
        return ", ".join(repr(r) for r in shown)
