"""Journal JSONL schema: the structural gate replay inputs must pass.

Mirrors scenarios/schema.py for the journal's on-disk trace format: one
validator shared by the replay path (scenarios/replay.py refuses a journal
that fails it) and the tests — so a hand-edited, truncated, or corrupted
JSONL fails with a line-numbered error instead of silently skewing the
replayed arrival structure.

Each line is one JSON object with the JournalEvent shape (journal.py):

    {"seq": 0, "t": 12.5, "kind": "pod", "entity": "load-1", "event": "created"}

Required: seq (int, strictly increasing), t (finite number, non-decreasing —
every timestamp flows through one clock seam, so a step backwards means a
corrupted or spliced file), kind (pod|node|solver|kube|chaos), entity
(non-empty string), event (in the kind's transition vocabulary). `attrs` is
an optional object.
"""

from __future__ import annotations

import json
import math
from typing import Iterable, List, Tuple

from .journal import (
    CHAOS_EVENTS,
    KIND_CHAOS,
    KIND_KUBE,
    KIND_NODE,
    KIND_POD,
    KIND_SOLVER,
    KUBE_EVENTS,
    NODE_EVENTS,
    POD_EVENTS,
    SOLVER_EVENTS,
)

_VOCAB = {
    KIND_POD: POD_EVENTS,
    KIND_NODE: NODE_EVENTS,
    KIND_SOLVER: SOLVER_EVENTS,
    KIND_KUBE: KUBE_EVENTS,
    KIND_CHAOS: CHAOS_EVENTS,
}


class JournalSchemaError(ValueError):
    """A journal file failed validation; str() lists line-numbered errors."""

    def __init__(self, path: str, errors: List[str]):
        self.path = path
        self.errors = errors
        preview = "\n".join(errors[:10])
        more = f"\n... and {len(errors) - 10} more" if len(errors) > 10 else ""
        super().__init__(f"{path}: {len(errors)} journal schema error(s):\n{preview}{more}")


def event_errors(obj, where: str = "event") -> List[str]:
    """Structural problems with one decoded journal event; empty = valid."""
    errs: List[str] = []
    if not isinstance(obj, dict):
        return [f"{where}: must be a JSON object, got {type(obj).__name__}"]
    seq = obj.get("seq")
    if not isinstance(seq, int) or isinstance(seq, bool):
        errs.append(f"{where}: seq must be an integer")
    t = obj.get("t")
    if not isinstance(t, (int, float)) or isinstance(t, bool) or not math.isfinite(t):
        errs.append(f"{where}: t must be a finite number")
    kind = obj.get("kind")
    if kind not in _VOCAB:
        errs.append(f"{where}: kind must be one of {sorted(_VOCAB)}, got {kind!r}")
    entity = obj.get("entity")
    if not isinstance(entity, str) or not entity:
        errs.append(f"{where}: entity must be a non-empty string")
    event = obj.get("event")
    if kind in _VOCAB and event not in _VOCAB[kind]:
        errs.append(f"{where}: unknown {kind} transition {event!r}; one of {list(_VOCAB[kind])}")
    attrs = obj.get("attrs")
    if attrs is not None and not isinstance(attrs, dict):
        errs.append(f"{where}: attrs must be an object when present")
    return errs


def journal_lines_errors(lines: Iterable[str], where: str = "journal") -> Tuple[List[dict], List[str]]:
    """Validate an iterable of JSONL lines. Returns (decoded events, errors);
    errors carry 1-based line numbers. Sequence/time monotonicity is checked
    across lines — the property the compressed campaign clock guarantees and
    replay's inter-arrival reconstruction depends on."""
    events: List[dict] = []
    errs: List[str] = []
    last_seq = None
    last_t = None
    for lineno, line in enumerate(lines, start=1):
        stripped = line.strip()
        if not stripped:
            errs.append(f"{where} line {lineno}: blank line (a truncated write?)")
            continue
        try:
            obj = json.loads(stripped)
        except json.JSONDecodeError as err:
            errs.append(f"{where} line {lineno}: invalid JSON ({err.msg} at column {err.colno})")
            continue
        line_errs = event_errors(obj, where=f"{where} line {lineno}")
        errs.extend(line_errs)
        if line_errs:
            continue
        if last_seq is not None and obj["seq"] <= last_seq:
            errs.append(f"{where} line {lineno}: seq {obj['seq']} does not increase (prev {last_seq})")
        if last_t is not None and obj["t"] < last_t:
            errs.append(
                f"{where} line {lineno}: t {obj['t']} goes backwards (prev {last_t}): "
                "journal timestamps are clock-seam monotonic"
            )
        last_seq, last_t = obj["seq"], obj["t"]
        events.append(obj)
    return events, errs


def load_journal(path: str) -> List[dict]:
    """Read and validate a journal JSONL file; raises JournalSchemaError
    (line-numbered) on the first malformation instead of returning a trace
    that would silently skew a replay."""
    with open(path, encoding="utf-8") as f:
        events, errs = journal_lines_errors(f, where=path)
    if errs:
        raise JournalSchemaError(path, errs)
    return events
