"""Decision tracing: spans + per-pod audit records across the pipeline.

The Dapper-lineage answer to "why did this node launch / why is this solve
slow": every controller pass opens a span, spans within one trigger share a
trace ID, and the dense solver's phase timings (encode/fill/device/commit)
attach as child spans — so a provisioning round is one span tree from
pending-pod batch through the device solve to node launch and pod bind,
inspectable live over the metrics port and exportable as a Chrome
trace-event / Perfetto timeline.

Design constraints, in order:

- **disabled == free**: tracing defaults OFF and a disabled tracer is a true
  no-op — no ring allocation, no span objects, no per-pod record objects.
  The guard is one attribute read per span() call.
- **zero deps, bounded memory**: completed traces live in a thread-safe ring
  (default 256 traces); overflow evicts oldest and counts into
  `karpenter_tracing_traces_dropped`. In-flight buffers are bounded too, so
  a span leak cannot grow without bound.
- **ambient seam**: `span()` reads the per-thread current span, so
  controllers never thread trace IDs manually. Work fanned out to worker
  threads (the launch pool) passes an explicit `parent=` context captured
  with `current_context()`.
- **synthetic child spans**: the dense solver measures its phases with
  perf_counter boundaries, not nested blocks; `record_span()` turns those
  measured intervals into completed child spans after the fact. All span
  starts derive from perf_counter plus one process-constant epoch offset, so
  exported timestamps are monotonic (Chrome/Perfetto require it).

Alongside spans, `DecisionLog` keeps per-pod **decision records** from the
scheduler's admission path: outcome (placed-existing | placed-new | failed),
the chosen node and instance type, and per-constraint rejection counts — the
audit trail behind `/debug/decisions?pod=...`.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from collections import OrderedDict, deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from .analysis.guards import guarded_by
from .metrics import REGISTRY

# perf_counter -> epoch seconds, fixed once per process: every span start is
# perf_counter + this, so ordering across spans is exactly perf_counter
# ordering (time.time can step backwards under NTP; trace viewers cannot)
_EPOCH_OFFSET = time.time() - time.perf_counter()

# registered at import so gen_docs sees the families without a live tracer
TRACES_DROPPED = REGISTRY.counter(
    "karpenter_tracing_traces_dropped",
    "Completed or in-flight traces evicted from the bounded trace ring",
)
TRACES_STORED = REGISTRY.gauge(
    "karpenter_tracing_traces_stored", "Completed traces currently held in the trace ring"
)
DECISIONS_DROPPED = REGISTRY.counter(
    "karpenter_tracing_decisions_dropped",
    "Per-pod decision records evicted from the bounded decision ring",
)

DEFAULT_RING = 256
DEFAULT_DECISION_RING = 4096
MAX_SPANS_PER_TRACE = 4096
MAX_INFLIGHT_TRACES = 64

OUTCOME_PLACED_EXISTING = "placed-existing"
OUTCOME_PLACED_NEW = "placed-new"
OUTCOME_FAILED = "failed"


def _now() -> float:
    return time.perf_counter() + _EPOCH_OFFSET


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    name: str
    start: float  # epoch seconds (perf_counter-derived, monotonic-consistent)
    duration: float = 0.0  # seconds; 0 while open
    attributes: Dict[str, object] = field(default_factory=dict)
    thread: str = ""

    def set(self, **attrs) -> None:
        self.attributes.update(attrs)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "duration_ms": round(self.duration * 1000, 3),
            "attributes": self.attributes,
            "thread": self.thread,
        }


class _NullSpan:
    """The disabled-path span: set() swallows attributes, nothing allocates."""

    __slots__ = ()

    def set(self, **attrs) -> None:
        pass


_NULL_SPAN = _NullSpan()


@guarded_by("_lock", "_ring", "_inflight", "_last_trace_id")
class Tracer:
    def __init__(self, capacity: int = DEFAULT_RING):
        self._lock = threading.Lock()
        self._local = threading.local()
        self.capacity = capacity
        self.enabled = False
        # allocated on enable(), never before — "disabled is a true no-op"
        self._ring: Optional[OrderedDict] = None  # trace_id -> List[Span] (completed)
        self._inflight: Optional[OrderedDict] = None  # trace_id -> List[Span] (open roots)
        self._last_trace_id: Optional[str] = None

    # -- lifecycle -----------------------------------------------------------

    def enable(self, capacity: Optional[int] = None) -> None:
        with self._lock:
            if capacity is not None:
                self.capacity = capacity
            if self._ring is None:
                self._ring = OrderedDict()
                self._inflight = OrderedDict()
            self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        """Drop every stored trace (tests); keeps the enabled flag."""
        with self._lock:
            if self._ring is not None:
                self._ring.clear()
                self._inflight.clear()
            self._last_trace_id = None
            TRACES_STORED.set(0)

    # -- ambient current-span seam ---------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current_context(self) -> Optional[Tuple[str, str]]:
        """(trace_id, span_id) of the ambient span, for handing to worker
        threads that should parent under it; None outside any span."""
        stack = getattr(self._local, "stack", None)
        if not stack:
            return None
        top = stack[-1]
        return (top.trace_id, top.span_id)

    def current_trace_id(self) -> Optional[str]:
        ctx = self.current_context()
        return ctx[0] if ctx else None

    def last_trace_id(self) -> Optional[str]:
        """Trace ID of the most recently COMPLETED trace."""
        with self._lock:
            return self._last_trace_id

    # -- span creation ---------------------------------------------------------

    @contextmanager
    def span(
        self, name: str, parent: Optional[Tuple[str, str]] = None, drop_childless: bool = False, **attrs
    ) -> Iterator[object]:
        """Open a span; nests under the ambient span of this thread (or the
        explicit `parent` context). A span that exits with no parent is a
        trace root: its completion moves the whole trace into the ring.

        `drop_childless` (roots only): discard the completed trace when it
        holds nothing but the root span — the idle-reconcile case, where
        storing every empty pass would churn provision/interruption traces
        out of the bounded ring."""
        if not self.enabled:
            yield _NULL_SPAN
            return
        stack = self._stack()
        if parent is None and stack:
            parent = (stack[-1].trace_id, stack[-1].span_id)
        trace_id = parent[0] if parent else _new_id()
        sp = Span(
            trace_id=trace_id,
            span_id=_new_id(),
            parent_id=parent[1] if parent else None,
            name=name,
            start=_now(),
            attributes=dict(attrs) if attrs else {},
            thread=threading.current_thread().name,
        )
        start_mono = time.perf_counter()
        is_root = parent is None
        if is_root:
            self._open_trace(trace_id)
        stack.append(sp)
        try:
            yield sp
        finally:
            sp.duration = time.perf_counter() - start_mono
            if stack and stack[-1] is sp:
                stack.pop()
            self._store(sp, complete_trace=is_root, drop_childless=is_root and drop_childless)

    def open_span(self, name: str, parent: Optional[Tuple[str, str]] = None, **attrs) -> Optional[Span]:
        """Open a span WITHOUT entering the ambient stack — for operations
        whose lifetime crosses reconcile passes (a disruption command:
        validate this pass, drain-handoff several passes later). The trace
        stays in-flight until close_span() on the root; children attach by
        passing ctx_of(span) as an explicit parent. Unlike span(), the
        AMBIENT span is deliberately NOT inherited — a cross-pass operation
        must outlive whatever reconcile pass happened to start it, so with
        no explicit parent it roots its own trace. Returns None (and every
        related call no-ops) when tracing is disabled."""
        if not self.enabled:
            return None
        sp = Span(
            trace_id=parent[0] if parent else _new_id(),
            span_id=_new_id(),
            parent_id=parent[1] if parent else None,
            name=name,
            start=_now(),
            attributes=dict(attrs) if attrs else {},
            thread=threading.current_thread().name,
        )
        sp._start_mono = time.perf_counter()  # type: ignore[attr-defined]
        if sp.parent_id is None:
            self._open_trace(sp.trace_id)
        return sp

    def close_span(self, sp: Optional[Span], **attrs) -> None:
        """Complete a span from open_span(); a root completion moves the
        whole trace into the ring."""
        if sp is None or not self.enabled:
            return
        if attrs:
            sp.attributes.update(attrs)
        sp.duration = time.perf_counter() - getattr(sp, "_start_mono", time.perf_counter())
        self._store(sp, complete_trace=sp.parent_id is None)

    @staticmethod
    def ctx_of(sp: Optional[Span]) -> Optional[Tuple[str, str]]:
        return (sp.trace_id, sp.span_id) if sp is not None else None

    def record_span(
        self,
        name: str,
        start: float,
        duration: float,
        attrs: Optional[dict] = None,
        parent: Optional[Tuple[str, str]] = None,
    ) -> Optional[Tuple[str, str]]:
        """Add an already-measured interval as a completed child span. `start`
        is a perf_counter value (the instrumentation sites all measure with
        perf_counter); it is mapped onto the same epoch offset every live
        span uses. Returns the new span's (trace_id, span_id) context so
        callers can hang further synthetic children under it."""
        if not self.enabled:
            return None
        if parent is None:
            parent = self.current_context()
        if parent is None:
            return None
        sp = Span(
            trace_id=parent[0],
            span_id=_new_id(),
            parent_id=parent[1],
            name=name,
            start=start + _EPOCH_OFFSET,
            duration=duration,
            attributes=dict(attrs) if attrs else {},
            thread=threading.current_thread().name,
        )
        self._store(sp, complete_trace=False)
        return (sp.trace_id, sp.span_id)

    # -- storage ---------------------------------------------------------------

    def _open_trace(self, trace_id: str) -> None:
        with self._lock:
            if self._inflight is None:
                return
            while len(self._inflight) >= MAX_INFLIGHT_TRACES:
                self._inflight.popitem(last=False)
                TRACES_DROPPED.inc()
            self._inflight[trace_id] = []

    def _store(self, sp: Span, complete_trace: bool, drop_childless: bool = False) -> None:
        with self._lock:
            if self._inflight is None:
                return
            buf = self._inflight.get(sp.trace_id)
            if buf is None:
                # late span of an evicted/completed trace, or a record_span
                # against a parent that never opened here: drop silently
                if not complete_trace:
                    return
                buf = []
            if len(buf) < MAX_SPANS_PER_TRACE:
                buf.append(sp)
            if complete_trace:
                self._inflight.pop(sp.trace_id, None)
                if drop_childless and len(buf) <= 1:
                    return  # an empty pass is not evidence; don't churn the ring
                while len(self._ring) >= self.capacity:
                    self._ring.popitem(last=False)
                    TRACES_DROPPED.inc()
                self._ring[sp.trace_id] = buf
                self._last_trace_id = sp.trace_id
                TRACES_STORED.set(float(len(self._ring)))

    # -- read surface ----------------------------------------------------------

    def traces(self) -> List[dict]:
        """Recent completed traces, newest first: the /debug/traces index."""
        with self._lock:
            items = list(self._ring.items()) if self._ring else []
        out = []
        for trace_id, spans in reversed(items):
            root = next((s for s in spans if s.parent_id is None), None)
            out.append(
                {
                    "trace_id": trace_id,
                    "root": root.name if root else (spans[0].name if spans else ""),
                    "start": root.start if root else (spans[0].start if spans else 0.0),
                    "duration_ms": round((root.duration if root else 0.0) * 1000, 3),
                    "spans": len(spans),
                }
            )
        return out

    def spans_of(self, trace_id: str) -> Optional[List[Span]]:
        with self._lock:
            if self._ring is None:
                return None
            spans = self._ring.get(trace_id)
            return list(spans) if spans is not None else None

    def span_tree(self, trace_id: str) -> Optional[dict]:
        """The trace as a nested tree keyed off the root span."""
        spans = self.spans_of(trace_id)
        if not spans:
            return None
        nodes = {s.span_id: {**s.to_dict(), "children": []} for s in spans}
        roots = []
        for s in sorted(spans, key=lambda s: s.start):
            node = nodes[s.span_id]
            parent = nodes.get(s.parent_id) if s.parent_id else None
            if parent is not None:
                parent["children"].append(node)
            else:
                roots.append(node)
        if not roots:
            return None
        return roots[0] if len(roots) == 1 else {"name": "trace", "trace_id": trace_id, "children": roots}

    def export_chrome(self, trace_id: str) -> Optional[dict]:
        """Chrome trace-event format (catapult/Perfetto loadable): complete
        ('X') events with microsecond ts/dur, one tid per source thread."""
        spans = self.spans_of(trace_id)
        if spans is None:
            return None
        tids: Dict[str, int] = {}
        events = []
        for s in sorted(spans, key=lambda s: s.start):
            tid = tids.setdefault(s.thread or "main", len(tids) + 1)
            events.append(
                {
                    "name": s.name,
                    "cat": "karpenter",
                    "ph": "X",
                    "ts": int(s.start * 1e6),
                    "dur": max(1, int(s.duration * 1e6)),
                    "pid": 1,
                    "tid": tid,
                    "args": {k: repr(v) if not isinstance(v, (str, int, float, bool)) else v for k, v in s.attributes.items()},
                }
            )
        meta = [
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": tid, "args": {"name": thread}}
            for thread, tid in tids.items()
        ]
        return {"displayTimeUnit": "ms", "traceEvents": meta + events}


# -- per-pod decision records -------------------------------------------------

# IncompatibleError messages -> constraint buckets. Keyword matching is the
# honest option here: the admission path raises strings, not typed reasons,
# and the buckets only need to be stable enough to aggregate.
_REJECTION_CLASSES = (
    ("tolerate", "taints"),
    ("taint", "taints"),
    ("host port", "host-ports"),
    ("hostport", "host-ports"),
    ("volume", "volume-limits"),
    ("exceeds node resources", "resources"),
    ("satisfied resources", "resources"),
    ("topology", "topology"),
    ("requirement", "requirements"),
    ("incompatible", "requirements"),
)


def classify_rejection(message: str) -> str:
    lowered = message.lower()
    for needle, bucket in _REJECTION_CLASSES:
        if needle in lowered:
            return bucket
    return "other"


@dataclass
class DecisionRecord:
    pod: str
    outcome: str  # placed-existing | placed-new | failed
    node: str = ""
    instance_type: str = ""
    provisioner: str = ""
    trace_id: str = ""
    error: str = ""
    rejections: Dict[str, int] = field(default_factory=dict)
    timestamp: float = field(default_factory=_now)

    def to_dict(self) -> dict:
        return {
            "pod": self.pod,
            "outcome": self.outcome,
            "node": self.node,
            "instance_type": self.instance_type,
            "provisioner": self.provisioner,
            "trace_id": self.trace_id,
            "error": self.error,
            "rejections": self.rejections,
            "timestamp": self.timestamp,
        }


@guarded_by("_lock", "_ring")
class DecisionLog:
    """Bounded ring of per-pod scheduling decisions, indexed by pod name.

    Only populated while the tracer is enabled (the scheduler checks before
    allocating any per-pod state), so the disabled path allocates nothing."""

    def __init__(self, capacity: int = DEFAULT_DECISION_RING):
        self._lock = threading.Lock()
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)

    def record(self, record: DecisionRecord) -> None:
        with self._lock:
            if len(self._ring) == self.capacity:
                DECISIONS_DROPPED.inc()
            self._ring.append(record)

    def update_node(self, pod_names, node: str, instance_type: str, placeholder: str = "") -> None:
        """Back-fill the real node name once the launch lands: the scheduler
        records placed-new against the placeholder virtual node; the launch
        path knows the cloud instance. `placeholder` pins the rewrite to the
        record created for THIS virtual node — a launch fed by a
        simulation-mode solve (the interruption proactive re-solve records
        no decisions) must not rewrite a pod's earlier, already-backfilled
        record."""
        names = set(pod_names)
        with self._lock:
            for record in reversed(self._ring):
                if record.pod in names and record.outcome == OUTCOME_PLACED_NEW and record.node == placeholder:
                    record.node = node
                    if instance_type:
                        record.instance_type = instance_type
                    names.discard(record.pod)
                    if not names:
                        return

    def for_pod(self, pod: str) -> List[dict]:
        with self._lock:
            return [r.to_dict() for r in self._ring if r.pod == pod]

    def latest_outcome_for(self, pod: str) -> Optional[dict]:
        """The newest decision record for one pod (the journal's waterfall
        detail joins it so /debug/waterfall?pod= answers outcome + rejection
        tallies in the same page); None when the ring holds nothing."""
        with self._lock:
            for record in reversed(self._ring):
                if record.pod == pod:
                    return record.to_dict()
        return None

    def recent(self, limit: int = 100, outcome: Optional[str] = None) -> List[dict]:
        """Newest-first records, bounded by `limit`; `outcome` filters to one
        outcome class BEFORE bounding (so ?outcome=failed&limit=50 is the
        last 50 failures, not the failures among the last 50 records)."""
        with self._lock:
            records = list(self._ring)
        out = []
        for record in reversed(records):
            if outcome is not None and record.outcome != outcome:
                continue
            out.append(record.to_dict())
            if len(out) >= limit:
                break
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()


# the process-wide instances (the REGISTRY analog): controllers import these,
# the Runtime enables them behind --enable-tracing, bench enables directly
TRACER = Tracer()
DECISIONS = DecisionLog()


def enabled() -> bool:
    return TRACER.enabled


# -- HTTP routes (ObservabilityServer extra routes) ---------------------------


def _json(status, payload) -> tuple:
    return status, "application/json; charset=utf-8", json.dumps(payload) + "\n"


def _traces_route(query: dict) -> tuple:
    trace_id = (query.get("id") or [None])[0]
    if trace_id is None:
        traces = TRACER.traces()
        # evidence-loss surface: how much of the ring is full and how many
        # traces have already been overwritten — a reader of a triggered
        # incident needs to know whether the window still covers it
        return _json(
            200,
            {
                "enabled": TRACER.enabled,
                "traces": traces,
                "traces_dropped": int(TRACES_DROPPED.value()),
                "occupancy": len(traces),
                "capacity": TRACER.capacity,
            },
        )
    fmt = (query.get("format") or ["tree"])[0]
    if fmt == "chrome":
        payload = TRACER.export_chrome(trace_id)
        if payload is None:
            return _json(404, {"error": f"trace {trace_id!r} not found", "status": 404})
        return _json(200, payload)
    tree = TRACER.span_tree(trace_id)
    if tree is None:
        return _json(404, {"error": f"trace {trace_id!r} not found", "status": 404})
    return _json(200, {"trace_id": trace_id, "root": tree})


_VALID_OUTCOMES = (OUTCOME_PLACED_EXISTING, OUTCOME_PLACED_NEW, OUTCOME_FAILED)

# the index listing is bounded: an unbounded ?limit= would serialize the
# whole 4096-record ring into one response on a busy cluster
_DECISIONS_DEFAULT_LIMIT = 100
_DECISIONS_MAX_LIMIT = 1000


def _decisions_route(query: dict) -> tuple:
    pod = (query.get("pod") or [None])[0]
    outcome = (query.get("outcome") or [None])[0]
    if outcome is not None and outcome not in _VALID_OUTCOMES:
        return _json(
            404,
            {"error": f"unknown outcome {outcome!r}; one of {list(_VALID_OUTCOMES)}", "status": 404},
        )
    raw_limit = (query.get("limit") or [None])[0]
    limit = _DECISIONS_DEFAULT_LIMIT
    if raw_limit is not None:
        try:
            limit = int(raw_limit)
        except ValueError:
            return _json(404, {"error": f"limit {raw_limit!r} is not an integer", "status": 404})
        limit = max(1, min(limit, _DECISIONS_MAX_LIMIT))
    if pod is None:
        records = DECISIONS.recent(limit=limit, outcome=outcome)
        payload = {"enabled": TRACER.enabled, "records": records, "limit": limit}
        if outcome is not None:
            payload["outcome"] = outcome
        return _json(200, payload)
    records = DECISIONS.for_pod(pod)
    if outcome is not None:
        records = [r for r in records if r["outcome"] == outcome]
    if not records:
        suffix = f" with outcome {outcome!r}" if outcome is not None else ""
        return _json(404, {"error": f"no decision records for pod {pod!r}{suffix}", "status": 404})
    # same bound and ordering as the index: newest first, one hot pod can
    # accumulate hundreds of ring entries
    records.reverse()
    return _json(200, {"pod": pod, "records": records[:limit]})


def routes() -> dict:
    """The tracing routes, served from the metrics listener alongside the
    live-profiling endpoints (cmd/controller.py wires them behind
    --enable-tracing)."""
    return {"/debug/traces": _traces_route, "/debug/decisions": _decisions_route}


def route_descriptions() -> dict:
    """One-line /debug-index descriptions, keyed like routes() — owned here
    so the index (observability.debug_index_route) can never drift from the
    paths this module actually serves."""
    return {
        "/debug/traces": "recent trace index; ?id= span tree, &format=chrome Perfetto export",
        "/debug/decisions": "per-pod scheduling decision records; ?pod=, ?outcome=, ?limit=",
    }
