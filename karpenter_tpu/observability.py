"""HTTP observability endpoints: health probes + Prometheus metrics.

The reference serves /healthz+/readyz on the health-probe port and /metrics
on the metrics port from its manager (controllers.go:167-181); the generated
Deployment's probes and the metrics Service point at these. Served by the
controller ENTRY POINT (cmd/controller.py), not the Runtime constructor, so
embedding runtimes in tests never binds real ports.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional

from .logsetup import get_logger
from .metrics import REGISTRY

log = get_logger("observability")


def debug_index_route(descriptions: Dict[str, str]):
    """Build the `/debug` index route: one JSON row per registered debug
    endpoint with its one-line description, so the read surface is
    discoverable from the process itself instead of the docs. The entry
    point (cmd/controller.py) passes the paths it actually wired — an
    endpoint behind a disabled flag is absent here too, matching what a
    GET against it would find."""

    def route(query: dict) -> tuple:
        endpoints = [
            {"path": path, "description": descriptions[path]} for path in sorted(descriptions)
        ]
        body = json.dumps({"endpoints": endpoints}) + "\n"
        return 200, "application/json; charset=utf-8", body

    return route


def _handler(routes):
    import inspect
    from urllib.parse import parse_qs, urlparse

    # arity decided once at registration: probe/metrics routes are zero-arg,
    # profiling routes take the parsed query. (Dispatching on TypeError at
    # call time would re-invoke a side-effectful route whose BODY raised
    # TypeError — a second live capture.)
    wants_query = {path: len(inspect.signature(fn).parameters) >= 1 for path, fn in routes.items()}

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            url = urlparse(self.path)
            route = routes.get(url.path)
            if route is None:
                self.send_error(404)
                return
            try:
                if wants_query[url.path]:
                    ok, content_type, body = route(parse_qs(url.query))
                else:
                    ok, content_type, body = route()
            except Exception as exc:  # noqa: BLE001 - a probe must answer, not die
                self.send_error(500, str(exc))
                return
            payload = body.encode()
            # a route may return an explicit int status (the tracing routes'
            # 404-shaped JSON); bool keeps the probe semantics (ok -> 200/503)
            status = ok if isinstance(ok, int) and not isinstance(ok, bool) else (200 if ok else 503)
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *args):  # kubelet probes every few seconds
            pass

    return Handler


class ObservabilityServer:
    """Two listeners: health (healthz/readyz) and metrics (/metrics)."""

    def __init__(
        self,
        healthy: Callable[[], bool],
        ready: Callable[[], bool],
        health_port: Optional[int],
        metrics_port: Optional[int],
        host: str = "0.0.0.0",
        registry=REGISTRY,
        extra_routes=None,
    ):
        def probe(fn, label):
            def route():
                ok = bool(fn())
                return ok, "text/plain; charset=utf-8", ("ok\n" if ok else f"{label} failing\n")

            return route

        def metrics_route():
            return True, "text/plain; version=0.0.4; charset=utf-8", registry.export_text()

        # port semantics: None/negative disables the listener; 0 binds an
        # ephemeral port (tests); positive binds that port (deployments)
        self._servers: List[ThreadingHTTPServer] = []
        self._threads: List[threading.Thread] = []
        if health_port is not None and health_port >= 0:
            self._servers.append(
                ThreadingHTTPServer((host, health_port), _handler({"/healthz": probe(healthy, "liveness"), "/readyz": probe(ready, "readiness")}))
            )
        if metrics_port is not None and metrics_port >= 0:
            # extra routes (e.g. the live profiling endpoints behind
            # --enable-profiling) share the metrics listener, the reference's
            # AddMetricsExtraHandler seam (controllers.go:183-202)
            metrics_routes = {"/metrics": metrics_route}
            metrics_routes.update(extra_routes or {})
            self._servers.append(ThreadingHTTPServer((host, metrics_port), _handler(metrics_routes)))

    @property
    def ports(self) -> List[int]:
        return [s.server_address[1] for s in self._servers]

    def start(self) -> None:
        for server in self._servers:
            thread = threading.Thread(target=server.serve_forever, name=f"obs-{server.server_address[1]}", daemon=True)
            thread.start()
            self._threads.append(thread)
        if self._servers:
            log.info("observability endpoints on ports %s", self.ports)

    def stop(self) -> None:
        for server in self._servers:
            server.shutdown()
            server.server_close()
        for thread in self._threads:
            thread.join(timeout=2)
