"""Solver flight recorder: per-solve telemetry for the device runtime.

The solve headline (BENCH_*.json) scores how fast a solve is; tracing says
where one solve's wall-clock went. Neither observes the *device runtime*
underneath: whether a solve recompiled its XLA programs (the r2-r5 headline
drift stayed unbisectable partly because nobody could say "r4 started
recompiling every pass"), or what the encode pushed through device memory.
This module is that instrument — the precondition for the incremental
steady-state solve work (ROADMAP item 1): before the O(delta) reformulation
can be *gated*, "a settled cluster re-solving under churn triggers zero new
compilations" has to be a measurable property.

Three instruments, one bounded ring:

- **per-solve records** — every dense presolve appends one `SolveRecord`:
  pod/group/bucket/type/zone cardinalities, the dispatch flavor and its
  padded vs actual shapes (with padding-waste %), every `DenseSolveStats`
  phase delta (encode/fill/device/mask/assemble/commit), fill routing, and
  the compile/HBM attribution below. Served at `/debug/solver` (index +
  `?id=` detail, 404-shaped JSON like the tracing routes).
- **JIT compile churn** — a `jax.monitoring` listener counts XLA
  backend-compile events and their seconds
  (`karpenter_jax_compilations_total{fn}` / `karpenter_jax_compile_seconds_total`);
  per-entry attribution comes from polling the registered jitted entries'
  `_cache_size()` around each solve, and each recompile is further
  attributed to the *dimension that changed shape* since the previous solve
  (pods grew past a pad boundary, the type universe changed, a new bucket
  count) — the record names the changed axes, so compile churn is
  actionable, not just counted.
- **HBM accounting** — per-solve device-memory snapshots from
  `device.memory_stats()` (TPU) with a `jax.live_arrays()` fallback (CPU/
  interpret), exported as `karpenter_solver_hbm_peak_bytes` /
  `karpenter_solver_hbm_live_bytes` gauges and stamped on each record.

Design constraints match tracing.py exactly:

- **disabled == free**: OFF by default; the ring allocates on `enable()`,
  never before, and every hot-path hook is one attribute read when
  disabled. The dense solver snapshots stats only when enabled.
- **zero deps, bounded memory**: the ring is a bounded deque (default 128
  records); overflow evicts oldest and counts into
  `karpenter_flight_records_dropped`.
- **one read surface**: `/debug/solver` on the metrics listener (wired
  behind `--enable-solver-telemetry` in cmd/controller.py); the same
  families export through `/metrics` for scrapers.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .analysis.guards import guarded_by
from .analysis.witness import WITNESS
from .capsule import CAPSULE, TRIGGER_STEADY_RECOMPILE
from .logsetup import get_logger
from .metrics import REGISTRY

log = get_logger("flight")

DEFAULT_RING = 128

# the committed solver contract (SOLVER_CONTRACTS.json at the repo root),
# loaded once per process for the capsule engine's steady-recompile
# cross-check; None (missing file) disables the check rather than firing
_CONTRACT_DOC: Optional[dict] = None
_CONTRACT_DOC_LOADED = False


def _committed_contracts() -> Optional[dict]:
    global _CONTRACT_DOC, _CONTRACT_DOC_LOADED
    if not _CONTRACT_DOC_LOADED:
        import os

        from .analysis import contracts as _contracts

        _CONTRACT_DOC = _contracts.load_committed(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        _CONTRACT_DOC_LOADED = True
    return _CONTRACT_DOC

# the backend-compile event jax.monitoring emits once per XLA compilation
# (trace-cache hits emit nothing): the one signal that IS a recompile
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# registered at import so gen_docs sees the families without a live recorder
COMPILATIONS = REGISTRY.counter(
    "karpenter_jax_compilations_total",
    "XLA compilations observed by the solver flight recorder, by jitted entry"
    " ('other' = a compile no registered entry's cache grew for).",
    ("fn",),
)
COMPILE_SECONDS = REGISTRY.counter(
    "karpenter_jax_compile_seconds_total",
    "Seconds spent in XLA backend compilation (jax.monitoring compile events).",
)
HBM_PEAK = REGISTRY.gauge(
    "karpenter_solver_hbm_peak_bytes",
    "Peak device-memory bytes reported at the last recorded solve"
    " (device memory_stats, or the live-array total where unavailable).",
)
HBM_LIVE = REGISTRY.gauge(
    "karpenter_solver_hbm_live_bytes",
    "Live device-memory bytes at the last recorded solve.",
)
RECORDS_STORED = REGISTRY.gauge(
    "karpenter_flight_records_stored", "Per-solve records currently held in the flight-recorder ring"
)
RECORDS_DROPPED = REGISTRY.counter(
    "karpenter_flight_records_dropped", "Per-solve records evicted from the bounded flight-recorder ring"
)
SOLVE_LATENCY = REGISTRY.summary(
    "karpenter_solver_solve_duration_seconds",
    "Wall-clock of real (non-simulation) Scheduler.solve calls while solver telemetry is enabled.",
    objectives=(0.5, 0.95, 0.99),
)

@guarded_by("_lock", "events", "seconds", "_registered")
class _CompileTally:
    """Process-wide backend-compile tally. jax.monitoring offers no
    per-listener unregister, so exactly ONE listener is ever installed (on
    the first recorder enable) and it feeds this shared tally + the
    COMPILE_SECONDS family exactly once per compile — a second enabled
    recorder (tests construct fresh instances in the shared tier-1 process)
    reads the same tally instead of double-counting."""

    def __init__(self):
        self._lock = threading.Lock()
        self._registered = False
        self.events = 0
        self.seconds = 0.0

    def register_listener(self) -> None:
        with self._lock:
            if self._registered:
                return
            self._registered = True  # set even on failure: don't retry every enable
        try:
            from jax import monitoring
        except Exception as exc:  # noqa: BLE001 - recorder must work jax-less
            log.warning("jax.monitoring unavailable; compile churn not counted: %r", exc)
            return

        def on_event(event: str, duration: float, **kwargs) -> None:
            if event != _COMPILE_EVENT:
                return
            with self._lock:
                self.events += 1
                self.seconds += duration
            COMPILE_SECONDS.inc(duration)

        monitoring.register_event_duration_secs_listener(on_event)

    def snapshot(self) -> tuple:
        with self._lock:
            return self.events, self.seconds


_TALLY = _CompileTally()

# the shape-signature axes recompiles are attributed to, in report order
_SIGNATURE_DIMS = (
    "pods",
    "groups",
    "buckets",
    "types",
    "zones",
    "capacity_types",
    "resources",
    "buckets_padded",
    "types_padded",
)


@dataclass
class SolveRecord:
    """One dense solve, as the flight recorder saw it."""

    id: int
    timestamp: float  # epoch seconds
    signature: Dict[str, int]  # the _SIGNATURE_DIMS cardinalities
    dispatch: str  # plain | pallas | sharded | none (no device dispatch ran)
    padding_waste_pct: float  # 100 * padded-but-dead share of the dispatch surface
    phases: Dict[str, float]  # per-phase seconds, this solve only (stats delta)
    fill_routing: Dict[str, int]  # fills/pods via the vectorized vs host fill
    pods_committed: int = 0
    pods_to_host: int = 0
    duration_seconds: float = 0.0
    recompile: bool = False
    compiled_fns: Dict[str, int] = field(default_factory=dict)  # entry -> compiles this solve
    # entries whose executable cache was EMPTY when this solve started: their
    # compile is that fn's first program build (a path engaging for the first
    # time), not a retrace — the contract cross-check exempts them the way it
    # exempts the process-wide ["cold-start"]
    first_compiles: List[str] = field(default_factory=list)
    compile_seconds: float = 0.0
    # the dimensions whose cardinality changed vs the PREVIOUS recorded
    # solve — empty on a recompile with an unchanged signature (a new code
    # path compiled), ["cold-start"] when there was no previous solve
    recompile_attribution: List[str] = field(default_factory=list)
    hbm_peak_bytes: int = 0
    hbm_live_bytes: int = 0
    # solver fault domain (solver/faults.py): classified device faults this
    # solve hit (taxonomy kind -> count), the degradation-ladder rungs it
    # took (in escalation order), and the circuit-breaker state at record
    # time — a healthy solve records {}, [], "closed"
    faults: Dict[str, int] = field(default_factory=dict)
    rungs: List[str] = field(default_factory=list)
    breaker: str = "closed"

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "timestamp": self.timestamp,
            "signature": self.signature,
            "dispatch": self.dispatch,
            "padding_waste_pct": round(self.padding_waste_pct, 2),
            "phases": {k: round(v, 6) for k, v in self.phases.items()},
            "fill_routing": self.fill_routing,
            "pods_committed": self.pods_committed,
            "pods_to_host": self.pods_to_host,
            "duration_seconds": round(self.duration_seconds, 6),
            "recompile": self.recompile,
            "compiled_fns": self.compiled_fns,
            "first_compiles": self.first_compiles,
            "compile_seconds": round(self.compile_seconds, 6),
            "recompile_attribution": self.recompile_attribution,
            "hbm_peak_bytes": self.hbm_peak_bytes,
            "hbm_live_bytes": self.hbm_live_bytes,
            "faults": self.faults,
            "rungs": self.rungs,
            "breaker": self.breaker,
        }

    def summary(self) -> dict:
        """The /debug/solver index row."""
        return {
            "id": self.id,
            "timestamp": self.timestamp,
            "pods": self.signature.get("pods", 0),
            "buckets": self.signature.get("buckets", 0),
            "types": self.signature.get("types", 0),
            "dispatch": self.dispatch,
            "duration_seconds": round(self.duration_seconds, 6),
            "recompile": self.recompile,
            "recompile_attribution": self.recompile_attribution,
            "hbm_peak_bytes": self.hbm_peak_bytes,
            "faults": self.faults,
            "rungs": self.rungs,
            "breaker": self.breaker,
        }


@guarded_by("_lock", "_ring", "_next_id", "_prev_signature", "_entries", "_run_engaged")
class FlightRecorder:
    """Bounded ring of per-solve records + the compile/HBM instruments."""

    # distinct jitted wrappers retained per {fn} name: the sharded path can
    # mint a fresh wrapper per mesh generation (lru-evicted meshes, chip
    # dropout + re-detect), and a registry that only ever appends would pin
    # every generation's compiled executables for process lifetime
    MAX_FNS_PER_ENTRY = 8

    def __init__(self, capacity: int = DEFAULT_RING):
        self._lock = WITNESS.lock("solver.flight")
        self.capacity = capacity
        self.enabled = False
        # allocated on enable(), never before — "disabled is a true no-op"
        self._ring: Optional[List[SolveRecord]] = None
        self._next_id = 0
        self._prev_signature: Optional[Dict[str, int]] = None
        # named jitted entries whose _cache_size() growth attributes compiles
        self._entries: Dict[str, List[object]] = {}
        # entries that compiled at least once since the last reset() — the
        # steady-recompile capsule cross-check's warm-up exemption
        self._run_engaged: set = set()

    # -- lifecycle -----------------------------------------------------------

    def enable(self, capacity: Optional[int] = None) -> None:
        with self._lock:
            if capacity is not None:
                self.capacity = capacity
            first = self._ring is None
            if first:
                self._ring = []
        if first and WITNESS.enabled:
            # first enable happens at Runtime construction, before any solve
            # holds the lock: adopt a witnessed lock so the ring joins the
            # lock-order graph the chaos suites assert acyclic
            self._lock = WITNESS.lock("solver.flight")
        _TALLY.register_listener()
        self._register_default_entries()
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        """Drop records and attribution state (per-run harness reset; the
        monotonic compile counters survive — consumers score deltas). The
        HBM gauges zero too: they mean "at the last recorded solve", and a
        stale reading from a previous run would otherwise pre-trip the
        solver's --solver-hbm-budget chunking before this run's first
        solve ever reaches the device."""
        with self._lock:
            if self._ring is not None:
                self._ring.clear()
            self._prev_signature = None
            self._run_engaged.clear()
        RECORDS_STORED.set(0)
        SOLVE_LATENCY.clear()
        HBM_PEAK.set(0.0)
        HBM_LIVE.set(0.0)

    # -- compile instruments ---------------------------------------------------

    def _register_default_entries(self) -> None:
        """Name the solver pipeline's jitted entries so compile counts carry
        a {fn} label. Import errors degrade to unattributed counting."""
        try:
            from .ops import feasibility, packing, warmfill

            self.register_jit_entry("resource_fit", feasibility.resource_fit)
            self.register_jit_entry("feasibility_mask", feasibility.feasibility_mask)
            self.register_jit_entry("availability_counts", feasibility.availability_counts)
            self.register_jit_entry("bucket_type_cost", feasibility.bucket_type_cost)
            self.register_jit_entry("bucket_type_cost_packed", feasibility.bucket_type_cost_packed)
            self.register_jit_entry("segment_usage", packing.segment_usage)
            self.register_jit_entry("audit_layout", packing.audit_layout)
            self.register_jit_entry("warm_fill_counts", warmfill.warm_fill_counts)
            self.register_jit_entry("warm_fill_counts_pallas", warmfill._warm_fill_counts_pallas_padded)
        except Exception as exc:  # noqa: BLE001 - per-fn attribution is best-effort
            log.warning("solver jit entries unavailable; compiles will count as 'other': %r", exc)
        try:
            from .ops import rebase

            # the incremental engine's donated delta kernel: its padded
            # stable shapes are exactly what the zero-steady-state-recompile
            # gate pins, so it MUST be attributable by name
            self.register_jit_entry("rebase_view_state", rebase.rebase_view_state)
            # the residency auditor's sampled-row readback rides the same
            # pow2 ladder; attributable by name so an audit-induced
            # recompile is visible (bench --smoke pins it at zero)
            self.register_jit_entry("gather_rows", rebase.gather_rows)
        except Exception as exc:  # noqa: BLE001 - per-fn attribution is best-effort
            log.warning("rebase jit entry unavailable: %r", exc)
        try:
            from .ops import pallas_kernels

            self.register_jit_entry("bucket_type_cost_pallas", pallas_kernels._bucket_type_cost_padded)
        except Exception as exc:  # noqa: BLE001 - Pallas-less builds are supported
            log.debug("pallas entry unavailable: %r", exc)

    def register_jit_entry(self, name: str, fn: object) -> None:
        """Attach a jitted function (anything exposing _cache_size()) to a
        {fn} label; repeated registrations of the same object are no-ops,
        and several objects may share one name (per-mesh sharded wrappers)."""
        if not hasattr(fn, "_cache_size"):
            return
        with self._lock:
            fns = self._entries.setdefault(name, [])
            if any(existing is fn for existing in fns):
                return
            fns.append(fn)
            if len(fns) > self.MAX_FNS_PER_ENTRY:
                del fns[0]  # oldest generation: stop pinning its executables

    def _cache_sizes_locked(self) -> Dict[str, int]:
        sizes: Dict[str, int] = {}
        for name, fns in self._entries.items():
            total = 0
            for fn in fns:
                try:
                    total += int(fn._cache_size())  # type: ignore[attr-defined]
                except Exception:  # noqa: BLE001 - a dead wrapper must not kill telemetry
                    log.debug("cache-size probe failed for %s", name)
            sizes[name] = total
        return sizes

    def _cache_sizes(self) -> Dict[str, int]:
        with self._lock:
            return self._cache_sizes_locked()

    def compilations_total(self) -> int:
        """Sum of the per-fn compile counter across labels (score surface)."""
        return int(sum(COMPILATIONS.values().values()))

    # -- HBM instrument --------------------------------------------------------

    @staticmethod
    def hbm_snapshot() -> tuple:
        """(peak_bytes, live_bytes) for the first addressable device.
        TPU backends report memory_stats(); where that is None (CPU, the
        interpret path) fall back to the live-array total — an HBM *model*,
        but a shape-faithful one: the arrays the solver keeps resident."""
        try:
            import jax

            device = jax.local_devices()[0]
            stats = device.memory_stats()
            if stats:
                live = int(stats.get("bytes_in_use", 0))
                peak = int(stats.get("peak_bytes_in_use", live))
                return peak, live
            live = int(sum(arr.nbytes for arr in jax.live_arrays()))
            return live, live
        except Exception as exc:  # noqa: BLE001 - telemetry must never fail a solve
            log.debug("hbm snapshot unavailable: %r", exc)
            return 0, 0

    # -- the per-solve seam (dense.py) ----------------------------------------

    def begin_solve(self) -> Optional[dict]:
        """Snapshot the compile tallies at the head of a dense solve; the
        matching complete_solve() attributes everything that moved."""
        if not self.enabled:
            return None
        events, seconds = _TALLY.snapshot()
        return {"sizes": self._cache_sizes(), "events": events, "seconds": seconds}

    def complete_solve(
        self,
        token: dict,
        signature: Dict[str, int],
        dispatch: Optional[dict],
        phases: Dict[str, float],
        fill_routing: Dict[str, int],
        pods_committed: int,
        pods_to_host: int,
        duration: float,
        faults: Optional[Dict[str, int]] = None,
        rungs: Optional[List[str]] = None,
        breaker: str = "closed",
    ) -> Optional[SolveRecord]:
        """Close the window begin_solve() opened: compute per-entry compile
        deltas, attribute them to the changed shape dimensions, snapshot
        HBM, and append the record to the ring."""
        if not self.enabled or token is None:
            return None
        sizes = self._cache_sizes()
        compiled = {
            name: sizes[name] - token["sizes"].get(name, 0)
            for name in sizes
            if sizes[name] > token["sizes"].get(name, 0)
        }
        tally_events, tally_seconds = _TALLY.snapshot()
        events = tally_events - token["events"]
        seconds = tally_seconds - token["seconds"]
        attributed = sum(compiled.values())
        if events > attributed:
            compiled["other"] = events - attributed
        for name, count in compiled.items():
            COMPILATIONS.inc(count, fn=name)
        peak, live = self.hbm_snapshot()
        HBM_PEAK.set(float(peak))
        HBM_LIVE.set(float(live))
        waste = 0.0
        surface = signature.get("buckets_padded", 0) * signature.get("types_padded", 0)
        if surface > 0:
            actual = signature.get("buckets", 0) * signature.get("types", 0)
            waste = 100.0 * (1.0 - actual / surface)
        with self._lock:
            if self._ring is None:
                return None
            attribution: List[str] = []
            if compiled:
                if self._prev_signature is None:
                    attribution = ["cold-start"]
                else:
                    attribution = [
                        dim
                        for dim in _SIGNATURE_DIMS
                        if signature.get(dim) != self._prev_signature.get(dim)
                    ]
            record = SolveRecord(
                id=self._next_id,
                timestamp=time.time(),
                signature={dim: int(signature.get(dim, 0)) for dim in _SIGNATURE_DIMS},
                dispatch=(dispatch or {}).get("flavor", "none"),
                padding_waste_pct=waste,
                phases=dict(phases),
                fill_routing=dict(fill_routing),
                pods_committed=pods_committed,
                pods_to_host=pods_to_host,
                duration_seconds=duration,
                recompile=bool(compiled),
                compiled_fns=compiled,
                first_compiles=sorted(
                    name for name in compiled if name != "other" and token["sizes"].get(name, 0) == 0
                ),
                compile_seconds=seconds,
                recompile_attribution=attribution,
                hbm_peak_bytes=peak,
                hbm_live_bytes=live,
                faults=dict(faults or {}),
                rungs=list(rungs or []),
                breaker=breaker,
            )
            self._next_id += 1
            self._prev_signature = dict(signature)
            # entries engaging for the first time SINCE THE LAST reset(): in
            # a long-lived process (a scenario campaign) the jit executable
            # caches survive across runs, so a warm entry's first growth in
            # a run is warm-up re-engagement, not a steady-state retrace —
            # the capsule cross-check below exempts it the way the contract
            # checker exempts process-wide first compiles
            run_first = {
                name for name in compiled if name != "other" and name not in self._run_engaged
            }
            self._run_engaged.update(name for name in compiled if name != "other")
            self._ring.append(record)
            if len(self._ring) > self.capacity:
                del self._ring[0]
                RECORDS_DROPPED.inc()
            RECORDS_STORED.set(float(len(self._ring)))
        if CAPSULE.enabled and record.recompile and attribution and attribution != ["cold-start"]:
            # the steady-state recompile cross-check: a recompile whose
            # attribution is entirely declared-STATIC axes contradicts the
            # committed solver contract — that IS the incident (healthy
            # runs and legitimate churn recompiles attribute to varying
            # axes and never fire). Only entries that already compiled this
            # run count as retraces: without the run_first exemption the
            # trigger is transport-asymmetric in campaigns (the first
            # transport populates the process-wide caches; the second sees
            # no compiles at all)
            doc = _committed_contracts()
            if doc is not None:
                from .analysis.contracts import recompile_violations

                view = {
                    "id": record.id,
                    "recompile": record.recompile,
                    "recompile_attribution": attribution,
                    "compiled_fns": record.compiled_fns,
                    "first_compiles": sorted(set(record.first_compiles) | run_first),
                    "signature": record.signature,
                }
                if recompile_violations([view], doc):
                    CAPSULE.trigger(TRIGGER_STEADY_RECOMPILE, attribution=sorted(attribution))
        return record

    def observe_solve_latency(self, seconds: float) -> None:
        """One observation per REAL Scheduler.solve (the scheduler gates on
        enabled + non-simulation before calling)."""
        SOLVE_LATENCY.observe(seconds)

    # -- read surface ----------------------------------------------------------

    def records(self) -> List[SolveRecord]:
        with self._lock:
            return list(self._ring) if self._ring is not None else []

    def last_record_id(self) -> Optional[int]:
        """Id of the newest recorded solve (the journal's per-pod `solved`
        events cross-link to it); None when nothing is recorded."""
        with self._lock:
            if not self._ring:
                return None
            return self._ring[-1].id

    def record_by_id(self, record_id: int) -> Optional[SolveRecord]:
        with self._lock:
            if self._ring is None:
                return None
            for record in self._ring:
                if record.id == record_id:
                    return record
        return None

    def snapshot(self) -> dict:
        """The /debug/solver index payload: newest-first record summaries
        plus the process-wide compile tallies and the solver fault-domain
        state (taxonomy counters, degradation-ladder tallies, breaker)."""
        # imported lazily: solver/__init__ pulls in the full dense solver,
        # and this module must stay importable without it (gen_docs, tests)
        from .solver.faults import BREAKER, DEGRADED_SOLVES, SOLVER_FAULTS

        records = self.records()
        events, seconds = _TALLY.snapshot()
        fault_domain = {
            "breaker": BREAKER.snapshot(),
            "faults_total": {
                (labels[0] or "unclassified"): int(value) for labels, value in SOLVER_FAULTS.values().items()
            },
            "degraded_solves_total": {
                (labels[0] or "unknown"): int(value) for labels, value in DEGRADED_SOLVES.values().items()
            },
        }
        return {
            "enabled": self.enabled,
            "records": [r.summary() for r in reversed(records)],
            "compilations_total": self.compilations_total(),
            "compile_events": events,
            "compile_seconds_total": round(seconds, 6),
            "compilations_by_fn": {
                (labels[0] or "other"): int(value) for labels, value in COMPILATIONS.values().items()
            },
            "hbm_peak_bytes": int(HBM_PEAK.value()),
            "hbm_live_bytes": int(HBM_LIVE.value()),
            "fault_domain": fault_domain,
        }


# the process-wide instance (the TRACER analog): dense.py feeds it, the
# Runtime enables it behind --enable-solver-telemetry, bench enables directly
FLIGHT = FlightRecorder()


def enabled() -> bool:
    return FLIGHT.enabled


# -- HTTP route (ObservabilityServer extra routes) ----------------------------


def _json(status, payload) -> tuple:
    return status, "application/json; charset=utf-8", json.dumps(payload) + "\n"


def _solver_route(query: dict) -> tuple:
    raw_id = (query.get("id") or [None])[0]
    if raw_id is None:
        return _json(200, FLIGHT.snapshot())
    try:
        record_id = int(raw_id)
    except ValueError:
        return _json(404, {"error": f"solve id {raw_id!r} is not an integer", "status": 404})
    record = FLIGHT.record_by_id(record_id)
    if record is None:
        return _json(404, {"error": f"solve record {record_id} not found", "status": 404})
    return _json(200, record.to_dict())


def routes() -> dict:
    """The flight-recorder read surface, served from the metrics listener
    alongside tracing/SLO (cmd/controller.py wires it behind
    --enable-solver-telemetry)."""
    return {"/debug/solver": _solver_route}


def route_descriptions() -> dict:
    """/debug-index descriptions, keyed like routes() (see tracing.py)."""
    return {
        "/debug/solver": "solver flight recorder: per-solve shapes/phases, recompile attribution, HBM, fault-domain breaker/ladder state; ?id= detail",
    }
