"""karpenter-tpu: a TPU-native cluster node-provisioning framework.

A ground-up rebuild of the capabilities of Karpenter (the Kubernetes
node-provisioning autoscaler, reference: asimshankar/karpenter): watch for
unschedulable pods, solve a constrained bin-packing problem over pods x
instance types (resources, node selectors, taints/tolerations, pod
affinity/anti-affinity, topology spread), launch cost-optimal nodes through a
pluggable cloud provider, and continuously consolidate the cluster.

Where the reference implements its scheduling core as a sequential
first-fit-decreasing loop in Go (reference:
pkg/controllers/provisioning/scheduling/scheduler.go), this framework reframes
provisioning and consolidation as dense constraint-matrix programs solved on
TPU via JAX/pjit, with an exact host-side FFD implementation serving as both
the differential-testing oracle and the fallback path.

Layout (mirrors SURVEY.md section 7):
  api/            object model + Provisioner CRD equivalent + label taxonomy
  scheduling/     constraint algebra (Requirement sets, taints, node templates)
  core/           host scheduler core (FFD oracle) + controllers
  ir/             dense problem IR: vocab interning + matrix encoders
  ops/            JAX kernels: feasibility masks, on-device packing
  solver/         the TPU solver service (jit, bucketing, fallback)
  parallel/       device mesh + sharded solver (ICI-scaled)
  cloudprovider/  provider plugin boundary + fake provider
  kube/           in-memory cluster API (apiserver stand-in for tests/sim)
  controllers/    provisioning, state, consolidation, node, termination, ...
  utils/          quantities, resource arithmetic
"""

__version__ = "0.1.0"
