"""SCENARIO_*.json schema: the structural gate behavioral artifacts must pass.

BENCH_*.json regressions became bisectable once their shape was pinned;
SCENARIO artifacts get the same treatment from day one. `scenario_doc_errors`
is the single validator shared by the campaign runner (every emitted file is
self-checked before it lands on disk) and the tier-1 smoke test — required
keys, a well-formed provenance block, monotonic sample timestamps, and the
scored invariants being the right types.
"""

from __future__ import annotations

from typing import List

from ..provenance import provenance_errors

RUN_KEYS = ("transport", "duration_seconds", "converged", "scores", "samples")
SCORE_KEYS = (
    "pending_latency_seconds",
    "node_ready_seconds",
    "cost_per_hour",
    "ideal_cost_per_hour",
    "cost_drift_ratio",
    "lost_pods",
    "leaked_instances",
    "budget_violations",
    "pods_desired",
    "pods_bound",
    "nodes_churned",
    "restarts",
    # capacity-failure scores: node launches that failed during the run
    # (insufficient capacity + other), and the integral of pending pods over
    # the sample timeline — how much pod-time the cluster spent unable to
    # place work (a crunch's user-visible cost even when nothing is lost)
    "launch_failures",
    "unschedulable_pod_seconds",
    # solver-telemetry scores (flight.py): XLA compilations observed during
    # the run (the steady-state property — a settled cluster re-solving
    # under churn must score 0 after warmup) and the p95 of real
    # Scheduler.solve wall-clock (null when the run solved nothing)
    "recompiles_total",
    "solver_latency_p95_seconds",
    # incremental-engine scores (solver/incremental.py): provision passes
    # whose full encode the device-resident state skipped this run (0 on
    # every non-incremental scenario), and the late/early solve-latency
    # p95 ratio (null when the run solved too little to window) — ~1.0 is
    # the O(delta) steady-state witness the soak settled predicate asserts
    "encode_skipped_passes",
    "solver_latency_p95_flatness",
    # the pending-latency waterfall (journal.py): per-segment p50/p95/p99
    # decomposing creation->bind into queue_wait / batch_wait / solve /
    # launch / node_ready / bind — the runner asserts the conservation
    # invariant (segments sum to the observed pending duration) before
    # this block is allowed to land in the artifact
    "waterfall",
    # solver fault-domain scores (solver/faults.py): classified device
    # faults observed during the run (every taxonomy kind summed), the
    # degradation-ladder rungs taken (flavor/chunked/host summed), the
    # faults the run's FaultPlan actually injected (faults_total >=
    # injected is the chaos-scenario acceptance bar), and the circuit
    # breaker's state at convergence — CLOSED proves the device path was
    # re-admitted, not permanently abandoned
    "solver_faults_total",
    "degraded_solves_total",
    "solver_faults_injected",
    "breaker_state",
    # control-plane fault-domain scores (kube/chaos.py + kube/coherence.py):
    # optimistic-concurrency conflicts clients observed during the run
    # (injected storms and organic races), faults the run's KubeFaultPlan
    # actually injected, informer-cache divergences still standing at the
    # teardown coherence check (ZERO is the acceptance bar — the lock-cycle
    # analog for cache coherence), and client-token launches that executed
    # twice (the two-leader / replay-miss witness; also pinned at zero)
    "kube_conflicts_total",
    "kube_faults_injected",
    "informer_divergences",
    "double_launches",
    # invariant-monitor scores (invariants.py): the slow-leak witnesses the
    # soak tier exists for, schema-gated on EVERY run — threads alive after
    # their Runtime released them, watch subscriptions above the armed
    # baseline, the least-squares traced-heap slope (null unless the run
    # traced memory, i.e. the soak tier), and distinct confirmed invariant
    # violations (threads/watches/ring-budget/lock-cycle/coherence/
    # double-launch, each (invariant, entity) counted once)
    "leaked_threads",
    "leaked_watches",
    "rss_growth_slope",
    "invariant_violations",
    # chaos-orchestrator scores (scenarios/chaos_orchestrator.py): total
    # cross-domain fault events delivered this run (imperative schedule
    # events + seeded solver/kube triggers that fired), the schedule's
    # history digest (null when the scenario ran no schedule — equal
    # digests across transports pin the cross-transport determinism
    # witness), and the compressed wall-time the run represents (the
    # recorded span a soak replays; the real duration otherwise)
    "chaos_injected_total",
    "chaos_history_digest",
    "compressed_seconds",
    # incident-capsule scores (capsule.py): evidence bundles captured this
    # run (chaos scenarios require >=1 through their settled predicates,
    # healthy scenarios pin 0) and the per-trigger fingerprint lists —
    # equal maps across transports are the capture-determinism witness the
    # campaign runner asserts before an artifact lands
    "capsules_captured",
    "capsule_triggers",
    # residency-auditor scores (solver/audit.py): divergences the auditor
    # detected this run (healthy scenarios pin 0 — a nonzero here on a run
    # with no corruption specs is a REAL integrity bug, and run_one raises),
    # auto-heals issued (the storm scenario requires heals == divergences),
    # and audits executed (>= 1 proves the auditor actually ran where the
    # scenario enabled it)
    "residency_divergences",
    "residency_heals",
    "audit_passes",
)

BREAKER_STATES = ("closed", "half-open", "open")

# the journal's waterfall segment vocabulary (journal.SEGMENTS mirrored by
# name only — the schema stays importable without the journal's witness/
# metrics imports in consumers that just validate files)
WATERFALL_SEGMENTS = ("queue_wait", "batch_wait", "solve", "launch", "node_ready", "bind")
QUANTILE_KEYS = ("p50", "p95", "p99", "count")
SAMPLE_KEYS = ("t", "pending_pods", "nodes", "cost_per_hour", "disrupting")


def _quantile_errors(block, where: str) -> List[str]:
    errs = []
    if not isinstance(block, dict):
        return [f"{where} must be a dict of per-provisioner quantiles"]
    for provisioner, entry in block.items():
        if not isinstance(entry, dict):
            # a non-dict entry would make `key not in entry` raise (int) or
            # substring-match (str) — report the malformation instead
            errs.append(f"{where}[{provisioner!r}] must be a dict, got {type(entry).__name__}")
            continue
        for key in QUANTILE_KEYS:
            if key not in entry:
                errs.append(f"{where}[{provisioner!r}] missing {key!r}")
    return errs


def run_errors(run, where: str = "run") -> List[str]:
    errs: List[str] = []
    if not isinstance(run, dict):
        return [f"{where} must be a dict"]
    for key in RUN_KEYS:
        if key not in run:
            errs.append(f"{where} missing key {key!r}")
    scores = run.get("scores")
    if isinstance(scores, dict):
        for key in SCORE_KEYS:
            if key not in scores:
                errs.append(f"{where}.scores missing key {key!r}")
        for field in (
            "lost_pods", "leaked_instances", "budget_violations", "restarts", "launch_failures",
            "recompiles_total", "solver_faults_total", "degraded_solves_total", "solver_faults_injected",
            "kube_conflicts_total", "kube_faults_injected", "informer_divergences", "double_launches",
            "leaked_threads", "leaked_watches", "invariant_violations", "chaos_injected_total",
            "encode_skipped_passes", "capsules_captured",
            "residency_divergences", "residency_heals", "audit_passes",
        ):
            value = scores.get(field)
            if value is not None and not isinstance(value, int):
                errs.append(f"{where}.scores.{field} must be an int, got {type(value).__name__}")
        breaker = scores.get("breaker_state")
        if breaker is not None and breaker not in BREAKER_STATES:
            errs.append(f"{where}.scores.breaker_state must be one of {list(BREAKER_STATES)}, got {breaker!r}")
        ups = scores.get("unschedulable_pod_seconds")
        if ups is not None and (not isinstance(ups, (int, float)) or isinstance(ups, bool) or ups < 0):
            errs.append(f"{where}.scores.unschedulable_pod_seconds must be a non-negative number")
        p95 = scores.get("solver_latency_p95_seconds")
        if p95 is not None and (not isinstance(p95, (int, float)) or isinstance(p95, bool) or p95 < 0):
            errs.append(f"{where}.scores.solver_latency_p95_seconds must be null or a non-negative number")
        flat = scores.get("solver_latency_p95_flatness")
        if flat is not None and (not isinstance(flat, (int, float)) or isinstance(flat, bool) or flat < 0):
            errs.append(f"{where}.scores.solver_latency_p95_flatness must be null or a non-negative number")
        slope = scores.get("rss_growth_slope")
        if slope is not None and (not isinstance(slope, (int, float)) or isinstance(slope, bool)):
            # negative is legal (a heap that SHRANK over the window); only
            # a non-number is a malformation
            errs.append(f"{where}.scores.rss_growth_slope must be null or a number")
        digest = scores.get("chaos_history_digest")
        if digest is not None and (not isinstance(digest, str) or not digest):
            errs.append(f"{where}.scores.chaos_history_digest must be null or a non-empty string")
        triggers = scores.get("capsule_triggers")
        if triggers is not None:
            if not isinstance(triggers, dict):
                errs.append(f"{where}.scores.capsule_triggers must be a dict of trigger -> fingerprint list")
            else:
                for trigger, fps in triggers.items():
                    if not isinstance(fps, list) or not fps or any(not isinstance(fp, str) or not fp for fp in fps):
                        errs.append(
                            f"{where}.scores.capsule_triggers[{trigger!r}] must be a non-empty list of"
                            " non-empty fingerprint strings"
                        )
        compressed = scores.get("compressed_seconds")
        if compressed is not None and (
            not isinstance(compressed, (int, float)) or isinstance(compressed, bool) or compressed < 0
        ):
            errs.append(f"{where}.scores.compressed_seconds must be a non-negative number")
        errs.extend(_quantile_errors(scores.get("pending_latency_seconds", {}), f"{where}.scores.pending_latency_seconds"))
        waterfall = scores.get("waterfall")
        if isinstance(waterfall, dict):
            for segment, entry in waterfall.items():
                if segment not in WATERFALL_SEGMENTS:
                    errs.append(
                        f"{where}.scores.waterfall[{segment!r}] is not a waterfall segment"
                        f" (one of {list(WATERFALL_SEGMENTS)})"
                    )
                    continue
                if not isinstance(entry, dict):
                    errs.append(f"{where}.scores.waterfall[{segment!r}] must be a dict, got {type(entry).__name__}")
                    continue
                for key in QUANTILE_KEYS:
                    if key not in entry:
                        errs.append(f"{where}.scores.waterfall[{segment!r}] missing {key!r}")
        elif waterfall is not None:
            errs.append(f"{where}.scores.waterfall must be a dict of per-segment quantiles")
    elif scores is not None:
        errs.append(f"{where}.scores must be a dict")
    samples = run.get("samples")
    if isinstance(samples, list):
        if not samples:
            errs.append(f"{where}.samples must be non-empty")
        last_t = None
        for i, sample in enumerate(samples):
            if not isinstance(sample, dict):
                errs.append(f"{where}.samples[{i}] must be a dict")
                continue
            for key in SAMPLE_KEYS:
                if key not in sample:
                    errs.append(f"{where}.samples[{i}] missing {key!r}")
            t = sample.get("t")
            if isinstance(t, (int, float)):
                if last_t is not None and t < last_t:
                    errs.append(f"{where}.samples[{i}].t={t} goes backwards (prev {last_t}): timestamps must be monotonic")
                last_t = t
    elif samples is not None:
        errs.append(f"{where}.samples must be a list")
    return errs


def scenario_doc_errors(doc) -> List[str]:
    """All structural problems with one SCENARIO_*.json document; empty
    means valid."""
    errs: List[str] = []
    if not isinstance(doc, dict):
        return ["document must be a JSON object"]
    for key in ("scenario", "provenance", "runs"):
        if key not in doc:
            errs.append(f"missing top-level key {key!r}")
    errs.extend(provenance_errors(doc.get("provenance", {})))
    runs = doc.get("runs")
    if isinstance(runs, list):
        if not runs:
            errs.append("runs must be non-empty")
        for i, run in enumerate(runs):
            errs.extend(run_errors(run, where=f"runs[{i}]"))
    elif runs is not None:
        errs.append("runs must be a list")
    return errs
