"""Replay: drive a scenario from a recorded journal's arrival trace.

The journal (journal.py) is the on-disk trace format; `ReplayTrace` closes
the loop: a captured journal (or any schema-valid JSONL — cluster-trace
datasets convert to the same shape) becomes a scenario primitive that
re-presents the recorded pod arrivals to a live Runtime with the original
inter-arrival structure preserved and optionally clock-compressed, so hours
of recorded wall-time replay in minutes through the same `utils/clock.py`
seam everything else is timed by.

    trace = ReplayTrace.from_journal("JOURNAL_pod_burst_inprocess.jsonl", compress=60.0)
    Scenario(name="replayed_burst", desired=0, duration=trace.total_seconds() + 2.0,
             primitives=[trace])

Only pod `created` events matter to the arrival schedule; everything else
in the journal (solve/launch/bind timing) is the RESULT the replayed run
will score for itself. Inputs are validated through journal_schema.py — a
truncated or hand-edited file fails loudly with a line-numbered error, not
silently as a skewed trace.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import List, Tuple

from ..journal import KIND_POD
from ..journal_schema import JournalSchemaError, event_errors, load_journal
from ..logsetup import get_logger
from .primitives import Primitive, ScenarioContext

log = get_logger("replay")


@dataclass
class ReplayTrace(Primitive):
    """Re-present a recorded arrival trace: one desired-count increment per
    recorded pod `created` event, spaced by the recorded inter-arrival gaps
    divided by `compress` (2.0 = twice as fast). The schedule is fixed at
    construction, so two replays of one journal present identical load."""

    # (delay-seconds-after-previous-arrival, recorded pod name), already
    # clock-compressed; first entry's delay is measured from the primitive's
    # own start (the `offset` field schedules that, like every primitive)
    arrivals: List[Tuple[float, str]] = field(default_factory=list)
    compress: float = 1.0
    source: str = ""  # provenance: where the trace came from
    source_digest: str = ""  # sha256[:16] of the arrival schedule

    @classmethod
    def from_events(cls, events, compress: float = 1.0, offset: float = 0.0, source: str = "") -> "ReplayTrace":
        """Build from decoded journal events (already schema-validated when
        they came through load_journal; raw lists are re-checked here)."""
        if compress <= 0:
            raise ValueError(f"compress must be positive, got {compress}")
        errs: List[str] = []
        for i, event in enumerate(events):
            errs.extend(event_errors(event, where=f"events[{i}]"))
        if errs:
            raise JournalSchemaError(source or "<events>", errs)
        created = [e for e in events if e["kind"] == KIND_POD and e["event"] == "created"]
        created.sort(key=lambda e: (e["t"], e["seq"]))
        arrivals: List[Tuple[float, str]] = []
        prev_t = None
        for event in created:
            delay = 0.0 if prev_t is None else (event["t"] - prev_t) / compress
            arrivals.append((round(delay, 6), event["entity"]))
            prev_t = event["t"]
        digest = hashlib.sha256(json.dumps(arrivals).encode()).hexdigest()[:16]
        return cls(offset=offset, arrivals=arrivals, compress=compress, source=source, source_digest=digest)

    @classmethod
    def from_journal(cls, path: str, compress: float = 1.0, offset: float = 0.0) -> "ReplayTrace":
        """Build from a journal JSONL file (the campaign spool, or any
        schema-valid trace); validation failures raise line-numbered."""
        return cls.from_events(load_journal(path), compress=compress, offset=offset, source=path)

    def schedule(self) -> List[Tuple[float, str]]:
        """The arrival schedule: (delay-after-previous, recorded name) in
        recorded order — inter-arrival structure preserved, compressed."""
        return list(self.arrivals)

    def total_seconds(self) -> float:
        """Compressed span from the first arrival to the last."""
        return sum(delay for delay, _ in self.arrivals)

    def run(self, ctx: ScenarioContext) -> None:
        log.info(
            "replay: %d recorded arrivals over %.2fs (compress %.1fx, source %s)",
            len(self.arrivals), self.total_seconds(), self.compress, self.source or "inline",
        )
        for delay, _name in self.arrivals:
            if delay > 0 and ctx.sleep(delay):
                return
            ctx.add_desired(1)

    def config(self) -> dict:
        """Provenance payload: the schedule is summarized by digest — a
        thousand-arrival trace must not inline itself into the config hash
        block, but two artifacts compare equal iff they replayed the same
        schedule at the same compression."""
        return {
            "kind": type(self).__name__,
            "offset": self.offset,
            "arrivals": len(self.arrivals),
            "total_seconds": round(self.total_seconds(), 6),
            "compress": self.compress,
            "source": self.source,
            "source_digest": self.source_digest,
        }
