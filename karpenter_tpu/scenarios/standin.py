"""Workload stand-in: kubelet + kube-scheduler + ReplicaSet, minimally.

The generalized form of the cluster stand-in the interruption- and
disruption-storm tests each hand-rolled: a thread that

- flips freshly launched nodes Ready (the kubelet),
- binds pending pods first-fit onto schedulable live capacity (the
  kube-scheduler) — live meaning the backing instance still exists,
- reconciles the replica count to the scenario's mutable `desired`
  (the ReplicaSet controller), scaling down pending-first so shrink waves
  exercise the deleted-while-Pending SLO path.

Everything else — provisioning new capacity, draining interrupted nodes,
replacing drifted ones — is the Runtime's job; the stand-in only plays the
cluster around it.
"""

from __future__ import annotations

import itertools
import random
import threading
from typing import Optional

from ..api.objects import Container, NodeCondition, ObjectMeta, OwnerReference, Pod, PodCondition, PodSpec, PodStatus, ResourceRequirements
from ..logsetup import get_logger
from .primitives import ScenarioContext

log = get_logger("standin")

_counter = itertools.count(1)


def workload_pod(cpu: float, app: str = "scenario") -> Pod:
    """A pending, unschedulable, ReplicaSet-owned pod (the provisionable
    shape, without importing test fixtures into the package)."""
    name = f"load-{next(_counter):06d}"
    return Pod(
        metadata=ObjectMeta(
            name=name,
            namespace="default",
            labels={"app": app},
            owner_references=[OwnerReference(kind="ReplicaSet", name=f"{app}-rs")],
        ),
        spec=PodSpec(
            containers=[Container(resources=ResourceRequirements(requests={"cpu": cpu, "memory": 256 * 2**20}))]
        ),
        status=PodStatus(
            phase="Pending",
            conditions=[PodCondition(type="PodScheduled", status="False", reason="Unschedulable")],
        ),
    )


def pod_cpu_request(pod) -> float:
    return sum(c.resources.requests.get("cpu", 0.0) for c in pod.spec.containers)


def live_pods(kube):
    return [p for p in kube.list_pods() if p.status.phase not in ("Succeeded", "Failed")]


class WorkloadStandIn(threading.Thread):
    def __init__(self, ctx: ScenarioContext, tick_interval: float = 0.1, app: str = "scenario", jitter_seed: Optional[int] = None):
        super().__init__(daemon=True, name="workload-standin")
        self.ctx = ctx
        self.tick_interval = tick_interval
        self.app = app
        # seeded tick jitter (the kubelet/scheduler never tick on a metronome):
        # +-30% per tick from the scenario's fanned-out master seed, so the
        # stand-in's interleaving is part of the one-number reproducibility
        # story instead of an unseeded source of run-to-run drift
        self._jitter = random.Random(jitter_seed) if jitter_seed is not None else None

    def _tick_timeout(self) -> float:
        if self._jitter is None:
            return self.tick_interval
        return self.tick_interval * self._jitter.uniform(0.7, 1.3)

    def run(self) -> None:
        while not self.ctx.stop.wait(timeout=self._tick_timeout()):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - the stand-in must survive races with the runtime
                log.debug("stand-in tick lost a race with the runtime; retrying next tick", exc_info=True)

    def tick(self) -> None:
        ctx = self.ctx
        nodes = ctx.kube.list_nodes()
        # kubelet: flip Ready
        for node in nodes:
            if not node.ready():
                node.status.conditions = [NodeCondition(type="Ready", status="True")]
                try:
                    ctx.kube.update(node)
                except Exception as err:  # noqa: BLE001 - lost update race with a controller
                    log.debug("kubelet stand-in ready-flip lost an update race on %s: %s", node.name, err)
        # kube-scheduler: first-fit cpu onto schedulable live capacity
        usable = []
        for node in nodes:
            if node.spec.unschedulable or node.metadata.deletion_timestamp is not None:
                continue
            instance_id = node.spec.provider_id.split("///", 1)[-1]
            if not ctx.backend.instance_exists(instance_id):
                continue
            used = sum(pod_cpu_request(p) for p in ctx.kube.pods_on_node(node.name))
            usable.append([node, node.status.allocatable.get("cpu", 0.0) - used])
        pods = live_pods(ctx.kube)
        for pod in pods:
            if pod.spec.node_name:
                continue
            need = pod_cpu_request(pod)
            for slot in usable:
                if slot[1] >= need:
                    try:
                        ctx.kube.bind_pod(pod, slot[0].name)
                    except Exception as err:  # noqa: BLE001 - pod deleted under us
                        log.debug("scheduler stand-in bind of %s raced a delete: %s", pod.metadata.name, err)
                        break
                    slot[1] -= need
                    break
        # ReplicaSet: reconcile to desired, both directions
        desired = ctx.desired
        pods = live_pods(ctx.kube)
        deficit = desired - len(pods)
        for _ in range(max(0, deficit)):
            ctx.kube.create(workload_pod(ctx.pod_cpu, app=self.app))
        if deficit < 0:
            # shrink pending-first (a ramp-down cancels queued work before
            # killing running replicas — and exercises the SLO rule that a
            # pod deleted while Pending observes nothing)
            doomed = sorted(pods, key=lambda p: (bool(p.spec.node_name), p.metadata.creation_timestamp))
            for pod in doomed[: -deficit]:
                ctx.kube.delete(pod, grace=False)
