"""Scenario campaign harness: composable load generation + SLO scoring.

See primitives.py (the load/chaos primitives and Scenario composition),
standin.py (the kubelet/scheduler/ReplicaSet stand-in), campaign.py (the
runner emitting scored SCENARIO_*.json on both transports),
chaos_orchestrator.py (the seeded cross-domain chaos schedule, the soak
tier, and the ddmin schedule shrinker), and schema.py (the artifact
validator shared with the tier-1 smoke test).
"""

from .campaign import (
    TRANSPORTS,
    CampaignRunner,
    chaos_soak_scenario,
    default_campaign,
    mini_soak_scenario,
    smoke_campaign,
)
from .chaos_orchestrator import (
    ChaosEvent,
    ChaosSchedule,
    Soak,
    ddmin,
    diurnal_trace,
    replay_failing_schedule,
    shrink_doc,
    shrink_doc_errors,
    shrink_failing_schedule,
    write_shrink,
)
from .primitives import (
    Burst,
    DiurnalRamp,
    DriftRollout,
    LeaseSteal,
    PoolCapacity,
    Primitive,
    ProcessCrash,
    ScaleTo,
    Scenario,
    ScenarioContext,
    SpotReclaimWave,
    TransportChaos,
    WatchGap,
)
from .replay import ReplayTrace
from .schema import scenario_doc_errors
from .standin import WorkloadStandIn, workload_pod

__all__ = [
    "TRANSPORTS",
    "CampaignRunner",
    "chaos_soak_scenario",
    "default_campaign",
    "mini_soak_scenario",
    "smoke_campaign",
    "ChaosEvent",
    "ChaosSchedule",
    "Soak",
    "ddmin",
    "diurnal_trace",
    "replay_failing_schedule",
    "shrink_doc",
    "shrink_doc_errors",
    "shrink_failing_schedule",
    "write_shrink",
    "Burst",
    "DiurnalRamp",
    "DriftRollout",
    "LeaseSteal",
    "PoolCapacity",
    "Primitive",
    "ProcessCrash",
    "ReplayTrace",
    "ScaleTo",
    "Scenario",
    "ScenarioContext",
    "SpotReclaimWave",
    "TransportChaos",
    "WatchGap",
    "scenario_doc_errors",
    "WorkloadStandIn",
    "workload_pod",
]
