"""Scenario campaign harness: composable load generation + SLO scoring.

See primitives.py (the load/chaos primitives and Scenario composition),
standin.py (the kubelet/scheduler/ReplicaSet stand-in), campaign.py (the
runner emitting scored SCENARIO_*.json on both transports), and schema.py
(the artifact validator shared with the tier-1 smoke test).
"""

from .campaign import TRANSPORTS, CampaignRunner, default_campaign, smoke_campaign
from .primitives import (
    Burst,
    DiurnalRamp,
    DriftRollout,
    LeaseSteal,
    PoolCapacity,
    Primitive,
    ProcessCrash,
    ScaleTo,
    Scenario,
    ScenarioContext,
    SpotReclaimWave,
    TransportChaos,
    WatchGap,
)
from .replay import ReplayTrace
from .schema import scenario_doc_errors
from .standin import WorkloadStandIn, workload_pod

__all__ = [
    "TRANSPORTS",
    "CampaignRunner",
    "default_campaign",
    "smoke_campaign",
    "Burst",
    "DiurnalRamp",
    "DriftRollout",
    "LeaseSteal",
    "PoolCapacity",
    "Primitive",
    "ProcessCrash",
    "ReplayTrace",
    "ScaleTo",
    "Scenario",
    "ScenarioContext",
    "SpotReclaimWave",
    "TransportChaos",
    "WatchGap",
    "scenario_doc_errors",
    "WorkloadStandIn",
    "workload_pod",
]
