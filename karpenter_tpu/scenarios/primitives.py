"""Scenario primitives: composable load/chaos generators for a live Runtime.

Each primitive drives ONE aspect of production pressure against the running
Runtime + simulated cloud — traffic shape (bursts, diurnal ramps), capacity
loss (spot reclaim waves), config churn (drift rollouts mid-storm), and
degraded infrastructure (injected transport latency / apiserver throttling).
A `Scenario` composes several primitives on a shared timeline; the campaign
runner (campaign.py) executes them against a real Runtime on either
transport and scores the outcome.

These generalize the hand-rolled seams of the interruption-storm and
disruption-storm tests: the workload stand-in (standin.py) plays kubelet /
kube-scheduler / ReplicaSet, primitives mutate the desired replica count and
the cloud, and the Runtime does everything else.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from ..api import labels as lbl
from ..logsetup import get_logger

log = get_logger("scenarios")


class ScenarioContext:
    """Everything a primitive may touch while a scenario runs."""

    def __init__(self, kube, backend, runtime, service=None, pod_cpu: float = 0.5, runtime_factory=None):
        self.kube = kube
        self.backend = backend  # the in-process CloudBackend (faults/reclaims)
        self.runtime = runtime
        self.service = service  # CloudAPIService on the http transport, else None
        self.pod_cpu = pod_cpu
        # crash/restart seam: builds a FRESH (un-started) Runtime over the
        # same kube + cloud — what the ProcessCrash primitive restarts into
        self.runtime_factory = runtime_factory
        self.restarts = 0
        # stamped by SpotReclaimWave: kube-clock instant the wave fired, so
        # predicates can scope assertions to REPLACEMENT nodes (a survivor
        # legitimately keeps running inside a quarantined pool)
        self.reclaim_started_at: Optional[float] = None
        # stamped by the campaign runner at run start: the process-lifetime
        # chunked-rung counter is monotonic, so settled predicates must
        # score this run's delta, not the absolute (a prior run in the same
        # process would pre-satisfy the bar)
        self.solver_chunked_at_start = 0
        # same run-start stamping for the incremental engine's monotonic
        # delta-pass counter (the soak settled predicate scores the delta)
        self.incremental_delta_at_start = 0
        # run-start stamps for the residency auditor's monotonic counters
        # (solver/audit.py): scores and settled predicates read this run's
        # divergence/heal/audit deltas, not process-lifetime absolutes
        self.residency_divergences_at_start = 0
        self.residency_heals_at_start = 0
        self.audit_passes_at_start = 0
        self.stop = threading.Event()
        self._lock = threading.Lock()
        self._desired = 0

    def crash_runtime(self) -> None:
        """Kill the live control plane and boot a successor: the old
        Runtime's threads halt with no graceful cleanup (its ledger, command
        queue, and dedupe memory die with it), then a new Runtime runs its
        startup reconstruction — resync, ledger recovery, GC sweep — against
        whatever the crash left behind."""
        if self.runtime_factory is None:
            raise RuntimeError("scenario context has no runtime_factory; crash/restart unavailable")
        old = self.runtime
        old.crash()
        successor = self.runtime_factory()
        self.runtime = successor
        successor.start()
        with self._lock:
            self.restarts += 1
        log.info("process crash #%d: control plane restarted", self.restarts)

    @property
    def desired(self) -> int:
        with self._lock:
            return self._desired

    @desired.setter
    def desired(self, value: int) -> None:
        with self._lock:
            self._desired = max(0, int(value))

    def add_desired(self, delta: int) -> int:
        """Atomic relative adjustment: primitives run on their own threads,
        so `ctx.desired = ctx.desired + n` is a torn read-modify-write when
        two of them fire together (a Burst during a DiurnalRamp step)."""
        with self._lock:
            self._desired = max(0, self._desired + int(delta))
            return self._desired

    def sleep(self, seconds: float) -> bool:
        """Interruptible sleep; True when the scenario was stopped."""
        return self.stop.wait(timeout=seconds)


@dataclass
class Primitive:
    """Base: `offset` schedules the primitive on the scenario timeline."""

    offset: float = 0.0

    def run(self, ctx: ScenarioContext) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def config(self) -> dict:
        return {"kind": type(self).__name__, **{k: v for k, v in vars(self).items() if not k.startswith("_")}}


@dataclass
class Burst(Primitive):
    """Raise the desired replica count by `count` in one step — the sharp
    edge of a deploy or an HPA overreaction."""

    count: int = 10

    def run(self, ctx: ScenarioContext) -> None:
        log.info("burst: desired -> %d", ctx.add_desired(self.count))


@dataclass
class ScaleTo(Primitive):
    """Set the desired replica count absolutely (ramp-down included)."""

    count: int = 0

    def run(self, ctx: ScenarioContext) -> None:
        ctx.desired = self.count


@dataclass
class OutOfBandBind(Primitive):
    """Create a pod already BOUND to live capacity, bypassing both the
    provisioner and the stand-in scheduler — the way a second scheduler, a
    static pod, or a manual bind lands in the informer. The solver never
    planned this placement, so the incremental engine's resident mirror can
    only learn it from the DeltaJournal record — which makes this the one
    bind whose SUPPRESSED record is detectable (suppressing a solver-planned
    bind is a no-op: the engine rebases its own placements into the mirror
    before the record ever matters). The residency storm aims its
    dropped-delta injection here."""

    cpu: float = 0.1
    app: str = "oob"

    def run(self, ctx: ScenarioContext) -> None:
        from .standin import pod_cpu_request, workload_pod

        for node in ctx.kube.list_nodes():
            if node.spec.unschedulable or node.metadata.deletion_timestamp is not None or not node.ready():
                continue
            used = sum(pod_cpu_request(p) for p in ctx.kube.pods_on_node(node.name))
            if node.status.allocatable.get("cpu", 0.0) - used < self.cpu:
                continue
            pod = workload_pod(self.cpu, app=self.app)
            pod.spec.node_name = node.name
            pod.status.phase = "Running"
            pod.status.conditions = []
            ctx.kube.create(pod)
            # the ReplicaSet stand-in reconciles ALL live pods against
            # `desired`: account for the interloper or the next tick would
            # evict a scenario replica to compensate
            ctx.add_desired(1)
            log.info(
                "out-of-band bind: %s -> %s (%.2f cpu, no solver involvement)",
                pod.metadata.name, node.name, self.cpu,
            )
            return
        log.warning("out-of-band bind found no schedulable spare capacity; skipped")


@dataclass
class DiurnalRamp(Primitive):
    """Traffic follows a half-cosine day: base -> base+peak -> base over
    `period` seconds, re-evaluated every `step`. `cycles` repeats it.

    The ramp owns only its own CONTRIBUTION to the desired count (applied
    through atomic deltas), so composing it with a concurrent Burst adds the
    two loads instead of the ramp's next step erasing the burst."""

    base: int = 5
    peak: int = 20
    period: float = 8.0
    step: float = 0.25
    cycles: int = 1

    def run(self, ctx: ScenarioContext) -> None:
        contribution = 0

        def set_contribution(value: int) -> None:
            nonlocal contribution
            ctx.add_desired(value - contribution)
            contribution = value

        start = time.monotonic()
        total = self.period * self.cycles
        while not ctx.stop.is_set():
            t = time.monotonic() - start
            if t >= total:
                break
            phase = (t % self.period) / self.period
            set_contribution(self.base + int(round(self.peak * 0.5 * (1 - math.cos(2 * math.pi * phase)))))
            if ctx.sleep(self.step):
                return
        set_contribution(self.base)


@dataclass
class PoolCapacity(Primitive):
    """Give every (zone x capacity-type) pool of `instance_type` FINITE
    remaining capacity (`capacity` launches each; 0 = exhausted now), or
    restore them to infinite with capacity=None — the capacity-crunch seam.
    `capacity_types`/`zones` narrow the affected pools (e.g. collapse only
    the spot side of a type)."""

    instance_type: str = ""
    capacity: Optional[int] = None
    zones: Optional[List[str]] = None  # default: every backend zone
    capacity_types: Optional[List[str]] = None  # default: spot + on-demand

    def run(self, ctx: ScenarioContext) -> None:
        zones = self.zones or [s.zone for s in ctx.backend.subnets]
        capacity_types = self.capacity_types or ["spot", "on-demand"]
        for zone in zones:
            for ct in capacity_types:
                ctx.backend.set_pool_capacity(self.instance_type, zone, ct, self.capacity)
        log.info(
            "pool capacity: %s -> %s across %d pool(s)",
            self.instance_type,
            "infinite" if self.capacity is None else self.capacity,
            len(zones) * len(capacity_types),
        )


@dataclass
class SpotReclaimWave(Primitive):
    """Interrupt a fraction of populated nodes at once with a short reclaim
    window — the correlated spot-capacity loss shape. The campaign's
    reclaimer thread makes the cloud good on the warnings."""

    fraction: float = 0.5
    warning_seconds: float = 1.5
    max_victims: int = 8

    def run(self, ctx: ScenarioContext) -> None:
        populated = [n for n in ctx.kube.list_nodes() if ctx.kube.pods_on_node(n.name)]
        victims = populated[: max(1, min(self.max_victims, int(len(populated) * self.fraction)))]
        ids = [n.spec.provider_id.split("///", 1)[-1] for n in victims]
        log.info("spot reclaim wave: interrupting %d/%d nodes", len(ids), len(populated))
        ctx.reclaim_started_at = ctx.kube.clock.now()
        for instance_id in ids:
            ctx.backend.interrupt_spot_instance(instance_id, warning_seconds=self.warning_seconds)


@dataclass
class DriftRollout(Primitive):
    """Mutate the provisioner spec mid-storm (a label rollout): every
    existing node's stamped provisioner-hash goes stale, the disruption
    orchestrator's drift method replaces them under the budget."""

    provisioner: str = "default"
    label_key: str = "rollout"
    label_value: str = "v2"

    def run(self, ctx: ScenarioContext) -> None:
        provisioner = ctx.kube.get("Provisioner", self.provisioner, namespace="")
        if provisioner is None:
            log.warning("drift rollout: provisioner %s not found", self.provisioner)
            return
        provisioner.spec.labels[self.label_key] = self.label_value
        ctx.kube.update(provisioner)
        log.info("drift rollout: provisioner %s labeled %s=%s", self.provisioner, self.label_key, self.label_value)


@dataclass
class TransportChaos(Primitive):
    """Degrade the cloud control plane for `duration` seconds: sustained
    API latency on the in-process transport, plus per-request delay and 429
    throttling on the HTTP transport (apiclient retries with backoff)."""

    latency_seconds: float = 0.15
    duration: float = 3.0
    delayed_requests: int = 40
    throttled_requests: int = 8

    def run(self, ctx: ScenarioContext) -> None:
        log.info("transport chaos: +%.0fms API latency for %.1fs", self.latency_seconds * 1000, self.duration)
        ctx.backend.inject_api_latency(self.latency_seconds)
        if ctx.service is not None:
            ctx.service.delay_next(self.delayed_requests, self.latency_seconds)
            ctx.service.throttle_next(self.throttled_requests)
        ctx.sleep(self.duration)
        ctx.backend.inject_api_latency(0.0)


@dataclass
class WatchGap(Primitive):
    """Kill the informers' watch delivery for `duration` seconds — the
    control-plane fault domain's connection-drop shape. On the in-memory
    transport the gap buffers dispatch (a killed stream's events wait in
    the server journal); with `compact=True` a forced journal compaction
    fires mid-gap, so closing the gap delivers a relist diff instead of a
    replay — the 410-Gone path. The gap ALWAYS closes, even when the
    scenario is stopped mid-gap (a gap leaking past its run would wedge
    every later scenario on the shared store)."""

    duration: float = 0.8
    compact: bool = False

    def run(self, ctx: ScenarioContext) -> None:
        log.info("watch gap: %.1fs%s", self.duration, " + forced compaction" if self.compact else "")
        ctx.kube.chaos_watch_gap_begin()
        try:
            if self.compact:
                if not ctx.sleep(self.duration / 2):
                    ctx.kube.chaos_compact()
                    ctx.sleep(self.duration / 2)
            else:
                ctx.sleep(self.duration)
        finally:
            ctx.kube.chaos_watch_gap_end()


@dataclass
class LeaseSteal(Primitive):
    """Steal the leader-election lease out from under the live control
    plane: a legal competing CAS overwrites the holder, the deposed leader
    must pause its singleton loops on its next renew round, and — since the
    thief never renews — a real candidate re-acquires after the lease
    duration and runs recovery before acting. The leader-flap storm fires
    this twice mid-drift-rollout."""

    thief: str = "chaos-thief"

    def run(self, ctx: ScenarioContext) -> None:
        from ..kube.leaderelection import steal_lease

        elector = getattr(ctx.runtime, "elector", None)
        if elector is None:
            log.warning("lease steal: runtime has no elector")
            return
        stolen = steal_lease(ctx.kube, identity=self.thief, name=elector.name, namespace=elector.namespace)
        log.info("lease steal by %s: %s", self.thief, "landed" if stolen else "no lease to steal")


@dataclass
class ProcessCrash(Primitive):
    """Kill -9 the control plane `times` times, `interval` seconds apart,
    starting at `offset` — timed by the composer to land mid-provision or
    mid-disruption. Each crash tears down the live Runtime with no graceful
    cleanup and boots a successor through its startup reconstruction
    (cluster resync, disruption-ledger recovery, GC sweep). Everything the
    scenario scores — zero leaked instances, zero lost pods, budget
    invariants — must hold ACROSS the restarts, which is the whole point."""

    times: int = 1
    interval: float = 2.0

    def run(self, ctx: ScenarioContext) -> None:
        for i in range(self.times):
            if i and ctx.sleep(self.interval):
                return
            ctx.crash_runtime()


@dataclass
class Scenario:
    """A named composition of primitives on one timeline."""

    name: str
    desired: int  # starting replica count (the stand-in reconciles to it)
    duration: float  # timeline length before the convergence wait begins
    primitives: List[Primitive] = field(default_factory=list)
    pod_cpu: float = 0.5
    budget_nodes: Optional[str] = None  # e.g. "40%" -> spec.disruption.budgets
    # restricting the provisioner to small shapes spreads the workload over
    # several nodes — what makes percentage budgets and reclaim fractions
    # meaningful (22 pods on one 96-cpu node give a 30% budget of zero)
    instance_types: Optional[List[str]] = None
    ttl_seconds_after_empty: Optional[float] = 2.0
    # spec.consolidation.enabled on the provisioner (mutually exclusive with
    # ttlSecondsAfterEmpty — set that to None when enabling this): the
    # consolidation-on diurnal variant pins the post-ramp cost drift
    consolidation: bool = False
    # override for the provider's unavailable-offerings TTL: the
    # capacity-crunch scenarios need the quarantine to expire (and the
    # exhausted pool to be re-selected) INSIDE the scenario window, or —
    # for the spot-collapse variant — to outlive it
    offering_ttl: Optional[float] = None
    # extra convergence condition beyond "every pod bound to live capacity"
    # (e.g. the drift scenario waits until no node carries a stale spec
    # hash); not part of the config hash — predicates describe WHEN the run
    # may stop, not WHAT it did
    settled: Optional[Callable[[ScenarioContext], bool]] = None
    # solver fault-domain seams (solver/faults.py): dense_solver=True runs
    # the scenario's Runtime with the dense device path on (min_batch=1, so
    # every provisioning batch dispatches); fault_specs is a list of
    # FaultSpec dicts installed as a seeded FaultPlan for the whole run —
    # the device-chaos scenarios inject exactly the typed fault class they
    # claim to test, deterministically. The breaker/budget knobs mirror the
    # --solver-breaker-threshold / --solver-breaker-backoff /
    # --solver-hbm-budget runtime flags on the scenario's timescale.
    dense_solver: bool = False
    # incremental solve engine (solver/incremental.py, --solver-incremental):
    # the scenario's Runtime keeps the warm-view encoding device-resident
    # across provision passes and applies journal deltas in place. The soak
    # tier runs with it ON — its settled predicate then requires the engine
    # to have ENGAGED (delta passes taken) and the solve-latency p95 to stay
    # FLAT as the cluster grows at fixed per-tick delta
    solver_incremental: bool = False
    fault_specs: Optional[List[dict]] = None
    # residency auditor (solver/audit.py, --residency-audit-interval): audit
    # every Nth incremental pass against re-encoded cluster truth; 0 = off.
    # Scenarios that turn it on score residency_divergences/heals/audit_passes
    # — healthy runs pin divergences at 0, the storm requires them to equal
    # its injections
    residency_audit_interval: int = 0
    # per-kind capsule capture debounce override (None = the campaign's
    # default): the residency storm injects two distinct corruptions close
    # together and needs BOTH residency-divergence captures inside its window
    capsule_debounce_seconds: Optional[float] = None
    # seed fan-out (utils/seeds.py): `seed` is the ONE master knob — the
    # solver fault seed, the kube fault seed, the stand-in's jitter, and a
    # chaos schedule's streams all derive from it splitmix-style, so two
    # runs of any scenario are reproducible from one number. The per-seam
    # overrides (None = derive) exist for unit tests that pin one seam; a
    # scenario that sets them independently re-opens the drift this closes.
    seed: int = 0
    fault_seed: Optional[int] = None
    solver_breaker_threshold: int = 3
    solver_breaker_backoff: float = 1.5
    solver_hbm_budget_bytes: int = 0
    # control-plane fault-domain seams (kube/chaos.py): kube_fault_specs is
    # a list of KubeFaultSpec dicts installed as a seeded KubeFaultPlan for
    # the whole run (conflict storms, stale reads, watch drops — injected
    # deterministically on the kube verb boundaries); leader_elect runs the
    # scenario's Runtime behind real Lease election (with the campaign's
    # short lease timing) so LeaseSteal primitives have a leader to depose
    kube_fault_specs: Optional[List[dict]] = None
    kube_fault_seed: Optional[int] = None
    leader_elect: bool = False
    description: str = ""

    def derived_seeds(self) -> dict:
        """Every consumer seed, fanned out from the master (or pinned by an
        explicit override) — recorded in provenance so the artifact itself
        says how to reproduce the run."""
        from ..utils.seeds import split_seed

        return {
            "fault_seed": self.fault_seed if self.fault_seed is not None else split_seed(self.seed, "solver.faults"),
            "kube_fault_seed": (
                self.kube_fault_seed if self.kube_fault_seed is not None else split_seed(self.seed, "kube.chaos")
            ),
            "standin_jitter_seed": split_seed(self.seed, "standin.jitter"),
            "chaos_schedule_seed": split_seed(self.seed, "chaos.schedule"),
            "audit_seed": split_seed(self.seed, "solver.audit"),
        }

    def config(self) -> dict:
        """The provenance config-hash payload: everything that shapes the
        run, so two SCENARIO artifacts are comparable iff hashes match."""
        return {
            "name": self.name,
            "kind": "standard",
            "seed": self.seed,
            "derived_seeds": self.derived_seeds(),
            "desired": self.desired,
            "duration": self.duration,
            "pod_cpu": self.pod_cpu,
            "budget_nodes": self.budget_nodes,
            "instance_types": self.instance_types,
            "ttl_seconds_after_empty": self.ttl_seconds_after_empty,
            "consolidation": self.consolidation,
            "offering_ttl": self.offering_ttl,
            "dense_solver": self.dense_solver,
            "solver_incremental": self.solver_incremental,
            "residency_audit_interval": self.residency_audit_interval,
            "capsule_debounce_seconds": self.capsule_debounce_seconds,
            "fault_specs": self.fault_specs,
            "fault_seed": self.fault_seed,
            "solver_breaker_threshold": self.solver_breaker_threshold,
            "solver_breaker_backoff": self.solver_breaker_backoff,
            "solver_hbm_budget_bytes": self.solver_hbm_budget_bytes,
            "kube_fault_specs": self.kube_fault_specs,
            "kube_fault_seed": self.kube_fault_seed,
            "leader_elect": self.leader_elect,
            "primitives": [p.config() for p in self.primitives],
        }
