"""Campaign runner: execute scored scenarios against a live Runtime.

One campaign = a list of composed scenarios (primitives.py), each run
against a REAL Runtime — its own threads, batcher, interruption poll loop,
disruption orchestrator — on one or both cloud transports (the in-process
CloudBackend and the HTTP CloudAPIService/Client pair), with the workload
stand-in (standin.py) playing the cluster around it.

Each scenario emits one `SCENARIO_<name>.json` next to the BENCH_*.json
artifacts: a provenance block (git SHA, timestamp, config hash), per-run
scores (pending-latency p50/p95/p99 per provisioner, time-to-node-ready,
cluster $/hr, cost-drift ratio vs the ideal fresh repack, lost pods, budget
violations, churn counters), and a monotonic sample timeline. Every emitted
document is self-validated against schema.py before it lands on disk, so a
malformed artifact is a crash at emit time, not a silent gap at bisect time.

    python -m karpenter_tpu.scenarios.campaign --out . --transports inprocess,http

Behavioral regressions — pending latency creeping under churn, cost drift
after a reclaim wave — are now diffable artifacts, the way solve-time
regressions have been since bench.py grew per-phase JSON.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from .. import capsule, flight, invariants, journal, slo
from ..kube import chaos as kube_chaos
from ..kube.coherence import COHERENCE
from ..solver import audit as solver_audit
from ..solver import faults as solver_faults
from ..utils.seeds import split_seed
from ..api import labels as lbl
from ..api.objects import NodeSelectorRequirement, ObjectMeta, OP_IN
from ..api.provisioner import Budget, Consolidation, Disruption, Provisioner, ProvisionerSpec
from ..cloudprovider.simulated.backend import CloudBackend
from ..cloudprovider.simulated.provider import SimulatedCloudProvider
from ..controllers.disruption.budgets import allowed_disruptions
from ..kube.cluster import KubeCluster
from ..logsetup import get_logger
from ..provenance import provenance_block
from ..runtime import Runtime
from ..utils.options import Options
from .chaos_orchestrator import ChaosSchedule, Soak, diurnal_trace
from .primitives import (
    Burst,
    DiurnalRamp,
    DriftRollout,
    LeaseSteal,
    OutOfBandBind,
    PoolCapacity,
    ProcessCrash,
    Scenario,
    ScenarioContext,
    SpotReclaimWave,
    TransportChaos,
    WatchGap,
)
from .schema import scenario_doc_errors
from .standin import WorkloadStandIn, live_pods

log = get_logger("campaign")

TRANSPORTS = ("inprocess", "http")


def _provisioner(scenario: Scenario) -> Provisioner:
    disruption = None
    if scenario.budget_nodes is not None:
        disruption = Disruption(budgets=[Budget(nodes=scenario.budget_nodes)])
    requirements = [
        NodeSelectorRequirement(
            key=lbl.LABEL_CAPACITY_TYPE,
            operator=OP_IN,
            values=[lbl.CAPACITY_TYPE_SPOT, lbl.CAPACITY_TYPE_ON_DEMAND],
        )
    ]
    if scenario.instance_types:
        requirements.append(
            NodeSelectorRequirement(key=lbl.LABEL_INSTANCE_TYPE, operator=OP_IN, values=list(scenario.instance_types))
        )
    return Provisioner(
        metadata=ObjectMeta(name="default", namespace=""),
        spec=ProvisionerSpec(
            requirements=requirements,
            # admission rejects consolidation + ttlSecondsAfterEmpty together
            ttl_seconds_after_empty=None if scenario.consolidation else scenario.ttl_seconds_after_empty,
            consolidation=Consolidation(enabled=True) if scenario.consolidation else None,
            disruption=disruption,
        ),
    )


def drift_settled(ctx: ScenarioContext) -> bool:
    """The drift scenario's extra convergence bar: every owned node carries
    the CURRENT provisioner spec hash (no survivor is stale) and the
    disruption ledger has drained — the rollout finished, not just paused."""
    from ..scheduling.nodetemplate import NodeTemplate

    provisioner = ctx.kube.get("Provisioner", "default", namespace="")
    if provisioner is None:
        return True
    current = NodeTemplate.from_provisioner(provisioner).spec_hash()
    for node in ctx.kube.list_nodes():
        if node.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL) != provisioner.name:
            continue
        if node.metadata.annotations.get(lbl.PROVISIONER_HASH_ANNOTATION) != current:
            return False
    disruption = ctx.runtime.disruption
    return disruption is None or disruption.tracker.total_in_flight() == 0


def _leaked_instances(ctx: ScenarioContext) -> int:
    """Cloud instances minus registered capacity: anything running at the
    cloud that no node object points at is paid-for capacity the cluster
    cannot use — the crash-between-launch-and-bind failure shape the GC
    sweep exists to reconcile away."""
    registered = {
        node.spec.provider_id.rsplit("/", 1)[-1] for node in ctx.kube.list_nodes() if node.spec.provider_id
    }
    return sum(1 for instance_id in list(ctx.backend.instances) if instance_id not in registered)


def consolidated_settled(ctx: ScenarioContext) -> bool:
    """The consolidation-on diurnal's convergence bar: the disruption ledger
    has drained AND an explicit drift re-solve prices the surviving fleet
    within 1.5x of the ideal fresh repack — ramp-down capacity was actually
    consolidated away, not merely left stranded (the PR 6 finding scored
    4.5x here with consolidation off)."""
    disruption = ctx.runtime.disruption
    if disruption is not None and disruption.tracker.total_in_flight() > 0:
        return False
    ctx.runtime.slo_metrics.scrape()
    ratio = ctx.runtime.slo_metrics.compute_drift()
    return ratio is not None and ratio <= 1.5


def _node_pool(node) -> tuple:
    labels = node.metadata.labels
    return (
        labels.get(lbl.LABEL_INSTANCE_TYPE),
        labels.get(lbl.LABEL_TOPOLOGY_ZONE),
        labels.get(lbl.LABEL_CAPACITY_TYPE),
    )


def capacity_recovered(ctx: ScenarioContext) -> bool:
    """The capacity-crunch convergence bar: the quarantine has fully
    expired (no offering is still marked unavailable) AND the newest owned
    node launched in the CHEAPEST (type, zone, capacity-type) pool — proof
    the exhausted pool was re-selected once its TTL lapsed, not permanently
    abandoned for the pricier fallback."""
    provider = ctx.runtime.cloud_provider  # metrics decorator forwards .unavailable
    if getattr(provider, "unavailable", None) is not None and provider.unavailable.snapshot():
        return False
    nodes = [
        n
        for n in ctx.kube.list_nodes()
        if n.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL) and n.metadata.deletion_timestamp is None
    ]
    if not nodes:
        return False
    od_books, spot_books = ctx.backend.describe_prices()

    def pool_price(pool: tuple) -> float:
        type_name, zone, ct = pool
        if ct == lbl.CAPACITY_TYPE_SPOT:
            return spot_books.get((type_name, zone), float("inf"))
        return od_books.get(type_name, float("inf"))

    newest = max(nodes, key=lambda n: n.metadata.creation_timestamp)
    # cheapest pool the fleet's type(s) could launch in (spot + od books)
    types = {n.metadata.labels.get(lbl.LABEL_INSTANCE_TYPE) for n in nodes}
    candidates = [(t, z, ct) for t in types for (t2, z) in spot_books if t2 == t for ct in (lbl.CAPACITY_TYPE_SPOT,)]
    candidates += [(t, s.zone, lbl.CAPACITY_TYPE_ON_DEMAND) for t in types for s in ctx.backend.subnets]
    cheapest = min(candidates, key=pool_price)
    return pool_price(_node_pool(newest)) <= pool_price(cheapest) + 1e-9


def avoids_unavailable_pools(ctx: ScenarioContext) -> bool:
    """The spot-collapse convergence bar: every node launched AFTER the
    reclaim wave avoids the quarantined pools (a pre-wave survivor may
    legitimately keep running inside one), and at least one such
    replacement exists. The offering TTL outlives the scenario, so
    convergence cannot ride a quarantine expiry."""
    if ctx.reclaim_started_at is None:
        return False  # the wave has not fired yet
    provider = ctx.runtime.cloud_provider
    unavailable = getattr(provider, "unavailable", None)
    quarantined = unavailable.snapshot() if unavailable is not None else set()
    if not quarantined:
        return False  # the interruption feed never marked the reclaimed pools
    replacements = [
        n for n in ctx.kube.list_nodes() if n.metadata.creation_timestamp > ctx.reclaim_started_at
    ]
    if not replacements:
        return False
    return all(_node_pool(n) not in quarantined for n in replacements)


def _unschedulable_pod_seconds(samples: List[dict]) -> float:
    """Integral of pending pods over the sample timeline (pod-seconds):
    the user-visible cost of a capacity crunch even when nothing is lost."""
    total = 0.0
    for prev, cur in zip(samples, samples[1:]):
        total += prev["pending_pods"] * max(0.0, cur["t"] - prev["t"])
    return round(total, 3)


def _launch_failures_total() -> int:
    """Process-wide launch-failure counter sum (all reasons); run_one
    snapshots it at start and scores the delta."""
    from ..metrics import REGISTRY

    counter = REGISTRY.get("karpenter_provisioning_launch_failures_total")
    return int(sum(counter.values().values())) if counter is not None else 0


def _solver_latency_p95():
    """p95 of real Scheduler.solve wall-clock this run (flight.py summary,
    reset at run start); None when the run never solved."""
    import math

    value = flight.SOLVE_LATENCY.quantile(0.95)
    return None if math.isnan(value) else round(value, 6)


# flatness bound the soak settled predicate enforces when the incremental
# engine is on: the second-half solve p95 may not exceed twice the
# first-half p95 — O(delta) steady state means latency tracks the per-tick
# delta, not the grown cluster (generous enough for CPU-sim timing noise,
# far below the drift a per-pass full re-encode of a growing cluster shows)
SOAK_P95_FLATNESS_BOUND = 2.0


def _solver_latency_p95_flatness():
    """Late/early solve-latency ratio this run: p95 of the second half of
    the real Scheduler.solve observations over p95 of the first half. ~1.0
    means flat — the incremental engine's O(delta) steady-state claim as
    the cluster grows at fixed per-tick delta. None when the run solved too
    little to window (fewer than 8 observations)."""
    obs = flight.SOLVE_LATENCY.observations()
    if len(obs) < 8:
        return None

    def p95(values):
        ordered = sorted(values)
        return ordered[min(len(ordered) - 1, int(0.95 * len(ordered)))]

    early, late = p95(obs[: len(obs) // 2]), p95(obs[len(obs) // 2 :])
    if early <= 0.0:
        return None
    return round(late / early, 4)


def _incremental_delta_passes() -> int:
    """Process-wide count of incremental-engine delta passes — provision
    passes whose encode+fill the resident state skipped
    (solver/incremental.py). run_one snapshots this at start and scores the
    run's delta as `encode_skipped_passes`; reads through the registry so
    a host-loop run that never imported the engine scores 0."""
    from ..metrics import REGISTRY

    counter = REGISTRY.get("karpenter_solver_incremental_passes_total")
    return int(counter.value(kind="delta")) if counter is not None else 0


def breaker_reclosed(ctx: ScenarioContext) -> bool:
    """The device-fault-storm convergence bar: at least one planned fault
    fired (the plan carries one spec per dispatch flavor, so only the active
    flavor's triggers are consumable), repeated faults actually opened the
    circuit breaker (the device attempt stopped being paid), and a half-open
    recovery probe has since re-admitted the fast path — CLOSED at
    convergence, not permanently abandoned."""
    plan = solver_faults.FAULTS.plan
    if plan is None or plan.fired() < 1:
        return False
    breaker = solver_faults.BREAKER
    if breaker.opened_total < 1 or breaker.state != solver_faults.STATE_CLOSED:
        return False
    # the breaker trip must have produced an incident capsule: the storm's
    # acceptance bar includes the evidence, not just the recovery
    return bool(capsule.CAPSULE.fingerprints().get(capsule.TRIGGER_BREAKER_OPEN))


def hbm_degraded_settled(ctx: ScenarioContext) -> bool:
    """The hbm-pressure convergence bar: the planned HBM faults fired, THIS
    run's chunked-solve rung absorbed the pressure (the counter is process-
    lifetime monotonic — score the delta over the run-start stamp, or a
    prior run in the same process pre-satisfies the bar), and the breaker
    NEVER opened — memory pressure is degradation, not an outage."""
    plan = solver_faults.FAULTS.plan
    if plan is None or plan.fired() < 1:
        return False
    chunked = solver_faults.DEGRADED_SOLVES.value(rung=solver_faults.RUNG_CHUNKED) - ctx.solver_chunked_at_start
    breaker = solver_faults.BREAKER
    return chunked >= 1 and breaker.opened_total == 0 and breaker.state == solver_faults.STATE_CLOSED


def leader_flap_settled(ctx: ScenarioContext) -> bool:
    """The leader-flap-storm convergence bar: both steals actually landed
    and were recovered from (each steal bumps lease_transitions once, each
    rightful re-acquisition bumps again -> >= 4), the runtime's elector
    holds the lease AND its gate is open again, no client token ever
    EXECUTED two launches (the two-leader witness), and the drift rollout
    the flaps interrupted still finished under its budget."""
    elector = getattr(ctx.runtime, "elector", None)
    if elector is None or not elector.is_leader():
        return False
    lease = ctx.kube.get("Lease", elector.name, elector.namespace)
    if lease is None or lease.spec.holder_identity != elector.identity:
        return False
    if (lease.spec.lease_transitions or 0) < 4:
        return False
    if ctx.backend.double_launches():
        return False
    return drift_settled(ctx)


def watch_gap_settled(ctx: ScenarioContext) -> bool:
    """The watch-gap-storm convergence bar: the planned conflict storm
    actually fired, and the chaos history shows both gap windows opened and
    CLOSED with at least one forced compaction — so converging with zero
    informer divergences proves the replay/relist repair ran, not a run
    where the weather never arrived."""
    plan = kube_chaos.KUBE_CHAOS.plan
    if plan is None or plan.fired() < 1:
        return False
    history = plan.history()
    gap_ends = sum(1 for h in history if h.get("action") == "watch-gap-end")
    compactions = sum(1 for h in history if h.get("action") == "compact")
    return gap_ends >= 2 and compactions >= 1


def soak_settled(ctx: ScenarioContext, schedule: ChaosSchedule, require_delta_passes: int = 0, require_capsules: int = 0) -> bool:
    """The soak convergence bar: the chaos schedule fully delivered (a run
    the weather never reached proves nothing), the solver breaker re-closed
    (a fault storm that permanently abandoned the device path is not
    'settled'), and the invariant monitor confirmed ZERO violations — the
    leak witnesses are the whole point of the tier."""
    if schedule.injected_total() < len(schedule.events):
        return False
    if solver_faults.BREAKER.state != solver_faults.STATE_CLOSED:
        return False
    if getattr(ctx.runtime.options, "solver_incremental", False):
        # the soak tier runs the incremental engine: settling additionally
        # requires that it ENGAGED (delta passes this run — a soak where
        # every pass fell back to a full re-encode would pass the invariant
        # bar while silently losing the O(delta) property) and that solve
        # latency stayed FLAT as the cluster grew (late-half p95 within
        # SOAK_P95_FLATNESS_BOUND of the early half; None = too few solves
        # to window). The engagement floor is per-scenario: the full soak
        # grows a cluster where delta passes MUST dominate, while the
        # mini-soak's 1-2-view cluster legitimately rides the bulk-
        # fallback fulls (its dirty fraction can never sit under the
        # threshold), so it pins only the flatness bound
        if _incremental_delta_passes() - ctx.incremental_delta_at_start < require_delta_passes:
            return False
        flat = _solver_latency_p95_flatness()
        if flat is not None and flat > SOAK_P95_FLATNESS_BOUND:
            return False
    if capsule.CAPSULE.captures_total() < require_capsules:
        # the soak's seeded solver faults must leave evidence behind: the
        # full soak demands at least one incident capsule (the host-rung
        # capture from the seeded compile faults); the mini-soak's shorter
        # schedule keeps the default of zero
        return False
    if getattr(ctx.runtime.options, "residency_audit_interval", 0) > 0:
        # the residency auditor rode the soak: it must have actually audited
        # (>= 1 executed audit), and — since a soak plans no corruption
        # specs — divergences pin at EXACTLY zero. Compressed hours of churn
        # with byte-equal residency is the auditor's specificity witness:
        # the storm scenario proves it catches real corruption, the soak
        # proves it never cries wolf
        if solver_audit.audit_passes_total() - ctx.audit_passes_at_start < 1:
            return False
        if solver_audit.divergences_total() - ctx.residency_divergences_at_start != 0:
            return False
    return not invariants.MONITOR.violations()


def residency_settled(ctx: ScenarioContext) -> bool:
    """The residency-divergence-storm convergence bar: both seeded
    corruptions actually fired (a run the injections never reached proves
    nothing), the auditor detected EXACTLY one divergence per injection
    (none missed, none spurious), every divergence healed (invalidate with
    reason 'audit' forced the byte-equal full re-encode path), at least one
    clean audit has run since the last divergence (the rebuilt resident
    state re-verified against cluster truth — the placement-parity
    witness), and each divergence kind left its own capsule behind."""
    plan = solver_faults.FAULTS.plan
    if plan is None or plan.corruptions_fired() < 2:
        return False
    divergences = solver_audit.divergences_total() - ctx.residency_divergences_at_start
    heals = solver_audit.heals_total() - ctx.residency_heals_at_start
    if divergences != plan.corruptions_fired() or heals != divergences:
        return False
    if solver_audit.AUDITOR.clean_streak() < 1:
        return False
    # two injections of different kinds -> two distinct fingerprints (the
    # capsule detail is {kinds, rows}, transport-stable by construction)
    return len(capsule.CAPSULE.fingerprints().get(capsule.TRIGGER_RESIDENCY, ())) >= 2


def _lost_pods(ctx: ScenarioContext) -> int:
    """Pods the cluster failed: unbound, or bound to a node whose backing
    instance is gone / whose node object vanished."""
    lost = 0
    for pod in live_pods(ctx.kube):
        if not pod.spec.node_name:
            lost += 1
            continue
        node = ctx.kube.get_node(pod.spec.node_name)
        if node is None or not ctx.backend.instance_exists(node.spec.provider_id.split("///", 1)[-1]):
            lost += 1
    return lost


def _converged(ctx: ScenarioContext, scenario: Scenario) -> bool:
    pods = live_pods(ctx.kube)
    if len(pods) != ctx.desired or any(not p.spec.node_name for p in pods):
        return False
    for node in ctx.kube.list_nodes():
        if not ctx.backend.instance_exists(node.spec.provider_id.split("///", 1)[-1]):
            return False  # a node object survives its dead instance
    if _leaked_instances(ctx):
        return False  # an instance survives with no node pointing at it
    if _lost_pods(ctx):
        return False
    if ctx.backend.notifications.depth() != 0:
        return False
    if COHERENCE.compare_registered():
        return False  # the informer caches have not caught the store yet
    return scenario.settled is None or scenario.settled(ctx)


class CampaignRunner:
    def __init__(
        self,
        out_dir: str = ".",
        transports=TRANSPORTS,
        sample_period: float = 0.4,
        convergence_timeout: float = 60.0,
        journal_dir: Optional[str] = None,
    ):
        self.out_dir = out_dir
        self.transports = tuple(transports)
        self.sample_period = sample_period
        self.convergence_timeout = convergence_timeout
        # when set, each run spools its lifecycle journal to
        # <journal_dir>/JOURNAL_<scenario>_<transport>.jsonl — the captured
        # arrival trace ReplayTrace replays (the SCENARIO artifacts stay the
        # committed record; journals are capture output, not comparison data)
        self.journal_dir = journal_dir

    # -- one scenario on one transport ----------------------------------------

    def run_one(self, scenario: Scenario, transport: str) -> dict:
        if transport not in TRANSPORTS:
            raise ValueError(f"unknown transport {transport!r}; one of {TRANSPORTS}")
        slo.SLO.reset()
        flight.FLIGHT.reset()  # per-run solver-latency quantiles + records
        journal.JOURNAL.reset()  # per-run lifecycle events + waterfalls
        capsule.CAPSULE.reset()  # per-run captures + dedupe/debounce state
        # solver fault domain (solver/faults.py): each run starts from a
        # CLOSED breaker and scores only its own fault/degradation deltas;
        # a device-chaos scenario installs its seeded FaultPlan for the
        # whole run so both transports inject the identical fault sequence
        solver_faults.BREAKER.reset()
        faults_at_start = solver_faults.faults_total()
        degraded_at_start = solver_faults.degraded_total()
        # ONE master seed per scenario (utils/seeds.py): every seeded
        # consumer below — the solver plan, the kube plan, the stand-in's
        # jitter — derives from scenario.seed, and the derivation lands in
        # provenance, so the whole run replays from one number
        derived_seeds = scenario.derived_seeds()
        if scenario.fault_specs:
            solver_faults.FAULTS.install(
                solver_faults.FaultPlan.from_specs(scenario.fault_specs, seed=derived_seeds["fault_seed"])
            )
        # residency auditor (solver/audit.py): per-run audit state drops;
        # the campaign pre-seeds the sampling knobs HERE (shadow_every=1 —
        # scenario clusters are small, so every audit is a full shadow and
        # detection is same-pass deterministic; the derived audit seed makes
        # both transports draw identical samples) and the scenario's Runtime
        # merges in interval + clock via its own kwargs-merge enable()
        solver_audit.AUDITOR.reset()
        if scenario.residency_audit_interval > 0:
            solver_audit.AUDITOR.enable(shadow_every=1, seed=derived_seeds["audit_seed"])
        kube_conflicts_at_start = kube_chaos.conflicts_total()
        kube = KubeCluster()
        backend = CloudBackend(clock=kube.clock)
        backend.notifications.visibility_timeout = 1.0
        service = None
        cloud = backend
        if transport == "http":
            from ..cloudprovider.simulated import CloudAPIClient, CloudAPIService

            service = CloudAPIService(backend=backend).start()
            cloud = CloudAPIClient(service.url)
        provider = SimulatedCloudProvider(backend=cloud, kube=kube, clock=kube.clock)
        if scenario.offering_ttl is not None:
            # crunch scenarios need the quarantine to expire (or outlive the
            # run) on the SCENARIO's timescale, not the production default
            provider.unavailable.ttl = scenario.offering_ttl

        def runtime_factory() -> Runtime:
            # each (re)boot is a FRESH control plane over the same cluster +
            # cloud: new state cache, new ledger, new loops — recovery is the
            # startup reconstruction, never shared memory. gc runs on a tight
            # interval with a short registration grace so crash leftovers
            # reconcile within the scenario's convergence window
            return Runtime(
                kube=kube,
                cloud_provider=provider,
                options=Options(
                    # the leader-flap scenarios elect for real (and the
                    # LeaseSteal primitive deposes); everything else skips
                    # election as before. Lease timing is scenario-scale so
                    # a stolen lease expires — and the rightful leader
                    # re-acquires + recovers — inside the run window
                    leader_elect=scenario.leader_elect,
                    lease_duration=1.5,
                    lease_renew_period=0.25,
                    # the informer-coherence witness runs live against every
                    # scenario's state cache; the runner also requires a
                    # clean compare for convergence and scores the teardown
                    # final_check as `informer_divergences`
                    coherence_interval=0.5,
                    # the device-chaos scenarios run the dense device path
                    # (min_batch=1: every provisioning batch dispatches, so
                    # the fault-injection seam sits under real traffic); all
                    # other scenarios keep the host loop
                    dense_solver_enabled=scenario.dense_solver,
                    dense_min_batch=1,
                    # the soak tier additionally runs the incremental solve
                    # engine (solver/incremental.py): settling then requires
                    # delta passes taken + a flat solve-latency p95
                    solver_incremental=scenario.solver_incremental,
                    # the residency storm (and the soak's healthy pin) audit
                    # the resident state on the scenario's cadence; restarts
                    # re-wire interval + clock without clobbering the
                    # campaign's pre-seeded sampling knobs above
                    residency_audit_interval=scenario.residency_audit_interval,
                    solver_breaker_threshold=scenario.solver_breaker_threshold,
                    solver_breaker_backoff=scenario.solver_breaker_backoff,
                    solver_hbm_budget_bytes=scenario.solver_hbm_budget_bytes,
                    batch_max_duration=0.3,
                    batch_idle_duration=0.05,
                    interruption_queue="interruptions",
                    interruption_poll_interval=0.2,
                    enable_slo=True,
                    # solver telemetry scores the steady-state property:
                    # recompiles_total (must be 0 for a settled cluster
                    # re-solving under churn) + solver-latency p95
                    enable_solver_telemetry=True,
                    # the lifecycle journal decomposes every pod's pending
                    # latency into waterfall segments (scored below, with
                    # the conservation invariant enforced) and records the
                    # arrival trace replay builds on
                    enable_journal=True,
                    # incident capsules ride every scenario: chaos runs
                    # must capture their evidence bundles (scored below),
                    # healthy runs must capture exactly none
                    enable_capsules=True,
                    # per-kind capture debounce override: the residency
                    # storm needs BOTH of its distinct divergence captures,
                    # which land closer together than the production default
                    **(
                        {"capsule_debounce_seconds": scenario.capsule_debounce_seconds}
                        if scenario.capsule_debounce_seconds is not None
                        else {}
                    ),
                    gc_interval=1.0,
                    gc_registration_grace=3.0,
                    # scenario timescales are seconds: a parked pod must
                    # re-probe within the run, not 10s later
                    ice_backoff_seconds=1.5,
                ),
            )

        runtime = runtime_factory()
        if self.journal_dir is not None:
            os.makedirs(self.journal_dir, exist_ok=True)
            journal.JOURNAL.set_spool(
                os.path.join(self.journal_dir, f"JOURNAL_{scenario.name}_{transport}.jsonl")
            )
        provisioner = _provisioner(scenario)
        kube.create(provisioner)
        ctx = ScenarioContext(
            kube, backend, runtime, service=service, pod_cpu=scenario.pod_cpu, runtime_factory=runtime_factory
        )
        ctx.solver_chunked_at_start = solver_faults.DEGRADED_SOLVES.value(rung=solver_faults.RUNG_CHUNKED)
        stand_in = WorkloadStandIn(ctx, jitter_seed=derived_seeds["standin_jitter_seed"])
        reclaim_thread = threading.Thread(
            target=self._reclaimer, args=(ctx,), name="cloud-reclaimer", daemon=True
        )
        samples: List[dict] = []
        violations = 0
        launch_failures_at_start = _launch_failures_total()
        recompiles_at_start = flight.FLIGHT.compilations_total()
        # incremental-engine pass counters are process-lifetime monotonic
        # (a prior incremental run in the same process would pre-satisfy
        # the soak engaged bar) — stamp run-start and score the delta
        incremental_delta_at_start = _incremental_delta_passes()
        ctx.incremental_delta_at_start = incremental_delta_at_start
        # residency-auditor counters are process-lifetime monotonic too:
        # stamp run-start so scores and settled predicates see THIS run's
        # divergence/heal/audit deltas
        ctx.residency_divergences_at_start = solver_audit.divergences_total()
        ctx.residency_heals_at_start = solver_audit.heals_total()
        ctx.audit_passes_at_start = solver_audit.audit_passes_total()
        start = time.monotonic()
        try:
            # control-plane fault domain (kube/chaos.py): the seeded
            # KubeFaultPlan arms INSIDE the try — the setup writes above
            # (provisioner create, runtime assembly) run clean, and a fault
            # that kills the run can never leak an armed plan into the next
            # scenario (the finally always disarms). Both transports inject
            # the identical fault sequence; every run scores its own delta
            if scenario.kube_fault_specs:
                kube_chaos.KUBE_CHAOS.install(
                    kube_chaos.KubeFaultPlan.from_specs(scenario.kube_fault_specs, seed=derived_seeds["kube_fault_seed"])
                )
            runtime.start()
            # the invariant monitor (invariants.py) arms AFTER the runtime
            # attached its watchers: the armed state is the healthy baseline
            # (crash/restart cycles are net-zero detach/attach by contract),
            # and every later sample — one per runner tick, ~one compressed
            # minute at soak compression — hunts growth above it. Memory is
            # traced only on the soak tier: tracemalloc taxes every
            # allocation, and the short storms have nothing to slow-leak
            invariants.MONITOR.arm(
                kube, backend=backend, clock=kube.clock, trace_memory=isinstance(scenario, Soak)
            )
            stand_in.start()
            reclaim_thread.start()
            ctx.desired = scenario.desired
            workers = []
            for primitive in scenario.primitives:
                thread = threading.Thread(
                    target=self._run_primitive, args=(ctx, primitive), name=f"primitive-{type(primitive).__name__}", daemon=True
                )
                thread.start()
                workers.append(thread)

            def timeline_live() -> bool:
                return time.monotonic() - start < scenario.duration or any(w.is_alive() for w in workers)

            while timeline_live():
                violations += self._sample(ctx, provisioner, samples, start)
                time.sleep(self.sample_period)
            deadline = time.monotonic() + self.convergence_timeout
            converged = False
            while time.monotonic() < deadline:
                violations += self._sample(ctx, provisioner, samples, start)
                if _converged(ctx, scenario):
                    converged = True
                    break
                time.sleep(self.sample_period)
            # final accounting: fresh cost gauges + an explicit drift solve
            # (through ctx.runtime — a crash scenario's live control plane is
            # the latest successor, not the Runtime this frame started with)
            ctx.runtime.slo_metrics.scrape()
            ctx.runtime.slo_metrics.compute_drift()
            violations += self._sample(ctx, provisioner, samples, start)
            snapshot = slo.SLO.snapshot()
            # the conservation invariant, enforced at emit time like the
            # schema: every completed pod's segments must sum to the pending
            # duration the SLO accountant independently observed
            conservation = journal.JOURNAL.conservation_errors()
            if conservation:
                raise AssertionError(
                    f"[{scenario.name}/{transport}] waterfall conservation violated: {conservation[:5]}"
                )
            # the teardown coherence check, the zero-lock-cycles analog:
            # after the run quiesces every informer cache must deep-match
            # the store; divergences still standing after the settle window
            # are scored (and pinned at zero by the chaos suites)
            divergences = COHERENCE.final_check(timeout=5.0)
            # the invariant monitor's final round + report: the slow-leak
            # witnesses (thread stragglers, watch growth, ring budgets, heap
            # slope) become scored artifact keys next to lost/leaked/budget
            invariants.MONITOR.sample()
            invariant_report = invariants.MONITOR.report()
            schedules = [p for p in scenario.primitives if isinstance(p, ChaosSchedule)]
            solver_injected = int(solver_faults.FAULTS.fired())
            # residency-integrity accounting: this run's divergence/heal/
            # audit deltas. A divergence on a run with NO corruption specs
            # is a REAL resident-state integrity bug (the auditor compared
            # against freshly re-encoded truth and lost) — fail the run
            # loudly, exactly like a conservation violation
            residency_divergences = int(solver_audit.divergences_total() - ctx.residency_divergences_at_start)
            residency_heals = int(solver_audit.heals_total() - ctx.residency_heals_at_start)
            audit_passes = int(solver_audit.audit_passes_total() - ctx.audit_passes_at_start)
            corruption_planned = any(
                spec.get("kind") in solver_faults.CORRUPTION_KINDS for spec in (scenario.fault_specs or ())
            )
            if residency_divergences and not corruption_planned:
                raise AssertionError(
                    f"[{scenario.name}/{transport}] residency auditor found {residency_divergences}"
                    f" divergence(s) on a run with no corruption specs: resident state diverged from truth"
                )
            kube_injected = int(kube_chaos.KUBE_CHAOS.fired())
            duration_wall = time.monotonic() - start
            compressed = scenario.compressed_span if isinstance(scenario, Soak) and scenario.compressed_span > 0 else duration_wall
            pods = live_pods(kube)
            run = {
                "transport": transport,
                "duration_seconds": round(duration_wall, 3),
                "converged": converged,
                "scores": {
                    "pending_latency_seconds": snapshot["pod_pending_latency_seconds"],
                    "node_ready_seconds": snapshot["node_ready_seconds"],
                    "cost_per_hour": snapshot["cost"]["cluster_cost_per_hour"],
                    "ideal_cost_per_hour": snapshot["cost"]["ideal_cost_per_hour"],
                    "cost_drift_ratio": snapshot["cost"]["cost_drift_ratio"],
                    "lost_pods": _lost_pods(ctx),
                    "leaked_instances": _leaked_instances(ctx),
                    "budget_violations": violations,
                    "pods_desired": ctx.desired,
                    "pods_bound": sum(1 for p in pods if p.spec.node_name),
                    "nodes_churned": snapshot["churn"]["nodes_churned"],
                    "pods_displaced": snapshot["churn"]["pods_displaced"],
                    "restarts": ctx.restarts,
                    "launch_failures": _launch_failures_total() - launch_failures_at_start,
                    "unschedulable_pod_seconds": _unschedulable_pod_seconds(samples),
                    "recompiles_total": flight.FLIGHT.compilations_total() - recompiles_at_start,
                    "solver_latency_p95_seconds": _solver_latency_p95(),
                    # incremental-engine engagement + the O(delta) flatness
                    # witness (late/early p95 ratio; None when the run
                    # solved too little to window) — scored on every run,
                    # asserted by the soak settled predicate
                    "encode_skipped_passes": int(_incremental_delta_passes() - incremental_delta_at_start),
                    "solver_latency_p95_flatness": _solver_latency_p95_flatness(),
                    "waterfall": journal.JOURNAL.segment_quantiles(),
                    "solver_faults_total": int(solver_faults.faults_total() - faults_at_start),
                    "degraded_solves_total": int(solver_faults.degraded_total() - degraded_at_start),
                    "solver_faults_injected": int(solver_faults.FAULTS.fired()),
                    "breaker_state": solver_faults.BREAKER.state,
                    "kube_conflicts_total": int(kube_chaos.conflicts_total() - kube_conflicts_at_start),
                    "kube_faults_injected": kube_injected,
                    "informer_divergences": len(divergences),
                    "double_launches": int(ctx.backend.double_launches()),
                    "leaked_threads": int(invariant_report["leaked_threads"]),
                    "leaked_watches": int(invariant_report["leaked_watches"]),
                    "rss_growth_slope": invariant_report["rss_growth_slope"],
                    "invariant_violations": len(invariant_report["violations"]),
                    "chaos_injected_total": int(
                        sum(s.injected_total() for s in schedules) + solver_injected + kube_injected
                    ),
                    "chaos_history_digest": schedules[0].history_digest() if schedules else None,
                    "compressed_seconds": round(compressed, 3),
                    # incident-capsule scores (capsule.py): evidence bundles
                    # captured this run (chaos scenarios require >=1 via
                    # their settled predicates; healthy scenarios pin 0)
                    # and the per-trigger fingerprint lists — equal maps
                    # across transports pin the capture-determinism witness
                    "capsules_captured": int(capsule.CAPSULE.captures_total()),
                    "capsule_triggers": capsule.CAPSULE.fingerprints(),
                    # residency-auditor scores (solver/audit.py): healthy
                    # runs pin divergences at 0 (asserted above); the storm
                    # scenario's settled predicate requires divergences ==
                    # injections and heals == divergences
                    "residency_divergences": residency_divergences,
                    "residency_heals": residency_heals,
                    "audit_passes": audit_passes,
                },
                "samples": samples,
            }
            log.info(
                "[%s/%s] converged=%s pods=%d/%d lost=%d leaked=%d drift=%.3f violations=%d restarts=%d in %.1fs",
                scenario.name, transport, converged, run["scores"]["pods_bound"], ctx.desired,
                run["scores"]["lost_pods"], run["scores"]["leaked_instances"], run["scores"]["cost_drift_ratio"],
                violations, ctx.restarts, run["duration_seconds"],
            )
            return run
        finally:
            ctx.stop.set()
            # only join threads that actually started: runtime.start() can
            # raise before they do, and join() on an unstarted Thread raises
            # RuntimeError — masking the real startup failure
            for thread in (stand_in, reclaim_thread):
                if thread.ident is not None:
                    thread.join(timeout=3)
            ctx.runtime.stop()  # the latest successor, if a crash primitive rotated it
            if service is not None:
                service.stop()
            # the Runtime enabled the process-wide accountant; a finished
            # run must not leave accounting on for unrelated work (the next
            # run_one re-enables through its own Runtime)
            slo.SLO.disable()
            flight.FLIGHT.disable()
            journal.JOURNAL.set_spool(None)  # close (and keep) the capture
            journal.JOURNAL.disable()
            capsule.CAPSULE.disable()
            solver_faults.FAULTS.clear()  # never leak a fault plan past its run
            solver_audit.AUDITOR.disable()  # same discipline for the auditor
            solver_audit.AUDITOR.reset()
            kube.chaos_watch_gap_end()  # a gap leaked past its run wedges nothing
            kube_chaos.KUBE_CHAOS.clear()
            invariants.MONITOR.disarm()  # ends the window; tracemalloc off

    @staticmethod
    def _run_primitive(ctx: ScenarioContext, primitive) -> None:
        if ctx.stop.wait(timeout=primitive.offset):
            return
        try:
            primitive.run(ctx)
        except Exception:  # noqa: BLE001 - one primitive must not kill the scenario
            log.exception("primitive %s failed", type(primitive).__name__)

    @staticmethod
    def _reclaimer(ctx: ScenarioContext) -> None:
        # the cloud makes good on its interruption warnings
        while not ctx.stop.wait(timeout=0.2):
            ctx.backend.reclaim_due_instances()

    def _sample(self, ctx: ScenarioContext, provisioner, samples: List[dict], start: float) -> int:
        """Append one timeline sample; returns 1 when voluntary disruption
        exceeds the provisioner's active budget (the budget-violation
        score), else 0. The check is TWO-WITNESS: the in-memory ledger AND
        an independent scan of the API for nodes carrying the durable
        karpenter.sh/disrupting marker mid-drain — so a restart that lost
        the ledger (or rebuilt it wrong) cannot hide an over-budget drain."""
        in_flight = 0
        if ctx.runtime.disruption is not None:
            in_flight = ctx.runtime.disruption.tracker.total_in_flight()
        nodes = ctx.kube.list_nodes()
        scanned = sum(
            1 for n in nodes
            if lbl.DISRUPTING_ANNOTATION in n.metadata.annotations and n.metadata.deletion_timestamp is not None
        )
        owned = sum(1 for n in nodes if n.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL) == provisioner.name)
        limit = allowed_disruptions(provisioner, owned, ctx.kube.clock.now())
        violated = limit is not None and max(in_flight, scanned) > limit
        # the invariant monitor rides the sample cadence: ~one round per
        # 0.4s of wall time, which at soak compression is about one round
        # per compressed minute — the "sample every N compressed minutes"
        # contract without a second timer
        invariants.MONITOR.sample()
        # the capsule engine polls on the same cadence (drains the trigger
        # bus + runs the burn-rate monitor) so captures exist BEFORE the
        # settled predicates that require them are checked
        capsule.CAPSULE.poll()
        samples.append(
            {
                "t": round(time.monotonic() - start, 3),
                "pending_pods": len(ctx.kube.pending_pods()),
                "nodes": len(nodes),
                "cost_per_hour": round(slo.CLUSTER_COST.value(), 6),
                "disrupting": in_flight,
                # informational: the rolling solve p95 at this sample — the
                # timeline behind the scored flatness ratio
                "solver_p95": _solver_latency_p95(),
            }
        )
        return 1 if violated else 0

    # -- the campaign ----------------------------------------------------------

    def run(self, scenarios: List[Scenario]) -> List[dict]:
        docs = []
        os.makedirs(self.out_dir, exist_ok=True)
        for scenario in scenarios:
            doc = {
                "scenario": scenario.name,
                "description": scenario.description,
                "provenance": provenance_block(scenario.config()),
                "runs": [self.run_one(scenario, transport) for transport in self.transports],
            }
            errors = scenario_doc_errors(doc)
            if errors:
                raise AssertionError(f"scenario {scenario.name} emitted an invalid document: {errors}")
            # the capture-determinism witness: the same scenario on every
            # transport must trip the same triggers with byte-identical
            # fingerprints (details carry only transport-stable fields)
            trigger_maps = [run["scores"]["capsule_triggers"] for run in doc["runs"]]
            if any(t != trigger_maps[0] for t in trigger_maps[1:]):
                raise AssertionError(
                    f"scenario {scenario.name} captured different capsules across transports: {trigger_maps}"
                )
            path = os.path.join(self.out_dir, f"SCENARIO_{scenario.name}.json")
            with open(path, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.write("\n")
            log.info("wrote %s", path)
            docs.append(doc)
        return docs


# -- the standard campaigns ----------------------------------------------------


def default_campaign() -> List[Scenario]:
    """The five composed production shapes the roadmap asked for, each
    exercising a different Runtime subsystem end to end."""
    return [
        Scenario(
            name="pod_burst",
            desired=0,
            duration=4.0,
            primitives=[Burst(offset=0.2, count=28)],
            description="cold burst: 28 replicas land at once on an empty cluster",
        ),
        Scenario(
            name="diurnal_ramp",
            desired=0,  # the ramp owns the load (its contribution starts at base)
            duration=10.0,
            primitives=[DiurnalRamp(offset=0.5, base=6, peak=22, period=8.0, cycles=1)],
            description="half-cosine day: 6 -> 28 -> 6 replicas over one period",
        ),
        Scenario(
            name="spot_reclaim_wave",
            desired=24,
            duration=9.0,
            instance_types=["general-4x8"],  # ~7 pods/node -> a real fleet to storm
            primitives=[SpotReclaimWave(offset=4.0, fraction=0.6, warning_seconds=1.5)],
            description="correlated spot loss: most of the populated fleet reclaimed on a short warning",
        ),
        Scenario(
            name="drift_rollout_storm",
            desired=14,
            duration=10.0,
            budget_nodes="40%",
            instance_types=["general-4x8"],  # several nodes, so 40% floors to >= 1
            settled=drift_settled,
            primitives=[Burst(offset=2.0, count=8), DriftRollout(offset=4.0)],
            description="provisioner label rollout mid-burst: every node drifts, replaced under a 40% budget",
        ),
        Scenario(
            name="diurnal_ramp_consolidated",
            desired=0,
            duration=10.0,
            consolidation=True,
            ttl_seconds_after_empty=None,  # mutually exclusive with consolidation
            instance_types=["general-4x8"],  # several small nodes: consolidation has bins to merge
            settled=consolidated_settled,
            primitives=[DiurnalRamp(offset=0.5, base=6, peak=22, period=8.0, cycles=1)],
            description=(
                "the PR 6 diurnal finding, closed: same half-cosine day with consolidation enabled — "
                "post-ramp stranded capacity is consolidated away until cost drift is pinned <= 1.5x"
            ),
        ),
        Scenario(
            name="crash_storm",
            desired=16,
            duration=12.0,
            budget_nodes="40%",
            instance_types=["general-4x8"],
            settled=drift_settled,
            primitives=[
                Burst(offset=0.3, count=10),
                ProcessCrash(offset=0.9),  # mid-provision: the burst is still launching
                SpotReclaimWave(offset=3.0, fraction=0.5, warning_seconds=1.5),
                DriftRollout(offset=4.5),
                ProcessCrash(offset=5.5),  # mid-disruption: the rollout is mid-replacement
                ProcessCrash(offset=8.0, times=1),
            ],
            description=(
                "burst + reclaim wave + drift rollout with the control plane kill -9'd three times "
                "mid-provision/mid-disruption: startup reconstruction + the GC sweep must converge to "
                "zero leaked instances, zero lost pods, budgets intact"
            ),
        ),
        Scenario(
            name="capacity_crunch",
            desired=0,
            duration=10.0,
            instance_types=["general-4x8"],
            offering_ttl=2.0,
            settled=capacity_recovered,
            primitives=[
                # phase 1 — the cheapest pool (zone-c spot) holds ONE more
                # launch: the burst exhausts it mid-flight, the fleet items
                # fall through to next-cheapest spot zones (partial
                # fulfillment) and the skipped pool quarantines
                PoolCapacity(offset=0.0, instance_type="general-4x8", zones=["zone-c"], capacity_types=["spot"], capacity=1),
                Burst(offset=0.4, count=26),
                # phase 2 — the TOTAL wall: every pool of the only allowed
                # type is exhausted, so the next burst's launches fail with
                # typed ICEs, the bounded re-solve escalates to
                # pod-unschedulable (events + decision records + backoff)
                PoolCapacity(offset=2.6, instance_type="general-4x8", capacity=0),
                Burst(offset=3.0, count=7),
                # phase 3 — capacity returns everywhere; parked pods
                # re-probe on their backoff, quarantines expire, and the
                # last launches land back in the cheapest pool
                PoolCapacity(offset=5.0, instance_type="general-4x8", capacity=None),
            ],
            description=(
                "the cheapest pool exhausts mid-burst (fallback to next-cheapest offering), then "
                "every pool walls off (typed ICE -> bounded re-solve -> unschedulable + backoff): "
                "nothing is lost, and the exhausted pool is re-selected after its TTL expires"
            ),
        ),
        Scenario(
            name="spot_collapse",
            desired=21,
            duration=9.0,
            instance_types=["general-4x8"],
            offering_ttl=300.0,  # outlives the run: convergence cannot ride an expiry
            settled=avoids_unavailable_pools,
            primitives=[SpotReclaimWave(offset=3.0, fraction=0.7, warning_seconds=1.5)],
            description=(
                "correlated spot loss with the reclaimed pools quarantined by the interruption "
                "controller: every replacement must route AROUND the collapsing pools (other-zone "
                "spot or on-demand), never back into them"
            ),
        ),
        Scenario(
            name="device_fault_storm",
            desired=0,
            duration=7.0,
            dense_solver=True,
            solver_breaker_threshold=3,
            solver_breaker_backoff=1.5,
            # the plan speaks the typed taxonomy: the first three device
            # dispatches of whichever flavor runs (plain single-device,
            # the sharded mesh, or the Pallas kernel on real TPU hardware —
            # a pallas fault retires that flavor, so its later dispatches
            # land on the plain spec) die with a device-lost fault — three
            # consecutive classified faults is exactly the breaker
            # threshold, so the fourth burst solves against an OPEN breaker
            # (host loop, no device attempt) and the last burst lands after
            # the backoff as the half-open recovery probe
            fault_specs=[
                {"kind": "device-lost", "entry": "plain", "nth": 1, "count": 3},
                {"kind": "device-lost", "entry": "sharded", "nth": 1, "count": 3},
                {"kind": "device-lost", "entry": "pallas", "nth": 1, "count": 3},
            ],
            settled=breaker_reclosed,
            primitives=[
                Burst(offset=0.3, count=5),
                Burst(offset=1.3, count=5),
                Burst(offset=2.3, count=5),
                Burst(offset=3.5, count=5),  # breaker OPEN: host fallback, no device attempt
                Burst(offset=5.5, count=4),  # after backoff: the half-open recovery probe
            ],
            description=(
                "typed device-lost faults on three consecutive solves trip the solver circuit "
                "breaker (host loop owns every batch, no device attempt paid), then a half-open "
                "recovery probe re-admits the fast path: converge with zero lost pods and the "
                "breaker CLOSED"
            ),
        ),
        Scenario(
            name="hbm_pressure",
            desired=0,
            duration=6.0,
            dense_solver=True,
            # a ~1 KiB budget is below any real dispatch surface, so once
            # the flight recorder's HBM-peak gauge is primed by the first
            # recorded solve, every later solve chunks PRE-EMPTIVELY —
            # the budget rung, on top of the injected reactive HBM faults
            solver_hbm_budget_bytes=1024,
            fault_specs=[
                {"kind": "hbm", "entry": "plain", "nth": 1, "count": 2},
                {"kind": "hbm", "entry": "sharded", "nth": 1, "count": 2},
                {"kind": "hbm", "entry": "pallas", "nth": 1, "count": 2},
            ],
            settled=hbm_degraded_settled,
            primitives=[
                Burst(offset=0.3, count=8),
                Burst(offset=2.0, count=8),
                Burst(offset=3.8, count=8),
            ],
            description=(
                "HBM RESOURCE_EXHAUSTED faults plus a pre-solve HBM budget drive the chunked-solve "
                "rung: the pod batch splits and re-dispatches on a smaller device surface, nothing "
                "is lost, and the breaker never opens — memory pressure degrades, it does not outage"
            ),
        ),
        Scenario(
            name="leader_flap_storm",
            desired=12,
            duration=11.0,
            budget_nodes="40%",
            instance_types=["general-4x8"],
            leader_elect=True,
            # two injected renew failures on top of the steals: a transport
            # blip mid-run must flap (pause -> re-renew -> recover) without
            # waiting out the lease, and the steals land in the same plan
            # history as the seeded triggers (the determinism witness)
            kube_fault_specs=[{"fault": "lease-lost", "verb": "lease-renew", "nth": 10, "count": 2}],
            settled=leader_flap_settled,
            primitives=[
                Burst(offset=0.3, count=8),
                DriftRollout(offset=2.0),
                LeaseSteal(offset=3.2),  # mid-rollout: replacements in flight
                LeaseSteal(offset=6.5),  # again, after the first recovery
            ],
            description=(
                "the lease is stolen twice mid-drift-rollout: the deposed leader's loops pause "
                "before the thief's (never-renewed) lease expires, the rightful leader re-acquires "
                "and runs recovery BEFORE acting, the rollout finishes under its 40% budget, and "
                "the client-token ledger proves no logical launch ever executed twice"
            ),
        ),
        Scenario(
            name="watch_gap_storm",
            desired=0,
            duration=10.0,
            instance_types=["general-4x8"],
            # a seeded conflict storm on node registration: the 2nd and 3rd
            # node creates 409 — the provisioner absorbs them (counted, not
            # swallowed), the instance briefly orphans, and the GC sweep
            # reconciles it while the watch chaos below runs
            kube_fault_specs=[{"fault": "conflict", "verb": "create", "obj_kind": "Node", "nth": 2, "count": 2}],
            settled=watch_gap_settled,
            primitives=[
                Burst(offset=0.3, count=12),
                WatchGap(offset=1.0, duration=0.8, compact=True),  # 410 Gone: relist diff
                Burst(offset=1.2, count=6),  # lands INSIDE the compacted gap
                WatchGap(offset=3.5, duration=0.6),  # plain drop: replay from the buffer
                Burst(offset=4.6, count=6),
            ],
            description=(
                "bursts under control-plane weather: watch streams killed mid-burst (reconnect-"
                "from-RV replay), a forced journal compaction (410 Gone -> relist diff, deletes "
                "included), and a seeded 409 storm on node registration — the informer-coherence "
                "witness must find ZERO divergences at teardown and nothing may be lost or leaked"
            ),
        ),
        Scenario(
            name="throttled_control_plane",
            desired=0,
            duration=8.0,
            primitives=[
                Burst(offset=0.2, count=18),
                TransportChaos(offset=0.6, latency_seconds=0.12, duration=4.0, delayed_requests=60, throttled_requests=10),
            ],
            description="burst under a degraded cloud API: injected latency + 429 throttling",
        ),
        Scenario(
            name="residency_divergence_storm",
            desired=0,
            duration=10.0,
            dense_solver=True,
            solver_incremental=True,
            residency_audit_interval=1,  # every real pass audited
            capsule_debounce_seconds=0.0,  # both divergences captured, not debounced
            instance_types=["general-4x8"],
            # the seeded corruption pair (solver/faults.py): flip one value
            # in the resident HOST mirror at the first resident pass — the
            # same-pass full shadow detects it as row-drift before the fill
            # consumes the encoding — then suppress the 11th pod-level
            # DeltaJournal record: the OUT-OF-BAND bind at t=4.8 below
            # (8 burst binds + t=2.0 + t=3.4 = records 1-10, so 11 is the
            # interloper). It must be out-of-band — the engine rebases its
            # OWN placements into the mirror before the record matters, so a
            # suppressed solver-planned bind is undetectable by design. The
            # 0.1-cpu pod lands on the burst-filled first node (0.4 cpu
            # spare, too tight for the stand-in's 0.5-cpu replicas), so that
            # node's journal window stays silent and the NEXT pass's audit
            # classifies the stale mirror row missed-delta, not row-drift
            fault_specs=[
                {"kind": "corrupt-row", "entry": "resident-row", "nth": 1},
                {"kind": "suppress-delta", "entry": "journal-record", "nth": 11},
            ],
            settled=residency_settled,
            primitives=[
                Burst(offset=0.3, count=8),  # builds the fleet; the engine warms to resident
                Burst(offset=2.0, count=1),  # single binds from here on: each pass's journal
                Burst(offset=3.4, count=1),  # traffic is exactly one record — no sibling masking
                OutOfBandBind(offset=4.8, cpu=0.1),  # the suppressed record (see fault_specs)
                Burst(offset=6.2, count=1),  # the detection pass: audit sees the stale row
                Burst(offset=7.6, count=1),  # post-heal pass: the clean-audit parity witness
            ],
            description=(
                "seeded resident-state corruption under churn: a host-mirror row flip and a "
                "suppressed delta-journal record — the auditor must detect exactly one divergence "
                "per injection (row-drift, then missed-delta), heal each by forcing the byte-equal "
                "full re-encode, re-verify clean, and leave one capsule per divergence kind, with "
                "zero lost pods"
            ),
        ),
        chaos_soak_scenario(),
    ]


def chaos_soak_scenario(seed: int = 11) -> Soak:
    """The standing soak: 75 minutes of diurnal arrivals compressed 150x
    into a ~30s run, under a low-rate cross-domain ChaosSchedule drawn from
    the scenario's ONE master seed — pool exhaustions with paired restores,
    reclaim waves, API latency, watch gaps/compactions, the odd kill -9,
    plus seeded solver and kube verb triggers. The invariant monitor
    samples every ~compressed-minute; convergence requires the schedule
    fully delivered, the breaker re-closed, and ZERO invariant violations.
    Every future perf PR must survive this for compressed hours, not
    seconds."""
    import functools

    schedule = ChaosSchedule(
        offset=1.0,
        seed=split_seed(seed, "chaos.schedule"),
        events_count=16,
        horizon=24.0,
        instance_type="general-4x8",
        solver_faults=2,
        kube_faults=3,
    )
    trace = diurnal_trace(seed, span_seconds=4500.0, arrivals=60, compress=150.0, offset=0.5)
    return Soak(
        name="chaos_soak",
        desired=0,  # the replayed trace owns the load
        duration=34.0,
        seed=seed,
        compress=150.0,
        compressed_span=4500.0,
        instance_types=["general-4x8"],
        dense_solver=True,  # the solver seam must sit under real dispatch
        # device-resident incremental engine under the chaos weather: the
        # settled predicate then also demands delta passes + flat p95
        solver_incremental=True,
        # the residency auditor rides every pass of the soak: with no
        # corruption specs planned, soak_settled pins divergences at
        # exactly zero — the specificity half of the auditor's proof
        residency_audit_interval=1,
        fault_specs=schedule.solver_specs(),
        kube_fault_specs=schedule.kube_specs(),
        settled=functools.partial(soak_settled, schedule=schedule, require_delta_passes=1, require_capsules=1),
        primitives=[trace, schedule],
        description=(
            "the soak tier: 75 compressed minutes of diurnal load replayed 150x under a "
            "seeded cross-domain chaos schedule spanning all three fault seams, with the "
            "invariant monitor sampling leak witnesses every compressed minute — converge "
            "with zero lost pods, zero leaked threads/watches, zero invariant violations"
        ),
    )


def mini_soak_scenario(seed: int = 5, extra_events: Optional[List[dict]] = None) -> Soak:
    """The tier-1 soak shape: 60 compressed seconds (20x over a ~3s replay)
    under a 3-event cross-domain schedule — one pool exhaustion (cloud),
    one watch gap (kube), the paired restore — plus one seeded solver
    trigger and one seeded kube trigger from the same master seed.
    `extra_events` appends imported events (the seeded negative control
    injects its watch-leak through it)."""
    import functools

    events = [
        {"index": 0, "offset": 0.6, "domain": "cloud", "action": "pool-exhaust",
         "params": {"instance_type": "general-4x8", "zone": "zone-c", "capacity_type": "spot", "capacity": 0}},
        {"index": 1, "offset": 1.2, "domain": "kube", "action": "watch-gap",
         "params": {"duration": 0.4, "compact": True}},
        {"index": 2, "offset": 1.8, "domain": "cloud", "action": "pool-restore",
         "params": {"instance_type": "general-4x8", "zone": "zone-c", "capacity_type": "spot"}},
    ]
    for i, extra in enumerate(extra_events or []):
        events.append(dict(extra, index=len(events)))
    schedule = ChaosSchedule(
        offset=0.3,
        seed=split_seed(seed, "chaos.schedule"),
        solver_faults=1,
        kube_faults=1,
        imported=events,
    )
    trace = diurnal_trace(seed, span_seconds=60.0, arrivals=10, compress=20.0, offset=0.3)
    return Soak(
        name="mini_soak",
        desired=0,
        duration=4.5,
        seed=seed,
        compress=20.0,
        compressed_span=60.0,
        instance_types=["general-4x8"],
        dense_solver=True,
        solver_incremental=True,  # same engine wiring as the full soak
        residency_audit_interval=1,  # and the same zero-divergence pin
        fault_specs=schedule.solver_specs(),
        kube_fault_specs=schedule.kube_specs(),
        settled=functools.partial(soak_settled, schedule=schedule),
        primitives=[trace, schedule],
        description=(
            "tier-1 mini-soak: 60 compressed seconds of diurnal replay under a 3-event "
            "cross-domain schedule with seeded solver + kube triggers; zero leaked "
            "threads/watches and zero invariant violations on both transports"
        ),
    )


def smoke_campaign() -> List[Scenario]:
    """The tier-1 shape: one tiny composed scenario (burst + a one-node
    reclaim) that still crosses every scored surface in a few seconds."""
    return [
        Scenario(
            name="smoke_burst",
            desired=0,
            duration=2.5,
            primitives=[Burst(offset=0.1, count=8), SpotReclaimWave(offset=1.2, fraction=0.34, warning_seconds=0.8, max_victims=1)],
            description="tier-1 smoke: small burst + single spot reclaim",
        )
    ]


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="karpenter-tpu-campaign")
    parser.add_argument("--out", default=".", help="directory for SCENARIO_*.json artifacts")
    parser.add_argument("--transports", default=",".join(TRANSPORTS), help="comma-separated: inprocess,http")
    parser.add_argument("--smoke", action="store_true", help="run the tier-1 smoke campaign instead of the full one")
    parser.add_argument("--scenarios", default="", help="comma-separated subset of scenario names")
    parser.add_argument(
        "--journal-dir", default=None,
        help="spool each run's lifecycle journal to JOURNAL_<scenario>_<transport>.jsonl here (replay capture)",
    )
    args = parser.parse_args(argv)
    scenarios = smoke_campaign() if args.smoke else default_campaign()
    if args.scenarios:
        wanted = set(args.scenarios.split(","))
        scenarios = [s for s in scenarios if s.name in wanted]
        if not scenarios:
            parser.error(f"no scenario matches {sorted(wanted)}")
    runner = CampaignRunner(out_dir=args.out, transports=tuple(args.transports.split(",")), journal_dir=args.journal_dir)
    docs = runner.run(scenarios)
    summary = {
        doc["scenario"]: {
            run["transport"]: {
                "converged": run["converged"],
                "lost_pods": run["scores"]["lost_pods"],
                "budget_violations": run["scores"]["budget_violations"],
                "cost_drift_ratio": run["scores"]["cost_drift_ratio"],
            }
            for run in doc["runs"]
        }
        for doc in docs
    }
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
