"""Cross-domain chaos orchestrator: one seed, three fault domains, one history.

PRs 9/13/14 built three deterministic fault seams — cloud capacity
(`PoolCapacity`/ICE + reclaim waves + crash/restart), solver
(`solver/faults.FAULTS`), and kube control plane (`kube/chaos.KUBE_CHAOS` +
the imperative gap/steal/compact verbs) — but every chaos scenario so far
storms exactly ONE domain. The races worth finding live in the
interactions (a watch gap across a pool exhaustion, a crash inside a
conflict storm mid-reclaim), and the Jepsen lesson is that randomized
*composition* of independent nemeses finds them where hand-composed
single-domain storms cannot. This module is that composer:

- **`ChaosSchedule`** — a seeded schedule of interleaved fault events
  across all three seams, drawn from ONE seed (fanned out splitmix-style,
  `utils/seeds.py`, so the imperative draw, the solver `FaultSpec` export,
  and the kube `KubeFaultSpec` export are independent streams of one
  number). The imperative timeline events (pool exhaustions with paired
  restores, spot-reclaim waves, API latency, watch gaps ± forced
  compaction, lease steals, kill -9 crash/restarts) execute as one scenario
  primitive; the seeded per-dispatch / per-verb triggers export as plain
  spec dicts (`solver_specs()` / `kube_specs()`) that the campaign arms on
  the existing injectors — spec export/import is what makes the three
  seams composable from one seed. `history()` is the determinism witness:
  a pure function of the construction inputs, byte-identical for the same
  seed, pinned cross-transport exactly like the PR 13/14 plans.
- **the soak tier** (`Soak` + `diurnal_trace`) — a scenario kind that
  drives HOURS of compressed load (a synthetic diurnal arrival trace
  replayed through PR 12's `ReplayTrace`, inter-arrival structure
  preserved, clock-compressed `compress`×) under a low-rate background
  `ChaosSchedule`, while the campaign runner samples the invariant monitor
  (`invariants.py`) every ~compressed-minute. The scored run lands the
  leak witnesses — `leaked_threads`, `leaked_watches`, `rss_growth_slope`,
  `invariant_violations` — in `SCENARIO_*.json` next to lost/leaked/budget.
- **the shrinker** (`ddmin`) — when a soak breaks an invariant, the
  recorded schedule replays SUBSETS deterministically (delta debugging,
  Zeller's ddmin) until the failure is minimal, and the minimal failing
  schedule is emitted as a committed `SHRINK_<scenario>.json` reproducer:
  a flaky multi-hour failure becomes a tier-1-sized seeded test.
"""

from __future__ import annotations

import hashlib
import json
import math
import random
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from ..analysis.guards import guarded_by
from ..analysis.witness import WITNESS
from ..journal import JOURNAL
from ..logsetup import get_logger
from ..metrics import REGISTRY
from ..provenance import provenance_block
from ..utils.seeds import split_seed
from .primitives import Primitive, Scenario, ScenarioContext

log = get_logger("chaos")

CHAOS_INJECTED = REGISTRY.counter(
    "karpenter_chaos_injected_total",
    "Cross-domain chaos events the orchestrator's schedule delivered, by fault"
    " domain (cloud, kube, solver): the imperative timeline actions — pool"
    " exhaustions, reclaim waves, API latency, watch gaps, lease steals,"
    " crashes. The seeded per-dispatch/per-verb triggers count through their"
    " own families (karpenter_solver_faults_total, karpenter_kube_faults_injected_total).",
    ("domain",),
)

DOMAIN_CLOUD = "cloud"
DOMAIN_KUBE = "kube"
DOMAIN_SOLVER = "solver"
DOMAINS = (DOMAIN_CLOUD, DOMAIN_KUBE, DOMAIN_SOLVER)

# imperative actions the seeded draw may pick, with weights: crashes are the
# heaviest hammer so they stay rare; capacity weather dominates, the way it
# does in production
ACTION_POOL_EXHAUST = "pool-exhaust"
ACTION_POOL_RESTORE = "pool-restore"
ACTION_SPOT_RECLAIM = "spot-reclaim"
ACTION_API_LATENCY = "api-latency"
ACTION_WATCH_GAP = "watch-gap"
ACTION_LEASE_STEAL = "lease-steal"
ACTION_CRASH = "crash"
# never drawn — import-only, the seeded negative control: attaches a watch
# subscription it deliberately never drains, the leak the invariant monitor
# must catch and the shrinker must isolate
ACTION_WATCH_LEAK = "watch-leak"

_ACTION_DOMAIN = {
    ACTION_POOL_EXHAUST: DOMAIN_CLOUD,
    ACTION_POOL_RESTORE: DOMAIN_CLOUD,
    ACTION_SPOT_RECLAIM: DOMAIN_CLOUD,
    ACTION_API_LATENCY: DOMAIN_CLOUD,
    ACTION_CRASH: DOMAIN_CLOUD,
    ACTION_WATCH_GAP: DOMAIN_KUBE,
    ACTION_LEASE_STEAL: DOMAIN_KUBE,
    ACTION_WATCH_LEAK: DOMAIN_KUBE,
}

DEFAULT_ACTIONS: Tuple[Tuple[str, float], ...] = (
    (ACTION_POOL_EXHAUST, 3.0),
    (ACTION_SPOT_RECLAIM, 2.0),
    (ACTION_API_LATENCY, 2.0),
    (ACTION_WATCH_GAP, 3.0),
    (ACTION_CRASH, 0.5),
)

_SOLVER_FAULT_KINDS = ("hbm", "device-lost", "compile")
_SOLVER_ENTRIES = ("plain", "sharded", "pallas")
# (fault, verb, obj_kind) combos the kube draw picks from — each legal at
# its verb per kube/chaos._FAULTS_BY_VERB, each absorbed by an existing
# retry/relist path (the storms must stress, never wedge)
_KUBE_FAULT_COMBOS = (
    ("conflict", "create", "Node"),
    ("conflict", "update", "Node"),
    ("conflict", "update", "Pod"),
    ("stale-read", "get", "Node"),
    ("stale-read", "get", "Pod"),
)


@dataclass
class ChaosEvent:
    """One imperative chaos action on the schedule timeline."""

    index: int
    offset: float  # seconds after the schedule's own start
    domain: str
    action: str
    params: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "index": self.index,
            "offset": self.offset,
            "domain": self.domain,
            "action": self.action,
            "params": dict(self.params),
        }

    @classmethod
    def from_dict(cls, obj: dict) -> "ChaosEvent":
        return cls(
            index=int(obj["index"]),
            offset=float(obj["offset"]),
            domain=str(obj["domain"]),
            action=str(obj["action"]),
            params=dict(obj.get("params", {})),
        )


@guarded_by("_lock", "_executed", "_failed")
@dataclass
class ChaosSchedule(Primitive):
    """A seeded cross-domain chaos schedule, drawn at construction: same
    inputs -> byte-identical `history()`, on every transport, every run.

    The imperative events run on the scenario timeline as one primitive
    (each pool exhaustion gets a paired restore so the schedule can never
    wedge convergence behind a forgotten wall); the seeded solver/kube
    trigger specs export via `solver_specs()` / `kube_specs()` for the
    campaign to arm on the existing injectors. `imported` replaces the
    draw with explicit event dicts — the shrinker's replay path and the
    negative-control composition seam."""

    seed: int = 0
    events_count: int = 12
    horizon: float = 8.0  # seconds of scenario timeline the events spread over
    instance_type: str = "general-4x8"
    zones: Tuple[str, ...] = ("zone-a", "zone-b", "zone-c")
    solver_faults: int = 2  # seeded FaultSpec draws (each emitted per dispatch flavor)
    kube_faults: int = 2  # seeded KubeFaultSpec draws
    actions: Tuple[Tuple[str, float], ...] = DEFAULT_ACTIONS
    imported: Optional[List[dict]] = None

    def __post_init__(self):
        self._lock = WITNESS.lock("chaos.schedule")
        # __post_init__ is not the checker-exempt __init__, so the guarded
        # state initializes under its lock like any other access
        with self._lock:
            self._executed: List[dict] = []
            self._failed: List[dict] = []
        self._solver_specs = self._draw_solver_specs()
        self._kube_specs = self._draw_kube_specs()
        if self.imported is not None:
            self.events = [ChaosEvent.from_dict(e) for e in self.imported]
        else:
            self.events = self._draw_events()

    # -- the seeded draw -------------------------------------------------------

    def _draw_solver_specs(self) -> List[dict]:
        if self.solver_faults <= 0:
            return []
        rng = random.Random(split_seed(self.seed, "chaos.solver-specs"))
        specs: List[dict] = []
        for _ in range(self.solver_faults):
            kind = rng.choice(_SOLVER_FAULT_KINDS)
            nth = rng.randint(2, 8)
            # one spec per dispatch flavor (the PR 13 lesson: only the
            # active flavor's triggers are consumable, so a plain-only spec
            # tests nothing on real TPU hardware where Pallas dispatches)
            for entry in _SOLVER_ENTRIES:
                specs.append({"kind": kind, "entry": entry, "nth": nth, "count": 1})
        return specs

    def _draw_kube_specs(self) -> List[dict]:
        if self.kube_faults <= 0:
            return []
        rng = random.Random(split_seed(self.seed, "chaos.kube-specs"))
        specs: List[dict] = []
        for _ in range(self.kube_faults):
            fault, verb, obj_kind = rng.choice(_KUBE_FAULT_COMBOS)
            specs.append(
                {"fault": fault, "verb": verb, "obj_kind": obj_kind, "nth": rng.randint(2, 12), "count": rng.randint(1, 2)}
            )
        return specs

    def _draw_events(self) -> List[ChaosEvent]:
        rng = random.Random(split_seed(self.seed, "chaos.events"))
        names = [a for a, _ in self.actions]
        weights = [w for _, w in self.actions]
        raw: List[Tuple[float, str, dict]] = []
        for _ in range(self.events_count):
            offset = round(rng.uniform(0.2, self.horizon), 3)
            action = rng.choices(names, weights=weights)[0]
            params = self._draw_params(rng, action)
            raw.append((offset, action, params))
            if action == ACTION_POOL_EXHAUST:
                # the paired restore: an exhausted pool ALWAYS comes back,
                # so a drawn wall can never outlive the schedule and wedge
                # the convergence phase behind it
                restore_at = round(offset + rng.uniform(0.8, 2.0), 3)
                raw.append(
                    (restore_at, ACTION_POOL_RESTORE, {"instance_type": self.instance_type, "zone": params["zone"], "capacity_type": params["capacity_type"]})
                )
        raw.sort(key=lambda e: (e[0], e[1], json.dumps(e[2], sort_keys=True)))
        return [
            ChaosEvent(index=i, offset=offset, domain=_ACTION_DOMAIN[action], action=action, params=params)
            for i, (offset, action, params) in enumerate(raw)
        ]

    def _draw_params(self, rng: random.Random, action: str) -> dict:
        if action == ACTION_POOL_EXHAUST:
            return {
                "instance_type": self.instance_type,
                "zone": rng.choice(list(self.zones)),
                "capacity_type": rng.choice(("spot", "on-demand")),
                "capacity": rng.choice((0, 1)),
            }
        if action == ACTION_SPOT_RECLAIM:
            return {
                "fraction": round(rng.uniform(0.2, 0.5), 2),
                "warning_seconds": 1.0,
                "max_victims": rng.randint(1, 3),
            }
        if action == ACTION_API_LATENCY:
            return {
                "seconds": round(rng.uniform(0.04, 0.1), 3),
                "duration": round(rng.uniform(0.5, 1.2), 2),
                "delayed_requests": 20,
                "throttled_requests": rng.randint(0, 4),
            }
        if action == ACTION_WATCH_GAP:
            return {"duration": round(rng.uniform(0.3, 0.8), 2), "compact": rng.random() < 0.4}
        return {}

    # -- the composition exports ----------------------------------------------

    def solver_specs(self) -> List[dict]:
        """FaultSpec dicts for `solver_faults.FaultPlan.from_specs` — the
        solver seam's share of this schedule's seed."""
        return [dict(s) for s in self._solver_specs]

    def kube_specs(self) -> List[dict]:
        """KubeFaultSpec dicts for `kube_chaos.KubeFaultPlan.from_specs`."""
        return [dict(s) for s in self._kube_specs]

    # -- the determinism witness -----------------------------------------------

    def history(self) -> dict:
        """The full planned chaos sequence — imperative events AND exported
        trigger specs — as a pure function of the construction inputs.
        Byte-identical (json.dumps of this, sorted keys) for the same seed,
        on every transport: the cross-domain determinism witness."""
        return {
            "seed": self.seed,
            "solver_specs": self.solver_specs(),
            "kube_specs": self.kube_specs(),
            "events": [e.to_dict() for e in self.events],
        }

    def history_digest(self) -> str:
        return hashlib.sha256(json.dumps(self.history(), sort_keys=True).encode()).hexdigest()[:16]

    def executed(self) -> List[dict]:
        """Events actually delivered this run, in delivery order."""
        with self._lock:
            return [dict(e) for e in self._executed]

    def failed(self) -> List[dict]:
        """Events whose delivery RAISED this run: never counted as
        injected — a soak whose weather could not be delivered must fail
        its 'schedule fully delivered' convergence bar, not launder the
        miss into chaos_injected_total."""
        with self._lock:
            return [dict(e) for e in self._failed]

    def injected_total(self) -> int:
        with self._lock:
            return len(self._executed)

    # -- execution -------------------------------------------------------------

    def run(self, ctx: ScenarioContext) -> None:
        with self._lock:
            # a fresh run replays the identical schedule
            self._executed = []
            self._failed = []
        if JOURNAL.enabled:
            JOURNAL.chaos_event("schedule", "schedule-armed", seed=self.seed, events=len(self.events))
        log.info("chaos schedule: %d event(s) over %.1fs (seed %d)", len(self.events), self.horizon, self.seed)
        elapsed = 0.0
        for event in self.events:
            wait = event.offset - elapsed
            if wait > 0:
                if ctx.sleep(wait):
                    return
                elapsed = event.offset
            try:
                blocking = self._execute(ctx, event)
            except Exception:  # noqa: BLE001 - one event must not kill the schedule
                # NOT delivered: the event lands in failed(), never in the
                # executed/injected accounting — soak_settled's fully-
                # delivered bar must see the miss, not a laundered count
                log.exception("chaos event %d (%s) failed", event.index, event.action)
                with self._lock:
                    self._failed.append(event.to_dict())
                continue
            elapsed += blocking
            with self._lock:
                self._executed.append(event.to_dict())
            CHAOS_INJECTED.inc(domain=event.domain)
            if JOURNAL.enabled:
                JOURNAL.chaos_event(event.action, "injected", domain=event.domain, index=event.index)

    def _execute(self, ctx: ScenarioContext, event: ChaosEvent) -> float:
        """Deliver one event; returns the seconds it blocked the timeline
        (gap/latency events sleep inline, so later offsets shift — the
        DELIVERY order is the deterministic contract, not wall instants)."""
        p = event.params
        if event.action == ACTION_POOL_EXHAUST:
            ctx.backend.set_pool_capacity(p["instance_type"], p["zone"], p["capacity_type"], int(p["capacity"]))
            return 0.0
        if event.action == ACTION_POOL_RESTORE:
            ctx.backend.set_pool_capacity(p["instance_type"], p["zone"], p["capacity_type"], None)
            return 0.0
        if event.action == ACTION_SPOT_RECLAIM:
            from .primitives import SpotReclaimWave

            SpotReclaimWave(
                fraction=p["fraction"], warning_seconds=p["warning_seconds"], max_victims=p["max_victims"]
            ).run(ctx)
            return 0.0
        if event.action == ACTION_API_LATENCY:
            ctx.backend.inject_api_latency(p["seconds"])
            if ctx.service is not None:
                ctx.service.delay_next(p["delayed_requests"], p["seconds"])
                if p["throttled_requests"]:
                    ctx.service.throttle_next(p["throttled_requests"])
            ctx.sleep(p["duration"])
            ctx.backend.inject_api_latency(0.0)
            return p["duration"]
        if event.action == ACTION_WATCH_GAP:
            from .primitives import WatchGap

            WatchGap(duration=p["duration"], compact=bool(p.get("compact"))).run(ctx)
            return p["duration"]
        if event.action == ACTION_LEASE_STEAL:
            from ..kube.leaderelection import steal_lease

            steal_lease(ctx.kube, identity=p.get("thief", "chaos-thief"))
            return 0.0
        if event.action == ACTION_CRASH:
            ctx.crash_runtime()
            return 0.0
        if event.action == ACTION_WATCH_LEAK:
            # the deliberate bug: a subscription nobody will ever drain —
            # the invariant monitor's watches.leak witness must catch it
            ctx.kube.watch("Pod", lambda _event: None, replay=False)
            return 0.0
        raise ValueError(f"unknown chaos action {event.action!r}")

    def config(self) -> dict:
        """Provenance payload: the drawn schedule is summarized by digest —
        two artifacts compare equal iff they ran the identical chaos."""
        return {
            "kind": type(self).__name__,
            "offset": self.offset,
            "seed": self.seed,
            "events_count": len(self.events),
            "horizon": self.horizon,
            "instance_type": self.instance_type,
            "solver_faults": self.solver_faults,
            "kube_faults": self.kube_faults,
            "history_digest": self.history_digest(),
        }


# -- the soak tier --------------------------------------------------------------


@dataclass
class Soak(Scenario):
    """A scenario kind that represents HOURS of wall time compressed into a
    seconds-scale run: a recorded (or synthesized) arrival trace replayed
    `compress`x faster through PR 12's ReplayTrace, a low-rate background
    ChaosSchedule, and the invariant monitor sampled on the campaign's
    cadence (~one compressed minute per sample at soak compression). The
    leak witnesses — threads, watches, ring budgets, heap slope — are the
    scored acceptance surface a short storm can never exercise."""

    compress: float = 60.0  # one real second = this many compressed seconds
    compressed_span: float = 0.0  # recorded wall-time the load trace spans

    def config(self) -> dict:
        out = super().config()
        out["kind"] = "soak"
        out["compress"] = self.compress
        out["compressed_span"] = self.compressed_span
        return out


def diurnal_trace(seed: int, span_seconds: float, arrivals: int, compress: float, offset: float = 0.0):
    """Synthesize a diurnal arrival trace and wrap it in a ReplayTrace:
    `arrivals` pod creations over `span_seconds` of recorded wall time,
    inter-arrival density following the half-cosine day (quiet night, busy
    midday), replayed `compress`x faster. Deterministic per seed — the
    inverse-CDF draw uses its own fanned-out stream."""
    from .replay import ReplayTrace

    rng = random.Random(split_seed(seed, "soak.trace"))

    def inverse_cdf(u: float) -> float:
        # density f(x) = 1 - cos(2*pi*x) on [0, 1); CDF F(x) = x - sin(2*pi*x)/(2*pi).
        # F is monotone (f >= 0), so bisection converges deterministically.
        lo, hi = 0.0, 1.0
        for _ in range(48):
            mid = (lo + hi) / 2
            if mid - math.sin(2 * math.pi * mid) / (2 * math.pi) < u:
                lo = mid
            else:
                hi = mid
        return (lo + hi) / 2

    times = sorted(inverse_cdf(rng.random()) * span_seconds for _ in range(arrivals))
    events = [
        {"seq": i, "t": round(t, 6), "kind": "pod", "entity": f"replay-{i:05d}", "event": "created"}
        for i, t in enumerate(times)
    ]
    return ReplayTrace.from_events(
        events, compress=compress, offset=offset, source=f"synthetic-diurnal/seed={seed}/span={span_seconds:g}s"
    )


# -- the shrinker ----------------------------------------------------------------


def ddmin(
    events: Sequence[dict], failing: Callable[[List[dict]], bool], max_tests: int = 128
) -> Tuple[List[dict], int]:
    """Delta debugging (Zeller's ddmin) over a recorded chaos schedule:
    deterministically replay subsets of `events` through `failing` until no
    smaller subset still fails. Returns (minimal failing schedule, replays
    run). `failing` must be deterministic — which is exactly what the
    seeded schedule + per-run-fresh cluster guarantee."""
    current = list(events)
    tests = 1
    if not failing(list(current)):
        raise ValueError("ddmin requires a failing input schedule")
    n = 2
    while len(current) >= 2 and tests < max_tests:
        chunk = math.ceil(len(current) / n)
        subsets = [current[i : i + chunk] for i in range(0, len(current), chunk)]
        reduced = False
        for subset in subsets:
            tests += 1
            if failing(list(subset)):
                current, n, reduced = subset, 2, True
                break
        if not reduced and n > 2:
            for i in range(len(subsets)):
                complement = [e for j, s in enumerate(subsets) for e in s if j != i]
                tests += 1
                if failing(list(complement)):
                    current, n, reduced = complement, max(2, n - 1), True
                    break
        if not reduced:
            if n >= len(current):
                break
            n = min(len(current), 2 * n)
    return current, tests


def replay_failing_schedule(events: Sequence[dict], invariant: str = "watches.leak") -> bool:
    """The shrinker's deterministic replay predicate: deliver a recorded
    schedule subset (offsets collapsed — DELIVERY ORDER is the recorded
    contract, wall spacing is not) against a fresh in-memory cluster +
    cloud with the invariant monitor armed, and report whether `invariant`
    fires. Fresh state per replay is what makes ddmin sound: no subset can
    inherit a leak from the previous probe. Re-arms the process-wide
    monitor, so never call it inside a live campaign run."""
    from ..cloudprovider.simulated.backend import CloudBackend
    from ..invariants import MONITOR
    from ..kube.cluster import KubeCluster

    kube = KubeCluster()
    backend = CloudBackend(clock=kube.clock)
    ctx = ScenarioContext(kube, backend, runtime=None)
    schedule = ChaosSchedule(imported=[dict(e, offset=0.0) for e in events])
    MONITOR.arm(kube, backend=backend, clock=kube.clock)
    try:
        schedule.run(ctx)
        MONITOR.sample()
        return any(v["invariant"] == invariant for v in MONITOR.violations())
    finally:
        MONITOR.disarm()
        ctx.stop.set()


def shrink_failing_schedule(scenario: str, seed: int, events: Sequence[dict], invariant: str = "watches.leak") -> dict:
    """ddmin a recorded failing schedule down to its minimal reproducer and
    return the committed SHRINK document: the workflow a broken soak run
    feeds its recorded history through."""
    minimal, replays = ddmin(list(events), lambda subset: replay_failing_schedule(subset, invariant))
    return shrink_doc(scenario, invariant, seed=seed, original=list(events), minimal=minimal, replays=replays)


SHRINK_KEYS = ("scenario", "invariant", "provenance", "seed", "original_events", "minimal_events", "replays")


def shrink_doc(scenario: str, invariant: str, seed: int, original: List[dict], minimal: List[dict], replays: int) -> dict:
    """The committed SHRINK_<scenario>.json shape: provenance + the full
    failing schedule + its ddmin-minimal reproducer."""
    return {
        "scenario": scenario,
        "invariant": invariant,
        "seed": seed,
        "provenance": provenance_block({"scenario": scenario, "invariant": invariant, "seed": seed, "events": minimal}),
        "original_events": list(original),
        "minimal_events": list(minimal),
        "replays": replays,
    }


def shrink_doc_errors(doc) -> List[str]:
    """Structural problems with one SHRINK_*.json document; empty = valid."""
    from ..provenance import provenance_errors

    errs: List[str] = []
    if not isinstance(doc, dict):
        return ["document must be a JSON object"]
    for key in SHRINK_KEYS:
        if key not in doc:
            errs.append(f"missing top-level key {key!r}")
    errs.extend(provenance_errors(doc.get("provenance", {})))
    for key in ("original_events", "minimal_events"):
        events = doc.get(key)
        if not isinstance(events, list) or not events:
            errs.append(f"{key} must be a non-empty list")
            continue
        for i, event in enumerate(events):
            if not isinstance(event, dict):
                errs.append(f"{key}[{i}] must be an object")
                continue
            for required in ("index", "offset", "domain", "action"):
                if required not in event:
                    errs.append(f"{key}[{i}] missing {required!r}")
            action = event.get("action")
            if action is not None:
                if action not in _ACTION_DOMAIN:
                    # a typo'd action replays as a swallowed ValueError — a
                    # reproducer that silently stopped reproducing
                    errs.append(f"{key}[{i}].action {action!r} is not a chaos action (one of {sorted(_ACTION_DOMAIN)})")
                elif event.get("domain") != _ACTION_DOMAIN[action]:
                    errs.append(
                        f"{key}[{i}].domain {event.get('domain')!r} does not match action {action!r}"
                        f" (expected {_ACTION_DOMAIN[action]!r})"
                    )
    minimal = doc.get("minimal_events")
    original = doc.get("original_events")
    if isinstance(minimal, list) and isinstance(original, list) and len(minimal) > len(original):
        errs.append("minimal_events cannot exceed original_events")
    replays = doc.get("replays")
    if replays is not None and (not isinstance(replays, int) or isinstance(replays, bool) or replays < 1):
        errs.append("replays must be a positive integer")
    return errs


def write_shrink(path: str, doc: dict) -> None:
    """Validate then land the reproducer (emit-time crash over silent gap,
    the SCENARIO emit contract)."""
    errors = shrink_doc_errors(doc)
    if errors:
        raise AssertionError(f"shrink document is invalid: {errors}")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    log.info("wrote %s (%d -> %d event(s), %d replay(s))", path, len(doc["original_events"]), len(doc["minimal_events"]), doc["replays"])
