"""Artifact provenance: the who/when/what block every scored JSON carries.

The r2–r5 headline drift stayed unbisectable because the BENCH_*.json
artifacts of that range carried no identity: no commit, no timestamp, no
record of the config that produced them (docs/dense-pipeline.md). Every
emitted artifact — bench phases JSON, `--smoke` summaries, and the scenario
campaign's SCENARIO_*.json — now embeds one `provenance` block so a drifted
number can be walked back to the exact tree and configuration that produced
it without rerunning anything.

    {"git_sha": "4d0b82e...", "dirty": false,
     "timestamp": "2026-08-03T12:00:00+00:00",
     "config_hash": "9f2ab31c04d1e8aa"}

`config_hash` is a stable digest of the caller-supplied config dict
(canonical JSON, sorted keys), so two artifacts are comparable iff the
hashes match — the first question of any bisect.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
from datetime import datetime, timezone
from typing import Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

REQUIRED_KEYS = ("git_sha", "timestamp", "config_hash")


def git_sha(cwd: Optional[str] = None) -> str:
    """HEAD of the repo this module lives in; "unknown" outside a work tree
    (an installed wheel, a bare CI sandbox) — provenance must never be the
    reason an artifact fails to emit."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=cwd or REPO_ROOT,
            capture_output=True,
            text=True,
            timeout=10,
        )
    except (OSError, subprocess.TimeoutExpired):
        return "unknown"
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else "unknown"


def _git_dirty(cwd: Optional[str] = None) -> Optional[bool]:
    try:
        out = subprocess.run(
            ["git", "status", "--porcelain"],
            cwd=cwd or REPO_ROOT,
            capture_output=True,
            text=True,
            timeout=10,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    return bool(out.stdout.strip())


def config_hash(config: dict) -> str:
    """Stable 16-hex digest of a config dict (canonical JSON; non-JSON
    values fall back to repr so a config carrying e.g. a class is still
    hashable deterministically)."""
    canonical = json.dumps(config, sort_keys=True, default=repr)
    return hashlib.sha256(canonical.encode()).hexdigest()[:16]


def provenance_block(config: Optional[dict] = None) -> dict:
    block = {
        "git_sha": git_sha(),
        "timestamp": datetime.now(timezone.utc).isoformat(timespec="seconds"),
        "config_hash": config_hash(config or {}),
    }
    dirty = _git_dirty()
    if dirty is not None:
        block["dirty"] = dirty
    return block


def provenance_errors(block) -> list:
    """Schema check shared by the scenario validator and the bench smoke
    test: required keys present, sha/hash well-formed, timestamp ISO-8601."""
    errs = []
    if not isinstance(block, dict):
        return [f"provenance must be a dict, got {type(block).__name__}"]
    for key in REQUIRED_KEYS:
        if key not in block:
            errs.append(f"provenance missing key {key!r}")
    sha = block.get("git_sha")
    if sha is not None and sha != "unknown":
        if not isinstance(sha, str) or not all(c in "0123456789abcdef" for c in sha) or len(sha) < 7:
            errs.append(f"provenance git_sha {sha!r} is not a commit hash")
    ts = block.get("timestamp")
    if ts is not None:
        try:
            datetime.fromisoformat(str(ts))
        except ValueError:
            errs.append(f"provenance timestamp {ts!r} is not ISO-8601")
    digest = block.get("config_hash")
    if digest is not None and (not isinstance(digest, str) or len(digest) != 16):
        errs.append(f"provenance config_hash {digest!r} is not a 16-hex digest")
    return errs
