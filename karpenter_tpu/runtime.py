"""Runtime bootstrap: assemble and run the full controller manager.

Equivalent of pkg/controllers/controllers.go:86-248 — builds the cloud
provider (wrapped in the metrics decorator), cluster-state cache, and every
controller; registers admission; runs reconciliation loops on threads with
leader-election gating for the singleton loops (provisioning, consolidation,
pricing refresh); exposes health/readiness probes and the metrics registry.

Leader election in a single-process in-memory deployment degenerates to a
local lock, but the gating seam is identical: followers run the state cache
and webhooks, only the leader provisions/consolidates (controllers.go:104).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import List, Optional

from . import webhooks
from .cloudprovider.metrics import decorate
from .cloudprovider.types import CloudProvider
from .config import Config, watch_config
from .controllers.consolidation import ConsolidationController
from .controllers.counter import CounterController
from .controllers.metrics import NodeMetricsScraper, PodMetricsController, ProvisionerMetricsController
from .controllers.node import NodeController
from .controllers.provisioning import ProvisionerController, ProvisioningReconciler
from .controllers.state.cluster import Cluster
from .controllers.termination import TerminationController
from .events import DedupeRecorder, Recorder
from .kube.cluster import KubeCluster
from .logsetup import configure as configure_logging, get_logger, set_level
from .capsule import CAPSULE
from .flight import FLIGHT
from .journal import JOURNAL
from .metrics import REGISTRY
from .slo import SLO
from .tracing import TRACER
from .utils.options import Options

log = get_logger("runtime")


class LeaderElector:
    """Single-flight in-process leadership (kept for embedded/test callers);
    Runtime itself elects through the coordination.k8s.io Lease protocol
    (kube/leaderelection.py), which works identically against the in-memory
    store and a real apiserver."""

    _lock = threading.Lock()
    _leader: Optional[str] = None

    def __init__(self, identity: str):
        self.identity = identity

    def try_acquire(self) -> bool:
        with LeaderElector._lock:
            if LeaderElector._leader in (None, self.identity):
                LeaderElector._leader = self.identity
                return True
            return False

    def release(self) -> None:
        with LeaderElector._lock:
            if LeaderElector._leader == self.identity:
                LeaderElector._leader = None


@dataclass
class Runtime:
    kube: KubeCluster
    cloud_provider: CloudProvider
    options: Options = field(default_factory=Options)
    dense_solver: object = None

    def __post_init__(self):
        configure_logging(self.options.log_level)
        if self.options.enable_lock_witness:
            # must flip BEFORE any component constructs its locks below:
            # witnessing happens at lock creation, and a disabled witness
            # hands out plain (never-wrapped) locks
            from .analysis.witness import WITNESS

            WITNESS.enable()
        if self.options.enable_tracing:
            # the process-wide tracer (tracing.py): spans from every
            # controller pass land in one bounded ring served over
            # /debug/traces on the metrics port
            TRACER.enable(capacity=self.options.trace_ring_size)
        if self.options.enable_solver_telemetry:
            # the solver flight recorder (flight.py): per-solve shape/phase
            # records, XLA compile-churn attribution, HBM gauges — served
            # over /debug/solver on the metrics port
            FLIGHT.enable(capacity=self.options.flight_ring_size)
        # solver circuit breaker (solver/faults.py): tune the process-wide
        # breaker and re-wire its clock to this runtime's seam WITHOUT
        # resetting state — the device is the same device across restarts,
        # so a crash/restart inherits the open/closed history
        from .solver.faults import BREAKER

        BREAKER.configure(
            threshold=self.options.solver_breaker_threshold,
            backoff=self.options.solver_breaker_backoff,
            clock=self.kube.clock,
        )
        if self.options.enable_journal:
            # the lifecycle journal (journal.py): pod/node transition stream
            # + the pending-latency waterfall over /debug/journal and
            # /debug/waterfall. The watch hooks attach below, AFTER the kube
            # backend exists but BEFORE the SLO accountant's (the journal's
            # bound handler must complete a pod's waterfall before the SLO
            # hook cross-feeds the observed pending duration into it)
            JOURNAL.enable(capacity=self.options.journal_ring_size)
            if self.options.journal_spool:
                JOURNAL.set_spool(self.options.journal_spool, self.options.journal_spool_max_bytes)
            JOURNAL.attach(self.kube)
        if self.options.enable_capsules:
            # incident capsules (capsule.py): the typed trigger bus + the
            # SLO burn-rate monitor freeze every telemetry ring into one
            # evidence bundle at /debug/capsules; enabled AFTER the rings
            # it snapshots, clocked by this runtime's seam, polled by the
            # metrics loop below
            CAPSULE.enable(
                spool=self.options.capsule_spool or None,
                spool_max_bytes=self.options.capsule_spool_max_bytes,
                debounce_seconds=self.options.capsule_debounce_seconds,
                clock=self.kube.clock,
            )
        if self.options.residency_audit_interval > 0:
            # residency auditor (solver/audit.py): interval + clock only —
            # enable() is a kwargs-merge, so a harness's shadow cadence and
            # audit seed survive a Runtime restart (the BREAKER.configure
            # discipline)
            from .solver.audit import AUDITOR

            AUDITOR.enable(interval=self.options.residency_audit_interval, clock=self.kube.clock)
        self.config = Config(self.options.batch_max_duration, self.options.batch_idle_duration, self.options.log_level)
        # live log-level reload, the config-logging ConfigMap analog
        # (controllers.go:240-248): a config update re-levels the tree
        self.config.on_change(lambda cfg: set_level(cfg.log_level))
        # live settings from the karpenter-global-settings ConfigMap; keep
        # the unsubscriber — watches dispatch synchronously on the shared
        # cluster, so a stopped/crashed Runtime must detach what it attached
        self._config_unwatch = watch_config(self.kube, self.config)
        self.recorder = DedupeRecorder(Recorder(), clock=self.kube.clock)
        self.cloud_provider = decorate(self.cloud_provider)
        webhooks.register(self.kube, self.cloud_provider)
        self.cluster = Cluster(self.kube, self.cloud_provider, clock=self.kube.clock)
        if self.dense_solver is None and self.options.dense_solver_enabled:
            from .solver import DenseSolver

            min_batch = self.options.dense_min_batch
            if min_batch <= 0:  # auto: measure the dispatch round trip once
                from .solver.dense import measure_dense_crossover

                min_batch = measure_dense_crossover()
            incremental = None
            if self.options.solver_incremental:
                # incremental solve engine (--solver-incremental): fed by the
                # cluster state mirror's delta journal, so the engine and the
                # views it rebases read the same source of truth
                from .solver.incremental import IncrementalEngine

                incremental = IncrementalEngine(self.cluster.delta_journal)
            self.dense_solver = DenseSolver(
                min_batch=min_batch, hbm_budget_bytes=self.options.solver_hbm_budget_bytes,
                incremental=incremental,
            )
        remote_solver = None
        if self.options.solver_service_address:
            from .service.client import SolverClient

            remote_solver = SolverClient(self.options.solver_service_address, timeout=self.options.solver_service_timeout)
        # leadership gate (leader-flap hardening): the singleton loops —
        # provisioning included — consult this event before acting; it is
        # set only while this runtime holds the lease AND its post-(re)gain
        # recovery has finished, so a displaced leader's loops pause before
        # any successor's recovery acts and a re-elected leader reconstructs
        # before it provisions. The epoch counter (written only by the
        # elector thread) fences a recovery that outlived its leadership:
        # a gate must never open for a term that already ended
        self._leader_active = threading.Event()
        self._leader_epoch = 0
        self._recovery_thread: Optional[threading.Thread] = None
        # serializes the recovery thread's check-and-open against the lost
        # callback's bump-and-close: without it the gate could open for a
        # term that ended between the check and the set, with no later
        # transition left to re-close it
        from .analysis.witness import WITNESS as _WITNESS

        self._gate_lock = _WITNESS.lock("runtime.leader-gate")
        self.provisioner = ProvisionerController(
            self.kube, self.cluster, self.cloud_provider, config=self.config,
            recorder=self.recorder, dense_solver=self.dense_solver,
            remote_solver=remote_solver, clock=self.kube.clock,
            ice_backoff_seconds=self.options.ice_backoff_seconds,
            leader_check=self._may_act if self.options.leader_elect else None,
        )
        self.reconciler = ProvisioningReconciler(self.kube, self.provisioner)
        self.node_controller = NodeController(
            self.kube, self.cluster, self.cloud_provider, clock=self.kube.clock,
            # with the disruption orchestrator on, emptiness/expiration are
            # pure candidate sources — the orchestrator owns every voluntary
            # deletion (budgets + the validated command queue)
            delegate_disruption=self.options.disruption_enabled,
        )
        self.termination = TerminationController(self.kube, self.cloud_provider, self.recorder, clock=self.kube.clock)
        self.counter = CounterController(self.kube, self.cluster)
        # the crash-consistency sweep (controllers/gc): cloud instances vs
        # node objects, both directions, at startup and on an interval — the
        # reconciliation that makes a restart-without-leaking possible
        from .controllers.gc import GarbageCollectionController

        self.gc = GarbageCollectionController(
            self.kube, self.cluster, self.cloud_provider, termination=self.termination,
            clock=self.kube.clock, registration_grace=self.options.gc_registration_grace,
        )
        self.consolidation = ConsolidationController(
            self.kube, self.cluster, self.cloud_provider, self.provisioner, self.recorder, clock=self.kube.clock
        )
        # the unified disruption orchestrator: consolidation participates as
        # a candidate source; the orchestrator owns budgets, validation, and
        # execution of ALL voluntary disruption (interruption stays separate
        # — involuntary capacity loss is never budget-limited)
        self.disruption = None
        if self.options.disruption_enabled:
            from .controllers.disruption import DisruptionController

            self.disruption = DisruptionController(
                self.kube, self.cluster, self.cloud_provider, self.provisioner,
                consolidation=self.consolidation, termination=self.termination,
                recorder=self.recorder, clock=self.kube.clock,
            )
        # interruption subsystem: enabled by --interruption-queue against a
        # provider that exposes a notification source (the metrics decorator
        # forwards notification_source to the inner provider); the reference
        # gates its SQS controllers on aws.interruptionQueueName the same way
        self.interruption = None
        if self.options.interruption_queue:
            source_fn = getattr(self.cloud_provider, "notification_source", None)
            source = source_fn() if source_fn is not None else None
            if source is None:
                log.warning(
                    "--interruption-queue=%s set but provider %s exposes no notification source; disabled",
                    self.options.interruption_queue, self.cloud_provider.name(),
                )
            else:
                from .controllers.interruption import InterruptionController

                self.interruption = InterruptionController(
                    self.kube, self.cluster, self.provisioner, source,
                    termination=self.termination, recorder=self.recorder, clock=self.kube.clock,
                    # offering-health feed: a reclaimed spot pool is
                    # quarantined before the proactive replacement solve
                    # (the metrics decorator forwards the provider hook)
                    cloud_provider=self.cloud_provider,
                )
        self.pod_metrics = PodMetricsController(self.kube)
        self.provisioner_metrics = ProvisionerMetricsController(self.kube)
        self.node_metrics = NodeMetricsScraper(self.cluster)
        # SLO accounting (slo.py): watch-driven pending/ready latency plus
        # the cost scraper below, behind --enable-slo. The watch hooks are
        # only attached when enabled, so a disabled runtime's bind path
        # carries no SLO dispatch at all (disabled == free, like tracing)
        from .controllers.metrics import SLOScraper

        self.slo = SLO
        self.slo_metrics = SLOScraper(
            self.kube, self.cluster, self.cloud_provider, provisioner_controller=self.provisioner
        )
        if self.options.enable_slo:
            SLO.enable()
            SLO.attach(self.kube)
        # restart state reconstruction, phase 1: re-list the API into the
        # state cache (the informer re-list) — closes the gap between the
        # watch-registration replay at Cluster construction and the end of
        # runtime assembly, so a successor process starts from the API's
        # truth
        self.cluster.resync()
        import socket
        import uuid

        from .kube.coherence import COHERENCE
        from .kube.leaderelection import LeaseElector

        # hostname + random suffix, the client-go identity recipe — unique
        # across processes (id(self) is a heap address and can collide)
        identity = f"{socket.gethostname()}_{uuid.uuid4().hex[:8]}"
        self.elector = LeaseElector(
            self.kube, identity=identity, clock=self.kube.clock,
            lease_duration=self.options.lease_duration,
            renew_period=self.options.lease_renew_period,
        )
        # informer-coherence witness: this runtime's state cache is under
        # deep-compare for its whole life (the periodic loop only runs when
        # --coherence-interval > 0, but registration is what lets chaos
        # harnesses run the teardown final_check); a stopped/crashed runtime
        # deregisters in _detach_watchers
        self._coherence_name = f"state.cluster/{identity}"
        COHERENCE.register(self._coherence_name, self.cluster)
        # thread census (invariants.py): every thread this runtime spawns —
        # control loops, the provisioner batcher thread, the elector, the
        # leader-recovery task — registers under this owner; stop()/crash()
        # join-with-timeout then release(), and anything still alive at
        # release is a straggler the invariant monitor counts until it dies
        self._census_owner = f"runtime/{identity}"
        # the invariant monitor loop (--invariants-interval): arm against
        # this runtime's backend and sample on the interval. The generation
        # token scopes the teardown: a stopped runtime disarms only the
        # window IT armed, never a successor's (two runtimes in one process,
        # or a crash/restart cycle, must not tear down each other's window)
        self._invariants_generation = None
        if self.options.invariants_interval > 0:
            from .invariants import MONITOR

            self._invariants_generation = MONITOR.arm(self.kube, clock=self.kube.clock)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.solve_duration = REGISTRY.histogram(
            "karpenter_allocation_controller_scheduling_duration_seconds",
            "Duration of provisioning scheduling rounds",
        )
        # one observation (and one span, when tracing is on) per controller
        # pass — the controller-runtime reconcile histogram analog; the
        # provisioning controller feeds the same family from its own round
        self.reconcile_duration = REGISTRY.histogram(
            "karpenter_reconcile_duration_seconds",
            "Duration of controller reconcile passes",
            ("controller",),
        )

    def _pass(self, controller: str, fn):
        """One reconcile pass of one controller: a span (trace root when no
        ambient trace) + the per-controller duration histogram. Idle passes
        (no child spans) are not retained — at ~3 empty traces/sec from the
        lifecycle loop they would evict every interesting trace from the
        bounded ring within minutes; the histogram still observes them."""
        with TRACER.span("reconcile", controller=controller, drop_childless=True):
            with self.reconcile_duration.time(controller=controller):
                return fn()

    # -- health --------------------------------------------------------------

    def healthy(self) -> bool:
        return not self._stop.is_set()

    def ready(self) -> bool:
        return self.cluster.synchronized()

    # -- lifecycle -------------------------------------------------------------

    def _may_act(self) -> bool:
        """The leadership gate the singleton loops consult before every
        pass. True while this runtime holds the lease and its post-gain
        recovery has completed; always True without leader election."""
        return self._leader_active.is_set()

    def _recover(self) -> None:
        """Restart/flap reconstruction, phases 2+3, leader-only (followers
        hold no ledger and must not race the leader's sweep): rebuild the
        disruption ledger / reap-or-adopt from durable markers, then run the
        startup GC sweep so crash leftovers reconcile BEFORE the control
        loops resume acting."""
        if self.disruption is not None:
            self._pass("disruption-recovery", self.disruption.recover)
        self._pass("gc", self.gc.reconcile)

    def _on_leadership_gained(self) -> None:
        """Elector callback, every transition INTO leadership (first
        election and every re-acquisition after a flap): reconstruction runs
        before the gate opens — a re-elected leader is a successor in every
        sense, its in-memory ledgers may have gone stale while someone (or
        no one) else held the lease. Recovery runs on its OWN thread: a
        slow ledger rebuild must not starve the elector's renew loop (a
        lease expiring mid-recovery would elect a peer while we still think
        we are reconstructing toward leadership). The epoch captured here
        fences the gate: if leadership was lost while recovery ran, the
        gate stays closed — the term it was recovering for is over."""
        epoch = self._leader_epoch

        def recover_then_open() -> None:
            try:
                self._recover()
            except Exception:  # noqa: BLE001 - a failed recovery must not strand leadership
                log.exception("post-election recovery failed; acting anyway (GC loop will reconcile)")
            with self._gate_lock:
                # atomic vs _on_leadership_lost: the lost callback always
                # runs AFTER the elector cleared _leading, and it bumps the
                # epoch + clears the gate under this same lock — so a set
                # here either belongs to a live term or is re-closed by the
                # lost callback queued right behind us, never left open
                if self._leader_epoch == epoch and self.elector.is_leader():
                    self._leader_active.set()
                else:
                    log.warning("leadership lost during recovery; gate stays closed for the ended term")

        # tracked apart from _threads (those are run-lifetime loops; this is
        # a short task that EXITS when recovery completes); stop() joins it,
        # and the census watches it like every other runtime-owned thread
        from .invariants import CENSUS

        self._recovery_thread = threading.Thread(target=recover_then_open, name="leader-recovery", daemon=True)
        CENSUS.register(self._census_owner, self._recovery_thread)
        self._recovery_thread.start()

    def _on_leadership_lost(self) -> None:
        """Elector callback, on the lost transition: close the gate FIRST —
        the old leader's loops must pause before any successor's recovery
        acts, and the next gain re-runs recovery before re-opening. The
        epoch bump invalidates any recovery still in flight for the term
        that just ended."""
        with self._gate_lock:
            self._leader_epoch += 1
            self._leader_active.clear()
        log.warning("leadership lost: singleton loops paused until re-elected")

    def start(self) -> None:
        from .invariants import CENSUS

        if self.options.leader_elect:
            # Lease-based election (controllers.go:104-106): block until this
            # runtime holds karpenter-leader-election, keep renewing after.
            # The callbacks drive the leadership gate: recovery runs inside
            # the gained callback, so waiting on _leader_active below means
            # "elected AND reconstructed"
            self.elector.start(
                on_started_leading=self._on_leadership_gained,
                on_stopped_leading=self._on_leadership_lost,
            )
            CENSUS.register(self._census_owner, self.elector.thread)
            while not self.elector.wait_for_leadership(timeout=0.5):
                if self._stop.is_set():
                    return
            log.info("leader election won by %s", self.elector.identity)
            while not self._leader_active.wait(timeout=0.5):
                if self._stop.is_set():
                    return
        log.info(
            "runtime starting: provider=%s dense_solver=%s batch window idle=%.2fs max=%.2fs",
            self.cloud_provider.name(), self.dense_solver is not None,
            self.config.batch_idle_duration, self.config.batch_max_duration,
        )
        if not self.options.leader_elect:
            # no election: this process is the only control plane — run the
            # startup reconstruction inline and open the gate permanently
            self._recover()
            self._leader_active.set()
        self.provisioner.start()
        CENSUS.register(self._census_owner, self.provisioner.thread)
        self._spawn(self._lifecycle_loop, "node-lifecycle")
        if self.options.gc_interval > 0:
            self._spawn(self._gc_loop, "gc")
        if self.disruption is not None:
            # the orchestrator loop REPLACES the consolidation loop: the
            # consolidation controller still evaluates, but as a candidate
            # source inside the orchestrator's budgeted, validated pass
            self._spawn(self._disruption_loop, "disruption")
        else:
            self._spawn(self._consolidation_loop, "consolidation")
        self._spawn(self._metrics_loop, "metrics-scraper")
        # leader-gated per pass (not merely at spawn): a leader whose lease
        # is stolen mid-run pauses these loops at their next tick and a
        # re-election re-opens the gate only after recovery — the election
        # gating of the reference's OD/spot price updaters (pricing.go:76-393)
        self._spawn(self._pricing_loop, "pricing-refresh")
        if self.interruption is not None:
            # same leader gating: only the leader acts on interruption
            # notices (two replicas polling would double-provision)
            self._spawn(self._interruption_loop, "interruption")
        if self.options.coherence_interval > 0:
            self._spawn(self._coherence_loop, "coherence-witness")
        if self.options.invariants_interval > 0:
            self._spawn(self._invariants_loop, "invariant-monitor")

    def _shutdown(self, release_lease: bool) -> None:
        """The shared teardown: halt + join every runtime-owned thread
        (loops, provisioner, recovery, elector), then release the census —
        any thread still alive after its join timeout is logged as a
        straggler and stays under the invariant monitor's watch until it
        dies. Leaving a straggler un-joined used to be invisible; the
        census makes the class impossible to miss."""
        from .invariants import CENSUS, MONITOR

        self._stop.set()
        self._leader_active.clear()
        self.provisioner.stop()
        if self.provisioner.remote_solver is not None:
            self.provisioner.remote_solver.close()
        for thread in self._threads:
            thread.join(timeout=5)
        if self._recovery_thread is not None:
            self._recovery_thread.join(timeout=5)
        self.elector.stop(release=release_lease)
        self._detach_watchers()
        stragglers = CENSUS.release(self._census_owner)
        if stragglers:
            log.warning("runtime shutdown left straggler thread(s) alive: %s", stragglers)
        if self._invariants_generation is not None:
            MONITOR.disarm(self._invariants_generation)
            self._invariants_generation = None

    def stop(self) -> None:
        self._shutdown(release_lease=True)

    def crash(self) -> None:
        """Simulated process death: every loop halts with NO graceful
        cleanup — in-memory state (the budget ledger, the command queue, the
        interruption dedupe memory, nominations) is simply gone, exactly
        what a kill -9 leaves behind. The lease is NOT released (a real
        crash can't); a successor waits out the lease or, in the
        leader_elect=False harnesses, starts immediately. Recovery is the
        next Runtime's startup reconstruction, not this method.

        Watch handlers ARE detached: in a real crash the process (and its
        in-memory subscriptions) dies with it — leaving them registered on
        the shared in-memory cluster would be a dead process still
        executing, not a crash."""
        self._shutdown(release_lease=False)

    def _detach_watchers(self) -> None:
        """Deregister every watch handler this Runtime's components attached
        to the shared KubeCluster. Dispatch is synchronous on the mutating
        thread, so handlers surviving their Runtime would keep mirroring —
        and charging every kube write for — a dead control plane, growing
        linearly with each crash/restart cycle."""
        from .kube.coherence import COHERENCE

        COHERENCE.deregister(self._coherence_name)
        self.cluster.detach()
        self.reconciler.detach()
        if self._config_unwatch is not None:
            self._config_unwatch()
            self._config_unwatch = None

    def _spawn(self, target, name: str) -> None:
        from .invariants import CENSUS

        thread = threading.Thread(target=target, name=name, daemon=True)
        CENSUS.register(self._census_owner, thread)
        thread.start()
        self._threads.append(thread)

    def _lifecycle_loop(self) -> None:
        while not self._stop.wait(timeout=1.0):
            if not self._may_act():
                continue  # not (or no longer) the leader: pause, don't act
            self._pass("node", self.node_controller.reconcile_all)
            self._pass("termination", self.termination.reconcile_all)
            self._pass("counter", self.counter.reconcile_all)

    def _consolidation_loop(self) -> None:
        while not self._stop.wait(timeout=ConsolidationController.POLL_INTERVAL):
            if self._may_act() and self.consolidation.should_run():
                self._pass("consolidation", self.consolidation.process_cluster)

    def _disruption_loop(self) -> None:
        from .controllers.disruption import DisruptionController

        while not self._stop.wait(timeout=DisruptionController.POLL_INTERVAL):
            if not self._may_act():
                continue
            self._pass("disruption", self.disruption.reconcile)

    def _gc_loop(self) -> None:
        while not self._stop.wait(timeout=self.options.gc_interval):
            if not self._may_act():
                continue
            self._pass("gc", self.gc.reconcile)

    def _metrics_loop(self) -> None:
        # never leader-gated: followers keep serving metrics and SLO gauges
        while not self._stop.wait(timeout=5.0):
            self._pass("pod-metrics", self.pod_metrics.scrape)
            self._pass("provisioner-metrics", self.provisioner_metrics.scrape)
            self._pass("node-metrics", self.node_metrics.scrape)
            if self.options.enable_slo:
                self._pass("slo-metrics", self.slo_metrics.scrape)
            if self.options.enable_capsules:
                # drain the trigger bus + run the burn-rate monitor; never
                # leader-gated — a follower's breaker trips are evidence too
                self._pass("capsule-poll", CAPSULE.poll)

    def _coherence_loop(self) -> None:
        from .kube.coherence import COHERENCE

        while not self._stop.wait(timeout=self.options.coherence_interval):
            self._pass("coherence", COHERENCE.check)

    def _invariants_loop(self) -> None:
        # never leader-gated: a follower leaks threads/watches exactly like
        # a leader, and the monitor is read-only over process state
        from .invariants import MONITOR

        while not self._stop.wait(timeout=self.options.invariants_interval):
            self._pass("invariants", MONITOR.sample)

    def _pricing_loop(self) -> None:
        while not self._stop.wait(timeout=self.options.pricing_refresh_period):
            if not self._may_act():
                continue
            self._pass("pricing", self.refresh_pricing_once)

    def _interruption_loop(self) -> None:
        # the receive itself long-polls (wait_seconds) while the transport
        # is healthy; a failed receive (-1) returns instantly, so THAT path
        # waits the full poll interval — otherwise an outage hot-spins.
        # (No _pass wrapper here: the long poll would drown the histogram in
        # idle waits; the controller spans/times each handled notice itself.)
        while not self._stop.is_set():
            if not self._may_act():
                if self._stop.wait(timeout=0.1):
                    return
                continue
            received = self.interruption.poll_once(wait_seconds=self.options.interruption_poll_interval)
            pause = self.options.interruption_poll_interval if received < 0 else 0.05
            if received <= 0 and self._stop.wait(timeout=pause):
                return

    def refresh_pricing_once(self) -> bool:
        """One pricing-refresh tick against providers that support it (the
        metrics decorator forwards refresh_pricing to the inner provider;
        providers without price books are a no-op). Returns True when the
        books changed and the catalog was invalidated."""
        refresh = getattr(self.cloud_provider, "refresh_pricing", None)
        if refresh is None:
            return False
        try:
            return bool(refresh())
        except Exception as err:  # noqa: BLE001 - refresh must never kill the loop
            log.warning("pricing refresh failed (will retry next period): %s", err)
            return False

    # -- synchronous drive (tests / simulations) --------------------------------

    def reconcile_once(self) -> None:
        """One pass of every non-provisioning controller."""
        if self.interruption is not None:
            self._pass("interruption", self.interruption.poll_once)
        self._pass("node", self.node_controller.reconcile_all)
        self._pass("termination", self.termination.reconcile_all)
        self._pass("counter", self.counter.reconcile_all)
        self._pass("gc", self.gc.reconcile)
        if self.disruption is not None:
            self._pass("disruption", self.disruption.reconcile)
        elif self.consolidation.should_run():
            self._pass("consolidation", self.consolidation.process_cluster)
        self._pass("pod-metrics", self.pod_metrics.scrape)
        self._pass("provisioner-metrics", self.provisioner_metrics.scrape)
        self._pass("node-metrics", self.node_metrics.scrape)
        if self.options.enable_slo:
            self._pass("slo-metrics", self.slo_metrics.scrape)

    def provision_once(self):
        from .profiling import maybe_profile_round

        with maybe_profile_round(self.options.enable_profiling, "provision"):
            with self.solve_duration.time():
                return self.provisioner.trigger_and_wait()
