"""Event recording: typed recorder + dedupe decorator (pkg/events)."""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List, Optional, Tuple

# retained events per recorder: a long-lived runtime records on every
# provisioning/termination/interruption action, so an unbounded list is a
# slow leak — the ring keeps the newest window, like the apiserver's event
# TTL keeps only recent history
DEFAULT_EVENT_CAPACITY = 1000


@dataclass
class Event:
    kind: str
    reason: str
    message: str
    object_name: str
    timestamp: float = field(default_factory=time.time)


class Recorder:
    """Typed event surface (pkg/events/recorder.go:24-41). Events live in a
    bounded ring: appending past capacity evicts the oldest."""

    def __init__(self, capacity: int = DEFAULT_EVENT_CAPACITY):
        self.capacity = capacity
        self.events: Deque[Event] = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def _record(self, kind: str, reason: str, message: str, name: str) -> None:
        with self._lock:
            self.events.append(Event(kind, reason, message, name))

    def nominate_pod(self, pod, node) -> None:
        self._record("Pod", "NominatePod", f"Pod should schedule on {node.name}", pod.name)

    def evict_pod(self, pod) -> None:
        self._record("Pod", "EvictPod", "Evicted pod", pod.name)

    def pod_failed_to_schedule(self, pod, err) -> None:
        self._record("Pod", "FailedScheduling", f"Failed to schedule pod, {err}", pod.name)

    def node_failed_to_drain(self, node, err) -> None:
        self._record("Node", "FailedDraining", f"Failed to drain node, {err}", node.name)

    def terminating_node(self, node, reason: str) -> None:
        self._record("Node", "TerminatingNode", reason, node.name)

    def launching_node(self, node, reason: str) -> None:
        self._record("Node", "LaunchingNode", reason, node.name)

    def waiting_on_readiness(self, node) -> None:
        self._record("Node", "WaitingOnReadiness", "Waiting on readiness to continue consolidation", node.name)

    def eviction_blocked(self, pod, reason: str) -> None:
        """A queued eviction that cannot proceed (do-not-disrupt veto):
        surfaced instead of silently retrying forever; identical repeats
        dedupe through DedupeRecorder's TTL window."""
        self._record("Pod", "EvictionBlocked", f"Eviction blocked, {reason}", pod.name)

    # interruption-subsystem events (controllers/interruption); identical
    # notices dedupe through DedupeRecorder's TTL window
    def node_interrupted(self, node, kind: str, message: str) -> None:
        reasons = {
            "spot_interruption": "SpotInterrupted",
            "rebalance_recommendation": "RebalanceRecommended",
            "scheduled_maintenance": "MaintenanceScheduled",
            "instance_stopped": "InstanceStopped",
            "instance_terminated": "InstanceTerminated",
        }
        self._record("Node", reasons.get(kind, "Interrupted"), message, node.name)

    def interruption_replacement_launched(self, node, pod_count: int) -> None:
        self._record(
            "Node", "InterruptionReplacement",
            f"Launching replacement capacity for {pod_count} pod(s) ahead of the drain", node.name,
        )

    def of(self, reason: str) -> List[Event]:
        with self._lock:
            return [e for e in self.events if e.reason == reason]

    def reset(self) -> None:
        with self._lock:
            self.events.clear()


class DedupeRecorder(Recorder):
    """TTL-deduped decorator (pkg/events/dedupe.go:25-95): identical events
    within the window are suppressed."""

    def __init__(self, inner: Recorder, ttl_seconds: float = 120.0, clock=None, capacity: int = DEFAULT_EVENT_CAPACITY):
        super().__init__(capacity=capacity)
        from .utils.clock import Clock

        self.inner = inner
        self.ttl = ttl_seconds
        self.clock = clock or Clock()
        self._seen: dict = {}

    def _record(self, kind: str, reason: str, message: str, name: str) -> None:
        key: Tuple[str, str, str, str] = (kind, reason, message, name)
        now = self.clock.now()
        with self._lock:
            expiry = self._seen.get(key)
            if expiry is not None and expiry > now:
                return
            self._seen[key] = now + self.ttl
            # mirror into our own list so the Recorder read surface
            # (of()/events) works on the wrapper too
            self.events.append(Event(kind, reason, message, name))
        self.inner._record(kind, reason, message, name)
