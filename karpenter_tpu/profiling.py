"""Profiling: host CPU profiles + JAX/XLA device traces.

The pprof analog (reference controllers.go:112-114,183-202 exposes Go pprof
behind --enable-profiling; the benchmark harness writes CPU/heap profiles,
scheduling_benchmark_test.go:79-90). Here:

- :func:`host_profile` — cProfile a block (a provisioning round, a solve)
  and dump a .prof file readable by ``pstats``/``snakeviz``.
- :func:`device_trace` — a JAX profiler trace (TensorBoard-compatible) of
  everything dispatched inside the block: the XLA-trace counterpart for the
  dense solver's device path.
- env seam ``KARPENTER_TPU_PROFILE_DIR``: when set (and profiling enabled
  via Options), Runtime.provision_once wraps every round with both.
"""

from __future__ import annotations

import cProfile
import os
import time
from contextlib import contextmanager
from pathlib import Path
from typing import Iterator, Optional

from .logsetup import get_logger

log = get_logger("profiling")

ENV_DIR = "KARPENTER_TPU_PROFILE_DIR"


@contextmanager
def host_profile(out_path: os.PathLike) -> Iterator[cProfile.Profile]:
    """cProfile the enclosed block; stats land at out_path (.prof)."""
    profiler = cProfile.Profile()
    profiler.enable()
    try:
        yield profiler
    finally:
        profiler.disable()
        out = Path(out_path)
        out.parent.mkdir(parents=True, exist_ok=True)
        profiler.dump_stats(str(out))
        log.info("host profile written to %s", out)


@contextmanager
def device_trace(out_dir: os.PathLike) -> Iterator[None]:
    """JAX profiler trace of every device dispatch in the block.

    Degrades to a no-op (with one warning) if the profiler cannot start —
    tracing must never take the control plane down.
    """
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    started = False
    jax = None
    try:
        import jax

        jax.profiler.start_trace(str(out))
        started = True
    except Exception as exc:  # noqa: BLE001 - incl. import errors: tracing
        # must never take the control plane down
        log.warning("device trace unavailable: %s", exc)
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                log.info("device trace written to %s", out)
            except Exception as exc:  # noqa: BLE001
                log.warning("device trace failed to stop: %s", exc)


def profile_dir() -> Optional[Path]:
    """The env-configured profile output directory, if any."""
    value = os.environ.get(ENV_DIR)
    return Path(value) if value else None


@contextmanager
def maybe_profile_round(enabled: bool, tag: str = "round") -> Iterator[None]:
    """Wrap one provisioning round with host+device profiling when enabled
    and KARPENTER_TPU_PROFILE_DIR is set; no-op otherwise."""
    directory = profile_dir() if enabled else None
    if directory is None:
        yield
        return
    stamp = f"{tag}-{time.strftime('%Y%m%d-%H%M%S')}-{time.monotonic_ns() % 10**9:09d}-{os.getpid()}"
    with host_profile(directory / f"{stamp}.prof"):
        with device_trace(directory / f"{stamp}-device"):
            yield


class LiveProfiler:
    """On-demand profiling over the metrics port — the live half of the
    pprof analog (controllers.go:183-202 serves /debug/pprof/* behind
    --enable-profiling). Per-round artifacts (maybe_profile_round) cover
    offline analysis; these routes profile a RUNNING process, so a live
    latency regression can be inspected without a restart:

      /debug/pprof/            index
      /debug/pprof/profile     ?seconds=N (default 1, cap 60): statistical
                               wall-clock sampler over sys._current_frames()
                               across ALL threads; returns collapsed-stack
                               text (flamegraph.pl / speedscope compatible)
      /debug/pprof/heap        tracemalloc top allocations (tracing starts
                               on the first call; the first response is the
                               baseline)
      /debug/pprof/trace       ?seconds=N: JAX/XLA device trace written
                               under the profile dir; returns the path

    One profile/trace at a time (a lock rejects concurrent captures), and
    the sampler excludes its own serving thread.
    """

    MAX_SECONDS = 60.0
    SAMPLE_INTERVAL = 0.005

    def __init__(self, directory: Optional[os.PathLike] = None):
        import threading

        self._capture_lock = threading.Lock()
        self._dir = Path(directory) if directory else (profile_dir() or Path("profiles"))

    def routes(self) -> dict:
        return {
            "/debug/pprof/": self.index,
            "/debug/pprof/profile": self.profile,
            "/debug/pprof/heap": self.heap,
            "/debug/pprof/trace": self.trace,
        }

    @staticmethod
    def route_descriptions() -> dict:
        """/debug-index descriptions, keyed like routes() (see tracing.py)."""
        return {
            "/debug/pprof/": "live profiling index",
            "/debug/pprof/profile": "statistical host CPU profile (?seconds=N, collapsed stacks)",
            "/debug/pprof/heap": "tracemalloc top allocations",
            "/debug/pprof/trace": "JAX/XLA device trace (?seconds=N, TensorBoard-ready)",
        }

    @staticmethod
    def _seconds(query: dict, default: float = 1.0) -> float:
        try:
            value = float(query.get("seconds", [default])[0])
        except (TypeError, ValueError):
            value = default
        return max(0.05, min(value, LiveProfiler.MAX_SECONDS))

    def index(self, query=None):
        body = "live profiling endpoints:\n  /debug/pprof/profile?seconds=N\n  /debug/pprof/heap\n  /debug/pprof/trace?seconds=N\n"
        return True, "text/plain; charset=utf-8", body

    def profile(self, query=None):
        import sys
        import threading

        if not self._capture_lock.acquire(blocking=False):
            return False, "text/plain; charset=utf-8", "a capture is already running\n"
        try:
            seconds = self._seconds(query or {})
            me = threading.get_ident()
            samples: dict = {}
            total = 0
            deadline = time.monotonic() + seconds
            while time.monotonic() < deadline:
                for tid, frame in sys._current_frames().items():
                    if tid == me:
                        continue
                    stack = []
                    f = frame
                    while f is not None and len(stack) < 64:
                        code = f.f_code
                        stack.append(f"{Path(code.co_filename).name}:{code.co_name}")
                        f = f.f_back
                    key = tuple(reversed(stack))
                    samples[key] = samples.get(key, 0) + 1
                total += 1
                time.sleep(self.SAMPLE_INTERVAL)
            lines = [f"{';'.join(stack)} {n}" for stack, n in sorted(samples.items(), key=lambda kv: -kv[1])]
            header = f"# wall-clock samples over {seconds:.2f}s ({total} sweeps, {self.SAMPLE_INTERVAL * 1000:.0f}ms interval), collapsed-stack format\n"
            return True, "text/plain; charset=utf-8", header + "\n".join(lines) + "\n"
        finally:
            self._capture_lock.release()

    def heap(self, query=None):
        import tracemalloc

        if not tracemalloc.is_tracing():
            tracemalloc.start()
            return True, "text/plain; charset=utf-8", "tracemalloc started; this response is the baseline — call again for allocations\n"
        snapshot = tracemalloc.take_snapshot()
        stats = snapshot.statistics("lineno")[:30]
        lines = [f"{stat.size / 1024:.1f} KiB in {stat.count} blocks: {stat.traceback}" for stat in stats]
        return True, "text/plain; charset=utf-8", "\n".join(lines) + "\n"

    def trace(self, query=None):
        if not self._capture_lock.acquire(blocking=False):
            return False, "text/plain; charset=utf-8", "a capture is already running\n"
        try:
            seconds = self._seconds(query or {})
            out = self._dir / f"live-trace-{time.strftime('%Y%m%d-%H%M%S')}"
            with device_trace(out):
                time.sleep(seconds)
            return True, "text/plain; charset=utf-8", f"device trace written to {out}\n"
        finally:
            self._capture_lock.release()
