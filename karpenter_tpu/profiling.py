"""Profiling: host CPU profiles + JAX/XLA device traces.

The pprof analog (reference controllers.go:112-114,183-202 exposes Go pprof
behind --enable-profiling; the benchmark harness writes CPU/heap profiles,
scheduling_benchmark_test.go:79-90). Here:

- :func:`host_profile` — cProfile a block (a provisioning round, a solve)
  and dump a .prof file readable by ``pstats``/``snakeviz``.
- :func:`device_trace` — a JAX profiler trace (TensorBoard-compatible) of
  everything dispatched inside the block: the XLA-trace counterpart for the
  dense solver's device path.
- env seam ``KARPENTER_TPU_PROFILE_DIR``: when set (and profiling enabled
  via Options), Runtime.provision_once wraps every round with both.
"""

from __future__ import annotations

import cProfile
import os
import time
from contextlib import contextmanager
from pathlib import Path
from typing import Iterator, Optional

from .logsetup import get_logger

log = get_logger("profiling")

ENV_DIR = "KARPENTER_TPU_PROFILE_DIR"


@contextmanager
def host_profile(out_path: os.PathLike) -> Iterator[cProfile.Profile]:
    """cProfile the enclosed block; stats land at out_path (.prof)."""
    profiler = cProfile.Profile()
    profiler.enable()
    try:
        yield profiler
    finally:
        profiler.disable()
        out = Path(out_path)
        out.parent.mkdir(parents=True, exist_ok=True)
        profiler.dump_stats(str(out))
        log.info("host profile written to %s", out)


@contextmanager
def device_trace(out_dir: os.PathLike) -> Iterator[None]:
    """JAX profiler trace of every device dispatch in the block.

    Degrades to a no-op (with one warning) if the profiler cannot start —
    tracing must never take the control plane down.
    """
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    started = False
    jax = None
    try:
        import jax

        jax.profiler.start_trace(str(out))
        started = True
    except Exception as exc:  # noqa: BLE001 - incl. import errors: tracing
        # must never take the control plane down
        log.warning("device trace unavailable: %s", exc)
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                log.info("device trace written to %s", out)
            except Exception as exc:  # noqa: BLE001
                log.warning("device trace failed to stop: %s", exc)


def profile_dir() -> Optional[Path]:
    """The env-configured profile output directory, if any."""
    value = os.environ.get(ENV_DIR)
    return Path(value) if value else None


@contextmanager
def maybe_profile_round(enabled: bool, tag: str = "round") -> Iterator[None]:
    """Wrap one provisioning round with host+device profiling when enabled
    and KARPENTER_TPU_PROFILE_DIR is set; no-op otherwise."""
    directory = profile_dir() if enabled else None
    if directory is None:
        yield
        return
    stamp = f"{tag}-{time.strftime('%Y%m%d-%H%M%S')}-{time.monotonic_ns() % 10**9:09d}-{os.getpid()}"
    with host_profile(directory / f"{stamp}.prof"):
        with device_trace(directory / f"{stamp}-device"):
            yield
