"""Structured logging for the controller runtime.

Equivalent of the reference's zap-via-knative setup with live level reload
from the config-logging ConfigMap (pkg/controllers/controllers.go:240-248):

- every module logs through ``get_logger("karpenter_tpu.<area>")``;
- :func:`configure` installs one stream handler with a structured
  single-line format on the package root logger;
- :func:`set_level` re-levels the whole tree at runtime — wired to the
  live Config (config.py) by the Runtime so operators can turn on debug
  logging without a restart, mirroring the ConfigMap watch.

Nothing here touches the global root logger: embedding applications keep
their own logging topology, and tests can assert on records with the
standard ``caplog`` machinery.
"""

from __future__ import annotations

import logging
import sys
import threading
from typing import Optional

ROOT = "karpenter_tpu"

LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}
_LEVELS = LEVELS  # backwards-compatible alias


def is_valid_level(name: str) -> bool:
    return str(name).lower() in LEVELS

_lock = threading.Lock()
_configured = False


class _Formatter(logging.Formatter):
    """level ts logger message — single line, machine-splittable."""

    default_msec_format = "%s.%03d"

    def format(self, record: logging.LogRecord) -> str:
        record.shortname = record.name[len(ROOT) + 1 :] if record.name.startswith(ROOT + ".") else record.name
        return super().format(record)


def get_logger(name: str = ROOT) -> logging.Logger:
    """Logger under the package tree; accepts short area names."""
    if not name.startswith(ROOT):
        name = f"{ROOT}.{name}"
    return logging.getLogger(name)


def configure(level: str = "info", stream=None) -> logging.Logger:
    """Install the package handler (idempotent) and set the level."""
    global _configured
    root = logging.getLogger(ROOT)
    with _lock:
        if not _configured:
            handler = logging.StreamHandler(stream or sys.stderr)
            handler.setFormatter(
                _Formatter("%(levelname).1s%(asctime)s %(shortname)s: %(message)s", datefmt="%H:%M:%S")
            )
            root.addHandler(handler)
            root.propagate = False
            _configured = True
    set_level(level)
    return root


def set_level(level: str) -> None:
    """Re-level the whole package tree (live reload seam).

    Unknown names fall back to info — a bad ConfigMap value must never
    take logging down.
    """
    logging.getLogger(ROOT).setLevel(_LEVELS.get(str(level).lower(), logging.INFO))


def current_level() -> str:
    lv = logging.getLogger(ROOT).getEffectiveLevel()
    for name, value in _LEVELS.items():
        if value == lv:
            return name
    return str(lv)


def reset_for_tests() -> None:
    """Remove the handler (and restore propagation, so pytest's caplog sees
    records again) so repeated configure() calls in tests start clean."""
    global _configured
    root = logging.getLogger(ROOT)
    with _lock:
        for h in list(root.handlers):
            root.removeHandler(h)
        root.propagate = True
        _configured = False
