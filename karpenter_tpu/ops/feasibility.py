"""Feasibility kernels: the dense [P, T] masks.

This is the tensor reformulation of the reference's per-pod instance-type
survivor filter (scheduling/node.go:139-161): instead of filtering a Go slice
per pod, the whole pods x types feasibility surface is one broadcasted
compare-reduce that XLA tiles onto the VPU/MXU. Label/taint/offering
compatibility arrives pre-reduced to [G, T] rows over constraint-signature
groups (ir/encode.py) and is gathered per pod.

Shapes are padded to fixed tiles by the solver so recompilation doesn't
happen per batch (compiled-shape bucketing).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def resource_fit(requests: jax.Array, caps: jax.Array) -> jax.Array:
    """[P, T] bool: pod p fits an *empty* node of type t.

    requests: [P, R] effective pod requests (daemon overhead NOT included —
    the caller bakes overhead into caps).
    caps: [T, R] effective capacities (resources - overhead - daemonset).
    """
    # [P, 1, R] <= [1, T, R] -> all over R
    return jnp.all(requests[:, None, :] <= caps[None, :, :] + 1e-6, axis=-1)


@jax.jit
def feasibility_mask(requests: jax.Array, caps: jax.Array, compat: jax.Array, group_ids: jax.Array) -> jax.Array:
    """[P, T] bool: resource fit AND label/taint/offering compatibility.

    compat: [G, T] bool group compatibility rows; group_ids: [P] int32.
    """
    rows = jnp.take(compat, group_ids, axis=0)  # [P, T]
    return resource_fit(requests, caps) & rows


@jax.jit
def availability_counts(pair: jax.Array, cube: jax.Array) -> jax.Array:
    """[B, T] bool: bucket b and type t share >= 1 available (zone,
    capacity-type) offering cell.

    pair: [B, Z*C] f32 0/1 bucket allowances (zone x capacity-type outer
    product, flattened); cube: [T, Z*C] f32 0/1 offering-availability cube
    rows (quarantined pools are zeros). One fused matmul + threshold; the
    bool download is a quarter of the f32 counts the host used to fetch.

    The cube is an ARGUMENT, never a closure: closing over the per-catalog
    cube here would bake it into every shape bucket's compiled executable
    (the program-constant contract, analysis/rules/programcheck.py, pins
    this surface at zero captured bytes).
    """
    return jnp.matmul(pair, cube.T) > 0.5


@jax.jit
def bucket_type_cost_packed(bucket_stats: jax.Array, caps: jax.Array, prices: jax.Array, allowed: jax.Array) -> jax.Array:
    """Transfer-minimal wrapper: bucket_stats = stack([sum, max]) [2, B, R];
    returns one packed int32 [3, B] = (tstar, bins, feasible). One upload of
    per-batch data, one download — dispatch latency over the host<->device
    link dominates at this problem size, so round trips are the budget."""
    tstar, bins, feasible = bucket_type_cost(bucket_stats[0], bucket_stats[1], caps, prices, allowed)
    return jnp.stack([tstar, bins, feasible.astype(jnp.int32)])


@jax.jit
def bucket_type_cost(sum_requests: jax.Array, max_requests: jax.Array, caps: jax.Array, prices: jax.Array, allowed: jax.Array):
    """Vectorized bucket -> instance-type choice.

    For each pack bucket b (a set of pods that will share nodes):
      bins[b, t]  = max_r ceil(sum_requests[b, r] / caps[t, r])   (how many
                    nodes of type t the bucket needs)
      frac[b, t]  = max_r (sum_requests[b, r] / caps[t, r])       (fractional
                    lower bound)
    feasible iff allowed AND the largest single pod fits the type.
    Choice key minimizes fractional cost first (the continuous optimum —
    favors large types whose last bin gets downsized at commit), then bin
    count, then price.

    Returns (tstar [B] int32, bins [B] int32, feasible_any [B] bool).
    """
    eps = 1e-9
    safe_caps = jnp.maximum(caps, eps)  # [T, R]
    ratio = sum_requests[:, None, :] / safe_caps[None, :, :]  # [B, T, R]
    # resources the type simply doesn't have (cap==0) but the bucket needs
    impossible = (caps[None, :, :] <= eps) & (sum_requests[:, None, :] > eps)
    frac = jnp.max(jnp.where(impossible, jnp.inf, ratio), axis=-1)  # [B, T]
    bins = jnp.ceil(jnp.maximum(frac, eps))
    pod_fits = jnp.all(max_requests[:, None, :] <= caps[None, :, :] + 1e-6, axis=-1)  # [B, T]
    ok = allowed & pod_fits & jnp.isfinite(frac)
    frac_cost = frac * prices[None, :]
    # composite lexicographic-ish key; verified exactly at commit time
    key = frac_cost + bins * 1e-4 + prices[None, :] * 1e-7
    key = jnp.where(ok, key, jnp.inf)
    # lax.argmin with an explicit index_dtype: jnp.argmin's index type follows
    # jax_enable_x64 (int64 under the flag), which makes the compiled program
    # depend on process config — the program-promotion contract pins i32
    tstar = jax.lax.argmin(key, 1, jnp.int32)
    chosen_bins = jnp.take_along_axis(bins, tstar[:, None], axis=1)[:, 0]
    feasible_any = jnp.any(ok, axis=1)
    return tstar, chosen_bins.astype(jnp.int32), feasible_any
