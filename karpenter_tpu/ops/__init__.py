from .feasibility import bucket_type_cost, feasibility_mask, resource_fit
from .packing import audit_layout, segment_usage

__all__ = ["bucket_type_cost", "feasibility_mask", "resource_fit", "audit_layout", "segment_usage"]
