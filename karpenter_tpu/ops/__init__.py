# ops.warmfill is deliberately NOT re-exported here: importing it executes
# jax.experimental.pallas at module level, and the solver's fallback
# discipline (warmfill._device_counts, pallas_kernels' lazy imports) depends
# on that import staying deferred until a kernel is actually requested
from .feasibility import bucket_type_cost, feasibility_mask, resource_fit
from .packing import audit_layout, segment_usage

__all__ = ["bucket_type_cost", "feasibility_mask", "resource_fit", "audit_layout", "segment_usage"]
