"""Warm-fill kernels: the dense [sizes x existing-views] admission surface.

The repack/consolidation flagship spends its whole budget filling existing
nodes (scheduler.go:191-195 existing-first), and through round 5 that fill
was a sequential host loop with zero device work (VERDICT r5 missing #1).
The device half of the vectorized fill is this kernel: for every distinct
pod SIZE CLASS in the batch and every existing view, how many pods of that
size the view's residual headroom could absorb — the same closed form the
certified cohort fast path evaluates per (run, view) pair
(existingnode.py:add_certified_view_run), lifted to one [S, V, R]
broadcast-reduce.

Numerics contract: the device computes in f32 with a deliberate upward
slack, so its counts are an UPPER BOUND on the exact f64 closed form. The
host scan (solver/warmfill.py) uses the surface only to prune views that
can never take a pod of a size class (count == 0 is then exact-safe); every
actual placement is re-derived with the host's exact f64 arithmetic, so a
boundary the f32 kernel rounds the other way costs one wasted probe, never
a wrong placement.

Like ops/feasibility.py vs pallas_kernels.py, the jnp path is the portable
fallback and the fused Pallas kernel is the TPU fast path; the differential
test (tests/test_pallas.py) pins the two to identical outputs on identical
f32 inputs, interpreter mode off-TPU.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# relative slack applied on the device so f32 rounding can only round the
# count UP vs the exact f64 closed form (f32 rel. error ~1.2e-7 per operand)
_SLACK = 4e-6

_LANE = 128
_SUBLANE = 8


def _ceil_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def warm_fill_counts_np(sizes: np.ndarray, head: np.ndarray) -> np.ndarray:
    """Exact f64 reference: [S, V] int32 closed-form counts.

    sizes: [S, R] f64 per-size-class request vectors; head: [V, R] f64
    residual headroom (available + tolerance - requests). A view whose
    headroom is negative on ANY resource takes nothing (the certified run's
    base-fits gate); a size's count is the min over its positive resources
    of floor(head / size)."""
    base_ok = (head >= 0).all(axis=1)  # [V]
    positive = sizes > 0  # [S, R]
    with np.errstate(divide="ignore", invalid="ignore"):
        ratio = head[None, :, :] / np.where(positive, sizes, 1.0)[:, None, :]  # [S, V, R]
    ratio = np.where(positive[:, None, :], ratio, np.inf)
    counts = np.floor(ratio.min(axis=2))
    counts = np.where(np.isfinite(counts), counts, float(np.iinfo(np.int32).max))
    counts = np.clip(counts, 0, np.iinfo(np.int32).max)
    return (counts * base_ok[None, :]).astype(np.int32)


@jax.jit
def warm_fill_counts(sizes: jax.Array, head: jax.Array) -> jax.Array:
    """jnp path: [S, V] int32 upper-bound counts on f32 [S, R] / [V, R]
    inputs (slacked up — see module docstring)."""
    eps = jnp.float32(1e-12)
    big = jnp.float32(2 ** 30)
    slack = jnp.float32(_SLACK)
    base_ok = jnp.all(head >= -eps, axis=1)  # [V]
    positive = sizes > 0  # [S, R]
    slack_head = head * (jnp.float32(1.0) + slack) + slack
    safe_sizes = jnp.where(positive, sizes, jnp.float32(1.0)) * (jnp.float32(1.0) - slack)
    ratio = slack_head[None, :, :] / safe_sizes[:, None, :]  # [S, V, R]
    ratio = jnp.where(positive[:, None, :], ratio, big)
    counts = jnp.floor(jnp.min(ratio, axis=2))
    counts = jnp.clip(counts, 0.0, big)
    return (counts * base_ok[None, :].astype(jnp.float32)).astype(jnp.int32)


# -- fused Pallas kernel ------------------------------------------------------


def _kernel(sizes_ref, head_ref, out_ref):
    """sizes: [S, R]; head: [R, V] (transposed for lane-contiguous view
    rows); out: [S, V] int32. R is unrolled (static, small); masks are
    materialized f32 0/1 tensors — see pallas_kernels.py's Mosaic note."""
    S = sizes_ref.shape[0]
    R = sizes_ref.shape[1]
    V = head_ref.shape[1]
    eps = jnp.float32(1e-12)
    one = jnp.float32(1.0)
    zero = jnp.float32(0.0)
    big = jnp.float32(2 ** 30)
    slack = jnp.float32(_SLACK)
    ones_sv = jnp.ones((S, V), jnp.float32)

    counts = big * ones_sv
    base_ok = ones_sv
    for r in range(R):  # static unroll: R is the (small) resource arity
        head_r = head_ref[r, :][None, :] * ones_sv  # [S, V]
        s_r = sizes_ref[:, r][:, None] * ones_sv
        base_ok = base_ok * jnp.where(head_r >= -eps, one, zero)
        slack_head = head_r * (one + slack) + slack
        safe_size = jnp.maximum(s_r, eps) * (one - slack)
        ratio = slack_head / safe_size
        ratio = jnp.where(s_r > zero, ratio, big)
        counts = jnp.minimum(counts, ratio)
    counts = jnp.floor(counts)
    counts = jnp.minimum(jnp.maximum(counts, zero), big)
    out_ref[:, :] = (counts * base_ok).astype(jnp.int32)


@partial(jax.jit, static_argnames=("interpret",))
def _warm_fill_counts_pallas_padded(sizes_p, head_t, interpret):
    S = sizes_p.shape[0]
    V = head_t.shape[1]
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((S, V), jnp.int32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(sizes_p, head_t)


def pad_warm_fill(sizes: np.ndarray, head: np.ndarray):
    """Host-side padding: [S, R] sizes + [V, R] head → ([Sp, R] f32 sizes,
    [R, Vp] f32 transposed head). Padded size rows are all-zero → their
    counts saturate and the caller strips them; padded view columns carry
    head = -1 → base_ok false → count 0, never probed."""
    S, R = sizes.shape
    V = head.shape[0]
    Sp = _ceil_to(max(S, 1), _SUBLANE)
    Vp = _ceil_to(max(V, 1), _LANE)
    sizes_p = np.zeros((Sp, R), np.float32)
    sizes_p[:S] = sizes
    head_t = np.full((R, Vp), -1.0, np.float32)
    head_t[:, :V] = head.T
    return sizes_p, head_t


def warm_fill_counts_pallas(sizes: np.ndarray, head: np.ndarray) -> np.ndarray:
    """Fused-kernel drop-in for warm_fill_counts on numpy inputs: pads,
    dispatches once, strips. Same contract (upper-bound counts)."""
    S = sizes.shape[0]
    V = head.shape[0]
    sizes_p, head_t = pad_warm_fill(np.asarray(sizes, np.float32), np.asarray(head, np.float32))
    out = _warm_fill_counts_pallas_padded(
        jnp.asarray(sizes_p), jnp.asarray(head_t), jax.default_backend() != "tpu"
    )
    return np.asarray(out)[:S, :V]
