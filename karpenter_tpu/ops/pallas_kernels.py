"""Pallas TPU kernel for the bucket→instance-type cost choice.

Fuses the whole bucket_type_cost computation (ops/feasibility.py:53 — the
tensor reformulation of the reference's per-node instance-type filter,
scheduling/node.go:139-161) into ONE kernel: the [B, T, R] ratio surface is
never materialized in HBM. The resource axis is unrolled in-register (R is
static and small), so the working set is a handful of [B, T] f32 tiles in
VMEM and the kernel is one VPU pass: ratio-max, ceil, feasibility mask,
composite cost key, masked argmin, and the packed int32 [3, B] result that
the solver downloads in a single transfer.

On non-TPU backends the kernel runs in interpreter mode (tests); the jnp
path in feasibility.py remains the fallback and the differential test
(tests/test_pallas.py) pins the two to identical outputs on identical f32
inputs.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128
_SUBLANE = 8


def _ceil_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _kernel(sum_ref, max_ref, caps_ref, prices_ref, allowed_ref, out_ref):
    """sum/max: [B, R]; caps: [R, T] (transposed for lane-contiguous rows);
    prices: [1, T]; allowed: [B, T] int8; out: [3, B] int32."""
    # Mosaic note: boolean (i1) vectors with broadcast/replicated layouts
    # fail to relayout on TPU, so every mask here is a materialized [B, T]
    # f32 0/1 tensor combined with multiplies, and comparisons only run on
    # already-broadcast f32 operands.
    B = sum_ref.shape[0]
    R = sum_ref.shape[1]
    T = caps_ref.shape[1]
    eps = jnp.float32(1e-9)
    inf = jnp.float32(jnp.inf)
    one = jnp.float32(1.0)
    zero = jnp.float32(0.0)
    ones_bt = jnp.ones((B, T), jnp.float32)

    frac = jnp.zeros((B, T), jnp.float32)
    fits = ones_bt
    for r in range(R):  # static unroll: R is the (small) resource arity
        cap_r = caps_ref[r, :][None, :] * ones_bt  # materialized [B, T]
        s_r = sum_ref[:, r][:, None] * ones_bt
        m_r = max_ref[:, r][:, None] * ones_bt
        ratio = s_r / jnp.maximum(cap_r, eps)
        # type lacks the resource entirely (cap==0) but the bucket needs it
        impossible = jnp.where(cap_r <= eps, one, zero) * jnp.where(s_r > eps, one, zero)
        frac = jnp.maximum(frac, jnp.where(impossible > zero, inf, ratio))
        fits = fits * jnp.where(m_r <= cap_r + jnp.float32(1e-6), one, zero)

    bins = jnp.ceil(jnp.maximum(frac, eps))
    allowed = allowed_ref[:].astype(jnp.float32)
    finite = jnp.where(frac < inf, one, zero)
    ok = allowed * fits * finite  # [B, T] 0/1
    prices = prices_ref[0, :][None, :] * ones_bt
    key = frac * prices + bins * jnp.float32(1e-4) + prices * jnp.float32(1e-7)
    key = jnp.where(ok > zero, key, inf)

    min_key = jnp.min(key, axis=1, keepdims=True) * ones_bt  # materialized
    col = jax.lax.broadcasted_iota(jnp.int32, (B, T), 1).astype(jnp.float32)
    # first index achieving the minimum — exact jnp.argmin semantics
    # (all-inf rows: inf == inf everywhere, so the min below is column 0)
    idx = jnp.where(key == min_key, col, jnp.float32(T))
    tstar_f = jnp.min(idx, axis=1)  # [B]
    tstar_b = tstar_f[:, None] * ones_bt
    at_star = jnp.where(col == tstar_b, one, zero)
    safe_bins = jnp.where(ok > zero, bins, zero)  # bins may be inf when infeasible
    chosen = jnp.sum(at_star * safe_bins, axis=1)  # 0 when infeasible
    feasible = jnp.max(ok, axis=1)

    out_ref[0, :] = tstar_f.astype(jnp.int32)
    out_ref[1, :] = chosen.astype(jnp.int32)
    out_ref[2, :] = feasible.astype(jnp.int32)


@partial(jax.jit, static_argnames=("interpret",))
def _bucket_type_cost_padded(sum_requests, max_requests, caps_t, prices, allowed, interpret):
    B = sum_requests.shape[0]
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((3, B), jnp.int32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(sum_requests, max_requests, caps_t, prices, allowed)


def pad_catalog(caps, prices):
    """Host-side (numpy) catalog padding: [T, R] caps + [T] prices →
    ([R, Tp] transposed caps, [1, Tp] prices), Tp a lane multiple. The caller
    uploads these once per catalog and reuses them across solves — over a
    tunnel-attached TPU, per-dispatch transfers are the latency budget."""
    import numpy as np

    T, R = caps.shape
    Tp = _ceil_to(max(T, 1), _LANE)
    caps_t = np.zeros((R, Tp), np.float32)
    caps_t[:, :T] = caps.T
    prices_p = np.zeros((1, Tp), np.float32)
    prices_p[0, :T] = prices
    return caps_t, prices_p


def pad_batch(bucket_stats, allowed):
    """Host-side (numpy) per-batch padding: [2, B, R] stats + [B, T] allowed
    → ([Bp, R] sum, [Bp, R] max, [Bp, Tp] int8 allowed). Padded rows keep
    allowed=0 → infeasible → stripped by the caller; padded type columns
    keep allowed=0 → key=inf → never chosen."""
    import numpy as np

    B, R = bucket_stats.shape[1], bucket_stats.shape[2]
    T = allowed.shape[1]
    Bp, Tp = _ceil_to(max(B, 1), _SUBLANE), _ceil_to(max(T, 1), _LANE)
    sum_p = np.zeros((Bp, R), np.float32)
    sum_p[:B] = bucket_stats[0]
    max_p = np.zeros((Bp, R), np.float32)
    max_p[:B] = bucket_stats[1]
    allowed_p = np.zeros((Bp, Tp), np.int8)
    allowed_p[:B, :T] = allowed
    return sum_p, max_p, allowed_p


def bucket_type_cost_padded(sum_p, max_p, caps_t, prices_p, allowed_p):
    """One fused kernel dispatch on pre-padded inputs → [3, Bp] int32."""
    # solver fault-domain injection seam (solver/faults.py): chaos tests
    # raise exactly the typed fault they claim to test at THIS device
    # boundary; one attribute read when no plan is installed
    from ..solver.faults import FAULTS

    FAULTS.check("pallas")
    return _bucket_type_cost_padded(sum_p, max_p, caps_t, prices_p, allowed_p, jax.default_backend() != "tpu")


def bucket_type_cost_pallas(bucket_stats, caps, prices, allowed):
    """Convenience drop-in for ops/feasibility.py:bucket_type_cost_packed
    (pads, dispatches, strips). bucket_stats: [2, B, R] f32; caps: [T, R]
    f32; prices: [T] f32; allowed: [B, T] bool. Returns [3, B] int32
    (tstar, bins, feasible) — identical contract and tie-breaking as the
    jnp path. The solver uses the split pad_catalog/pad_batch entry points
    to amortize catalog upload."""
    B = bucket_stats.shape[1]
    caps_t, prices_p = pad_catalog(caps, prices)
    sum_p, max_p, allowed_p = pad_batch(bucket_stats, allowed)
    out = bucket_type_cost_padded(
        jnp.asarray(sum_p), jnp.asarray(max_p), jnp.asarray(caps_t), jnp.asarray(prices_p), jnp.asarray(allowed_p)
    )
    return out[:, :B]
