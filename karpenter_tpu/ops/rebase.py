"""Rebase kernel: in-place delta application for the resident view surface.

The incremental engine (solver/incremental.py) keeps the warm-view headroom
matrix `head0` device-resident across provision passes.  Between passes the
cluster shifts under it — nodes appear and vanish (rows come and go) and
pods bind/unbind (surviving rows change values).  This kernel rebases the
prior pass's buffer into the current pass's layout in ONE fused dispatch:

    out[v] = rows[j]            if v is dirty (idx[j] == v)
    out[v] = buf[perm[v]]       if v survived (perm[v] is its old row)
    out[v] = -1.0               if v is new-but-clean padding (perm[v] < 0)

`buf` is DONATED (donate_argnums=0): the prior pass's device buffer is
consumed and its storage reused for the output, so steady-state residency
costs one buffer, not two — the same `donate_argnums` lifecycle the sharded
solve step uses for its carry (SNIPPETS [2], PR 11).  The contracts suite
byte-audits that donation (out and buf agree in size/dtype by contract).

Shapes are PADDED STABLE so steady state never recompiles: the view axis
pads to the lane multiple (128, only regrowing when the cluster outgrows
the pad), and the dirty axis pads on a pow2 ladder from 8 — a tick that
dirties 3 rows and one that dirties 7 share the Dp=8 entry.  Padding is
encoded in-band: padded idx slots point past the buffer (`mode="drop"`
makes the scatter a no-op) and padded perm slots are -1 (gather yields the
-1.0 dead-row sentinel, matching encode_warm_views' unusable-view rows).

f32 only — this is the same surface ops/warmfill.py consumes, and its
upper-bound slack discipline (counts pruned on device, placements re-derived
exactly on host) already absorbs f32 rounding.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_LANE = 128
_DIRTY_BASE = 8


def _ceil_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def pad_views(n: int) -> int:
    """View-axis pad: lane multiple, minimum one lane."""
    return _ceil_to(max(n, 1), _LANE)


def pad_dirty(n: int) -> int:
    """Dirty-axis pad: pow2 ladder from 8, so per-tick delta sizes collapse
    onto a handful of compiled shapes."""
    p = _DIRTY_BASE
    while p < n:
        p *= 2
    return p


@partial(jax.jit, donate_argnums=(0,))
def rebase_view_state(buf: jax.Array, perm: jax.Array, rows: jax.Array, idx: jax.Array) -> jax.Array:
    """Fused gather-by-perm + scatter-dirty on a donated buffer.

    buf:  [Vp, R] f32  prior resident surface (DONATED)
    perm: [Vp]    i32  old row index per new row, -1 = no prior row
    rows: [Dp, R] f32  recomputed values for the dirty rows
    idx:  [Dp]    i32  destination row per dirty entry, >= Vp = padding
    returns [Vp, R] f32 in buf's storage."""
    gathered = jnp.where((perm >= 0)[:, None], buf[jnp.clip(perm, 0, None)], jnp.float32(-1.0))
    return gathered.at[idx].set(rows, mode="drop")


@jax.jit
def gather_rows(buf: jax.Array, idx: jax.Array) -> jax.Array:
    """Sampled-row readback for the residency auditor: gather `idx` rows of
    the resident buffer in one dispatch. `idx` is ladder-padded by
    `pack_gather` (pad slots point at row 0 — harmless duplicates the host
    discards), so steady-state audits reuse a handful of compiled shapes
    and never recompile. `buf` is NOT donated: the audit is a read."""
    return buf[idx]


def pack_gather(idx: np.ndarray, pad: Optional[int] = None) -> np.ndarray:
    """Host-side padding for gather_rows: logical row indices → padded i32
    (pad slots 0; callers slice the gather back to len(idx)). Default pad
    is the pow2 dirty ladder; the residency auditor instead passes the
    resident buffer's own row pad, so a sampled audit and a full shadow
    share ONE compiled gather shape per buffer shape — an audit can then
    only ever compile alongside a views-pad change, which the solve
    signature attributes (contract-declared varying axis), never on its
    own mid-steady-state."""
    d = idx.shape[0]
    dp = pad_dirty(d) if pad is None else max(int(pad), d)
    idx_p = np.zeros(dp, np.int32)
    idx_p[:d] = idx
    return idx_p


def rebase_view_state_np(buf: np.ndarray, perm: np.ndarray, rows: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Exact host reference for the differential/parity tests."""
    out = np.where((perm >= 0)[:, None], buf[np.clip(perm, 0, None)], np.float32(-1.0))
    keep = idx < out.shape[0]
    out[idx[keep]] = rows[keep]
    return out.astype(np.float32)


def pack_rebase(
    perm: np.ndarray,
    rows: np.ndarray,
    idx: np.ndarray,
    vp: int,
) -> tuple:
    """Host-side padding: logical perm/rows/idx → ladder-padded device
    operands. perm pads with -1 (dead rows), idx pads with `vp` (dropped by
    the scatter), rows pads with -1.0 (never lands)."""
    r = rows.shape[1] if rows.ndim == 2 else 0
    d = idx.shape[0]
    dp = pad_dirty(d)
    perm_p = np.full(vp, -1, np.int32)
    perm_p[: perm.shape[0]] = perm
    idx_p = np.full(dp, vp, np.int32)
    idx_p[:d] = idx
    rows_p = np.full((dp, r), -1.0, np.float32)
    if d:
        rows_p[:d] = rows
    return perm_p, rows_p, idx_p
