"""On-device reductions over a proposed packing layout.

Historical note: an earlier revision packed pods with a per-pod lax.scan
(bounded-space first-fit). Measurement on v5e showed ~10us of loop overhead
per scan step — ~100ms for a 10k-pod batch before doing any work — so
sequential packing moved to the counts-based host algorithm
(solver/pack_counts.py) and the device keeps the genuinely parallel pieces:
feasibility masks (ops/feasibility.py) and the segment reductions below that
audit a proposed layout in one fused program.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("num_segments",))
def segment_usage(requests: jax.Array, bin_ids: jax.Array, num_segments: int):
    """Per-bin resource usage and pod counts via segment sums.

    Callers pass num_segments = max_bins + 1; bin_ids of -1 (unpacked pods)
    accumulate into the final scratch segment, which must stay unused by any
    real bin.
    """
    safe_ids = jnp.where(bin_ids < 0, num_segments - 1, bin_ids)
    usage = jax.ops.segment_sum(requests, safe_ids, num_segments=num_segments)
    counts = jax.ops.segment_sum(jnp.ones_like(bin_ids, dtype=jnp.int32), safe_ids, num_segments=num_segments)
    return usage, counts


@jax.jit
def audit_layout(usage: jax.Array, caps_of_bin: jax.Array) -> jax.Array:
    """[B] bool: each bin's summed usage fits its assigned capacity."""
    return jnp.all(usage <= caps_of_bin + 1e-6, axis=-1)
