"""Dense problem IR: the pods x instance-types constraint matrices.

This is the bridge between the host object model and the TPU solver. The key
architectural split (vs. the reference's per-pod sequential filtering in
scheduling/node.go:139-161):

- **Label/taint/offering algebra runs on host, but only G times, not P times.**
  Pods are deduplicated by *constraint signature* (node selector, affinity
  terms, tolerations, spread constraints, labels); real batches collapse from
  10k pods to a handful of groups. Each group's instance-type compatibility
  row is computed with the *exact same host algebra* the FFD oracle uses —
  zero semantic drift between the dense path and the host path.

- **Everything P-scale ships to the device as dense matrices**: requests
  [P, R], capacities [T, R], prices [T], compat [G, T], offering masks
  [T, Z] / [T, C]. Resource fit, domain assignment, packing, and
  verification reductions are tensor programs (ops/, solver/).

Groups whose constraints the dense path can't express (multi-term affinity,
volume limits, host ports, inverse anti-affinity interference, ...) are
classified HOST and fall back to the exact sequential loop.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..api import labels as lbl
from ..api.objects import DO_NOT_SCHEDULE, OP_IN, Pod
from ..cloudprovider.types import InstanceType
from ..scheduling.nodetemplate import NodeTemplate
from ..scheduling.requirements import Requirements
from ..utils import resources as res

# Fixed resource axis. Extended resources beyond these fall back to host
# (rare); the axis is padded so compiled shapes stay stable.
RESOURCE_AXIS: Tuple[str, ...] = (
    res.CPU,
    res.MEMORY,
    res.PODS,
    res.EPHEMERAL_STORAGE,
    res.NVIDIA_GPU,
    res.AMD_GPU,
    res.AWS_NEURON,
    res.AWS_POD_ENI,
)
R = len(RESOURCE_AXIS)
_RESOURCE_INDEX = {name: i for i, name in enumerate(RESOURCE_AXIS)}

# A huge capacity stands in for "resource not limited by this type" when the
# type doesn't define the resource but also can't satisfy it — fit handles it
# by treating missing capacity as zero, same as resources.fits().


class GroupKind(enum.Enum):
    PLAIN = "plain"  # resource + label constraints only
    SPREAD = "spread"  # one DoNotSchedule spread over zone/hostname/capacity-type
    AFFINITY = "affinity"  # one required self-affinity over zone/hostname
    ANTI_HOST = "anti-host"  # hostname anti-affinity: dedicated nodes
    HOST = "host"  # not dense-expressible: exact host loop


SPREAD_KEYS = (lbl.LABEL_TOPOLOGY_ZONE, lbl.LABEL_HOSTNAME, lbl.LABEL_CAPACITY_TYPE)


@dataclass
class GroupInfo:
    kind: GroupKind
    pods: List[Pod] = field(default_factory=list)
    requirements: Optional[Requirements] = None  # pod-derived requirements
    template_index: int = -1
    # spread/affinity descriptor
    topology_key: str = ""
    max_skew: int = 1
    selector_signature: tuple = ()
    # dense row indices
    index: int = -1
    # subkey of constraint_signature that determines the compat row: node
    # selector + node-affinity terms + tolerations (what Requirements.from_pod
    # and Taints.tolerates read) — pod labels/namespace/spread terms group
    # pods but cannot change template/type compatibility
    compat_sig: tuple = ()


def resource_vector(rl: Dict[str, float]) -> Optional[np.ndarray]:
    """Project a resource list onto the fixed axis; None if it names a
    resource outside the axis (host fallback)."""
    vec = np.zeros((R,), dtype=np.float64)
    for name, value in (rl or {}).items():
        idx = _RESOURCE_INDEX.get(name)
        if idx is None:
            if value > 0:
                return None
            continue
        vec[idx] = value
    return vec


def _toleration_signature(pod: Pod) -> tuple:
    return tuple(sorted((t.key, t.operator, t.value, t.effect) for t in pod.spec.tolerations))


def _selector_signature(selector) -> tuple:
    if selector is None:
        return ()
    return (
        tuple(sorted(selector.match_labels.items())),
        tuple(sorted((e.key, e.operator, tuple(sorted(e.values))) for e in selector.match_expressions)),
    )


def constraint_signature(pod: Pod) -> tuple:
    """Everything that affects where a pod may go (and how it groups)."""
    spec = pod.spec
    # fast path: unconstrained pods (the common deployment shape) — avoid
    # walking container ports when no constraint machinery is present
    if (
        spec.affinity is None
        and not spec.topology_spread_constraints
        and not spec.node_selector
        and not spec.tolerations
        and not spec.volumes
        and not spec.init_containers
        and all(not p.host_port for c in spec.containers for p in c.ports)
    ):
        return (pod.namespace, tuple(sorted(pod.metadata.labels.items())), (), (), (), (), (), False)
    affinity_sig: tuple = ()
    if spec.affinity is not None:
        a = spec.affinity
        node_sig = ()
        if a.node_affinity is not None:
            node_sig = (
                tuple(
                    tuple(sorted((r.key, r.operator, tuple(sorted(r.values))) for r in term.match_expressions))
                    for term in a.node_affinity.required
                ),
                tuple(
                    (t.weight, tuple(sorted((r.key, r.operator, tuple(sorted(r.values))) for r in t.preference.match_expressions)))
                    for t in a.node_affinity.preferred
                ),
            )
        pod_aff_sig = ()
        if a.pod_affinity is not None:
            pod_aff_sig = (
                tuple((t.topology_key, _selector_signature(t.label_selector), tuple(sorted(t.namespaces))) for t in a.pod_affinity.required),
                tuple((wt.weight, wt.pod_affinity_term.topology_key, _selector_signature(wt.pod_affinity_term.label_selector)) for wt in a.pod_affinity.preferred),
            )
        anti_sig = ()
        if a.pod_anti_affinity is not None:
            anti_sig = (
                tuple((t.topology_key, _selector_signature(t.label_selector), tuple(sorted(t.namespaces))) for t in a.pod_anti_affinity.required),
                tuple((wt.weight, wt.pod_affinity_term.topology_key, _selector_signature(wt.pod_affinity_term.label_selector)) for wt in a.pod_anti_affinity.preferred),
            )
        affinity_sig = (node_sig, pod_aff_sig, anti_sig)
    spread_sig = tuple(
        (c.max_skew, c.topology_key, c.when_unsatisfiable, _selector_signature(c.label_selector))
        for c in spec.topology_spread_constraints
    )
    ports_sig = tuple(
        sorted(
            (p.host_ip, p.host_port, p.protocol)
            for c in list(spec.containers) + list(spec.init_containers)
            for p in c.ports
            if p.host_port
        )
    )
    return (
        pod.namespace,
        tuple(sorted(pod.metadata.labels.items())),
        tuple(sorted(spec.node_selector.items())),
        affinity_sig,
        spread_sig,
        _toleration_signature(pod),
        ports_sig,
        bool(spec.volumes),
    )


def classify_group(pod: Pod) -> Tuple[GroupKind, str, int, tuple]:
    """Decide whether this constraint shape is dense-expressible.

    Returns (kind, topology_key, max_skew, selector_signature).
    """
    spec = pod.spec
    # volumes and host ports need per-node stateful checks -> host
    if spec.volumes:
        return (GroupKind.HOST, "", 0, ())
    if any(p.host_port for c in list(spec.containers) + list(spec.init_containers) for p in c.ports):
        return (GroupKind.HOST, "", 0, ())

    spreads = spec.topology_spread_constraints
    a = spec.affinity
    has_node_pref = bool(a and a.node_affinity and a.node_affinity.preferred)
    multi_required_terms = bool(a and a.node_affinity and len(a.node_affinity.required) > 1)
    if has_node_pref or multi_required_terms:
        # relaxation ladder territory -> host
        return (GroupKind.HOST, "", 0, ())
    pod_aff = a.pod_affinity if a else None
    pod_anti = a.pod_anti_affinity if a else None
    n_constraints = (
        len(spreads)
        + (len(pod_aff.required) + len(pod_aff.preferred) if pod_aff else 0)
        + (len(pod_anti.required) + len(pod_anti.preferred) if pod_anti else 0)
    )
    if n_constraints == 0:
        return (GroupKind.PLAIN, "", 0, ())
    if n_constraints > 1:
        return (GroupKind.HOST, "", 0, ())

    if len(spreads) == 1:
        c = spreads[0]
        if c.when_unsatisfiable != DO_NOT_SCHEDULE:
            return (GroupKind.HOST, "", 0, ())  # ScheduleAnyway enters relaxation
        if c.topology_key not in SPREAD_KEYS:
            return (GroupKind.HOST, "", 0, ())
        # dense spread requires the constraint to select the pod itself
        # (the usual deployment shape); otherwise counting is cross-group
        if c.label_selector is None or not c.label_selector.matches(pod.metadata.labels):
            return (GroupKind.HOST, "", 0, ())
        return (GroupKind.SPREAD, c.topology_key, c.max_skew, _selector_signature(c.label_selector))

    if pod_aff and len(pod_aff.required) == 1 and not pod_aff.preferred and not pod_anti:
        term = pod_aff.required[0]
        if term.topology_key not in (lbl.LABEL_TOPOLOGY_ZONE, lbl.LABEL_HOSTNAME):
            return (GroupKind.HOST, "", 0, ())
        if term.namespace_selector is not None or term.namespaces:
            return (GroupKind.HOST, "", 0, ())
        # dense affinity requires self-selection (the pod is in its own
        # affinity cluster) so components close over the group
        if term.label_selector is None or not term.label_selector.matches(pod.metadata.labels):
            return (GroupKind.HOST, "", 0, ())
        return (GroupKind.AFFINITY, term.topology_key, 0, _selector_signature(term.label_selector))

    if pod_anti and len(pod_anti.required) == 1 and not pod_anti.preferred and not pod_aff:
        term = pod_anti.required[0]
        if term.topology_key != lbl.LABEL_HOSTNAME:
            # zonal anti-affinity blocks whole zones; keep exact host semantics
            return (GroupKind.HOST, "", 0, ())
        if term.namespace_selector is not None or term.namespaces:
            return (GroupKind.HOST, "", 0, ())
        if term.label_selector is None or not term.label_selector.matches(pod.metadata.labels):
            return (GroupKind.HOST, "", 0, ())
        return (GroupKind.ANTI_HOST, lbl.LABEL_HOSTNAME, 0, _selector_signature(term.label_selector))

    return (GroupKind.HOST, "", 0, ())


@dataclass
class DenseProblem:
    """The full dense encoding of one provisioning batch."""

    # axes
    resource_names: Tuple[str, ...]
    zones: List[str]
    capacity_types: List[str]
    # pods (dense-eligible, original order)
    pods: List[Pod]
    requests: np.ndarray  # [P, R] float64 (host math is exact; device casts to f32)
    group_ids: np.ndarray  # [P] int32
    groups: List[GroupInfo]  # G entries
    # instance types: the concatenation of each template's (weight-ordered)
    # provisioner universe — a type column belongs to exactly one template
    templates: List[NodeTemplate]
    instance_types: List[InstanceType]
    type_template: np.ndarray  # [T] int32: owning template index per column
    caps: np.ndarray  # [T, R] float64 (resources - system overhead, missing -> 0)
    prices: np.ndarray  # [T] float64
    avail: np.ndarray  # [T, Z, C] bool: available-offering cube (see CatalogEncoding.avail)
    compat: np.ndarray  # [G, T] bool (nonzero only inside the group's template segment)
    group_zone_allowed: np.ndarray  # [G, Z] bool
    group_ct_allowed: np.ndarray  # [G, C] bool
    daemon_overhead: np.ndarray  # [T, R] float64: daemonset overhead of each column's template
    # quarantined offerings in this catalog (CatalogEncoding.masked_offerings)
    masked_offerings: int = 0
    # pods that must take the exact host path
    host_pods: List[Pod] = field(default_factory=list)

    @property
    def P(self) -> int:
        return len(self.pods)

    @property
    def T(self) -> int:
        return len(self.instance_types)

    @property
    def G(self) -> int:
        return len(self.groups)

    def template_of_group(self, group: "GroupInfo") -> NodeTemplate:
        return self.templates[group.template_index]

    def shape_signature(self) -> Dict[str, int]:
        """The axis cardinalities that key the solver's compiled-shape
        universe — what the flight recorder (flight.py) attributes a
        recompile to when one of them changes between solves. Bucket and
        padded-dispatch dimensions are appended by the solver (they only
        exist after domain assignment / dispatch padding)."""
        return {
            "pods": self.P,
            "groups": self.G,
            "types": self.T,
            "zones": len(self.zones),
            "capacity_types": len(self.capacity_types),
            "resources": len(self.resource_names),
        }


@dataclass
class CatalogEncoding:
    """Per-catalog dense matrices, cacheable across solves.

    Everything here is a function of (templates, instance-type universe,
    topology domains) only — independent of the pod batch — so a long-lived
    solver reuses it for every solve against the same catalog (the
    incremental device-state idea from SURVEY.md §7 applied to the host-side
    encode). Contract: instance-type lists are immutable snapshots (the
    reference's GetInstanceTypes returns cached objects the same way); a
    provider that changes its universe must return a new list object.
    `compat_cache` memoizes per-constraint-shape compat rows keyed by
    GroupInfo.compat_sig; entries are (row [T] bool, template_index,
    zone_allowed [Z] bool, ct_allowed [C] bool), with template_index == -1
    marking shapes no template can host."""

    key: tuple
    # strong refs to the keyed instance-type lists: the cache key uses their
    # id()s, which must not be recycled while this entry is alive
    source_lists: tuple
    type_list: List[InstanceType]
    type_template_ids: List[int]
    segment_bounds: List[Tuple[int, int]]
    zone_list: List[str]
    ct_list: List[str]
    zone_index: Dict[str, int]
    ct_index: Dict[str, int]
    caps: np.ndarray  # [T, R]
    prices: np.ndarray  # [T]
    # the availability CUBE: avail[t, z, c] == an AVAILABLE offering of type
    # t exists in (zone z, capacity-type c). Strictly finer than a
    # per-axis type-zone x type-ct product (which would let a bucket pinned
    # to (zone, ct) pick a type offering that pair only across two
    # DIFFERENT offerings), and the carrier of offering-health: a pool
    # quarantined by the unavailable-offerings cache is simply a zero here,
    # so the device mask routes around it with no host loop (see
    # dense._device_solve).
    avail: np.ndarray  # [T, Z, C] bool
    # offerings present in the universe but flagged available=False (the
    # unavailable-offerings cache quarantine) — distinct from structural
    # zeros (a type simply not offered in a pool); nonzero means offering
    # health is actively constraining this catalog
    masked_offerings: int
    empty_fit: np.ndarray  # [T] bool: overhead alone fits the type
    compat_cache: Dict[tuple, tuple] = field(default_factory=dict)


@dataclass
class WarmViewEncoding:
    """Dense arrays over the existing-node views of one solve — the
    [views x resources] half of the vectorized warm fill (solver/warmfill.py).

    All capacity math is f64 and uses the exact expressions of the certified
    cohort fast paths (existingnode.py): avail_tol = available +
    resources.tolerance(available) per axis entry, so `avail_tol - requests`
    IS the `limit + tolerance(limit) - base` headroom of the closed-form
    count and `requests + size <= avail_tol` IS resources.fits on the
    merged request list. Views whose available/requests name a resource
    outside the fixed axis are marked unusable (same rule as the host
    fill's `usable` screen)."""

    usable: np.ndarray  # [V] bool
    avail_tol: np.ndarray  # [V, R] f64
    requests0: np.ndarray  # [V, R] f64
    head0: np.ndarray  # [V, R] f64 (avail_tol - requests0; -1 rows when unusable)
    zone: List[Optional[str]]  # per-view zone label (None when absent)
    ct: List[Optional[str]]  # per-view capacity-type label
    hostname: List[str]
    taint_sig: List[tuple]  # content signature of the view's scheduling taints


def encode_warm_views(views: Sequence) -> WarmViewEncoding:
    """Encode existing-node views into the dense warm-fill arrays."""
    V = len(views)
    usable = np.zeros((V,), dtype=bool)
    avail = np.zeros((V, R), dtype=np.float64)
    requests0 = np.zeros((V, R), dtype=np.float64)
    zone: List[Optional[str]] = []
    ct: List[Optional[str]] = []
    hostname: List[str] = []
    taint_sig: List[tuple] = []
    for vi, view in enumerate(views):
        a = resource_vector(view.available)
        u = resource_vector(view.requests)
        if a is not None and u is not None:
            avail[vi] = a
            requests0[vi] = u
            usable[vi] = True
        labels = view.node.metadata.labels
        zone.append(labels.get(lbl.LABEL_TOPOLOGY_ZONE))
        ct.append(labels.get(lbl.LABEL_CAPACITY_TYPE))
        hostname.append(labels.get(lbl.LABEL_HOSTNAME) or view.node.name)
        taint_sig.append(tuple(sorted((t.key, t.value, t.effect) for t in view.taints)))
    # elementwise: limit + tolerance(limit), limit = 0.0 for axis resources
    # the view does not define (dict .get default) — one [V, R] pass, same
    # f64 expressions as the per-row loop (tolerance is elementwise)
    avail_tol = np.where(usable[:, None], avail + res.tolerance(avail), 0.0)
    requests0 = np.where(usable[:, None], requests0, 0.0)
    head0 = np.where(usable[:, None], avail_tol - requests0, -1.0)
    return WarmViewEncoding(
        usable=usable,
        avail_tol=avail_tol,
        requests0=requests0,
        head0=head0,
        zone=zone,
        ct=ct,
        hostname=hostname,
        taint_sig=taint_sig,
    )


def template_signature(template: NodeTemplate) -> tuple:
    """Content signature of the compat-relevant template fields (templates
    are rebuilt from provisioners every solve; identity is useless)."""
    reqs = tuple(
        sorted(
            (r.key, r.complement, tuple(sorted(r.values)), r.greater_than, r.less_than)
            for r in template.requirements.values()
        )
    )
    taints = tuple(sorted((t.key, t.value, t.effect) for t in template.taints))
    return (template.provisioner_name, taints, reqs)


def catalog_key(
    templates: Sequence[NodeTemplate],
    instance_types: Dict[str, Sequence[InstanceType]],
    zones: Optional[Sequence[str]] = None,
    capacity_types: Optional[Sequence[str]] = None,
) -> tuple:
    # keyed by the identity of the instance-type OBJECTS, not the list:
    # providers hand out a fresh list copy per get_instance_types call while
    # TTL-caching the items, so item identity is what's stable across solves.
    # Cache holders must pin the items (see catalog_pin) so a live entry's
    # ids can never be recycled onto different objects.
    return (
        tuple(template_signature(t) for t in templates),
        tuple(tuple(id(it) for it in instance_types.get(t.provisioner_name) or ()) for t in templates),
        tuple(sorted(zones or ())),
        tuple(sorted(capacity_types or ())),
    )


def catalog_pin(
    templates: Sequence[NodeTemplate], instance_types: Dict[str, Sequence[InstanceType]]
) -> tuple:
    """The object references a catalog_key's ids point at — stored alongside
    the cached encoding to keep them alive (id-reuse safety)."""
    return tuple(tuple(instance_types.get(t.provisioner_name) or ()) for t in templates)


def encode_catalog(
    templates: Sequence[NodeTemplate],
    instance_types: Dict[str, Sequence[InstanceType]],
    zones: Optional[Sequence[str]] = None,
    capacity_types: Optional[Sequence[str]] = None,
) -> CatalogEncoding:
    """Build the batch-independent half of the encoding (type matrices,
    offering masks, axes)."""
    templates = list(templates)
    type_list: List[InstanceType] = []
    type_template_ids: List[int] = []
    segment_bounds: List[Tuple[int, int]] = []  # [ti] -> (start, end) on the type axis
    for ti, template in enumerate(templates):
        segment_types = list(instance_types.get(template.provisioner_name, ()))
        start = len(type_list)
        type_list.extend(segment_types)
        type_template_ids.extend([ti] * len(segment_types))
        segment_bounds.append((start, len(type_list)))

    zone_set: Set[str] = set(zones or ())
    ct_set: Set[str] = set(capacity_types or ())
    for it in type_list:
        for offering in it.offerings():
            zone_set.add(offering.zone)
            ct_set.add(offering.capacity_type)
    zone_list = sorted(zone_set)
    ct_list = sorted(ct_set)
    zone_index = {z: i for i, z in enumerate(zone_list)}
    ct_index = {c: i for i, c in enumerate(ct_list)}

    T = len(type_list)
    caps = np.zeros((T, R), dtype=np.float64)
    prices = np.zeros((T,), dtype=np.float64)
    avail = np.zeros((T, len(zone_list), len(ct_list)), dtype=bool)
    masked_offerings = 0
    for t, it in enumerate(type_list):
        cap_vec = resource_vector(it.resources())
        over_vec = resource_vector(it.overhead())
        if cap_vec is None or over_vec is None:
            cap_vec = cap_vec if cap_vec is not None else np.zeros((R,), np.float64)
            over_vec = over_vec if over_vec is not None else np.zeros((R,), np.float64)
        caps[t] = np.maximum(cap_vec - over_vec, 0.0)
        prices[t] = it.price()
        for offering in it.offerings():
            # quarantined offerings (unavailable-offerings cache) stay in
            # the zone/ct axes (domains stable) but are zeros in the cube —
            # never a selectable (type, zone, ct) cell
            if getattr(offering, "available", True):
                avail[t, zone_index[offering.zone], ct_index[offering.capacity_type]] = True
            else:
                masked_offerings += 1
    empty_fit = np.array([res.fits(it.overhead(), it.resources()) for it in type_list], dtype=bool)
    return CatalogEncoding(
        key=catalog_key(templates, instance_types, zones, capacity_types),
        source_lists=tuple(instance_types.get(t.provisioner_name) for t in templates),
        type_list=type_list,
        type_template_ids=type_template_ids,
        segment_bounds=segment_bounds,
        zone_list=zone_list,
        ct_list=ct_list,
        zone_index=zone_index,
        ct_index=ct_index,
        caps=caps,
        prices=prices,
        avail=avail,
        masked_offerings=masked_offerings,
        empty_fit=empty_fit,
    )


def encode_problem(
    pods: Sequence[Pod],
    templates: Sequence[NodeTemplate],
    instance_types: Dict[str, Sequence[InstanceType]],
    daemon_overhead: Optional[Dict[str, Dict[str, float]]] = None,
    zones: Optional[Sequence[str]] = None,
    capacity_types: Optional[Sequence[str]] = None,
    catalog: Optional[CatalogEncoding] = None,
    catalog_key_hint: Optional[tuple] = None,
    cohort_label_keys: Optional[frozenset] = None,
) -> DenseProblem:
    """Encode a batch against the weight-ordered node templates.

    Each group binds to the FIRST template (weight order) it is compatible
    with and that offers at least one compatible instance type — the same
    first-workable-template rule the host loop applies when opening a fresh
    node (reference scheduler.go:207-232). The type axis is the concatenation
    of every template's instance-type universe; a group's compat row is zero
    outside its chosen template's segment, so the device argmin can never
    pick a cross-template type.

    `cohort_label_keys` (when given) is the set of label KEYS that any
    selector in play — batch pods' spread/affinity/anti selectors plus the
    scheduler topology's existing cohort selectors — could match. Pod labels
    outside this set cannot influence placement (no selector counts them),
    so they are dropped from the GROUPING key: identically-constrained
    cohorts that differ only in unmatched labels collapse into one group and
    pack as one FFD stream, the same cross-cohort node sharing the host
    loop's single global queue produces. The per-pod signature cache is
    unaffected (filtering happens on the cached value).
    """
    templates = list(templates)
    if catalog is None:
        catalog = encode_catalog(templates, instance_types, zones, capacity_types)
    else:
        # a stale catalog would silently bind groups to the wrong template's
        # type segment — fail loud instead. A caller that just looked the
        # catalog up under its key passes it as catalog_key_hint to avoid
        # recomputing template signatures on the hot path.
        expected = catalog_key_hint if catalog_key_hint is not None else catalog_key(templates, instance_types, zones, capacity_types)
        if catalog.key != expected:
            raise ValueError("CatalogEncoding does not match the supplied templates/instance_types/domains")
    type_list = catalog.type_list
    type_template_ids = catalog.type_template_ids
    segment_bounds = catalog.segment_bounds
    zone_list = catalog.zone_list
    ct_list = catalog.ct_list
    T = len(type_list)
    caps = catalog.caps
    prices = catalog.prices

    # daemonset overhead per type column = its template's overhead
    overhead_by_template: List[np.ndarray] = []
    for template in templates:
        vec = resource_vector((daemon_overhead or {}).get(template.provisioner_name, {}) or {})
        overhead_by_template.append(vec if vec is not None else np.zeros((R,), np.float64))
    overhead_t = (
        np.stack(overhead_by_template)[np.asarray(type_template_ids, dtype=np.int64)]
        if type_list
        else np.zeros((0, R), np.float64)
    )

    # -- group pods by constraint signature ---------------------------------
    groups: List[GroupInfo] = []
    group_by_sig: Dict[tuple, GroupInfo] = {}
    host_pods: List[Pod] = []
    dense_pods: List[Pod] = []
    dense_group_of_pod: List[int] = []
    request_rows: List[np.ndarray] = []

    for pod in pods:
        # per-pod encode cache: pods are immutable during scheduling
        # (relaxation returns fresh copies — preferences.py), so the signature
        # and request vector can live on the object across solves. This is
        # the incremental device-state idea from SURVEY.md §7: pending pods
        # that survive a batch re-encode for free on the next solve. The
        # cache is keyed on metadata.resource_version: live pods DO mutate
        # between solves (kube update events, e.g. a resized pod feeding a
        # consolidation simulation), and a stale request vector here would
        # silently mis-place the pod.
        version = pod.metadata.resource_version
        cached = getattr(pod, "_encode_cache", None)
        if cached is not None and cached[0] == version:
            _, sig, req_vec = cached
        else:
            req_vec = resource_vector(res.pod_requests(pod))
            sig = constraint_signature(pod) if req_vec is not None else None
            try:
                pod._encode_cache = (version, sig, req_vec)
            except AttributeError:
                pass  # slotted/frozen pod objects simply skip the cache
        if req_vec is None:
            host_pods.append(pod)
            continue
        if cohort_label_keys is not None and sig[1]:
            filtered = tuple(kv for kv in sig[1] if kv[0] in cohort_label_keys)
            if filtered != sig[1]:
                sig = (sig[0], filtered) + sig[2:]
        group = group_by_sig.get(sig)
        if group is None:
            kind, key, max_skew, sel_sig = classify_group(pod)
            group = GroupInfo(kind=kind, topology_key=key, max_skew=max_skew, selector_signature=sel_sig)
            if kind != GroupKind.HOST:
                group.requirements = Requirements.from_pod(pod)
                group.index = len(groups)
                # node_selector + node-affinity + tolerations slots of the
                # constraint signature (see GroupInfo.compat_sig)
                group.compat_sig = (sig[2], sig[3][0] if sig[3] else (), sig[5])
                groups.append(group)
            group_by_sig[sig] = group
        if group.kind == GroupKind.HOST:
            host_pods.append(pod)
            continue
        group.pods.append(pod)
        dense_pods.append(pod)
        dense_group_of_pod.append(group.index)
        request_rows.append(req_vec)

    G = len(groups)
    compat = np.zeros((G, T), dtype=bool)
    group_zone_allowed = np.ones((G, len(zone_list)), dtype=bool)
    group_ct_allowed = np.ones((G, len(ct_list)), dtype=bool)

    # -- per-group compatibility via the exact host algebra ------------------
    from ..scheduler.node import type_is_compatible, type_has_offering

    # overhead-fits-resources holds independently of the group (requests are
    # checked per bin later); precomputed once per catalog
    empty_fit = catalog.empty_fit
    if len(catalog.compat_cache) > 4096:  # unbounded user labels can't leak
        catalog.compat_cache.clear()
    for group in groups:
        cached_row = catalog.compat_cache.get(group.compat_sig)
        if cached_row is not None:
            row, ti, z_allow, c_allow = cached_row
            if ti < 0:
                group.kind = GroupKind.HOST
            else:
                compat[group.index] = row
                group.template_index = ti
                group_zone_allowed[group.index] = z_allow
                group_ct_allowed[group.index] = c_allow
            continue
        pod = group.pods[0]
        # first workable template in weight order (scheduler.go:207-232):
        # taints tolerated, requirements compatible, >=1 compatible type
        chosen = -1
        for ti, template in enumerate(templates):
            if template.taints.tolerates(pod) is not None:
                continue
            node_requirements = Requirements(*template.requirements.values())
            if node_requirements.compatible(group.requirements) is not None:
                continue
            node_requirements.add(*group.requirements.values())
            start, end = segment_bounds[ti]
            any_type = False
            for t in range(start, end):
                it = type_list[t]
                if empty_fit[t] and type_is_compatible(it, node_requirements) and type_has_offering(it, node_requirements):
                    compat[group.index, t] = True
                    any_type = True
            if not any_type:
                continue
            chosen = ti
            group.template_index = ti
            zone_req = node_requirements.get(lbl.LABEL_TOPOLOGY_ZONE)
            group_zone_allowed[group.index] = [zone_req.has(z) for z in zone_list]
            ct_req = node_requirements.get(lbl.LABEL_CAPACITY_TYPE)
            group_ct_allowed[group.index] = [ct_req.has(c) for c in ct_list]
            break
        if chosen < 0:
            # no template can open a node for this shape (compat row is
            # all-False): exact host loop owns the (identical) failure message
            group.kind = GroupKind.HOST
            catalog.compat_cache[group.compat_sig] = (None, -1, None, None)
        else:
            catalog.compat_cache[group.compat_sig] = (
                compat[group.index].copy(),
                chosen,
                group_zone_allowed[group.index].copy(),
                group_ct_allowed[group.index].copy(),
            )

    # groups demoted to HOST during compat: move their pods to host_pods
    if any(g.kind == GroupKind.HOST for g in groups):
        keep = [g for g in groups if g.kind != GroupKind.HOST]
        old_to_new = {}
        for new_index, g in enumerate(keep):
            old_to_new[g.index] = new_index
        new_dense_pods, new_group_ids, new_rows = [], [], []
        for pod, gid, row in zip(dense_pods, dense_group_of_pod, request_rows):
            if gid in old_to_new:
                new_dense_pods.append(pod)
                new_group_ids.append(old_to_new[gid])
                new_rows.append(row)
            else:
                host_pods.append(pod)
        compat = compat[[g.index for g in keep]] if keep else np.zeros((0, T), dtype=bool)
        group_zone_allowed = group_zone_allowed[[g.index for g in keep]] if keep else np.ones((0, len(zone_list)), bool)
        group_ct_allowed = group_ct_allowed[[g.index for g in keep]] if keep else np.ones((0, len(ct_list)), bool)
        for g in keep:
            g.index = old_to_new[g.index]
        groups = keep
        dense_pods, dense_group_of_pod, request_rows = new_dense_pods, new_group_ids, new_rows

    requests = np.stack(request_rows) if request_rows else np.zeros((0, R), np.float64)
    group_ids = np.asarray(dense_group_of_pod, dtype=np.int32)

    return DenseProblem(
        resource_names=RESOURCE_AXIS,
        zones=zone_list,
        capacity_types=ct_list,
        pods=dense_pods,
        requests=requests,
        group_ids=group_ids,
        groups=groups,
        templates=templates,
        instance_types=type_list,
        type_template=np.asarray(type_template_ids, dtype=np.int32),
        caps=caps,
        prices=prices,
        avail=catalog.avail,
        masked_offerings=catalog.masked_offerings,
        compat=compat,
        group_zone_allowed=group_zone_allowed,
        group_ct_allowed=group_ct_allowed,
        daemon_overhead=overhead_t,
        host_pods=host_pods,
    )
