"""Cluster delta grammar: the epoch/journal feed for the incremental engine.

The dense pipeline's steady-state cost through round 5 was dominated by
re-encoding the ENTIRE cluster every provision pass — `encode_warm_views`
walks every existing view even when the pass only bound three pods.  The
incremental engine (solver/incremental.py) keeps the prior pass's encoding
resident and rebases only the rows that changed; this module is the feed
that tells it WHICH rows those are.

Grammar.  Every cluster mutation collapses to one of four delta kinds
against the view axis (a view == one existing node's schedulable surface):

  NODE_ADDED    a node appeared (launched, or first seen by the informer)
  NODE_REMOVED  a node vanished (terminated, deleted, cordoned away)
  POD_BOUND     a pod landed on a node → that node's residual headroom shrank
  POD_REMOVED   a pod left a node → headroom grew (includes rebinds: the old
                node gets POD_REMOVED, the new one POD_BOUND)

All four are recorded against a NODE name — the engine's unit of dirtiness
is the view row, so a pod event just dirties its node.  Catalog/provisioner
version bumps are NOT journal events: the engine compares `catalog_key`
directly each pass and a mismatch forces a full re-encode (attributed as
`invalidate.catalog`), because a catalog change can re-shape every row.

Epochs and gaps.  The journal is a bounded ring keyed by a monotonically
increasing epoch.  `dirty_since(epoch)` returns the set of node names
touched after `epoch`, or None when the window has been overwritten (the
reader fell too far behind) — None means "I cannot enumerate your delta",
and the engine must full re-encode (attributed as `invalidate.gap`).
`mark_gap()` forces the same outcome explicitly; the informer's resync path
uses it because a re-list may reflect mutations the watch never delivered.

Locking.  The journal has its own leaf lock and takes no others, so it is
safe to call `record()` while holding the cluster state lock (cluster.py's
mutators do exactly that).  Readers (`dirty_since`) only copy.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import FrozenSet, List, Optional, Tuple

NODE_ADDED = "node-added"
NODE_REMOVED = "node-removed"
POD_BOUND = "pod-bound"
POD_REMOVED = "pod-removed"

DELTA_KINDS = (NODE_ADDED, NODE_REMOVED, POD_BOUND, POD_REMOVED)

# seeded corruption seam (solver/faults.py): when armed, record() consults
# this hook and — if it answers True — SUPPRESSES the delta (no epoch bump,
# no ring entry), modeling a missed journal event for the residency
# auditor's detection proofs. This module is an import leaf, so the fault
# injector reaches in through a module global instead of an import; None
# (the production state) keeps record() at one global read.
_corrupt_consult = None


def set_corrupt_seam(consult) -> None:
    """Arm (callable `(node, kind) -> bool`, True suppresses the record) or
    disarm (None) the journal's corruption seam. The hook sees the delta
    before deciding so injectors can target a kind family — suppressing a
    pod-level record is the detectable missed-delta shape; node add/remove
    suppressions are invisible (the engine diffs the row set directly)."""
    global _corrupt_consult
    _corrupt_consult = consult

# default ring capacity: sized for a large cluster's worst-case burst
# between two provision passes (a reclaim wave touching every node once is
# ~cluster-size events; 4096 covers the 16k-view bench's per-pass churn
# with a wide margin while keeping the ring a few hundred KB)
DEFAULT_CAPACITY = 4096


@dataclass(frozen=True)
class Delta:
    """One journal entry: at `epoch`, node `node` changed per `kind`."""

    epoch: int
    node: str
    kind: str


class DeltaJournal:
    """Bounded ring of cluster deltas with monotone epochs.

    Writers call `record(node, kind)` under any outer lock they like (the
    journal lock is a leaf).  Readers call `current_epoch()` to checkpoint
    and later `dirty_since(checkpoint)` to enumerate what changed — or
    learn (None) that the window is gone and they must resync from scratch.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError("journal capacity must be >= 1")
        self._lock = threading.Lock()
        self._capacity = int(capacity)
        self._ring: List[Delta] = []
        self._head = 0  # next write slot when the ring is full
        self._epoch = 0
        # epoch of the oldest entry still in the ring; entries at or below
        # this bound may have been overwritten → readers behind it get None
        self._floor = 0

    @property
    def capacity(self) -> int:
        return self._capacity

    def current_epoch(self) -> int:
        """The epoch of the newest recorded delta (0 when empty)."""
        with self._lock:
            return self._epoch

    def record(self, node: str, kind: str) -> int:
        """Append one delta; returns its epoch. Thread-safe, leaf-locked."""
        if kind not in DELTA_KINDS:
            raise ValueError(f"unknown delta kind: {kind!r}")
        consult = _corrupt_consult
        if consult is not None and consult(node, kind):
            # seeded suppression: the mutation happened but the journal
            # never hears of it — the missed-delta shape the auditor hunts.
            # The current epoch is returned so callers see a valid handle.
            with self._lock:
                return self._epoch
        with self._lock:
            self._epoch += 1
            entry = Delta(self._epoch, node, kind)
            if len(self._ring) < self._capacity:
                self._ring.append(entry)
            else:
                evicted = self._ring[self._head]
                self._floor = evicted.epoch
                self._ring[self._head] = entry
                self._head = (self._head + 1) % self._capacity
            return self._epoch

    def mark_gap(self) -> None:
        """Invalidate every outstanding checkpoint: readers at any epoch
        before NOW get None from dirty_since. The informer resync path calls
        this because a re-list may fold in mutations the watch dropped."""
        with self._lock:
            self._epoch += 1
            self._floor = self._epoch
            self._ring.clear()
            self._head = 0

    def dirty_since(self, epoch: int) -> Optional[FrozenSet[str]]:
        """Node names touched strictly after `epoch`, or None when the ring
        no longer covers that span (overwritten, or a declared gap)."""
        with self._lock:
            if epoch < self._floor:
                return None
            if epoch >= self._epoch:
                return frozenset()
            return frozenset(d.node for d in self._ring if d.epoch > epoch)

    def deltas_since(self, epoch: int) -> Optional[Tuple[Delta, ...]]:
        """The raw entries after `epoch` in epoch order, or None on a gap —
        for tests and attribution, not the hot path."""
        with self._lock:
            if epoch < self._floor:
                return None
            out = sorted((d for d in self._ring if d.epoch > epoch), key=lambda d: d.epoch)
            return tuple(out)
