from .encode import DenseProblem, GroupInfo, GroupKind, encode_problem

__all__ = ["DenseProblem", "GroupInfo", "GroupKind", "encode_problem"]
