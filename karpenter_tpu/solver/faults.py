"""Solver fault domain: typed device-failure taxonomy, deterministic fault
injection, and the host-fallback circuit breaker.

PR 9 gave the *cloud* side a typed failure family (`cloudprovider/errors.py`)
so the provisioner could dispatch on WHAT failed instead of retrying blindly.
The solver side had nothing comparable: every device fault — an XLA compile
failure, an HBM RESOURCE_EXHAUSTED, a Pallas kernel error, a lost device —
collapsed into one catch-all at the scheduler boundary, indistinguishable,
re-paid from scratch every solve, and invisible to the flight recorder and
campaign scoring. This module is the solver's mirror of that discipline:

- **taxonomy** — `SolverCompileError` / `SolverHbmExhaustedError` /
  `SolverKernelError` / `SolverDeviceLostError`, plus `classify(exc)`
  mapping raw JAX/XLA exception surfaces (RESOURCE_EXHAUSTED, INTERNAL,
  Mosaic/Pallas failures, dead-backend shapes) into it. `classify` is
  text-based by necessity — jaxlib's error types are version-soup — and an
  unmatchable exception returns None so a NEW failure mode surfaces as
  `kind="unclassified"` instead of hiding as routine fallback.
- **injection seam** — `FaultPlan` + the process-wide `FAULTS` injector:
  seeded, per-entry-name, nth-call triggers consulted at every device
  dispatch boundary (`solver/dense.py` plain/sharded/chunk sites,
  `ops/pallas_kernels.py`, the warm-fill surface). Unset, the seam is one
  attribute read (the tracing/SLO/FLIGHT disabled-is-free bar); installed,
  the same seed + plan produce the identical fault sequence on every run —
  chaos tests inject exactly the fault class they claim to test.
  Simulation-mode re-solves (consolidation / SLO what-ifs) bypass the
  injector entirely: their epoch-driven timing would otherwise consume
  triggers nondeterministically out from under the real provisioner.
- **degradation ladder accounting** — `karpenter_solver_faults_total{kind}`
  and `karpenter_solver_degraded_solves_total{rung}` count every classified
  fault and every rung transition (`flavor` retirement -> `chunked`
  HBM-pressure solve -> `host` fill); the dense solver records the same
  transitions on its flight records and as journal `solver` events.
- **circuit breaker** — `SolverCircuitBreaker` (process-wide `BREAKER`, the
  FLIGHT/TRACER singleton pattern): `threshold` CONSECUTIVE classified
  device faults open it, an open breaker short-circuits the device attempt
  (the exact host loop owns every batch, no encode, no dispatch), and after
  `backoff` seconds (clock-seam timed) the next REAL solve runs a half-open
  recovery probe — success re-admits the fast path, failure re-opens.
  Simulation-mode solves share the state (they skip the device path while
  it is open) but never trip it, never probe it, and never reset it: a
  consolidation what-if burning the real provisioner's recovery probe would
  be cross-loop interference. State is served inside `/debug/solver`.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..analysis.guards import guarded_by
from ..analysis.witness import WITNESS
from ..capsule import CAPSULE, TRIGGER_BREAKER_OPEN
from ..journal import JOURNAL
from ..logsetup import get_logger
from ..metrics import REGISTRY
from ..utils.clock import Clock

log = get_logger("solver.faults")

# -- the taxonomy ---------------------------------------------------------------

KIND_COMPILE = "compile"
KIND_HBM = "hbm"
KIND_KERNEL = "kernel"
KIND_DEVICE_LOST = "device-lost"
KIND_UNCLASSIFIED = "unclassified"

KINDS = (KIND_COMPILE, KIND_HBM, KIND_KERNEL, KIND_DEVICE_LOST, KIND_UNCLASSIFIED)

# ladder rungs, in escalation order: retire the kernel/mesh flavor, chunk
# the dispatch surface under HBM pressure, hand the batch to the host loop
RUNG_FLAVOR = "flavor"
RUNG_CHUNKED = "chunked"
RUNG_HOST = "host"
RUNGS = (RUNG_FLAVOR, RUNG_CHUNKED, RUNG_HOST)


class SolverFault(RuntimeError):
    """Base of the typed device-failure family (the solver-side analog of
    cloudprovider/errors.py). `kind` is the metric label."""

    kind = KIND_UNCLASSIFIED


class SolverCompileError(SolverFault):
    """XLA/Mosaic failed to BUILD a program for this shape class (lowering
    or compilation): retrying the same dispatch cannot succeed, but another
    flavor (plain jnp instead of Pallas) may compile fine."""

    kind = KIND_COMPILE


class SolverHbmExhaustedError(SolverFault):
    """The device ran out of memory (RESOURCE_EXHAUSTED / OOM): the same
    work in smaller pieces can still succeed — the chunked-solve rung."""

    kind = KIND_HBM


class SolverKernelError(SolverFault):
    """A compiled program failed at RUN time (INTERNAL, a Pallas/Mosaic
    runtime fault): the flavor is suspect, not the device."""

    kind = KIND_KERNEL


class SolverDeviceLostError(SolverFault):
    """The device (or its transport) is gone — dead backend, lost
    connection, halted chip. Nothing dispatched this pass can succeed."""

    kind = KIND_DEVICE_LOST


_FAULT_BY_KIND = {
    KIND_COMPILE: SolverCompileError,
    KIND_HBM: SolverHbmExhaustedError,
    KIND_KERNEL: SolverKernelError,
    KIND_DEVICE_LOST: SolverDeviceLostError,
}

# state-corruption kinds: unlike the raising taxonomy above, these never
# raise — a fired corruption spec tells ITS seam to silently damage the
# incremental engine's resident state (flip a resident row, suppress a
# DeltaJournal record, perturb the donated device buffer), so the
# residency auditor's detection claims are provable against a known,
# seeded, history-witnessed injection rather than vacuous on healthy runs
KIND_CORRUPT_ROW = "corrupt-row"
KIND_SUPPRESS_DELTA = "suppress-delta"
KIND_CORRUPT_DEVICE = "corrupt-device"
CORRUPTION_KINDS = (KIND_CORRUPT_ROW, KIND_SUPPRESS_DELTA, KIND_CORRUPT_DEVICE)

# textual signatures per kind, checked in order: jaxlib raises version-soup
# exception types, but the gRPC status words and the XLA error vocabulary
# are stable across releases. HBM first (an OOM message often also says
# INTERNAL), device-lost before compile/kernel (a dead backend wraps
# whatever it was doing when it died).
_HBM_MARKS = ("resource_exhausted", "resource exhausted", "out of memory", "oom", "hbm")
# bare common words ("internal", "aborted", "unavailable") would reclassify
# ordinary software bugs raised inside the dispatch try-blocks as device
# faults and feed them to the breaker — the gRPC status vocabulary always
# arrives colon-anchored ("UNAVAILABLE: socket closed"), so anchor those
_DEVICE_LOST_MARKS = (
    "device lost",
    "unavailable:",
    "socket closed",
    "connection reset",
    "failed to connect",
    "dead backend",
    "backend was destroyed",
    "halted",
    "aborted:",
)
_COMPILE_MARKS = ("compilation", "compile", "lowering", "unimplemented")
_KERNEL_MARKS = ("internal:", "internal error", "pallas", "mosaic", "kernel")


def classify(exc: BaseException) -> Optional[SolverFault]:
    """Map a raw device-path exception into the typed family; an already-
    typed fault passes through. None means UNCLASSIFIED — the caller must
    keep failing open to the host loop but count it distinctly, so a new
    JAX failure mode cannot hide as routine fallback forever."""
    if isinstance(exc, SolverFault):
        return exc
    text = f"{type(exc).__name__}: {exc}".lower()
    for marks, cls in (
        (_HBM_MARKS, SolverHbmExhaustedError),
        (_DEVICE_LOST_MARKS, SolverDeviceLostError),
        (_COMPILE_MARKS, SolverCompileError),
        (_KERNEL_MARKS, SolverKernelError),
    ):
        if any(mark in text for mark in marks):
            return cls(str(exc) or type(exc).__name__)
    return None


# -- metrics (registered at import so gen_docs sees the families) ---------------

SOLVER_FAULTS = REGISTRY.counter(
    "karpenter_solver_faults_total",
    "Classified solver device faults by taxonomy kind (compile, hbm, kernel,"
    " device-lost; 'unclassified' = a failure classify() could not map — a new"
    " JAX failure mode that must not hide as routine host fallback).",
    ("kind",),
)
DEGRADED_SOLVES = REGISTRY.counter(
    "karpenter_solver_degraded_solves_total",
    "Dense solves that took a degradation-ladder rung: 'flavor' (Pallas/mesh"
    " retirement to plain jnp), 'chunked' (HBM-pressure split dispatch),"
    " 'host' (the exact host loop took the batch).",
    ("rung",),
)
BREAKER_TRANSITIONS = REGISTRY.counter(
    "karpenter_solver_breaker_transitions_total",
    "Solver circuit-breaker state transitions, by the state entered.",
    ("state",),
)
BREAKER_STATE = REGISTRY.gauge(
    "karpenter_solver_breaker_state",
    "Current solver circuit-breaker state: 0 = closed (device path admitted),"
    " 1 = half-open (recovery probe in flight), 2 = open (host fallback).",
)


def faults_total() -> int:
    """Sum of the classified-fault counter across kinds (score surface)."""
    return int(sum(SOLVER_FAULTS.values().values()))


def degraded_total() -> int:
    """Sum of the degraded-solve counter across rungs (score surface)."""
    return int(sum(DEGRADED_SOLVES.values().values()))


# -- deterministic fault injection ----------------------------------------------


@dataclass
class FaultSpec:
    """One planned trigger. `entry` names the dispatch boundary ('plain',
    'sharded', 'pallas', 'chunk', 'warmfill', 'rebase', or '*' — corruption
    kinds target the state seams 'resident-row', 'journal-record',
    'rebase'); `nth` fires on the nth matching call (1-based) for `count`
    consecutive matching calls; with `nth` None, `probability` draws a
    seeded coin per matching call — still fully deterministic for a given
    (plan, seed, call sequence)."""

    kind: str
    entry: str = "*"
    nth: Optional[int] = None
    count: int = 1
    probability: float = 0.0

    def __post_init__(self):
        if self.kind not in _FAULT_BY_KIND and self.kind not in CORRUPTION_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; one of {sorted((*_FAULT_BY_KIND, *CORRUPTION_KINDS))}"
            )
        if self.nth is not None and self.nth < 1:
            raise ValueError("nth is 1-based")
        if not (0.0 <= self.probability <= 1.0):
            raise ValueError("probability must be in [0, 1]")


@guarded_by("_lock", "_calls", "_spec_calls", "_history")
class FaultPlan:
    """A seeded, deterministic schedule of device faults. Same plan + same
    seed + same dispatch sequence -> identical fault sequence, byte for
    byte — the property the determinism tests pin on both dispatch
    flavors."""

    def __init__(self, specs: Sequence[FaultSpec], seed: int = 0):
        self.specs = list(specs)
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = WITNESS.lock("solver.faults")
        self._calls = 0
        self._spec_calls = [0] * len(self.specs)
        self._history: List[dict] = []

    @classmethod
    def from_specs(cls, specs: Sequence[dict], seed: int = 0) -> "FaultPlan":
        return cls([FaultSpec(**spec) for spec in specs], seed=seed)

    def _consult(self, entry: str, corruption: bool) -> Optional[FaultSpec]:
        """Shared trigger logic: one plan call against either the raising
        taxonomy specs (dispatch boundaries) or the corruption specs (state
        seams). A spec's per-match counter only advances at ITS seam family,
        so mixing both families in one plan stays deterministic."""
        fire: Optional[FaultSpec] = None
        with self._lock:
            self._calls += 1
            call = self._calls
            for i, spec in enumerate(self.specs):
                if (spec.kind in CORRUPTION_KINDS) != corruption:
                    continue
                if spec.entry != "*" and spec.entry != entry:
                    continue
                self._spec_calls[i] += 1
                matched = self._spec_calls[i]
                if spec.nth is not None:
                    hit = spec.nth <= matched < spec.nth + spec.count
                else:
                    # one seeded draw per matching call per spec, consumed
                    # whether or not it fires — the sequence is a pure
                    # function of (seed, dispatch order)
                    hit = self._rng.random() < spec.probability
                if hit and fire is None:
                    fire = spec
            if fire is not None:
                self._history.append({"call": call, "entry": entry, "kind": fire.kind})
        return fire

    def check(self, entry: str) -> None:
        """Consult the plan at one dispatch-boundary call; raises the
        planned typed fault when a trigger fires (first matching spec
        wins)."""
        fire = self._consult(entry, corruption=False)
        if fire is not None:
            raise _FAULT_BY_KIND[fire.kind](f"injected {fire.kind} fault at dispatch entry {entry!r}")

    def corrupt(self, entry: str) -> Optional[str]:
        """Consult the plan at one state seam; returns the corruption kind
        to apply (never raises — the seam damages its own state silently,
        which is the whole point: the auditor must FIND it). Fired triggers
        land in the same determinism `history()` as the raising kinds."""
        fire = self._consult(entry, corruption=True)
        return fire.kind if fire is not None else None

    def corruptions_fired(self) -> int:
        """Fired corruption triggers only (the storm scenario's
        divergences == injections bar)."""
        with self._lock:
            return sum(1 for h in self._history if h["kind"] in CORRUPTION_KINDS)

    def history(self) -> List[dict]:
        """The fired triggers, in dispatch order (determinism witness)."""
        with self._lock:
            return [dict(h) for h in self._history]

    def fired(self) -> int:
        with self._lock:
            return len(self._history)


class FaultInjector:
    """Process-wide seam the dispatch boundaries consult. No plan installed
    (production) = one attribute read per dispatch; `install()` arms a
    FaultPlan, `clear()` disarms. The solver marks simulation-mode solves
    per thread (`set_simulation`) so every boundary on that thread — dense
    dispatch, the ops kernels, the warm-fill surface — bypasses the plan
    without plumbing a flag through each call."""

    def __init__(self):
        self._plan: Optional[FaultPlan] = None
        self._local = threading.local()

    @property
    def plan(self) -> Optional[FaultPlan]:
        return self._plan

    def install(self, plan: FaultPlan) -> None:
        self._plan = plan
        # the journal's mutator seam lives in ir/delta.py, which imports
        # nothing from this package (it must stay a leaf): arm its module
        # hook ONLY when the plan actually carries suppress-delta specs, so
        # every other plan leaves record() at one module-global read
        if any(spec.kind == KIND_SUPPRESS_DELTA for spec in plan.specs):
            from ..ir import delta as ir_delta

            # only pod-level records are suppressible: a dropped NODE_ADDED/
            # NODE_REMOVED is invisible (the engine diffs the row set without
            # the journal), so suppressing one would spend a trigger on an
            # injection no auditor could ever detect
            ir_delta.set_corrupt_seam(
                lambda node, kind: kind in (ir_delta.POD_BOUND, ir_delta.POD_REMOVED)
                and self.corrupt("journal-record") == KIND_SUPPRESS_DELTA
            )
        log.info("solver fault plan installed: %d spec(s), seed %d", len(plan.specs), plan.seed)

    def clear(self) -> None:
        self._plan = None
        from ..ir import delta as ir_delta

        ir_delta.set_corrupt_seam(None)

    def fired(self) -> int:
        plan = self._plan
        return plan.fired() if plan is not None else 0

    def corruptions_fired(self) -> int:
        plan = self._plan
        return plan.corruptions_fired() if plan is not None else 0

    def set_simulation(self, simulation: bool) -> None:
        """Mark THIS thread's in-flight solve as a simulation re-solve
        (consolidation / SLO what-if): injected faults target the real
        provisioner's dispatch sequence — a what-if consuming triggers
        would make every plan nondeterministic."""
        self._local.simulation = bool(simulation)

    def check(self, entry: str, simulation: Optional[bool] = None) -> None:
        plan = self._plan
        if plan is None:
            return
        if simulation is None:
            simulation = getattr(self._local, "simulation", False)
        if simulation:
            return
        plan.check(entry)

    def corrupt(self, entry: str, simulation: Optional[bool] = None) -> Optional[str]:
        """State-seam mirror of check(): returns the corruption kind to
        apply at `entry`, or None. Same no-plan fast path, same per-thread
        simulation bypass."""
        plan = self._plan
        if plan is None:
            return None
        if simulation is None:
            simulation = getattr(self._local, "simulation", False)
        if simulation:
            return None
        return plan.corrupt(entry)


FAULTS = FaultInjector()


# -- the circuit breaker --------------------------------------------------------

STATE_CLOSED = "closed"
STATE_HALF_OPEN = "half-open"
STATE_OPEN = "open"
_STATE_GAUGE = {STATE_CLOSED: 0.0, STATE_HALF_OPEN: 1.0, STATE_OPEN: 2.0}

DEFAULT_BREAKER_THRESHOLD = 3
DEFAULT_BREAKER_BACKOFF = 30.0


@guarded_by("_lock", "state", "consecutive", "opened_total", "_open_until", "last_fault_kind")
class SolverCircuitBreaker:
    """Consecutive-fault breaker over the solver's device path with
    half-open recovery probes. Clock-seam timed (FakeClock drives the
    backoff deterministically in tests); state transitions are counted
    (`karpenter_solver_breaker_transitions_total{state}`) and journaled as
    `solver` events when the journal is enabled."""

    def __init__(self, threshold: int = DEFAULT_BREAKER_THRESHOLD, backoff: float = DEFAULT_BREAKER_BACKOFF):
        self._lock = WITNESS.lock("solver.breaker")
        self.threshold = threshold
        self.backoff = backoff
        self.clock: Clock = Clock()
        self.state = STATE_CLOSED
        self.consecutive = 0
        self.opened_total = 0
        self.last_fault_kind = ""
        self._open_until = 0.0

    def configure(self, threshold: Optional[int] = None, backoff: Optional[float] = None, clock: Optional[Clock] = None) -> None:
        """(Re)tune without resetting state: a restarted Runtime re-wires
        its clock and thresholds but inherits the process's breaker history
        (the device is the same device across restarts). Adopts a witnessed
        lock when the witness came up after import, so the breaker joins
        the lock-order graph the chaos suites assert acyclic."""
        if WITNESS.enabled and isinstance(self._lock, threading.Lock().__class__):
            # constructed before the witness came up: swap in a witnessed
            # lock (configure runs at Runtime assembly, before any solve
            # can hold it — the flight-recorder enable() pattern)
            self._lock = WITNESS.lock("solver.breaker")
        with self._lock:
            if threshold is not None:
                self.threshold = max(1, int(threshold))
            if backoff is not None:
                self.backoff = float(backoff)
        if clock is not None:
            self.clock = clock

    def reset(self) -> None:
        """Back to CLOSED with zeroed counters (per-run harness reset)."""
        with self._lock:
            self.state = STATE_CLOSED
            self.consecutive = 0
            self.opened_total = 0
            self.last_fault_kind = ""
            self._open_until = 0.0
        BREAKER_STATE.set(_STATE_GAUGE[STATE_CLOSED])

    def _transition_locked(self, state: str) -> None:
        self.state = state
        BREAKER_TRANSITIONS.inc(state=state)
        BREAKER_STATE.set(_STATE_GAUGE[state])
        if JOURNAL.enabled:
            JOURNAL.solver_event("breaker", f"breaker-{'opened' if state == STATE_OPEN else state}")
        if state == STATE_OPEN and CAPSULE.enabled:
            # enqueue-only while this lock is held: the capsule engine
            # captures later, in poll(), without the breaker lock — the
            # breaker->capsule edge stays a leaf in the lock-order graph
            CAPSULE.trigger(TRIGGER_BREAKER_OPEN, fault_kind=self.last_fault_kind, threshold=self.threshold)
        log.warning("solver circuit breaker -> %s (consecutive=%d threshold=%d)", state, self.consecutive, self.threshold)

    def admit(self, simulation: bool = False) -> bool:
        """May this solve attempt the device path? CLOSED admits everyone;
        OPEN denies until the backoff expires, then the first REAL solve
        becomes the half-open recovery probe (simulation solves share the
        open/closed answer but never ride — or become — the probe)."""
        with self._lock:
            if self.state == STATE_CLOSED:
                return True
            if self.state == STATE_OPEN and self.clock.now() >= self._open_until:
                if simulation:
                    return False  # a what-if must not spend the recovery probe
                self._transition_locked(STATE_HALF_OPEN)
                return True
            if self.state == STATE_HALF_OPEN:
                return not simulation
            return False

    def record_fault(self, kind: str, simulation: bool = False) -> None:
        """One classified device fault that ended a solve's device attempt.
        Simulation solves never trip the breaker (cross-loop interference:
        the scraper's what-if would open the real provisioner's path)."""
        if simulation:
            return
        with self._lock:
            self.last_fault_kind = kind
            if self.state == STATE_HALF_OPEN:
                # the probe failed: back to OPEN for another backoff
                self._open_until = self.clock.now() + self.backoff
                self.opened_total += 1
                self._transition_locked(STATE_OPEN)
                return
            self.consecutive += 1
            if self.state == STATE_CLOSED and self.consecutive >= self.threshold:
                self._open_until = self.clock.now() + self.backoff
                self.opened_total += 1
                self._transition_locked(STATE_OPEN)

    def record_success(self, simulation: bool = False) -> None:
        """A solve's device attempt succeeded (any rung that still reached
        the device — plain, retired-flavor, or chunked)."""
        if simulation:
            return
        with self._lock:
            self.consecutive = 0
            if self.state == STATE_HALF_OPEN:
                self._transition_locked(STATE_CLOSED)

    def snapshot(self) -> dict:
        """The /debug/solver breaker block."""
        with self._lock:
            now = self.clock.now()
            return {
                "state": self.state,
                "threshold": self.threshold,
                "backoff_seconds": self.backoff,
                "consecutive_faults": self.consecutive,
                "opened_total": self.opened_total,
                "last_fault_kind": self.last_fault_kind,
                "reopen_probe_in_seconds": (
                    round(max(0.0, self._open_until - now), 3) if self.state == STATE_OPEN else 0.0
                ),
            }


BREAKER = SolverCircuitBreaker()
