"""Exact minimum-cost packing reference for cost-regret measurement.

The BASELINE target says the production solver's node cost must stay within
3% of an exhaustive ILP. The reference repo never measures this (its
instance_selection_test.go:38 suite only asserts cheapest-single-choice
behavior); this module is the measuring stick: a mixed-integer program over
node slots that computes the true minimum node cost for small instances
(<=~50 pods x ~20 types), solved with HiGHS via scipy.optimize.milp.

This is a test/bench harness, not a production path: the MILP is exponential
in the worst case and is deliberately capped by `time_limit`. Production
solves go through DenseSolver/Scheduler; tests/test_cost_regret.py compares
the two and asserts the <=3% gate.

Formulation (slot model):
  z[n,t] = 1 iff node slot n is realized as instance type t
  x[p,n] = 1 iff pod p lands on slot n
  min  sum_{n,t} price[t] z[n,t]
  s.t. each pod placed exactly once; per-slot capacity over every resource
       (slot capacity = chosen type's allocatable, so an unused slot has
       zero capacity and can host nothing because every pod requests
       pods>=1); at most one type per slot; pods only on slots whose type
       is requirement-compatible; slots used in order (symmetry breaking).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np


@dataclass
class OptimalResult:
    cost: float
    status: str  # "optimal" | "timeout" | "infeasible" | "unavailable"
    nodes: int = 0

    @property
    def ok(self) -> bool:
        return self.status == "optimal"


def optimal_node_cost(
    requests: np.ndarray,
    caps: np.ndarray,
    prices: np.ndarray,
    compat: Optional[np.ndarray] = None,
    max_slots: Optional[int] = None,
    time_limit: float = 60.0,
) -> OptimalResult:
    """Minimum total node price to place every pod.

    requests: [P, R] pod resource requests (include the synthetic `pods`
              resource at 1.0 per pod so per-type pod density binds).
    caps:     [T, R] allocatable per type (resources minus overhead minus
              any daemonset overhead — the same effective capacity the
              scheduler packs against).
    prices:   [T]
    compat:   [P, T] bool requirement-compatibility mask (default all-true).
    """
    try:
        from scipy import sparse
        from scipy.optimize import Bounds, LinearConstraint, milp
    except Exception:
        return OptimalResult(cost=float("nan"), status="unavailable")

    requests = np.asarray(requests, dtype=np.float64)
    caps = np.asarray(caps, dtype=np.float64)
    prices = np.asarray(prices, dtype=np.float64)
    P, R = requests.shape
    T = caps.shape[0]
    if compat is None:
        compat = np.ones((P, T), dtype=bool)
    # a pod with no compatible type makes the whole instance infeasible
    if not compat.any(axis=1).all():
        return OptimalResult(cost=float("nan"), status="infeasible")
    N = min(P, max_slots) if max_slots else P

    # variable layout: x[p,n] then z[n,t]
    nx = P * N
    nz = N * T
    nvar = nx + nz

    def xi(p: int, n: int) -> int:
        return p * N + n

    def zi(n: int, t: int) -> int:
        return nx + n * T + t

    rows: List[int] = []
    cols: List[int] = []
    vals: List[float] = []
    lo: List[float] = []
    hi: List[float] = []
    row = 0

    def emit(entries, lb, ub):
        nonlocal row
        for c, v in entries:
            rows.append(row)
            cols.append(c)
            vals.append(v)
        lo.append(lb)
        hi.append(ub)
        row += 1

    # 1. each pod on exactly one slot
    for p in range(P):
        emit([(xi(p, n), 1.0) for n in range(N)], 1.0, 1.0)
    # 2. slot capacity per resource: sum_p req[p,r] x[p,n] <= sum_t cap[t,r] z[n,t]
    for n in range(N):
        for r in range(R):
            entries = [(xi(p, n), requests[p, r]) for p in range(P) if requests[p, r] > 0]
            entries += [(zi(n, t), -caps[t, r]) for t in range(T) if caps[t, r] > 0]
            emit(entries, -np.inf, 0.0)
    # 3. at most one type per slot
    for n in range(N):
        emit([(zi(n, t), 1.0) for t in range(T)], -np.inf, 1.0)
    # 4. compatibility: x[p,n] <= sum_t compat[p,t] z[n,t] (skip if all compat)
    if not compat.all():
        for p in range(P):
            incompat_t = np.nonzero(~compat[p])[0]
            if len(incompat_t) == 0:
                continue
            for n in range(N):
                entries = [(xi(p, n), 1.0)]
                entries += [(zi(n, t), -1.0) for t in np.nonzero(compat[p])[0]]
                emit(entries, -np.inf, 0.0)
    # 5. symmetry: used slots first — sum_t z[n,t] >= sum_t z[n+1,t]
    for n in range(N - 1):
        entries = [(zi(n, t), 1.0) for t in range(T)]
        entries += [(zi(n + 1, t), -1.0) for t in range(T)]
        emit(entries, 0.0, np.inf)

    A = sparse.csr_matrix((vals, (rows, cols)), shape=(row, nvar))
    c = np.zeros(nvar)
    for n in range(N):
        for t in range(T):
            c[zi(n, t)] = prices[t]

    res = milp(
        c=c,
        constraints=LinearConstraint(A, np.asarray(lo), np.asarray(hi)),
        integrality=np.ones(nvar),
        bounds=Bounds(0, 1),
        options={"time_limit": time_limit, "mip_rel_gap": 1e-6},
    )
    if res.status == 0:
        z = res.x[nx:].reshape(N, T)
        return OptimalResult(cost=float(res.fun), status="optimal", nodes=int(round(z.sum())))
    if res.status == 1:  # iteration/time limit
        return OptimalResult(cost=float(res.fun) if res.x is not None else float("nan"), status="timeout")
    if res.status == 2:
        return OptimalResult(cost=float("nan"), status="infeasible")
    # 3 = unbounded (impossible here), 4 = numerical/other solver failure —
    # distinct from infeasibility so harness failures don't masquerade as
    # modeling bugs
    return OptimalResult(cost=float("nan"), status=f"failed({res.status}: {res.message})")


def problem_matrices(pods: Sequence, types: Sequence, template=None):
    """Build (requests, caps, prices, compat) for `optimal_node_cost` from
    the same objects the scheduler consumes, using the same host algebra
    (requirement compatibility, type-overhead subtraction, synthetic pod
    count) so the MILP measures exactly the problem the scheduler solved.
    Assumes no daemonset overhead (the regret instances carry none); if a
    caller schedules with daemonsets it must subtract that overhead from
    the returned caps itself."""
    from ..scheduling.requirements import Requirements
    from ..utils import resources as res

    resource_names = sorted({k for p in pods for k in res.pod_requests(p)} | {"pods"})
    idx = {name: i for i, name in enumerate(resource_names)}
    P, T, R = len(pods), len(types), len(resource_names)

    requests = np.zeros((P, R))
    for i, pod in enumerate(pods):
        for name, v in res.pod_requests(pod).items():
            requests[i, idx[name]] = v
        requests[i, idx["pods"]] = max(requests[i, idx["pods"]], 1.0)

    caps = np.zeros((T, R))
    prices = np.zeros(T)
    for j, it in enumerate(types):
        allocatable = res.subtract(it.resources(), it.overhead())
        for name, v in allocatable.items():
            if name in idx:
                caps[j, idx[name]] = max(v, 0.0)
        prices[j] = it.price()
    # the scheduler packs with res.fits tolerance slack; give the MILP the
    # same headroom so its optimum stays a true lower bound for the
    # tolerant packer (a near-boundary fit must not differ between the two)
    caps = caps + res.tolerance(caps)

    compat = np.ones((P, T), dtype=bool)
    base = list(template.requirements.values()) if template is not None else []
    for i, pod in enumerate(pods):
        pod_reqs = Requirements.from_pod(pod)
        for j, it in enumerate(types):
            node_reqs = Requirements(*base)
            node_reqs.add(*it.requirements().values())
            compat[i, j] = node_reqs.compatible(pod_reqs) is None
    return requests, caps, prices, compat
