"""Incremental solve engine: device-resident cluster state, O(delta) passes.

Every provision pass through round 5 re-encoded the ENTIRE cluster —
`encode_warm_views` walks every existing view even when the pass only bound
three pods, and `repack_16k` shows that host-side assembly (~40 ms encode +
~850 ms fill against ~0 ms device) dominates end-to-end latency at
production churn. CvxCluster (PAPERS.md) reports 100-1000x from exactly
this reformulation: keep the encoded problem resident, apply the delta.

The engine keeps three things alive across passes:

  * a host mirror of the last pass's `WarmViewEncoding` plus the node-name →
    row map that gives its rows identity across passes;
  * the f32 headroom surface `head0` as a DEVICE buffer, padded to the lane
    multiple, rebased in place each pass by `ops/rebase.rebase_view_state`
    — the prior buffer is donated into the rebase (`donate_argnums`), so
    steady-state residency costs one buffer and zero host->device
    re-uploads of the unchanged rows;
  * its checkpoint into the cluster `DeltaJournal` (ir/delta.py), the feed
    that names the dirty rows.

Each `advance()` classifies the pass:

  delta   the journal covers the span since the checkpoint and the dirty
          set is small: re-encode ONLY the dirty views (encode_warm_views
          is row-independent, so the spliced mirror is byte-identical to a
          fresh full encode), realign survivors by permutation, rebase the
          device buffer in one fused donated dispatch.
  full    anything that voids row identity or the journal window: cold
          start, catalog-key change (`invalidate.catalog` — a catalog bump
          can re-shape every row), journal gap/overflow (`invalidate.gap`),
          view-pad regrowth, a forced fault invalidation (breaker opened,
          flavor/mesh retired or a ladder rung taken mid-solve, a classified
          device fault at the rebase boundary — `invalidate.fault-*`), a
          residency-auditor heal (`invalidate.audit`, solver/audit.py), or a
          dirty set so large the delta machinery would cost more than the
          full encode (`invalidate.bulk`).
  bypass  the incremental flag is on but there is nothing to manage (no
          views); the caller runs the fresh path untouched.

Correctness posture: the engine NEVER trusts resident values for a row the
journal (or the previous pass — see below) named dirty; those rows are
recomputed from the CURRENT views with the same f64 expressions as the
fresh path, so the mirror is byte-equal by determinism, pinned every pass
by tests/test_incremental_parity.py. A mutation that lands between the
caller's views snapshot and the engine's epoch checkpoint is covered by the
DOUBLE-WINDOW rule: every pass re-dirties the names the JOURNAL reported
on the previous pass (the only rows whose recompute a concurrent mutation
could have straddled), so a row encoded from a stale snapshot is re-encoded
from a fresh one on the very next pass — exact in single-threaded use,
one-pass-lag self-healing under concurrency. Rows re-encoded purely for
healing leave the window immediately: the steady-state dirty set is bounded
by two passes of churn, never cumulative.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Sequence

import numpy as np

from ..ir.delta import DeltaJournal
from ..ir.encode import WarmViewEncoding, encode_warm_views
from ..metrics import REGISTRY

log = logging.getLogger("karpenter_tpu.solver.incremental")

# above this fraction of dirty rows the delta path costs more than it saves
# (the splice is O(dirty) numpy + one padded dispatch; the full encode is
# one O(V) vectorized pass) — and a half-churned cluster has no stable
# steady state to protect anyway
MAX_DIRTY_FRACTION = 0.5

PASS_FULL = "full"
PASS_DELTA = "delta"
PASS_BYPASS = "bypass"

INCREMENTAL_PASSES = REGISTRY.counter(
    "karpenter_solver_incremental_passes_total",
    "Incremental-engine provision passes by kind: 'delta' (resident state"
    " rebased in place, encode skipped), 'full' (resident state rebuilt —"
    " cold start, catalog change, journal gap, fault invalidation, or bulk"
    " churn), 'bypass' (nothing to manage; fresh path untouched).",
    ("kind",),
)
INCREMENTAL_INVALIDATIONS = REGISTRY.counter(
    "karpenter_solver_incremental_invalidations_total",
    "Resident-state invalidations forcing a full re-encode, by reason:"
    " 'cold', 'catalog', 'gap', 'grow', 'bulk', a fault seam"
    " ('fault-breaker', 'fault-flavor', 'fault-chunked', 'fault-host',"
    " 'fault-device'), or 'audit' (the residency auditor found divergence"
    " and healed by forcing the fresh full re-encode path).",
    ("reason",),
)


@dataclass
class _Resident:
    """What survives between passes."""

    epoch: int
    ckey: tuple
    enc: WarmViewEncoding
    names: List[str]
    row_of: Dict[str, int]
    head_dev: object  # jax [Vp, R] f32, or None when device residency failed
    vp: int
    prev_dirty: FrozenSet[str] = frozenset()


@dataclass
class AdvanceResult:
    """One pass's outcome: the encoding (byte-equal to a fresh
    encode_warm_views over the same views), its attribution, and the time
    the engine spent producing it (charged to delta_apply or full_encode
    by the caller)."""

    enc: Optional[WarmViewEncoding]
    kind: str  # PASS_DELTA | PASS_FULL | PASS_BYPASS
    reason: str  # "" for delta; invalidation reason for full
    seconds: float
    dirty_rows: int


class IncrementalEngine:
    """Per-solver resident-state manager. Not thread-safe: it lives inside
    DenseSolver.presolve's single-threaded provisioning loop (the journal
    it reads IS thread-safe — that is the concurrent edge)."""

    def __init__(self, journal: DeltaJournal, max_dirty_fraction: float = MAX_DIRTY_FRACTION):
        self.journal = journal
        self.max_dirty_fraction = float(max_dirty_fraction)
        self._resident: Optional[_Resident] = None
        self._pending_invalidate: Optional[str] = None
        # pass-kind tallies mirrored off the process-wide counters so tests
        # and the bench can read one engine's history in isolation
        self.passes: Dict[str, int] = {PASS_FULL: 0, PASS_DELTA: 0, PASS_BYPASS: 0}

    # -- invalidation ------------------------------------------------------

    def invalidate(self, reason: str) -> None:
        """Void the resident state: the next pass is a clean full re-encode
        attributed `invalidate.<reason>`. Called by the fault seams — an
        open breaker or a mid-solve flavor retirement means device buffers
        may be stale, donated-away, or sitting on a retired path."""
        self._pending_invalidate = reason
        self._resident = None

    # -- the per-pass entry point -----------------------------------------

    def advance(self, views: Sequence, ckey: tuple) -> AdvanceResult:
        """Produce this pass's WarmViewEncoding from the resident state plus
        the journal's delta, or rebuild it. `views` is the caller's
        already-taken snapshot of scheduler.existing_nodes; `ckey` the
        catalog key of this solve."""
        t0 = time.perf_counter()
        if not views:
            # nothing resident to protect; drop state so a later non-empty
            # pass starts clean rather than diffing against a stale map
            self._resident = None
            self._note(PASS_BYPASS)
            return AdvanceResult(None, PASS_BYPASS, "", time.perf_counter() - t0, 0)

        # checkpoint AFTER the views snapshot: over-dirtying (a mutation
        # between snapshot and checkpoint lands in this window) is safe —
        # the row is recomputed from the snapshot now and re-dirtied next
        # pass by the double-window rule, which heals any staleness
        epoch = self.journal.current_epoch()
        names = [v.node.name for v in views]

        reason = self._full_reason(names, ckey, epoch)
        if reason is not None:
            enc = self._rebuild(views, names, ckey, epoch, reason)
            dt = time.perf_counter() - t0
            self._note(PASS_FULL)
            INCREMENTAL_INVALIDATIONS.inc(reason=reason)
            self._maybe_corrupt_row()
            return AdvanceResult(enc, PASS_FULL, reason, dt, len(views))

        res = self._resident
        assert res is not None
        dirty_names = self._dirty_names  # set by _full_reason's probe
        dirty_idx = [
            i for i, n in enumerate(names) if n in dirty_names or n not in res.row_of
        ]
        enc = self._apply_delta(views, names, dirty_idx, epoch, ckey)
        dt = time.perf_counter() - t0
        self._note(PASS_DELTA)
        self._maybe_corrupt_row()
        return AdvanceResult(enc, PASS_DELTA, "", dt, len(dirty_idx))

    # -- classification ----------------------------------------------------

    def _full_reason(self, names: List[str], ckey: tuple, epoch: int) -> Optional[str]:
        from ..ops.rebase import pad_views

        self._dirty_names: FrozenSet[str] = frozenset()
        self._journal_dirty: FrozenSet[str] = frozenset()
        if self._pending_invalidate is not None:
            reason, self._pending_invalidate = self._pending_invalidate, None
            return reason
        res = self._resident
        if res is None:
            return "cold"
        if res.ckey != ckey:
            return "catalog"
        if res.head_dev is None:
            # device residency failed last pass (transfer error); the host
            # mirror alone cannot skip the device re-upload, so rebuild
            return "cold"
        dirty = self.journal.dirty_since(res.epoch)
        if dirty is None:
            return "gap"
        if pad_views(len(names)) != res.vp:
            return "grow"
        dirty_all = dirty | res.prev_dirty
        known = set(res.row_of)
        touched = sum(1 for n in names if n in dirty_all or n not in known)
        if touched > self.max_dirty_fraction * len(names):
            return "bulk"
        self._dirty_names = frozenset(dirty_all)
        self._journal_dirty = frozenset(dirty)
        return None

    def _note(self, kind: str) -> None:
        self.passes[kind] += 1
        INCREMENTAL_PASSES.inc(kind=kind)

    def _maybe_corrupt_row(self) -> None:
        """Seeded resident-row corruption seam (solver/faults.py): when the
        installed plan fires 'corrupt-row' at 'resident-row', flip one value
        in the HOST mirror — not head_dev, so the device check cannot
        double-count the same injection — modeling a splice/aliasing bug the
        residency auditor must detect as row-drift."""
        res = self._resident
        if res is None:
            return
        from .faults import FAULTS, KIND_CORRUPT_ROW

        if FAULTS.corrupt("resident-row") == KIND_CORRUPT_ROW:
            res.enc.avail_tol[0] += 1.0
            log.warning("injected resident-row corruption: host mirror row 0 avail_tol flipped")

    # -- full rebuild ------------------------------------------------------

    def _rebuild(self, views: Sequence, names: List[str], ckey: tuple, epoch: int, reason: str) -> WarmViewEncoding:
        enc = encode_warm_views(views)
        head_dev, vp = self._upload(enc.head0)
        self._resident = _Resident(
            epoch=epoch,
            ckey=ckey,
            enc=enc,
            names=names,
            row_of={n: i for i, n in enumerate(names)},
            head_dev=head_dev,
            vp=vp,
            prev_dirty=frozenset(),
        )
        self._attach(enc)
        if reason != "cold":
            log.info("incremental resident state invalidated (%s): full re-encode of %d views", reason, len(views))
        return enc

    def _upload(self, head0: np.ndarray):
        """Fresh device residency: [V, R] f64 → padded [Vp, R] f32 device
        buffer, -1.0 pad rows (the dead-row sentinel the rebase keeps)."""
        from ..ops.rebase import pad_views

        V, R = head0.shape
        vp = pad_views(V)
        padded = np.full((vp, R), -1.0, np.float32)
        padded[:V] = head0.astype(np.float32)
        try:
            import jax.numpy as jnp

            return jnp.asarray(padded), vp
        except Exception as exc:  # noqa: BLE001 - residency is an optimization
            log.warning("incremental device upload failed; host-only pass: %r", exc)
            return None, vp

    # -- delta application -------------------------------------------------

    def _apply_delta(
        self, views: Sequence, names: List[str], dirty_idx: List[int], epoch: int, ckey: tuple
    ) -> WarmViewEncoding:
        res = self._resident
        assert res is not None
        old = res.enc
        V = len(views)

        # survivor permutation: new row i ← old row perm[i] (or -1)
        perm = np.fromiter((res.row_of.get(n, -1) for n in names), dtype=np.int32, count=V)
        take = np.clip(perm, 0, None)
        alive = perm >= 0

        usable = old.usable[take] & alive
        avail_tol = np.where(alive[:, None], old.avail_tol[take], 0.0)
        requests0 = np.where(alive[:, None], old.requests0[take], 0.0)
        head0 = np.where(alive[:, None], old.head0[take], -1.0)
        zone = [old.zone[p] if p >= 0 else None for p in perm]
        ct = [old.ct[p] if p >= 0 else None for p in perm]
        hostname = [old.hostname[p] if p >= 0 else "" for p in perm]
        taint_sig = [old.taint_sig[p] if p >= 0 else () for p in perm]

        # dirty rows: recomputed from the CURRENT views with the exact fresh
        # expressions (encode_warm_views is row-independent → byte-equal)
        sub = encode_warm_views([views[i] for i in dirty_idx])
        for j, i in enumerate(dirty_idx):
            usable[i] = sub.usable[j]
            avail_tol[i] = sub.avail_tol[j]
            requests0[i] = sub.requests0[j]
            head0[i] = sub.head0[j]
            zone[i] = sub.zone[j]
            ct[i] = sub.ct[j]
            hostname[i] = sub.hostname[j]
            taint_sig[i] = sub.taint_sig[j]

        enc = WarmViewEncoding(
            usable=usable,
            avail_tol=avail_tol,
            requests0=requests0,
            head0=head0,
            zone=zone,
            ct=ct,
            hostname=hostname,
            taint_sig=taint_sig,
        )

        # device rebase: one fused donated dispatch moves survivors by
        # permutation and scatters the dirty rows; the prior pass's buffer
        # is consumed (donate_argnums) and its storage reused
        head_dev = None
        if res.head_dev is not None:
            try:
                import jax.numpy as jnp

                from ..ops.rebase import pack_rebase, rebase_view_state
                from .faults import FAULTS, KIND_CORRUPT_DEVICE

                FAULTS.check("rebase")
                rows32 = sub.head0.astype(np.float32) if dirty_idx else np.zeros((0, head0.shape[1]), np.float32)
                perm_p, rows_p, idx_p = pack_rebase(
                    perm, rows32, np.asarray(dirty_idx, dtype=np.int32), res.vp
                )
                head_dev = rebase_view_state(
                    res.head_dev, jnp.asarray(perm_p), jnp.asarray(rows_p), jnp.asarray(idx_p)
                )
                if FAULTS.corrupt("rebase") == KIND_CORRUPT_DEVICE and head_dev is not None:
                    # seeded device-buffer corruption: perturb one element of
                    # the rebased buffer AFTER the dispatch — the host mirror
                    # stays byte-exact, so only the auditor's device check
                    # (gather_rows vs f32(head0)) can see it
                    head_dev = head_dev.at[0, 0].add(1.0)
                    log.warning("injected device-buffer corruption: resident head_dev[0, 0] perturbed")
            except Exception as exc:  # noqa: BLE001 - residency is an optimization
                from .faults import SOLVER_FAULTS, classify

                fault = classify(exc)
                if fault is not None:
                    # a CLASSIFIED device fault at the rebase boundary: the
                    # prior buffer was donated into the failed dispatch and
                    # must never be reused — void residency entirely so the
                    # recovery pass is a clean full re-encode (fresh upload),
                    # and count the fault like every other dispatch boundary
                    SOLVER_FAULTS.inc(kind=fault.kind)
                    log.warning(
                        "device fault at rebase boundary (%s): residency voided, next pass full re-encode: %r",
                        fault.kind, exc,
                    )
                    self.invalidate("fault-device")
                    return enc
                log.warning("incremental device rebase failed; host-only pass: %r", exc)
                head_dev = None

        # next pass's healing window: ONLY the rows the journal named this
        # pass (plus rows new to the map) can have been encoded from a
        # snapshot a concurrent mutation straddled. Rows re-encoded merely
        # because they sat in the previous window are healed and must leave
        # it — carrying all of dirty_idx would make the window transitively
        # cumulative, growing every pass until it trips 'bulk' (and crossing
        # dirty-pad rungs, retracing the rebase kernel, on the way)
        prev = frozenset(
            names[i]
            for i in dirty_idx
            if names[i] in self._journal_dirty or names[i] not in res.row_of
        )
        self._resident = _Resident(
            epoch=epoch,
            ckey=ckey,
            enc=enc,
            names=names,
            row_of={n: i for i, n in enumerate(names)},
            head_dev=head_dev,
            vp=res.vp,
            prev_dirty=prev,
        )
        self._attach(enc)
        return enc

    def _attach(self, enc: WarmViewEncoding) -> None:
        """Carry the resident device buffer on the encoding so the warm-fill
        admission surface (warmfill._device_counts) can dispatch against it
        without a fresh host→device transfer."""
        res = self._resident
        if res is not None and res.head_dev is not None:
            enc.head_dev = res.head_dev
            enc.head_vp = res.vp
