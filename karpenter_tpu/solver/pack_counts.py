"""Counts-based bin packing over deduplicated pod sizes.

Why not a per-pod scan on device: a lax.scan pays ~10us/step of loop overhead
on TPU, so a 10k-pod sequential pack costs ~100ms before doing any work —
sequential control flow is the one thing the hardware punishes. Instead we
exploit that bins within a pack bucket are *identical* (same chosen instance
type) and pod sizes are heavily repeated (requests come from discrete
cpu/memory menus): dedupe pods to U distinct request vectors with counts,
fill one bin greedily largest-first (exact multi-resource check), then emit
that bin pattern as many times as the remaining counts allow. Rounds are
bounded by ~U (each round exhausts at least one size class), so packing cost
is U-scale regardless of P — and the quality matches bin-by-bin greedy FFD,
the same family as the reference's algorithm (scheduler.go:189-232).

The P-scale work — feasibility masks and layout verification — stays on
device (ops/feasibility.py).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from .. import native


def pack_and_assign(unique: np.ndarray, counts: np.ndarray, inverse: np.ndarray, cap: np.ndarray) -> Tuple[np.ndarray, int]:
    """Pack deduplicated sizes and expand to per-item bin ids in one call.

    Uses the native C++ core (karpenter_tpu/native) when available; the pure
    numpy path below is the always-available fallback with identical
    semantics (held together by tests/test_native.py).

    Returns (bin_of_item [P] int64 with -1 unplaced, number of bins).
    """
    result = native.pack_assign(unique, counts, inverse, cap, 0)
    if result is not None:
        bin_of_item, next_bin, _unplaced = result
        return bin_of_item, next_bin
    patterns, unplaced = pack_counts(unique, counts, cap)
    return assign_bins(inverse, patterns, unplaced, 0)


def pack_dedicated(requests: np.ndarray, cap: np.ndarray) -> Tuple[np.ndarray, int]:
    """One item per bin when it fits an empty bin; -1 otherwise."""
    result = native.pack_dedicated(requests, cap, 0)
    if result is not None:
        return result
    from ..utils.resources import tolerance

    fits = np.all(requests <= cap[None, :] + tolerance(cap)[None, :], axis=1)
    ids = np.where(fits, np.cumsum(fits) - 1, -1)
    return ids, int(fits.sum())


def dedupe_sizes(requests: np.ndarray, quantum: np.ndarray = None) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Group identical request vectors.

    Returns (unique [U, R] same dtype as the input, counts [U] int64,
    inverse [P] int64), with unique sorted descending by the first resource
    column, later columns as tiebreaks — FFD order. An optional
    per-resource quantum rounds requests *up* to bound U for continuous size
    distributions (feasible by construction: we only over-estimate).
    """
    reqs = requests
    if quantum is not None:
        q = np.maximum(quantum, 1e-12)
        reqs = np.ceil(requests / q) * q
    unique, inverse, counts = np.unique(reqs, axis=0, return_inverse=True, return_counts=True)
    # descending by first column, later columns as tiebreaks (FFD order)
    order = np.lexsort(tuple(-unique[:, c] for c in range(unique.shape[1] - 1, -1, -1)))
    rank = np.empty_like(order)
    rank[order] = np.arange(len(order))
    return unique[order], counts[order], rank[inverse]


def pack_counts(unique: np.ndarray, counts: np.ndarray, cap: np.ndarray) -> Tuple[List[Tuple[np.ndarray, int]], np.ndarray]:
    """Pack `counts[u]` items of size `unique[u]` into identical bins `cap`.

    Returns (bins, unplaced):
      bins: list of (pattern [U] int64, repeat int) — `repeat` identical bins
            each holding pattern[u] items of size u.
      unplaced: [U] int64 counts of items that don't fit an empty bin.
    """
    from ..utils.resources import tolerance

    U, R = unique.shape
    tol = tolerance(cap)
    remaining = counts.astype(np.int64).copy()
    # items that can never fit (single item exceeds empty-bin capacity)
    impossible = ~np.all(unique <= cap[None, :] + tol[None, :], axis=1)
    unplaced = np.where(impossible, remaining, 0)
    remaining[impossible] = 0

    bins: List[Tuple[np.ndarray, int]] = []
    guard = 0
    while remaining.sum() > 0:
        guard += 1
        if guard > 4 * U + 64:  # safety net; should be unreachable
            unplaced += remaining
            break
        pattern = np.zeros((U,), np.int64)
        free = cap.astype(np.float64).copy()
        for u in range(U):
            if remaining[u] - pattern[u] <= 0:
                continue
            size = unique[u]
            # how many of size u fit in the remaining free capacity
            with np.errstate(divide="ignore", invalid="ignore"):
                per_r = np.where(size > 1e-9, np.floor((free + tol) / np.maximum(size, 1e-9)), np.inf)
            k = int(min(per_r.min(), remaining[u]))
            if k > 0:
                pattern[u] = k
                free -= size * k
        if pattern.sum() == 0:
            unplaced += remaining
            break
        with np.errstate(divide="ignore"):
            repeats = np.where(pattern > 0, remaining // np.maximum(pattern, 1), np.iinfo(np.int64).max)
        repeat = max(1, int(repeats.min()))
        bins.append((pattern, repeat))
        remaining -= pattern * repeat
    return bins, unplaced


def assign_bins(
    inverse: np.ndarray, bins: List[Tuple[np.ndarray, int]], unplaced: np.ndarray, first_bin_id: int
) -> Tuple[np.ndarray, int]:
    """Expand bin patterns into a per-item bin id (-1 for unplaced).

    Items of each size class are assigned to bins in class order; which item
    of a class lands in which identical bin is arbitrary (they're
    interchangeable).
    """
    U = len(unplaced)
    P = len(inverse)
    bin_of_item = np.full((P,), -1, np.int64)
    # rows per size class, in original order
    class_rows: List[List[int]] = [[] for _ in range(U)]
    for row, u in enumerate(inverse):
        class_rows[u].append(row)
    cursors = np.zeros((U,), np.int64)
    bin_id = first_bin_id
    for pattern, repeat in bins:
        for _ in range(repeat):
            for u in np.nonzero(pattern)[0]:
                take = int(pattern[u])
                rows = class_rows[u][int(cursors[u]) : int(cursors[u]) + take]
                cursors[u] += take
                for r in rows:
                    bin_of_item[r] = bin_id
            bin_id += 1
    return bin_of_item, bin_id
