"""DenseSolver: the TPU fast path for provisioning solves.

Pipeline (one call per batch, attached to Scheduler via `dense_solver=`):

  1. encode    — ir/encode.py: dedupe pods into constraint groups, compute
                 exact [G, T] compatibility with host algebra, build dense
                 matrices.
  2. domains   — water-fill spread groups across their topology domains,
                 pin affinity components, mark dedicated/single-bin buckets.
  3. device    — ops/: bucket→type choice ([B, T] fractional-cost argmin) and
                 the bounded-space FFD packing scan over the sorted pod
                 stream; both jitted, shapes padded to tile buckets.
  4. verify    — vectorized numpy feasibility audit of the proposed layout
                 (per-bin capacity, compat, offerings); skew is NOT audited —
                 it is correct by construction from the water-filling domain
                 assignment of step 2, and the exact view/add protocols own
                 it wherever placements touch live state. Any bin that fails
                 the audit is evicted wholesale to the host loop.
  5. commit    — construct VirtualNodes directly (no per-pod search) and
                 record topology domains, so host-path pods that follow see
                 consistent counts.

Existing/in-flight nodes are first-class: before opening new bins, each
bucket fills compatible existing capacity (mirroring the host loop's
existing-nodes-first rule, reference scheduler.go:191-195 and
existingnode.go:97), committing through the exact ExistingNodeView.add
protocol so any modeling drift degrades to a per-pod fallback, never an
invalid placement. This is what makes consolidation simulations (which
always carry existing nodes) a real consumer of the dense path.

Multi-provisioner batches encode every template: the type axis concatenates
each template's (weight-ordered) universe and a group binds to its first
workable template, the host loop's rule. Provisioner limits apply at commit
with the same filter-then-subtractMax pessimism the host loop keeps per
opened node (scheduler.go:263-284).

Pods whose constraints the dense IR can't express — and all pods whenever
populated inverse anti-affinities are in play — return to the caller for
the exact host loop. Correct-by-construction: the host loop re-checks
nothing that was committed, but everything committed was verified against
the same invariants the host protocol enforces.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

log = logging.getLogger("karpenter_tpu.solver")

from ..api import labels as lbl
from ..api.objects import OP_IN, Pod
from ..capsule import CAPSULE, TRIGGER_HOST_RUNG
from ..flight import FLIGHT, HBM_PEAK
from ..ir.encode import DenseProblem, GroupKind, catalog_key, catalog_pin, encode_catalog, encode_problem, resource_vector
from ..journal import JOURNAL
from ..tracing import TRACER
from .faults import (
    BREAKER,
    DEGRADED_SOLVES,
    FAULTS,
    KIND_HBM,
    KIND_UNCLASSIFIED,
    RUNG_CHUNKED,
    RUNG_FLAVOR,
    RUNG_HOST,
    SOLVER_FAULTS,
    SolverFault,
    classify,
)
from ..scheduling.requirement import Requirement
from ..scheduling.requirements import Requirements
from ..utils import resources as res

_PAD = 128  # pad the pod axis to multiples of this for compile caching

# The host-loop/device crossover (see the note on DenseSolver.__init__),
# canonical in utils/options.py so every routing site shares one number.
from ..utils.options import DENSE_MIN_BATCH_DEFAULT as MIN_BATCH_DEFAULT  # noqa: E402

# Host-loop throughput calibration for the measured crossover: the exact
# loop schedules ~4k pods/sec on the reference sweep (100 pods: 26ms, 300:
# 73ms — the r3 measurement on DenseSolver.__init__), and unlike the device
# round trip it does not vary with the deployment's device link.
HOST_SECONDS_PER_POD = 2.5e-4
CROSSOVER_FLOOR = 64
CROSSOVER_CEILING = 2048


def measure_dense_crossover(
    trials: int = 3,
    dispatch=None,
    host_seconds_per_pod: float = HOST_SECONDS_PER_POD,
    floor: int = CROSSOVER_FLOOR,
    ceiling: int = CROSSOVER_CEILING,
) -> int:
    """Measure the device dispatch round trip and derive the batch size
    below which the exact host loop is the faster scheduler.

    The dense path's fixed cost is dispatch latency, not compute — a local
    chip answers in ~1 ms where a tunneled one takes 90-180 ms — so a baked
    crossover constant is wrong on every deployment but the one it was
    measured on. At startup (Runtime with dense_min_batch=0, bench sweep)
    this times the SAME jitted op the solver dispatches (compile excluded:
    one warmup call, then min over `trials`) and returns
    round_trip / host_seconds_per_pod clamped to [floor, ceiling]. Any
    measurement failure falls back to the calibrated default — routing must
    never break startup. `dispatch` is injectable so tests can prove the
    constant adapts to a simulated slow link."""
    if dispatch is None:

        def dispatch():
            import jax.numpy as jnp

            from ..ops.feasibility import bucket_type_cost_packed

            stats = jnp.asarray(np.ones((2, 8, 4), np.float32))
            caps = jnp.asarray(np.full((32, 4), 8.0, np.float32))
            prices = jnp.asarray(np.ones((32,), np.float32))
            allowed = jnp.asarray(np.ones((8, 32), bool))
            np.asarray(bucket_type_cost_packed(stats, caps, prices, allowed))

    try:
        dispatch()  # compile + cache warmup, excluded from the measurement
        round_trip = min(_timed(dispatch) for _ in range(max(1, trials)))
    except Exception as exc:  # noqa: BLE001 - measurement must never break startup
        log.warning("dense crossover measurement failed (%s); using default %d", exc, MIN_BATCH_DEFAULT)
        return MIN_BATCH_DEFAULT
    crossover = int(round_trip / host_seconds_per_pod)
    measured = max(floor, min(ceiling, crossover))
    log.info(
        "measured dense routing crossover: dispatch rt %.1f ms -> min_batch %d (default %d)",
        round_trip * 1000, measured, MIN_BATCH_DEFAULT,
    )
    return measured


def _timed(fn) -> float:
    start = time.perf_counter()
    fn()
    return time.perf_counter() - start


def _preview_type_cost(bucket_stats: np.ndarray, caps: np.ndarray, prices: np.ndarray, allowed: np.ndarray):
    """Host preview of ops/feasibility.py:bucket_type_cost — same formula,
    numpy float32 — used to speculate while the device round trip is in
    flight. Returns (tstar [B], feasible [B], key [B, T]): the key matrix
    lets the caller judge whether a device disagreement is material (a
    genuinely cheaper choice) or a sub-ulp argmin tie (TPU division rounds
    differently by 1 ulp, and price-proportional catalogs make frac*price
    near-constant across types, so ties are systematic, not rare)."""
    eps = np.float32(1e-9)
    sum_req, max_req = bucket_stats[0], bucket_stats[1]
    safe_caps = np.maximum(caps, eps)
    with np.errstate(divide="ignore", invalid="ignore"):
        ratio = sum_req[:, None, :] / safe_caps[None, :, :]
    impossible = (caps[None, :, :] <= eps) & (sum_req[:, None, :] > eps)
    frac = np.max(np.where(impossible, np.inf, ratio), axis=-1)
    bins = np.ceil(np.maximum(frac, eps))
    pod_fits = np.all(max_req[:, None, :] <= caps[None, :, :] + np.float32(1e-6), axis=-1)
    ok = allowed & pod_fits & np.isfinite(frac)
    key = frac * prices[None, :] + bins * np.float32(1e-4) + prices[None, :] * np.float32(1e-7)
    key = np.where(ok, key, np.inf)
    return np.argmin(key, axis=1).astype(np.int32), ok.any(axis=1), key


@dataclass
class DenseSolveStats:
    batches: int = 0
    pods_in: int = 0
    pods_committed: int = 0
    pods_on_existing: int = 0  # subset of pods_committed placed on existing nodes
    pods_to_host: int = 0
    nodes_created: int = 0
    sharded_batches: int = 0  # batches dispatched over a multi-device mesh
    encode_seconds: float = 0.0
    fill_seconds: float = 0.0  # existing-node fill (incl. its exact commits)
    device_seconds: float = 0.0
    commit_seconds: float = 0.0
    # warm-fill routing: vectorized (solver/warmfill.py) vs host-loop solves,
    # and the device share of fill_seconds (the [sizes x views] surface)
    fills_vectorized: int = 0
    fills_host: int = 0
    fill_device_seconds: float = 0.0
    # per-POD routing of the fill stream (PR-2 satellite: bench.py reports
    # how much of the fill is still host-routed): items offered to the
    # vectorized scan vs items a plan() fail-open sent through the host loop
    fill_pods_vectorized: int = 0
    fill_pods_host: int = 0
    # host-side assembly/audit/merge time hidden UNDER the device round trip
    # (subset of device_seconds): when the headline's device phase drifts,
    # this splits device-link time from host work — the attribution the r5
    # headline-drift bisect ask needed and the artifacts couldn't give
    assemble_seconds: float = 0.0
    # incremental engine (solver/incremental.py) assembly split: delta
    # passes rebase the resident encoding in O(changes) (delta_apply);
    # full passes rebuild it from scratch (full_encode — cold start,
    # catalog change, journal gap, fault invalidation, bulk churn).
    # encode_skipped_passes counts the delta passes: solves whose warm-view
    # encode never ran because the resident mirror stood in for it
    delta_apply_seconds: float = 0.0
    full_encode_seconds: float = 0.0
    encode_skipped_passes: int = 0
    # residency auditor (solver/audit.py): time spent re-encoding the seeded
    # row sample / full shadow and comparing it against the resident state —
    # the integrity tax on the incremental path, bounded by bench --smoke
    audit_seconds: float = 0.0
    # offering-availability mask application (subset of device_seconds): the
    # [T, Z, C] cube reduced over per-bucket zone/ct allowances as one
    # batched device matmul — quarantined pools are routed around here, and
    # this phase is where that cost lives (visible per-trace as the 'mask'
    # child span under 'device')
    mask_seconds: float = 0.0
    # (type, zone, ct) cells the cube masked out across solves: nonzero
    # means offering-health actually constrained selection
    masked_offerings: int = 0
    # node-count divergence guard (VERDICT r5 weak #3): new nodes the dense
    # commit opened, the algorithm-independent host floor it was held
    # against (capacity + dedicated lower bound), and how many solves failed
    # open to the host loop because dense would exceed NODE_GUARD_RATIO x
    # the floor
    nodes_opened_dense: int = 0
    nodes_opened_host_floor: int = 0
    node_guard_failopens: int = 0


@dataclass
class _Bucket:
    group_index: int
    zone: Optional[str] = None  # pinned zone
    capacity_type: Optional[str] = None  # pinned capacity type
    dedicated: bool = False
    single_bin: bool = False
    # zone/ct spread group whose water-fill is deferred until after the warm
    # fill (exact-fill scale only): pods first take warm capacity per-pod in
    # global FFD order under the host loop's transient-count skew rule, then
    # the remainder is water-filled over domains with accurate counts
    deferred_spread: bool = False
    pod_rows: List[int] = field(default_factory=list)  # rows into problem arrays
    # composite dedicated bucket (see _stack_dedicated_buckets): bins hold
    # one pod from each member anti/hostname-spread group, the node sharing
    # the host loop's FFD gets for free. members = [(group_index, rows)],
    # preset_pack the zipped (ids, nbins), compat_row the AND of member
    # compat rows (overrides problem.compat[group_index] wherever bins are
    # audited or priced).
    members: Optional[List[tuple]] = None
    preset_pack: Optional[tuple] = None
    compat_row: Optional[np.ndarray] = None


class DenseSolver:
    """Attachable TPU presolver for Scheduler (scheduler.py)."""

    # process-wide: whether the fused Pallas kernel works on this backend
    # (None = not probed yet; flips False permanently on any failure)
    _pallas_ok: Optional[bool] = None

    # Batches below min_batch route to the exact host loop. Measured on the
    # reference 400-type sweep workload (v5e-1, r3): the host loop is both
    # faster AND cheaper below ~350 pods (100 pods: host 26ms/$26.8 vs dense
    # 146ms/$32.1; 300: 73ms/$74.9 vs 148ms/$76.5), while dense wins on both
    # axes from ~400-500 up (2000: host 531ms/$589.5 vs dense 124ms/$539.2).
    # The fixed dense cost is device dispatch + encode, not compute, so the
    # crossover is stable across catalog sizes.
    def __init__(
        self,
        min_batch: int = MIN_BATCH_DEFAULT,
        num_slots: int = 8,
        mesh=None,
        peer_fabric=None,
        hbm_budget_bytes: int = 0,
        use_mesh: bool = True,
        incremental=None,
    ):
        self.min_batch = min_batch
        self.num_slots = num_slots
        self.stats = DenseSolveStats()
        # incremental solve engine (solver/incremental.py, --solver-
        # incremental): keeps the warm-view encoding + device headroom
        # surface resident across passes and applies the cluster journal's
        # delta instead of re-encoding; None = fresh-encode every pass.
        # Simulation re-solves (consolidation what-ifs run against
        # hypothetical state with no journal feed) always bypass it.
        self.incremental = incremental
        # solver fault domain (faults.py): pre-solve HBM pressure budget —
        # when the flight recorder's HBM-peak gauge exceeds this many bytes
        # the dispatch surface chunks pre-emptively (--solver-hbm-budget;
        # 0 = no budget). Per-solve fault/rung accounting feeds the flight
        # record and the degradation-ladder counters.
        self.hbm_budget_bytes = int(hbm_budget_bytes)
        self._solve_faults: Dict[str, int] = {}
        self._solve_rungs: List[str] = []
        # per-solve memos (reset at each presolve; see _accepting_view_free)
        self._view_free_memo: Dict[int, Optional[np.ndarray]] = {}
        self._view_accepts_memo: Dict[tuple, bool] = {}
        # multi-host SPMD: with a PeerFabric (parallel/peers.py) the sharded
        # dispatch broadcasts each solve so every process of the global mesh
        # enters the same jitted program; the fabric's mesh becomes the mesh
        self.peer_fabric = peer_fabric
        if peer_fabric is not None and mesh is None:
            mesh = peer_fabric.mesh
        # warm the native packing core at construction (solver construction
        # is bootstrap) so a lazy g++ build never lands inside a live solve;
        # process-wide cached, no-op after the first solver
        from .. import native

        native.load()
        # per-catalog device arrays (caps/prices), uploaded once and reused
        # across solves — host->device transfers over the tunnel are the
        # dominant per-dispatch cost, so only per-batch data moves per solve.
        # Keyed per path flavor ("plain" | "pallas" | "sharded"), a few
        # catalogs resident per flavor (multi-provisioner alternation), and
        # eviction is per-flavor so a path flip (pallas retirement, env
        # toggle) never evicts the other flavor of the same catalog.
        self._device_catalog: Dict[str, Dict[tuple, tuple]] = {}
        self._catalogs_per_flavor = 4
        # host-side catalog encodings (type matrices + compat rows), same
        # lifetime story: batch-independent, rebuilt only when the template
        # set / type universe / domain axes change (ir/encode.py
        # CatalogEncoding — holds refs to the keyed lists, so FIFO eviction
        # here also releases them)
        self._catalog_encodings: Dict[tuple, object] = {}
        # explicit mesh wins; otherwise auto-detect on first device solve.
        # use_mesh=False pins the plain single-device flavor (deterministic
        # dispatch sequences for the fault-injection chaos scenarios)
        self._mesh = mesh
        self._mesh_checked = mesh is not None or not use_mesh

    def _active_mesh(self):
        """The (pods x types) device mesh when >1 device is visible.

        Multi-chip is the production path on pods/slices: the bucket->type
        cost surface shards over (buckets, types) and XLA carries the argmin
        combines over ICI (parallel/sharded.py). KARPENTER_TPU_MESH=0
        disables; an integer value forces that device count (used by the
        virtual-device dryrun).
        """
        if self._mesh_checked:
            return self._mesh
        self._mesh_checked = True
        import os

        setting = os.environ.get("KARPENTER_TPU_MESH", "")
        if setting == "0":
            return None
        try:
            import jax

            from ..parallel.mesh import default_mesh
            from ..parallel.multihost import host_mesh_axes

            # ADDRESSABLE devices only: a jitted program over non-local
            # devices requires every process to enter it (SPMD) — the
            # cross-host execution loop is the solver service's future work,
            # and auto-detect must never build a mesh this process cannot
            # drive alone (jax.devices() spans other hosts once
            # jax.distributed is up). host_mesh_axes keeps the chatty types
            # axis small.
            if setting:
                # explicit count (the virtual-device dryrun): unclamped, and
                # devices unpinned so solver_mesh's CPU-backend fallback can
                # satisfy a forced host-device count
                n = int(setting)
                local = None
            else:
                local = jax.local_devices()
                n = len(local)
            if n > 1:
                _, types_parallel = host_mesh_axes(n, n)
                self._mesh = default_mesh(n, types_parallel=types_parallel, devices=local)
        except Exception as exc:  # mesh is an optimization; never break solving
            log.warning("solver mesh unavailable, staying single-device: %s", exc)
            self._mesh = None
        return self._mesh

    # -- Scheduler hook ------------------------------------------------------

    def presolve(self, scheduler, pods: Sequence[Pod]) -> List[Pod]:
        """Commit dense-expressible placements into `scheduler`; returns the
        pods that still need the exact host loop."""
        pods = list(pods)
        if len(pods) < self.min_batch:
            return pods
        # Inverse anti-affinity from *already-placed* cluster pods (non-zero
        # recorded domains) can block arbitrary dense placements -> host path.
        # Inverse groups from pods of this batch start with zero counts and
        # are handled by commit-order recording: dense pods commit first and
        # the host loop sees their domains when placing the anti pods.
        for inverse_group in scheduler.topology.inverse_topologies.values():
            if any(count > 0 for count in inverse_group.domains.values()):
                return pods
        if not scheduler.node_templates:
            return pods
        if not any(scheduler.instance_types.get(t.provisioner_name) for t in scheduler.node_templates):
            return pods
        # solver fault domain (faults.py): an OPEN breaker short-circuits the
        # whole device attempt — no encode, no dispatch, the exact host loop
        # owns the batch until a half-open probe re-admits the fast path.
        # Simulation re-solves share the state (they skip the device path
        # while it is open) but never become the probe.
        sim = bool(scheduler.opts.simulation_mode)
        FAULTS.set_simulation(sim)  # this thread's dispatch boundaries bypass injection for what-ifs
        if not BREAKER.admit(simulation=sim):
            if not sim:
                DEGRADED_SOLVES.inc(rung=RUNG_HOST)
                # an OPEN breaker voids the incremental resident state: the
                # device is suspect, passes are host-routed while it heals,
                # and the journal checkpoint goes stale meanwhile — the
                # first re-admitted pass must be a clean full re-encode
                # (satellite pin: tests/test_incremental_faults.py)
                if self.incremental is not None:
                    self.incremental.invalidate("fault-breaker")
                if JOURNAL.enabled:
                    JOURNAL.solver_event("dense", "degraded", rung=RUNG_HOST, reason="breaker-open")
            return pods
        self._solve_faults = {}
        self._solve_rungs = []
        self.stats.batches += 1
        self.stats.pods_in += len(pods)
        # reset the per-solve memos over (group, existing-view) queries:
        # bucket construction (warm tie-break + affinity bootstrap) and the
        # fill probe ask acceptance/freeness for the same pairs
        self._view_free_memo.clear()
        self._view_accepts_memo.clear()
        # flight recorder (flight.py): open the compile-attribution window
        # and snapshot cumulative stats so the record carries THIS solve's
        # deltas. Both are gated — disabled telemetry allocates nothing.
        flight_token = FLIGHT.begin_solve()
        if flight_token is not None:
            from dataclasses import replace as _stats_copy

            stats_before = _stats_copy(self.stats)
            self._flight_dispatch = None

        assemble_before = self.stats.assemble_seconds  # delta -> this solve's assemble child span
        mask_before = self.stats.mask_seconds  # delta -> this solve's mask child span
        delta_before = self.stats.delta_apply_seconds  # incremental split of the assemble story
        full_before = self.stats.full_encode_seconds
        audit_before = self.stats.audit_seconds  # residency auditor's share of the fill phase
        t0 = time.perf_counter()
        zones = scheduler.topology.domains.get(lbl.LABEL_TOPOLOGY_ZONE, ())
        capacity_types = scheduler.topology.domains.get(lbl.LABEL_CAPACITY_TYPE, ())
        ckey = catalog_key(scheduler.node_templates, scheduler.instance_types, zones, capacity_types)
        # the incremental engine keys resident-state validity on this same
        # catalog key (_fill_existing): a catalog/provisioner bump is a
        # legitimate full-re-encode trigger, attributed 'catalog'
        self._solve_ckey = ckey
        entry = self._catalog_encodings.get(ckey)
        if entry is None:
            catalog = encode_catalog(scheduler.node_templates, scheduler.instance_types, zones, capacity_types)
            while len(self._catalog_encodings) >= self._catalogs_per_flavor:
                self._catalog_encodings.pop(next(iter(self._catalog_encodings)))  # FIFO
            # the pin keeps the keyed instance-type objects alive so their
            # ids can't be recycled onto a different catalog
            self._catalog_encodings[ckey] = (catalog, catalog_pin(scheduler.node_templates, scheduler.instance_types))
        else:
            catalog = entry[0]
        problem = encode_problem(
            pods,
            scheduler.node_templates,
            scheduler.instance_types,
            daemon_overhead=scheduler.daemon_overhead,
            zones=zones,
            capacity_types=capacity_types,
            catalog=catalog,
            catalog_key_hint=ckey,
            cohort_label_keys=self._cohort_label_keys(scheduler, pods),
        )
        leftover = list(problem.host_pods)
        if problem.P == 0:
            self.stats.pods_to_host += len(leftover)
            return leftover

        defer_spread = bool(scheduler.existing_nodes)
        buckets = self._build_buckets(problem, scheduler.topology, scheduler, defer_spread=defer_spread)
        t_encoded = time.perf_counter()
        existing_committed = 0
        taken = None
        if scheduler.existing_nodes:
            existing_committed, taken, placed_extras = self._fill_existing(
                scheduler, problem, buckets, extra_pods=leftover
            )
            if placed_extras:
                leftover = [p for p in leftover if id(p) not in placed_extras]
            buckets = [b for b in buckets if b.pod_rows]
        if any(b.deferred_spread for b in buckets):
            # the warm fill consumed what it could; assign domains to the
            # remainder with counts that now include every warm placement.
            # The freeness memo predates the fill's commits — drop it so the
            # domain scoring sees post-fill capacity.
            self._view_free_memo.clear()
            expanded: List[_Bucket] = []
            for b in buckets:
                if not b.deferred_spread:
                    expanded.append(b)
                    continue
                group = problem.groups[b.group_index]
                if group.kind == GroupKind.AFFINITY:
                    # colocation: warm placements (if any) bootstrapped the
                    # domain, so the pick now collapses to that zone
                    zone = self._pick_affinity_zone(problem, scheduler.topology, group, b.pod_rows, scheduler)
                    expanded.append(
                        _Bucket(group_index=b.group_index, pod_rows=b.pod_rows, zone=zone if zone is not None else "__infeasible__")
                    )
                elif group.topology_key == lbl.LABEL_TOPOLOGY_ZONE:
                    expanded.extend(
                        self._water_fill(
                            problem, scheduler.topology, group, b.pod_rows, problem.zones, problem.group_zone_allowed[b.group_index], "zone", scheduler
                        )
                    )
                else:
                    expanded.extend(
                        self._water_fill(
                            problem, scheduler.topology, group, b.pod_rows, problem.capacity_types, problem.group_ct_allowed[b.group_index], "ct", scheduler
                        )
                    )
            buckets = [b for b in expanded if b.pod_rows]
        t1 = time.perf_counter()
        if buckets:
            try:
                prep = self._device_solve(scheduler, problem, buckets, taken)
            except SolverFault as fault:
                # classified device fault the ladder could not absorb (or
                # that was fatal by kind): the final rung — the exact host
                # loop takes every un-taken pod. Counted at the dispatch
                # site for ladder-internal faults; faults raised straight
                # from the seam (injected typed, or classified here) are
                # counted by _note_fault's per-solve dedupe-free tally.
                self._note_fault(fault.kind, "device")
                self._note_rung(RUNG_HOST, kind=fault.kind)
                BREAKER.record_fault(fault.kind, simulation=sim)
                # exc_info: classification is textual — if a software bug was
                # misclassified as a device fault, the traceback is the only
                # way to notice
                log.warning("device solve hit a %s fault; host loop takes the batch: %s", fault.kind, fault, exc_info=True)
                prep = None
            except Exception as exc:  # noqa: BLE001 - classify, then re-raise the truly unknown
                fault = classify(exc)
                if fault is None:
                    raise  # unclassified: the scheduler boundary counts + logs it at ERROR
                self._note_fault(fault.kind, "device")
                self._note_rung(RUNG_HOST, kind=fault.kind)
                BREAKER.record_fault(fault.kind, simulation=sim)
                log.warning("device solve hit a %s fault; host loop takes the batch: %s", fault.kind, exc, exc_info=True)
                prep = None
            t2 = time.perf_counter()
            if prep is not None:
                # the device attempt succeeded (any rung that still reached
                # the device); the node guard below is a packing-quality
                # fail-open, not a device fault — it must not trip the breaker
                BREAKER.record_success(simulation=sim)
            if prep is None or self._node_guard_tripped(problem, buckets, prep, taken):
                # fault fallback, or dense would open pathologically many
                # nodes vs the algorithm-independent floor: fail open, the
                # exact host loop repacks every un-taken pod (warm commits
                # stand — they went through the exact protocol)
                unassigned = np.arange(problem.P) if taken is None else np.nonzero(~taken)[0]
                committed, fallback_rows = 0, [int(r) for r in unassigned]
            else:
                committed, fallback_rows = self._apply_commit(scheduler, prep)
        else:
            t2 = time.perf_counter()
            unassigned = np.arange(problem.P) if taken is None else np.nonzero(~taken)[0]
            committed, fallback_rows = 0, [int(r) for r in unassigned]
        committed += existing_committed
        self.stats.pods_on_existing += existing_committed
        t3 = time.perf_counter()

        self.stats.encode_seconds += t_encoded - t0
        self.stats.fill_seconds += t1 - t_encoded
        self.stats.device_seconds += t2 - t1
        self.stats.commit_seconds += t3 - t2
        leftover.extend(problem.pods[row] for row in fallback_rows)
        self.stats.pods_committed += committed
        self.stats.pods_to_host += len(leftover)
        flight_record = None
        if flight_token is not None and FLIGHT.enabled:
            dispatch = getattr(self, "_flight_dispatch", None) or {}
            signature = {
                **problem.shape_signature(),
                "buckets": dispatch.get("buckets", len(buckets)),
                "buckets_padded": dispatch.get("buckets_padded", len(buckets)),
                "types_padded": dispatch.get("types_padded", problem.T),
            }
            stats = self.stats
            flight_record = FLIGHT.complete_solve(
                token=flight_token,
                signature=signature,
                dispatch=dispatch,
                phases={
                    "encode": stats.encode_seconds - stats_before.encode_seconds,
                    "fill": stats.fill_seconds - stats_before.fill_seconds,
                    "device": stats.device_seconds - stats_before.device_seconds,
                    "mask": stats.mask_seconds - mask_before,
                    "assemble": stats.assemble_seconds - assemble_before,
                    "commit": stats.commit_seconds - stats_before.commit_seconds,
                    "fill_device": stats.fill_device_seconds - stats_before.fill_device_seconds,
                    "delta_apply": stats.delta_apply_seconds - delta_before,
                    "full_encode": stats.full_encode_seconds - full_before,
                    "audit_seconds": stats.audit_seconds - audit_before,
                },
                fill_routing={
                    "fills_vectorized": stats.fills_vectorized - stats_before.fills_vectorized,
                    "fills_host": stats.fills_host - stats_before.fills_host,
                    "fill_pods_vectorized": stats.fill_pods_vectorized - stats_before.fill_pods_vectorized,
                    "fill_pods_host": stats.fill_pods_host - stats_before.fill_pods_host,
                },
                pods_committed=committed,
                pods_to_host=len(leftover),
                duration=t3 - t0,
                faults=dict(self._solve_faults),
                rungs=list(self._solve_rungs),
                breaker=BREAKER.state,
            )
        if TRACER.enabled:
            # the measured phase boundaries as completed child spans under the
            # ambient solve span (tracing.py record_span): the per-solve half
            # of the DenseSolveStats story, so device vs host time is visible
            # per trace, not just aggregated per bench run
            TRACER.record_span("encode", t0, t_encoded - t0, {"pods": problem.P, "groups": len(problem.groups)})
            TRACER.record_span("fill", t_encoded, t1 - t_encoded, {"on_existing": existing_committed})
            device_attrs = {"buckets": len(buckets)}
            if flight_record is not None:
                # compile/memory attribution on the span the drift hunts
                # start from (the flight recorder's per-solve record carries
                # the full detail keyed by the same solve)
                device_attrs.update(
                    recompiles=sum(flight_record.compiled_fns.values()),
                    compile_seconds=round(flight_record.compile_seconds, 6),
                    hbm_peak_bytes=flight_record.hbm_peak_bytes,
                    flight_record=flight_record.id,
                )
            device_ctx = TRACER.record_span("device", t1, t2 - t1, device_attrs)
            mask = self.stats.mask_seconds - mask_before
            if mask > 0 and device_ctx is not None:
                # offering-availability cube reduction (a device matmul at
                # the head of the device phase): quarantined pools are
                # routed around HERE, visible per trace
                TRACER.record_span(
                    "mask", t1, mask, {"masked_offerings": problem.masked_offerings}, parent=device_ctx
                )
            assemble = self.stats.assemble_seconds - assemble_before
            if assemble > 0 and device_ctx is not None:
                # host-side assembly hidden under the device round trip
                TRACER.record_span("assemble", max(t1, t2 - assemble), assemble, parent=device_ctx)
            TRACER.record_span("commit", t2, t3 - t2, {"committed": committed, "to_host": len(leftover)})
        return leftover

    @staticmethod
    def _cohort_label_keys(scheduler, pods: Sequence[Pod]) -> frozenset:
        """Label KEYS any selector in play could match: batch pods' spread /
        affinity / anti-affinity selectors (required and preferred) plus the
        scheduler topology's existing cohort selectors (owned and inverse).
        Labels outside this set cannot affect placement, so encode_problem
        drops them from the grouping key (see its docstring). Key-level
        granularity is a safe over-approximation of per-namespace selector
        matching."""
        keys: set = set()

        def add_selector(sel) -> None:
            if sel is None:
                return
            keys.update(sel.match_labels.keys())
            keys.update(e.key for e in sel.match_expressions)

        for pod in pods:
            spec = pod.spec
            for c in spec.topology_spread_constraints:
                add_selector(c.label_selector)
            a = spec.affinity
            if a is not None:
                if a.pod_affinity is not None:
                    for t in a.pod_affinity.required:
                        add_selector(t.label_selector)
                    for wt in a.pod_affinity.preferred:
                        add_selector(wt.pod_affinity_term.label_selector)
                if a.pod_anti_affinity is not None:
                    for t in a.pod_anti_affinity.required:
                        add_selector(t.label_selector)
                    for wt in a.pod_anti_affinity.preferred:
                        add_selector(wt.pod_affinity_term.label_selector)
        for group in scheduler.topology.topologies.values():
            add_selector(group.selector)
        for group in scheduler.topology.inverse_topologies.values():
            add_selector(group.selector)
        return frozenset(keys)

    # -- step 2: domain assignment / bucket construction ---------------------

    def _build_buckets(self, problem: DenseProblem, topology, scheduler=None, defer_spread: bool = False) -> List[_Bucket]:
        buckets: List[_Bucket] = []
        rows_by_group: Dict[int, List[int]] = {}
        for row, gid in enumerate(problem.group_ids):
            rows_by_group.setdefault(int(gid), []).append(row)

        self._demote_cross_selecting_groups(problem)
        for group in problem.groups:
            rows = rows_by_group.get(group.index, [])
            if not rows:
                continue
            g = group.index
            if group.kind == GroupKind.PLAIN:
                buckets.append(_Bucket(group_index=g, pod_rows=rows))
            elif group.kind == GroupKind.SPREAD:
                if group.topology_key == lbl.LABEL_HOSTNAME:
                    # every hostname is a fresh domain: one pod per node
                    buckets.append(_Bucket(group_index=g, dedicated=True, pod_rows=rows))
                elif defer_spread:
                    # warm clusters at exact-fill scale: water-fill AFTER the
                    # warm fill (see _Bucket.deferred_spread) — planning the
                    # per-domain split before knowing which pods land warm
                    # makes the fill's skew checks judge counts the host
                    # loop's transient order never sees
                    buckets.append(_Bucket(group_index=g, deferred_spread=True, pod_rows=rows))
                elif group.topology_key == lbl.LABEL_TOPOLOGY_ZONE:
                    buckets.extend(
                        self._water_fill(problem, topology, group, rows, problem.zones, problem.group_zone_allowed[g], "zone", scheduler)
                    )
                else:  # capacity type
                    buckets.extend(
                        self._water_fill(problem, topology, group, rows, problem.capacity_types, problem.group_ct_allowed[g], "ct", scheduler)
                    )
            elif group.kind == GroupKind.AFFINITY:
                if group.topology_key == lbl.LABEL_HOSTNAME:
                    # Required self-affinity pins the component to an
                    # *already-populated* domain when one exists
                    # (topologygroup.py _next_domain_affinity): a fresh-host
                    # bin would violate it, so populated groups take the
                    # exact host loop. Zero-count groups bootstrap: the
                    # whole component shares one (possibly fresh) node.
                    populated = any(
                        count > 0
                        for tg in topology.topologies.values()
                        if tg.key == lbl.LABEL_HOSTNAME and tg.is_owned_by(group.pods[0].uid)
                        for count in tg.domains.values()
                    )
                    if populated:
                        buckets.append(_Bucket(group_index=g, pod_rows=rows, zone="__infeasible__"))
                    else:
                        buckets.append(_Bucket(group_index=g, single_bin=True, pod_rows=rows))
                elif defer_spread:
                    # zonal self-affinity at exact-fill scale: the host loop
                    # bootstraps the cohort's zone from the first pod's first
                    # accepting view — pre-pinning from an estimate diverges
                    # from that choice and cascades. Defer: warm fill per-pod
                    # (the exact add enforces bootstrap-then-colocate), pin
                    # the remainder afterwards.
                    buckets.append(_Bucket(group_index=g, deferred_spread=True, pod_rows=rows))
                else:
                    zone = self._pick_affinity_zone(problem, topology, group, rows, scheduler)
                    if zone is None:
                        # no viable zone: host loop will produce the error
                        buckets.append(_Bucket(group_index=g, pod_rows=rows, zone="__infeasible__"))
                    else:
                        buckets.append(_Bucket(group_index=g, zone=zone, pod_rows=rows))
            elif group.kind == GroupKind.ANTI_HOST:
                buckets.append(_Bucket(group_index=g, dedicated=True, pod_rows=rows))
            elif group.kind == GroupKind.HOST:
                # demoted after encode (cross-selection): route to host loop
                buckets.append(_Bucket(group_index=g, pod_rows=rows, zone="__infeasible__"))
        return buckets

    @staticmethod
    def _dedicated_selector(group) -> Optional[object]:
        """The anti-affinity / hostname-spread selector a dedicated group
        enforces per host (both shapes are self-selecting by classify)."""
        spec = group.pods[0].spec
        if group.kind == GroupKind.ANTI_HOST:
            return spec.affinity.pod_anti_affinity.required[0].label_selector
        if group.kind == GroupKind.SPREAD and spec.topology_spread_constraints:
            return spec.topology_spread_constraints[0].label_selector
        return None

    def _stack_dedicated_buckets(self, problem: DenseProblem, buckets: List[_Bucket]) -> List[_Bucket]:
        """Stack dedicated (one-pod-per-host) buckets from DIFFERENT groups
        onto shared bins: one pod from each member group per bin, which is
        exactly the node sharing the host loop's FFD produces for
        anti-affinity cohorts (a node takes one pod of each label). Without
        this the per-bucket pack opens one near-empty node per dedicated pod
        and the dense path diverges up to 9x from the host's node count
        (VERDICT r5 weak #3: 482 vs 51 nodes on the 2000-pod sweep).

        Correct-by-construction gates (all-or-nothing per cluster):
          - groups share a template, carry no node requirements, no zone/ct
            pins, and their selectors do not cross-match another member's
            pods (a cross-matching selector would make co-location violate
            the OTHER group's per-host zero-count rule);
          - the sum of every member's LARGEST pod fits one commonly
            compatible type (so every zipped bin audits feasible, no
            per-bin fallback path needed).

        Bins are the zip of member streams (each sorted largest-first):
        bin i holds the i-th pod of every member — bin count collapses from
        sum(group sizes) to max(group size). Composite buckets carry the
        AND-compat row and per-member rows for topology recording."""
        dedicated = [
            b
            for b in buckets
            if b.dedicated
            and not b.single_bin
            and b.zone is None
            and b.capacity_type is None
            and b.members is None
            and len(b.pod_rows) > 0
        ]
        if len(dedicated) < 2:
            return buckets
        cap_tol = problem.caps + res.tolerance(problem.caps) - problem.daemon_overhead  # [T, R]
        # cluster by template; gate on empty group requirements
        by_template: Dict[int, List[_Bucket]] = {}
        for b in dedicated:
            group = problem.groups[b.group_index]
            if group.requirements is not None and list(group.requirements.values()):
                continue
            by_template.setdefault(group.template_index, []).append(b)
        ded_ids = {id(b) for b in dedicated}
        out = [b for b in buckets if id(b) not in ded_ids]
        stacked: set = set()
        for members in by_template.values():
            if len(members) < 2:
                continue
            # pairwise selector cross-match gate
            reps = [problem.groups[b.group_index].pods[0] for b in members]
            sels = [self._dedicated_selector(problem.groups[b.group_index]) for b in members]
            ok = True
            for i in range(len(members)):
                for j in range(len(members)):
                    if i == j or sels[i] is None:
                        continue
                    if reps[i].namespace == reps[j].namespace and sels[i].matches(reps[j].metadata.labels):
                        ok = False
                        break
                if not ok:
                    break
            if not ok:
                continue
            compat_row = np.ones((problem.T,), dtype=bool)
            for b in members:
                compat_row &= problem.compat[b.group_index]
            if not compat_row.any():
                continue
            # conservative capacity gate: the sum of per-group max pods fits
            # at least one commonly-compatible type -> every zipped bin fits
            worst = np.zeros((problem.requests.shape[1],), np.float64)
            for b in members:
                worst += problem.requests[b.pod_rows].max(axis=0)
            if not np.any(compat_row & np.all(worst[None, :] <= cap_tol + 1e-9, axis=1)):
                continue
            # zip: largest group drives bin count; rows largest-first
            members = sorted(members, key=lambda b: -len(b.pod_rows))
            rows_all: List[int] = []
            ids_all: List[int] = []
            member_info: List[tuple] = []
            for b in members:
                rows = list(b.pod_rows)
                order = np.lexsort(tuple(-problem.requests[rows][:, c] for c in (1, 0)))
                rows = [rows[k] for k in order]
                rows_all.extend(rows)
                ids_all.extend(range(len(rows)))
                member_info.append((b.group_index, rows))
                stacked.add(id(b))
            nbins = max(len(b.pod_rows) for b in members)
            composite = _Bucket(
                group_index=members[0].group_index,
                dedicated=True,
                pod_rows=rows_all,
                members=member_info,
                preset_pack=(np.asarray(ids_all, dtype=np.int64), nbins),
                compat_row=compat_row,
            )
            out.append(composite)
        # keep any dedicated bucket that did not stack
        out.extend(b for b in dedicated if id(b) not in stacked)
        return out

    def _demote_cross_selecting_groups(self, problem: DenseProblem) -> None:
        """A zone/capacity-type spread group whose selector also matches pods
        in a *different* group that pins the same key cannot be water-filled
        independently — the other group's pinned placements change its domain
        counts mid-solve. Those groups take the exact host loop.

        This mirrors the reference's Record rule (topology.go:126-135): only
        placements whose requirement collapses to a single domain are counted,
        so unpinned (plain) groups never interfere; hostname-keyed dense
        shapes are dedicated/single-bin and therefore safe by construction;
        zone-pinned affinity components stay valid because their own pods
        populate the chosen domain.
        """
        pinned_by_key: Dict[str, List] = {}
        for g in problem.groups:
            if g.kind == GroupKind.SPREAD and g.topology_key in (lbl.LABEL_TOPOLOGY_ZONE, lbl.LABEL_CAPACITY_TYPE):
                pinned_by_key.setdefault(g.topology_key, []).append(g)
            elif g.kind == GroupKind.AFFINITY and g.topology_key == lbl.LABEL_TOPOLOGY_ZONE:
                pinned_by_key.setdefault(lbl.LABEL_TOPOLOGY_ZONE, []).append(g)

        for group in problem.groups:
            if group.kind != GroupKind.SPREAD or group.topology_key not in (lbl.LABEL_TOPOLOGY_ZONE, lbl.LABEL_CAPACITY_TYPE):
                continue
            selector = group.pods[0].spec.topology_spread_constraints[0].label_selector
            me = group.pods[0]
            for other in pinned_by_key.get(group.topology_key, []):
                if other.index == group.index:
                    continue
                rep = other.pods[0]
                if rep.namespace == me.namespace and selector.matches(rep.metadata.labels):
                    group.kind = GroupKind.HOST
                    break

    def _existing_counts(self, topology, group, key: str, domains: Sequence[str]) -> np.ndarray:
        """Current per-domain counts from any matching topology group."""
        counts = np.zeros((len(domains),), dtype=np.int64)
        pod = group.pods[0]
        for tg in topology.topologies.values():
            if tg.key == key and tg.is_owned_by(pod.uid):
                for i, domain in enumerate(domains):
                    counts[i] += tg.domains.get(domain, 0)
        return counts

    def _accepting_view_free(self, group, view) -> Optional[np.ndarray]:
        """Free-capacity vector of an existing-node view IF this group's
        constraint shape can land there (the shared warm-capacity model of
        _pick_affinity_zone and _warm_absorbable). The freeness half is
        group-independent and memoized per solve — valid ONLY before
        _fill_existing starts committing (view.add rebinds view.requests);
        the fill invalidates the memo on entry."""
        if not self._view_accepts(group, view):
            return None
        if id(view) in self._view_free_memo:
            return self._view_free_memo[id(view)]
        avail = resource_vector(view.available)
        used = resource_vector(view.requests)
        free = None if avail is None or used is None else np.maximum(avail - used, 0.0)
        self._view_free_memo[id(view)] = free
        return free

    def _warm_absorbable(self, scheduler, problem, group, rows: List[int], domains: List[str]) -> np.ndarray:
        """Per-domain estimate of how many of this cohort's pods the ACCEPTING
        existing-node views there could absorb. Zeroes when there is no warm
        capacity."""
        scores = np.zeros(len(domains), dtype=np.float64)
        if scheduler is None or not scheduler.existing_nodes or not rows:
            return scores
        typical = problem.requests[rows].mean(axis=0)
        positive = typical > 1e-12
        if not positive.any():
            return scores
        index = {d: i for i, d in enumerate(domains)}
        for view in scheduler.existing_nodes:
            pos = index.get(view.node.metadata.labels.get(group.topology_key))
            if pos is None:
                continue
            free = self._accepting_view_free(group, view)
            if free is None:
                continue
            scores[pos] += float(np.floor((free[positive] / typical[positive]).min()))
        return scores

    def _choose_spread_targets(
        self, c: np.ndarray, warm: np.ndarray, n: int, s: int, frozen_levels: np.ndarray
    ) -> Optional[np.ndarray]:
        """Per-domain adds for a spread cohort that maximize
        (pods placed, warm absorption, evenness) over every final-skew-
        feasible assignment.

        Feasibility is the kube invariant on FINAL counts: with M the final
        global minimum over pod-eligible domains, every final level — fillable
        c[i]+a[i] and frozen (eligible but unreachable, fixed) — must sit in
        [M, M+s]. Any such assignment is reachable by the reference's per-pod
        min-count order (topologygroup.go:157-184): always placing into the
        currently-lowest fillable domain below target keeps transient skew
        within s. The search walks candidate M values (the loop is bounded by
        mandatory-fill exceeding n, ~n/D + s steps); per M the allocation is
        mandatory lifts to M, then warm-capacity preference, then an even
        water-fill of the remainder under the M+s cap. Returns adds aligned
        with `c`'s order, or None when no band is feasible (frozen levels
        more than s apart — the even path's cap semantics handle that)."""
        D = len(c)
        have_frozen = frozen_levels.size > 0
        lo = int(c.max()) - s
        if have_frozen:
            lo = max(lo, int(frozen_levels.max()) - s)
        # M below every current level only shrinks the band's ceiling with the
        # same lower bounds — dominated by M = floor, so start there
        floor = min(int(c.min()), int(frozen_levels.min())) if have_frozen else int(c.min())
        lo = max(lo, floor)
        hi = int(frozen_levels.min()) if have_frozen else int(c.min()) + n
        if lo > hi:
            return None
        best_score = None
        best_adds = None
        for M in range(lo, hi + 1):
            lower = np.maximum(c, M)
            mandatory = int((lower - c).sum())
            if mandatory > n:
                break  # monotone in M
            upper = M + s
            max_total = int((upper - c).sum())
            placed = min(n, max_total)
            a = (lower - c).astype(np.int64)
            budget = placed - mandatory
            # warm preference: absorb into domains with remaining warm
            # capacity, lowest current count first (deterministic)
            if budget > 0:
                for i in np.lexsort((np.arange(D), c)):
                    if budget <= 0:
                        break
                    t = min(max(int(warm[i]) - int(a[i]), 0), upper - int(c[i]) - int(a[i]), budget)
                    if t > 0:
                        a[i] += t
                        budget -= t
            # even water-fill of the remainder under the band cap
            while budget > 0:
                levels = c + a
                open_i = np.flatnonzero(levels < upper)
                if open_i.size == 0:
                    break
                lvl_sorted = open_i[np.argsort(levels[open_i], kind="stable")]
                # raise the lowest tier as one block
                lowest = levels[lvl_sorted[0]]
                tier = [int(i) for i in lvl_sorted if levels[i] == lowest]
                next_stop = min(
                    int(levels[lvl_sorted[len(tier)]]) if len(tier) < lvl_sorted.size else upper, upper
                )
                gap = (next_stop - lowest) * len(tier)
                take = min(budget, gap)
                per = take // len(tier)
                extra = take - per * len(tier)
                for k, i in enumerate(tier):
                    a[i] += per + (1 if k < extra else 0)
                budget -= take
            absorption = int(np.minimum(a, warm).sum())
            score = (placed, absorption, M)
            if best_score is None or score > best_score:
                best_score = score
                best_adds = a.copy()
        return best_adds

    def _water_fill(
        self, problem, topology, group, rows: List[int], domains: List[str], allowed: np.ndarray, pin_kind: str, scheduler=None
    ) -> List[_Bucket]:
        """Distribute the group's pods across allowed domains, lowest current
        count first (water filling) — the closed-form of the reference's
        per-pod min-count domain choice (topologygroup.go:157-184)."""
        allowed_idx = [i for i in range(len(domains)) if allowed[i]]
        if not allowed_idx:
            return [_Bucket(group_index=group.index, pod_rows=rows, zone="__infeasible__")]
        counts_all = self._existing_counts(topology, group, group.topology_key, domains).astype(np.float64)
        counts = counts_all[allowed_idx]
        n = len(rows)
        # kube skew cap (topologygroup.go:157-169): no domain may exceed the
        # global minimum over the POD-eligible universe by more than maxSkew.
        # `allowed` can be narrower than eligibility (provisioner/type
        # availability), so an untouched-but-eligible domain outside it still
        # pins the minimum — without this cap the fill would happily stack a
        # provisioner-pinned zone past the skew the host loop enforces.
        cap = np.inf
        if group.max_skew:  # only SPREAD groups reach _water_fill's zone/ct pins
            pod_req = None
            if group.requirements is not None and group.requirements.has(group.topology_key):
                pod_req = group.requirements.get(group.topology_key)
            # domains the POD could count toward but placement cannot reach
            # (provisioner/offering narrowing): their counts are FROZEN, so
            # they pin the global minimum no matter how the fill proceeds.
            # When every eligible domain is fillable the water level IS the
            # rising minimum and needs no cap.
            frozen = [i for i, d in enumerate(domains) if not allowed[i] and (pod_req is None or pod_req.has(d))]
            if frozen:
                cap = counts_all[frozen].min() + group.max_skew
        # capacity-aware assignment (scheduler.go:191-195 existing-first, in
        # closed form): among all final-skew-feasible per-domain targets,
        # maximize warm absorption — a pod assigned to a domain whose warm
        # nodes can take it never opens a fresh bin, which is how the host
        # loop's per-pod existing-nodes-first order spends warm capacity.
        # Evenness is only the tie-break, not the objective.
        warm = self._warm_absorbable(scheduler, problem, group, rows, [domains[i] for i in allowed_idx])
        frozen_levels = counts_all[frozen] if (group.max_skew and frozen) else np.empty(0)
        adds = None
        order = np.argsort(counts, kind="stable")
        if group.max_skew and warm.any():
            chosen = self._choose_spread_targets(
                counts.astype(np.int64), warm.astype(np.int64), n, int(group.max_skew), frozen_levels.astype(np.int64)
            )
            if chosen is not None:
                adds = chosen  # aligned with allowed_idx order
                order = np.arange(len(allowed_idx))
        if adds is None:
            # even water-fill (no warm capacity / no skew bound / no feasible
            # band): lowest-count domains first, frozen-domain cap applied
            counts_sorted = counts[order]
            targets = counts_sorted.copy()
            remaining = n
            # raise the water level step by step (vectorized over ~few domains)
            for level_idx in range(1, len(targets) + 1):
                if remaining <= 0:
                    break
                if level_idx < len(targets):
                    gap = (counts_sorted[level_idx] - targets[:level_idx]).sum()
                    take = min(remaining, gap)
                else:
                    take = remaining
                if take > 0:
                    per = int(take // level_idx)
                    extra = int(take - per * level_idx)
                    targets[:level_idx] += per
                    targets[:extra] += 1
                    remaining -= take
            if np.isfinite(cap):
                targets = np.minimum(targets, np.maximum(counts_sorted, cap))
            adds = (targets - counts_sorted).astype(np.int64)
        buckets = []
        cursor = 0
        for pos, count in zip(order, adds):
            if count <= 0:
                continue
            chunk = rows[cursor : cursor + int(count)]
            cursor += int(count)
            domain = domains[allowed_idx[pos]]
            if pin_kind == "zone":
                buckets.append(_Bucket(group_index=group.index, zone=domain, pod_rows=chunk))
            else:
                buckets.append(_Bucket(group_index=group.index, capacity_type=domain, pod_rows=chunk))
        if cursor < len(rows):
            # skew-capped leftovers: the host loop owns them and will fail
            # them one by one exactly as the reference does
            buckets.append(_Bucket(group_index=group.index, pod_rows=rows[cursor:], zone="__infeasible__"))
        return buckets

    def _pick_affinity_zone(self, problem, topology, group, rows, scheduler=None) -> Optional[str]:
        g = group.index
        allowed = [z for i, z in enumerate(problem.zones) if problem.group_zone_allowed[g][i]]
        if not allowed:
            return None
        counts = self._existing_counts(topology, group, lbl.LABEL_TOPOLOGY_ZONE, allowed)
        populated = [z for z, c in zip(allowed, counts) if c > 0]
        if populated:
            return populated[0]
        # bootstrap choice: prefer the allowed zone holding the most free
        # warm capacity, so the cohort fills existing nodes instead of
        # opening fresh bins in an arbitrarily-pinned empty zone (the host
        # loop gets this for free by trying existing nodes first)
        if scheduler is not None and scheduler.existing_nodes:
            # score zones by how much of the cohort's OWN request mix the
            # accepting views there could absorb — cpu-only ranking would
            # pin accelerator cohorts to zones with no usable accelerator
            total = problem.requests[rows].sum(axis=0) if rows else None
            score_by_zone: Dict[str, float] = {}
            for view in scheduler.existing_nodes:
                zone = view.node.metadata.labels.get(lbl.LABEL_TOPOLOGY_ZONE)
                if zone not in allowed or total is None:
                    continue
                free = self._accepting_view_free(group, view)
                if free is None:
                    continue
                positive = total > 1e-12
                if not positive.any():
                    continue
                frac = float(np.minimum(free[positive] / total[positive], 1.0).min())
                score_by_zone[zone] = score_by_zone.get(zone, 0.0) + frac
            if score_by_zone:
                best = max(score_by_zone.items(), key=lambda kv: kv[1])
                if best[1] > 0:
                    return best[0]
        return allowed[0]

    # -- step 2.5: fill existing/in-flight node capacity ----------------------

    def _view_accepts(self, group, view) -> bool:
        """Exact host-algebra gate: can this group's constraint shape land on
        this existing node at all (taints + requirement compatibility)?
        Resource fit and topology tightening are re-checked per pod at commit
        time by ExistingNodeView.add, so this gate only prunes. Memoized per
        solve: bucket construction and the fill probe ask the same pairs."""
        key = (id(group), id(view))
        cached = self._view_accepts_memo.get(key)
        if cached is None:
            cached = self._view_accepts_memo[key] = self._view_accepts_uncached(group, view)
        return cached

    def _view_accepts_uncached(self, group, view) -> bool:
        pod = group.pods[0]
        if view.taints.tolerates(pod) is not None:
            return False
        if group.requirements is None:
            return True
        # hostname-keyed pod requirements (IN a host, but also DoesNotExist /
        # Gt / Lt, which compatible() can't veto against a real hostname) are
        # host-loop territory — same rule as bucket_proto for new bins
        if group.requirements.has(lbl.LABEL_HOSTNAME):
            return False
        return view.requirements.compatible(group.requirements) is None

    def _fill_existing(self, scheduler, problem: DenseProblem, buckets: List[_Bucket], extra_pods: Sequence[Pod] = ()):
        """Fill existing-node capacity before opening new bins.

        Mirrors the host loop's existing-nodes-first rule
        (scheduler.go:191-195, existingnode.go:97): ONE pass in the host
        queue's FFD order over every pod kind — plain/pinned buckets,
        domain-deferred spread and affinity cohorts, dedicated (per-host
        zero-count) pods, single-bin components, host-routed rows, and the
        IR-inexpressible extras — each placement through the exact
        ExistingNodeView protocol. Consecutive same-bucket same-size items
        batch into add_cohort runs whose per-pod residue is integer/capacity
        arithmetic (existingnode.py), which keeps the exact pass flat at
        10k+ pods with no scale switch.

        `extra_pods` are the IR-inexpressible pods (problem.host_pods) bound
        for the exact host loop. They join this fill at their global FFD
        position, attempted against each view through the same exact
        view.add the host loop's existing-first pass would run with the
        pod's full unrelaxed constraint set — so their claim on warm
        capacity is decided by the one global FFD order, not by which phase
        processes them. Without this, every dense commit lands before ANY
        host-routed pod, and a warm slot the host loop's interleaved order
        would have given to a host pod goes to a dense pod instead — the
        host pod then opens a fresh (often upgraded) node the host oracle
        never pays for (campaign seed 12 is the canonical shape). A veto
        leaves the pod for the host loop, which still owns relaxation.

        Every placement commits through ExistingNodeView.add, so capacity
        modeling here only *proposes*; a rejected add leaves the pod in its
        bucket for the new-bin solve. Returns (count committed, taken [P],
        ids of extra_pods placed).

        Routing: the certified common case — every fill item a plain /
        dedicated / deferred-spread / deferred-affinity cohort whose
        BucketCert reduces the add() verdict to taints + capacity + integer
        domain lookups — runs the vectorized fill (solver/warmfill.py:
        encode → device admission surface → exact scan → bulk commit)
        instead of this per-item loop; byte-identical placements, pinned by
        tests/test_warm_fill_vectorized.py. Anything outside that case
        (IR-inexpressible extras, host-routed buckets, single-bin
        components, requirement-carrying cohorts) fails open to the loop
        below, wholesale, so one algorithm owns the global FFD order.
        """
        from . import warmfill

        fill_items = sum(len(b.pod_rows) for b in buckets) + len(extra_pods)
        enc = None
        if self.incremental is not None and not scheduler.opts.simulation_mode:
            # incremental engine (solver/incremental.py): advance the
            # resident warm-view state by the cluster journal's delta — a
            # delta pass hands back a byte-equal encoding with the O(cluster)
            # encode skipped and the device headroom surface already
            # resident; a full pass rebuilds it (attributed by reason).
            # Simulation re-solves bypass: hypothetical views have no
            # journal feed and must not clobber the real resident state.
            from .audit import AUDITOR
            from .incremental import PASS_DELTA, PASS_FULL

            adv = self.incremental.advance(scheduler.existing_nodes, getattr(self, "_solve_ckey", ()))
            healed = None
            if AUDITOR.enabled:
                # residency auditor (solver/audit.py): this is the one point
                # where the resident state, the views snapshot, and the
                # journal checkpoint all describe the same instant — audit
                # BEFORE the pass's encoding shapes any placement
                ta = time.perf_counter()
                cached_cube = getattr(self, "_avail_cube_dev", None)
                healed = AUDITOR.maybe_audit(
                    self.incremental,
                    scheduler.existing_nodes,
                    cube_host=cached_cube[0] if cached_cube is not None else None,
                    cube_dev=cached_cube[1] if cached_cube is not None else None,
                )
                self.stats.audit_seconds += time.perf_counter() - ta
            if healed is not None:
                # divergence found and healed (residency already invalidated
                # with reason 'audit'): the audited pass's encoding is
                # suspect — discard it so warmfill takes the fresh path, and
                # drop the cached availability cube when it was the stale
                # artifact
                if healed.get("cube_stale"):
                    self._avail_cube_dev = None
            elif adv.kind == PASS_DELTA:
                self.stats.delta_apply_seconds += adv.seconds
                self.stats.encode_skipped_passes += 1
                enc = adv.enc
            elif adv.kind == PASS_FULL:
                self.stats.full_encode_seconds += adv.seconds
                enc = adv.enc
        fill_plan = warmfill.plan(scheduler, problem, buckets, extra_pods=extra_pods, enc=enc)
        if fill_plan is not None:
            # commits rebind view.requests: the pre-fill freeness memo is
            # invalid from here on (same contract as the host loop)
            self._view_free_memo.clear()
            committed, taken = warmfill.execute(scheduler, problem, buckets, fill_plan, solver=self)
            self.stats.fills_vectorized += 1
            self.stats.fill_pods_vectorized += fill_items
            return committed, taken, set()
        self.stats.fills_host += 1
        self.stats.fill_pods_host += fill_items

        from ..scheduler.errors import IncompatibleError
        from ..scheduler.existingnode import ExistingNodeView
        from ..scheduler.queue import ffd_sort_key

        views = scheduler.existing_nodes
        zone_index = {z: i for i, z in enumerate(problem.zones)}
        ct_index = {c: i for i, c in enumerate(problem.capacity_types)}
        taken = np.zeros((problem.P,), dtype=bool)
        zone_of: List[Optional[str]] = []
        ct_of: List[Optional[str]] = []
        # headroom matrix [V, R] (free + fits() tolerance), maintained by the
        # commit helpers — the single authoritative capacity model for this
        # fill; every screen below is one vector compare against a row or
        # slice of it instead of per-view Python arithmetic
        Rdim = problem.requests.shape[1]
        head = np.full((len(views), Rdim), -1.0)
        usable = np.zeros((len(views),), dtype=bool)
        for vi, view in enumerate(views):
            avail = resource_vector(view.available)
            used = resource_vector(view.requests)
            if avail is not None and used is not None:
                head[vi] = np.maximum(avail - used, 0.0) + res.tolerance(avail)
                usable[vi] = True
            zone_of.append(view.node.metadata.labels.get(lbl.LABEL_TOPOLOGY_ZONE))
            ct_of.append(view.node.metadata.labels.get(lbl.LABEL_CAPACITY_TYPE))

        # commits below rebind view.requests: the pre-fill freeness memo is
        # invalid from here on (the acceptance memo stays — view.add re-checks
        # exactly, so stale-True only costs a probe)
        self._view_free_memo.clear()
        committed = 0
        # group-membership scans are cohort-constant: one context per solver
        # group, one inverse-owner index per fill (topology.cohort_context)
        shared_inverse = scheduler.topology.inverse_owner_index()
        ctx_cache: Dict[int, object] = {}

        def ctx_of(group_index: int):
            c = ctx_cache.get(group_index)
            if c is None:
                rep = problem.groups[group_index].pods[0]
                c = scheduler.topology.cohort_context(rep, inverse_index=shared_inverse)
                ctx_cache[group_index] = c
            return c

        def view_ok(bucket: _Bucket, group, vi: int) -> bool:
            if not usable[vi]:
                return False
            if bucket.zone is not None and zone_of[vi] != bucket.zone:
                return False
            if bucket.capacity_type is not None and ct_of[vi] != bucket.capacity_type:
                return False
            return self._view_accepts(group, views[vi])  # per-solve memoized

        def commit(vi: int, row: int, ctx=None) -> bool:
            nonlocal committed
            try:
                views[vi].add(problem.pods[row], ctx=ctx)
            except IncompatibleError:
                return False
            taken[row] = True
            committed += 1
            head[vi] -= problem.requests[row]
            return True

        # Two certificate tiers amortize the full add() protocol:
        # - per-BUCKET (certify_bucket): cohorts with no node requirements —
        #   the common shape — get exact verdicts on ANY view from set/
        #   integer lookups, so they never pay a full add (except an
        #   affinity bootstrap round, which the full protocol must own);
        # - per-(bucket, view) (certify): cohorts WITH requirements pay one
        #   full add per pair, then the per-pod residue, guarded by the
        #   view's requirement-content epoch.
        bucket_certs: Dict[int, object] = {}
        cert_cache: Dict[tuple, object] = {}
        _UNSET = object()

        def bucket_cert_of(bucket: _Bucket, rep_row: int, ctx):
            gid = id(bucket)
            cert = bucket_certs.get(gid, _UNSET)
            if cert is _UNSET:
                cert = ExistingNodeView.certify_bucket(problem.pods[rep_row], ctx)
                bucket_certs[gid] = cert
            if cert is not None and cert.affinity_groups:
                # bootstrap round: no populated domain anywhere means the
                # full protocol must make (and record) the domain choice
                for g in cert.affinity_groups:
                    if not any(g.domains.values()):
                        return None
            return cert

        def commit_run(vi: int, rows: List[int], bucket: _Bucket, ctx=None) -> int:
            """Commit a same-bucket same-size run through the certified
            cohort fast paths; returns how many landed (a prefix of rows)."""
            nonlocal committed
            view = views[vi]
            bcert = bucket_cert_of(bucket, rows[0], ctx)
            if bcert is not None:
                n = view.add_certified_view_run([problem.pods[r] for r in rows], bcert)
            else:
                key = (id(bucket), vi)
                cert = cert_cache.get(key)
                if cert is not None and cert.epoch == view.req_epoch:
                    n = view.add_certified_run([problem.pods[r] for r in rows], cert)
                else:
                    n = view.add_cohort([problem.pods[r] for r in rows], ctx=ctx)
                    if n:
                        cert = view.certify(problem.pods[rows[0]], ctx)
                        if cert is not None:
                            cert_cache[key] = cert
                        else:
                            cert_cache.pop(key, None)
            for r in rows[:n]:
                taken[r] = True
            committed += n
            if n:
                head[vi] -= problem.requests[rows[:n]].sum(axis=0)
            return n

        placed_extras: set = set()

        def try_extra(pod: Pod) -> bool:
            """One host-routed pod's existing-first attempt at its FFD
            position: first view (in the host loop's order) the exact add
            protocol accepts, full unrelaxed constraints."""
            nonlocal committed
            vec = resource_vector(res.pod_requests(pod))
            if vec is None:
                return False  # resources outside the axis: host loop owns it
            fit_views = np.flatnonzero(usable & (vec <= head).all(axis=1))
            if fit_views.size == 0:
                return False
            ctx = scheduler.topology.cohort_context(pod, inverse_index=shared_inverse)
            for vi in fit_views:
                vi = int(vi)
                try:
                    views[vi].add(pod, ctx=ctx)
                except IncompatibleError:
                    continue
                committed += 1
                head[vi] -= vec
                placed_extras.add(id(pod))
                return True
            return False

        plain_buckets: List[_Bucket] = []
        special_buckets: List[_Bucket] = []  # dedicated / single_bin
        deferred_buckets: List[_Bucket] = []  # spread/affinity, domain deferred
        host_route_buckets: List[_Bucket] = []  # __infeasible__: host loop owns them
        for bucket in buckets:
            if not bucket.pod_rows:
                continue
            if bucket.zone == "__infeasible__":
                # these pods are bound for the exact host loop (inexpressible
                # domain shape), but the host loop runs AFTER every dense
                # commit — without a warm attempt at their global FFD
                # position they lose warm slots the host oracle gives them,
                # shifting the entire downstream packing. The exact add
                # re-checks everything, so per-pod attempts here are safe
                # for any constraint shape.
                host_route_buckets.append(bucket)
            elif bucket.dedicated or bucket.single_bin:
                special_buckets.append(bucket)
            elif bucket.deferred_spread:
                deferred_buckets.append(bucket)
            else:
                plain_buckets.append(bucket)

        # ONE unified pass in the host queue's FFD order over every pod kind
        # — bucketed (plain/pinned), domain-deferred spread and affinity,
        # dedicated, single-bin components, host-routed rows, and the
        # IR-inexpressible extras — so the claim on warm capacity is decided
        # by the one global FFD order the host loop uses, at any batch size.
        # Consecutive same-bucket same-size items batch into add_cohort runs
        # (existingnode.py): the first pod of a run pays the full protocol,
        # the rest pay only the genuinely per-pod checks, which is what
        # keeps this exact pass flat at 10k+ pods (the former
        # _FILL_EXACT_MAX_PODS switch to a class-vectorized approximation
        # is gone — one algorithm, one semantics, every scale).
        all_buckets = plain_buckets + special_buckets + deferred_buckets + host_route_buckets
        items: List[tuple] = [
            (problem.pods[r], r, bucket) for bucket in all_buckets for r in bucket.pod_rows
        ]
        items.extend((pod, None, None) for pod in extra_pods)
        items.sort(key=lambda t: ffd_sort_key(t[0]))

        singlebin_tried: set = set()
        N = len(items)
        i = 0
        while i < N:
            pod_obj, row, bucket = items[i]
            if bucket is None:  # host-routed extra at its FFD position
                try_extra(pod_obj)
                i += 1
                continue
            group = problem.groups[bucket.group_index]
            req = problem.requests[row]
            if bucket.zone == "__infeasible__":
                # host-routed rows: raw exact adds, view order — no
                # group-level prescreen (hostname-keyed requirements make
                # _view_accepts meaningless here; the add is authority)
                for vi in np.flatnonzero(usable & (req <= head).all(axis=1)):
                    if commit(int(vi), row, ctx_of(bucket.group_index)):
                        break
                i += 1
                continue
            if bucket.single_bin:
                # bootstrap hostname-affinity component: all-or-nothing
                # swallow at the component's first FFD position (greedy
                # per-pod adds cannot backtrack a half-placed component;
                # the whole-component contract schedules the cohort on a
                # fresh host where per-pod order would strand its tail)
                i += 1
                if id(bucket) in singlebin_tried:
                    continue
                singlebin_tried.add(id(bucket))
                rows_sb = bucket.pod_rows
                order_sb = np.lexsort(tuple(-problem.requests[rows_sb][:, c] for c in (1, 0)))
                queue_sb = [rows_sb[k] for k in order_sb]
                total_sb = problem.requests[rows_sb].sum(axis=0)
                ctx = ctx_of(bucket.group_index)
                for vi in np.flatnonzero(usable & (total_sb <= head).all(axis=1)):
                    vi = int(vi)
                    if not view_ok(bucket, group, vi):
                        continue
                    if commit(vi, queue_sb[0], ctx):
                        for r in queue_sb[1:]:
                            if not commit(vi, r, ctx):
                                # rare (ports/volume veto mid-component):
                                # the host loop owns the remainder — it
                                # sees the recorded affinity domain and
                                # applies the exact bootstrap rules
                                bucket.zone = "__infeasible__"
                                break
                        break  # component is bound to this host now
                continue
            if bucket.dedicated:
                # at most one pod per host: per-pod, first accepting view
                # (the zero-count rule is per-host, so a veto moves to the
                # next view, never ends the scan). Certified cohorts reduce
                # each attempt to set/integer lookups — without this, N
                # anti-affinity pods cost N full protocol runs each scanning
                # every registered hostname.
                ctx = ctx_of(bucket.group_index)
                dcert = bucket_cert_of(bucket, row, ctx)
                for vi in np.flatnonzero(usable & (req <= head).all(axis=1)):
                    vi = int(vi)
                    if not view_ok(bucket, group, vi):
                        continue
                    if dcert is not None:
                        if views[vi].add_certified_view(problem.pods[row], dcert):
                            taken[row] = True
                            committed += 1
                            head[vi] -= req
                            break
                    elif commit(vi, row, ctx):
                        break
                i += 1
                continue

            # plain / pinned / deferred: maximal same-bucket same-size run
            j = i + 1
            while j < N and items[j][2] is bucket and np.array_equal(problem.requests[items[j][1]], req):
                j += 1
            run = [items[k][1] for k in range(i, j)]
            i = j
            gi = bucket.group_index
            ctx = ctx_of(gi)
            if not bucket.deferred_spread:
                # rejections are persistent for identical pods on a plain
                # run (capacity and port state only shrink, acceptance memo
                # is static), so one forward scan over fit views is exact
                for vi in np.flatnonzero(usable & (req <= head).all(axis=1)):
                    vi = int(vi)
                    if not view_ok(bucket, group, vi):
                        continue
                    n = commit_run(vi, run, bucket, ctx)
                    if n:
                        run = run[n:]
                        if not run:
                            break
                continue
            # deferred spread/affinity: any group-allowed domain; the exact
            # add judges transient counts exactly as the host loop would at
            # this queue position. Skew admission is NOT monotone (another
            # domain's placements can raise the global min), so after each
            # placed sub-run the scan restarts from view 0 — the same views
            # the next pod would probe per-pod.
            zone_keyed = group.topology_key == lbl.LABEL_TOPOLOGY_ZONE
            while run:
                placed_any = False
                for vi in np.flatnonzero(usable & (req <= head).all(axis=1)):
                    vi = int(vi)
                    if zone_keyed:
                        dv = zone_index.get(zone_of[vi])
                        if dv is None or not problem.group_zone_allowed[gi][dv]:
                            continue
                    else:
                        dv = ct_index.get(ct_of[vi])
                        if dv is None or not problem.group_ct_allowed[gi][dv]:
                            continue
                    if not self._view_accepts(group, views[vi]):
                        continue
                    n = commit_run(vi, run, bucket, ctx)
                    if n:
                        run = run[n:]
                        placed_any = True
                        break
                if not placed_any:
                    break

        for bucket in all_buckets:
            bucket.pod_rows = [r for r in bucket.pod_rows if not taken[r]]
        return committed, taken, placed_extras


    def _pallas_enabled(self) -> bool:
        import os

        if os.environ.get("KARPENTER_TPU_NO_PALLAS"):
            return False
        cls = type(self)
        if cls._pallas_ok is None:
            import jax

            if jax.default_backend() != "tpu":
                # interpreter mode is for tests only; the jnp path IS the
                # production path off-TPU
                cls._pallas_ok = False
                return False
            # Probe limitation: this compiles only the smallest padded shape
            # class (Bp=8, Tp=128); a larger production shape class can still
            # fail Mosaic compilation later. That failure is handled at
            # dispatch time by _device_solve's retire-and-fallback, so the
            # probe only needs to catch "Pallas is wholly unavailable".
            try:
                from ..ops.pallas_kernels import bucket_type_cost_pallas

                stats = np.ones((2, 1, 2), np.float32)
                probe = np.asarray(
                    bucket_type_cost_pallas(stats, np.full((1, 2), 4, np.float32), np.ones((1,), np.float32), np.ones((1, 1), bool))
                )
                cls._pallas_ok = probe.shape == (3, 1) and bool(probe[2, 0])
            except Exception as exc:  # noqa: BLE001 - no Pallas is a supported mode
                log.debug("Pallas probe failed; kernels disabled for this process: %r", exc)
                cls._pallas_ok = False
        return cls._pallas_ok

    # -- solver fault domain (faults.py) ---------------------------------------

    def _note_fault(self, kind: str, entry: str) -> None:
        """Count one classified device fault: the taxonomy counter, this
        solve's flight-record tally, and a journal `solver` event."""
        SOLVER_FAULTS.inc(kind=kind)
        self._solve_faults[kind] = self._solve_faults.get(kind, 0) + 1
        if JOURNAL.enabled:
            JOURNAL.solver_event("dense", "fault", kind=kind, entry=entry)

    def _note_rung(self, rung: str, **attrs) -> None:
        """Count a degradation-ladder transition, once per rung per solve."""
        if rung in self._solve_rungs:
            return
        self._solve_rungs.append(rung)
        DEGRADED_SOLVES.inc(rung=rung)
        # fault-domain interaction with the incremental engine: ANY rung
        # taken mid-solve means a device dispatch already faulted under this
        # pass — buffers may be stale, half-donated, or pinned to a retired
        # path, and the chunked path's split-dispatch lifetimes are outside
        # the residency contract too — so every rung voids the resident
        # state and the NEXT pass is a clean full re-encode (attributed
        # fault-flavor / fault-chunked / fault-host; pinned by
        # tests/test_incremental_faults.py).
        if self.incremental is not None:
            self.incremental.invalidate(f"fault-{rung}")
        if rung == RUNG_HOST and CAPSULE.enabled:
            # the ladder hit the floor: freeze the evidence rings (the
            # capsule engine captures on its next poll)
            CAPSULE.trigger(TRIGGER_HOST_RUNG, rung=rung)
        if JOURNAL.enabled:
            JOURNAL.solver_event("dense", "degraded", rung=rung, **attrs)

    def _ladder_action(self, exc: Exception, flavor: str) -> str:
        """Classify a device-dispatch failure and pick the next rung.

        Returns 'chunk' (HBM pressure: split the surface and re-dispatch)
        or 'retire' (pallas/mesh flavor retirement to plain jnp). Faults the
        plain flavor cannot absorb raise TYPED so presolve's handler runs
        the final rung (host fill + breaker); truly unclassifiable plain
        failures re-raise raw so the scheduler boundary counts them as
        `kind="unclassified"` at ERROR — never silently."""
        fault = classify(exc)
        if fault is None:
            if flavor in ("pallas", "sharded"):
                # preserve the pre-taxonomy resilience: an unknown pallas/
                # mesh failure retires the flavor rather than losing the
                # whole device path — but it is still counted distinctly
                self._note_fault(KIND_UNCLASSIFIED, flavor)
                return "retire"
            raise exc
        if fault.kind == KIND_HBM:
            self._note_fault(fault.kind, flavor)
            return "chunk"
        if flavor in ("pallas", "sharded"):
            self._note_fault(fault.kind, flavor)
            return "retire"
        raise fault from (exc if exc is not fault else None)

    def _hbm_over_budget(self) -> bool:
        """Pre-solve HBM-pressure check: the flight recorder's HBM-peak
        gauge against --solver-hbm-budget (0 / telemetry off = no budget)."""
        if self.hbm_budget_bytes <= 0 or not FLIGHT.enabled:
            return False
        return HBM_PEAK.value() > self.hbm_budget_bytes

    _CHUNK_SPLIT = 2

    def _chunked_dispatch(self, bucket_stats: np.ndarray, allowed: np.ndarray, catalog: tuple) -> np.ndarray:
        """The HBM-pressure rung: split the bucket axis and dispatch the
        plain path per chunk, shrinking the live [B, T] device surface.
        Synchronous by design (degraded mode trades the speculation overlap
        for memory headroom). Returns packed [3, B]; a chunk failure
        propagates for presolve's final-rung handler to classify."""
        import jax.numpy as jnp

        from ..ops.feasibility import bucket_type_cost_packed

        caps_dev, prices_dev = catalog
        B = bucket_stats.shape[1]
        step = max(1, -(-B // self._CHUNK_SPLIT))
        parts: List[np.ndarray] = []
        for lo in range(0, B, step):
            hi = min(B, lo + step)
            FAULTS.check("chunk")
            part = bucket_type_cost_packed(
                jnp.asarray(bucket_stats[:, lo:hi]), caps_dev, prices_dev, jnp.asarray(allowed[lo:hi])
            )
            parts.append(np.asarray(part))
        return np.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]

    # -- step 3: device solve -------------------------------------------------

    def _availability_mask(self, avail: np.ndarray, zmask: np.ndarray, cmask: np.ndarray) -> np.ndarray:
        """bucket_extra[b, t] = any (z, c) with avail[t, z, c] and the
        bucket allowing zone z and capacity-type c — the offering-health
        mask applied as ONE batched device matmul over the flattened (z, c)
        axis, not a per-bucket host loop: [B, Z*C] @ [Z*C, T] counts the
        available cells each (bucket, type) pair shares; > 0 is the mask.

        Quarantined pools (unavailable-offerings cache) are zeros in the
        cube, so they are unselectable by construction — for the device
        argmin, the host preview, and the commit-time audit alike, which
        all consume this one array."""
        B = zmask.shape[0]
        T, Z, C = avail.shape
        if B == 0 or T == 0:
            return np.zeros((B, T), dtype=bool)
        pair = (zmask[:, :, None] & cmask[:, None, :]).reshape(B, Z * C).astype(np.float32)
        cube = avail.reshape(T, Z * C).astype(np.float32)
        if self.incremental is not None:
            # incremental residency for the availability cube: it is a pure
            # function of the catalog, so under the engine it rides device-
            # resident — only the [B, Z*C] pair matrix moves host->device
            # per solve. Keyed by the IDENTITY of the catalog's avail array
            # (held strongly here, so the id can never be recycled — the
            # same id-reuse discipline as catalog_pin); a catalog change
            # swaps the array object and naturally misses. Values are
            # identical: the same f32 array, uploaded once per catalog.
            cached = getattr(self, "_avail_cube_dev", None)
            if cached is not None and cached[0] is avail:
                cube = cached[1]
            else:
                try:
                    import jax.numpy as jnp

                    cube = jnp.asarray(cube)
                    self._avail_cube_dev = (avail, cube)
                except Exception as exc:  # noqa: BLE001 - residency is an optimization
                    log.warning("availability-cube device upload failed; per-solve host cube: %r", exc)
                    self._avail_cube_dev = None
        try:
            # one fused jitted program (registered flight/contract entry)
            # instead of the former eager asarray/matmul/compare chain; the
            # cube rides as an argument — see availability_counts' docstring
            # for why closing over it would violate the program-constant
            # contract
            from ..ops.feasibility import availability_counts

            return np.asarray(availability_counts(pair, cube))
        except Exception as exc:  # noqa: BLE001 - the mask must never fail a solve
            log.warning("availability-mask device dispatch failed; numpy fallback: %r", exc)
            return (pair @ np.asarray(cube).T) > 0.5

    def _device_solve(self, scheduler, problem: DenseProblem, buckets: List[_Bucket], taken: Optional[np.ndarray] = None):
        """Bucket→type choice on device; packing via counts (see
        pack_counts.py for why the per-pod scan is the wrong shape for TPU).

        The device dispatch is asynchronous and its round trip over the TPU
        tunnel is pure latency (~70 ms), so the host *speculates*: it previews
        the same argmin formula in numpy float32 and packs every bucket while
        the device result is in flight. When the result lands it is
        authoritative — any bucket where the device *materially* disagrees
        with the preview (feasibility flip, or a strictly cheaper choice
        beyond f32 tie noise) is repacked against the device's choice. On
        directly-attached TPU (us-scale dispatch) the speculation is simply
        always-confirmed work that overlapped nothing.

        Returns the prepared-commit dict from _prepare_commit (records,
        fallback_rows, remaining, committed) for _apply_commit to make real.
        """
        import jax.numpy as jnp

        from ..ops.feasibility import bucket_type_cost_packed

        buckets = self._stack_dedicated_buckets(problem, buckets)
        B = len(buckets)
        mesh = self._active_mesh()
        use_pallas = mesh is None and self._pallas_enabled()
        if FLIGHT.enabled:
            # flight recorder: actual vs padded dispatch surface. The plain
            # path pads nothing; the pallas/sharded paths overwrite the
            # padded dims (and flavor, on mid-solve retirement) below.
            self._flight_dispatch = {
                "flavor": "sharded" if mesh is not None else ("pallas" if use_pallas else "plain"),
                "buckets": B,
                "types": problem.T,
                "buckets_padded": B,
                "types_padded": problem.T,
            }
        zone_index = {z: i for i, z in enumerate(problem.zones)}
        ct_index = {c: i for i, c in enumerate(problem.capacity_types)}

        # bucket aggregates (numpy, bucket-scale); bucket_extra is the
        # offering-AVAILABILITY mask — the [T, Z, C] cube reduced over each
        # bucket's allowed zones/capacity-types on DEVICE (one batched
        # matmul, see _availability_mask) — shared by the device's `allowed`
        # input and the commit-time audit (one definition, can't diverge).
        # A pool the unavailable-offerings cache quarantined is a zero in
        # the cube, so a masked offering can never be selected anywhere.
        sum_req = np.zeros((B, problem.requests.shape[1]), np.float64)
        max_req = np.zeros_like(sum_req)
        Z, C = len(problem.zones), len(problem.capacity_types)
        zmask = np.zeros((B, Z), dtype=bool)
        cmask = np.zeros((B, C), dtype=bool)
        for b, bucket in enumerate(buckets):
            rows = bucket.pod_rows
            sum_req[b] = problem.requests[rows].sum(axis=0)
            max_req[b] = problem.requests[rows].max(axis=0)
            if bucket.zone == "__infeasible__":
                continue  # all-zero masks: the bucket stays infeasible
            if bucket.zone is not None:
                zmask[b, zone_index[bucket.zone]] = True
            elif bucket.members is not None:
                # composite bucket: the shared node must satisfy EVERY member
                zm = np.ones((Z,), dtype=bool)
                for g, _rows in bucket.members:
                    zm &= problem.group_zone_allowed[g]
                zmask[b] = zm
            else:
                zmask[b] = problem.group_zone_allowed[bucket.group_index]
            if bucket.capacity_type is not None:
                cmask[b, ct_index[bucket.capacity_type]] = True
            elif bucket.members is not None:
                cm = np.ones((C,), dtype=bool)
                for g, _rows in bucket.members:
                    cm &= problem.group_ct_allowed[g]
                cmask[b] = cm
            else:
                cmask[b] = problem.group_ct_allowed[bucket.group_index]
        t_mask = time.perf_counter()
        bucket_extra = self._availability_mask(problem.avail, zmask, cmask)
        self.stats.mask_seconds += time.perf_counter() - t_mask
        self.stats.masked_offerings += problem.masked_offerings
        allowed = np.zeros((B, problem.T), dtype=bool)
        for b, bucket in enumerate(buckets):
            if bucket.zone != "__infeasible__":
                compat_row = bucket.compat_row if bucket.compat_row is not None else problem.compat[bucket.group_index]
                allowed[b] = compat_row & bucket_extra[b]

        # host math stays float64 (exact vs resources.fits); the device sees
        # f32 — its choice is advisory, commit-time checks are authoritative.
        # daemon_overhead is [T, R]: each column carries its own template's
        # daemonset overhead (multi-template concatenated axis)
        caps_eff = np.maximum(problem.caps - problem.daemon_overhead, 0.0)

        bucket_stats = np.stack([sum_req, max_req]).astype(np.float32)  # [2, B, R]

        # per-catalog device arrays are uploaded once and cached (a few per
        # flavor; eviction is per-flavor — see __init__)
        def _catalog(flavor: str):
            key = (caps_eff.tobytes(), problem.prices.tobytes())
            flavor_cache = self._device_catalog.setdefault(flavor, {})
            cached = flavor_cache.get(key)
            if cached is not None:
                return cached
            if flavor == "pallas":
                from ..ops.pallas_kernels import pad_catalog

                caps_t, prices_p = pad_catalog(caps_eff.astype(np.float32), problem.prices.astype(np.float32))
                catalog = (jnp.asarray(caps_t), jnp.asarray(prices_p))
            elif flavor == "sharded":
                from jax.sharding import PartitionSpec as P

                from ..parallel.sharded import place

                types_dim = mesh.shape["types"]
                Tp = -(-problem.T // types_dim) * types_dim
                caps_p = np.zeros((Tp, caps_eff.shape[1]), np.float32)
                caps_p[: problem.T] = caps_eff
                prices_p = np.zeros((Tp,), np.float32)
                prices_p[: problem.T] = problem.prices
                if self.peer_fabric is not None and self.peer_fabric.multiprocess:
                    # multi-process mesh: the fabric broadcasts the catalog
                    # with each solve and places shards per process — a local
                    # device_put cannot address the peer devices
                    catalog = (caps_p, prices_p)
                else:
                    catalog = (place(mesh, caps_p, P("types", None)), place(mesh, prices_p, P("types")))
            else:
                catalog = (jnp.asarray(caps_eff, dtype=jnp.float32), jnp.asarray(problem.prices, dtype=jnp.float32))
            while len(flavor_cache) >= self._catalogs_per_flavor:
                flavor_cache.pop(next(iter(flavor_cache)))  # FIFO within flavor
            flavor_cache[key] = catalog
            return catalog

        def _plain_dispatch():
            FAULTS.check("plain")
            caps_dev, prices_dev = _catalog("plain")
            return bucket_type_cost_packed(jnp.asarray(bucket_stats), caps_dev, prices_dev, jnp.asarray(allowed))

        def _jnp_dispatch():
            if mesh is not None:
                return self._sharded_dispatch(mesh, _catalog("sharded"), bucket_stats, allowed)
            return _plain_dispatch()

        def _flight_plain():
            if getattr(self, "_flight_dispatch", None) is not None:
                self._flight_dispatch.update(flavor="plain", buckets_padded=B, types_padded=problem.T)

        def _chunk(reason: str):
            self._note_rung(RUNG_CHUNKED, reason=reason)
            _flight_plain()
            return self._chunked_dispatch(bucket_stats, allowed, _catalog("plain"))

        packed_fut = None
        packed_np: Optional[np.ndarray] = None  # set when a degraded rung already materialized the result
        if self._hbm_over_budget():
            # pre-solve HBM pressure over --solver-hbm-budget: don't build
            # the full dispatch surface at all — straight to the chunked rung
            use_pallas = False
            mesh = None
            packed_np = _chunk("hbm-budget")
        elif use_pallas:
            try:
                from ..ops.pallas_kernels import bucket_type_cost_padded, pad_batch

                caps_dev, prices_dev = _catalog("pallas")
                sum_p, max_p, allowed_p = pad_batch(bucket_stats, allowed)
                if getattr(self, "_flight_dispatch", None) is not None:
                    self._flight_dispatch.update(
                        buckets_padded=int(allowed_p.shape[0]), types_padded=int(allowed_p.shape[1])
                    )
                packed_fut = bucket_type_cost_padded(
                    jnp.asarray(sum_p), jnp.asarray(max_p), caps_dev, prices_dev, jnp.asarray(allowed_p)
                )
            except Exception as exc:  # unexpected shape class the kernel can't compile
                use_pallas = False
                if self._ladder_action(exc, "pallas") == "chunk":
                    packed_np = _chunk("hbm-fault")
                else:
                    type(self)._pallas_ok = False
                    self._note_rung(RUNG_FLAVOR, retired="pallas")
                    log.warning("retiring Pallas kernel (compile/dispatch failure), falling back to jnp path: %r", exc)
                    _flight_plain()
                    packed_fut = _jnp_dispatch()
        else:
            try:
                packed_fut = _jnp_dispatch()
            except Exception as exc:
                if mesh is None:
                    # plain flavor: _ladder_action raises for everything the
                    # chunked rung cannot absorb (typed for classified, raw
                    # for unclassified — the scheduler boundary counts those)
                    self._ladder_action(exc, "plain")
                    packed_np = _chunk("hbm-fault")
                elif self._ladder_action(exc, "sharded") == "chunk":
                    mesh = None
                    packed_np = _chunk("hbm-fault")
                else:
                    # mesh is an optimization, never a failure mode: retire it
                    # for this solver (chip dropout, placement failure) and
                    # continue single-device
                    self._mesh = None
                    mesh = None
                    self._note_rung(RUNG_FLAVOR, retired="sharded")
                    log.warning("retiring solver mesh (dispatch failure), falling back to single device: %r", exc)
                    _flight_plain()
                    packed_fut = _plain_dispatch()
        if mesh is not None and packed_fut is not None:
            self.stats.sharded_batches += 1
        # start the device->host copy as soon as the result is ready, so the
        # fetch overlaps the speculation below instead of starting at the
        # blocking asarray. Errors stay deferred to the guarded blocking
        # np.asarray below — a runtime failure surfacing here must not bypass
        # the pallas/mesh retirement fallbacks.
        try:
            copy_async = getattr(packed_fut, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        except Exception:
            pass  # the blocking fetch below re-raises under its handlers

        # speculate under the in-flight round trip
        prev_tstar, prev_feasible, prev_key = _preview_type_cost(bucket_stats, caps_eff.astype(np.float32), problem.prices.astype(np.float32), allowed)
        # small batches refine the per-bucket pack over several candidate
        # types (_best_pack) — the one-type-per-bucket argmin wastes the
        # last bin on mixed-size streams where the host loop's FFD ladder
        # downsizes adaptively; at scale the last-bin effect vanishes and
        # the single argmin pack keeps wall-clock flat
        refine = problem.P <= self._PACK_REFINE_MAX_PODS
        local: List[tuple] = []
        for b, bucket in enumerate(buckets):
            rows = np.asarray(bucket.pod_rows, dtype=np.int64)
            reqs = problem.requests[rows]
            if not prev_feasible[b]:
                pack = None
            elif bucket.preset_pack is not None:
                pack = bucket.preset_pack
            elif refine and not bucket.dedicated:
                # dedicated packs are type-invariant (one pod per bin for
                # every candidate) and each bin is priced at its cheapest
                # audited type at commit — refinement would re-pack and
                # re-price N identical bins per candidate for nothing (the
                # r5 mid-size sweep collapse, BENCH_r04->r05 2000 pods
                # 116->332 ms, was exactly this loop)
                pack = self._best_pack(problem, bucket, reqs, caps_eff, int(prev_tstar[b]))
            else:
                pack = self._pack_bucket(bucket, reqs, caps_eff[prev_tstar[b]])
            local.append((rows, reqs, pack))

        # speculative assembly + audit + full commit preparation (node
        # construction), still under the in-flight round trip
        reroute = bool(scheduler.existing_nodes)
        t_asm = time.perf_counter()
        sol = self._assemble(problem, buckets, local, bucket_extra, caps_eff, reroute_fragments=reroute)
        prep = self._prepare_commit(scheduler, problem, buckets, sol, taken)
        self.stats.assemble_seconds += time.perf_counter() - t_asm

        if packed_np is not None:
            packed = packed_np[:, :B]  # a degraded rung already fetched it
        else:
            try:
                packed = np.asarray(packed_fut)[:, :B]  # blocks until the device result lands
            except Exception as exc:
                if use_pallas:
                    if self._ladder_action(exc, "pallas") == "chunk":
                        packed = _chunk("hbm-fault")
                    else:
                        type(self)._pallas_ok = False  # runtime failure: retire the kernel
                        self._note_rung(RUNG_FLAVOR, retired="pallas")
                        log.warning("retiring Pallas kernel (runtime failure), falling back to jnp path: %r", exc)
                        _flight_plain()
                        packed = np.asarray(_jnp_dispatch())[:, :B]
                elif mesh is not None:
                    self.stats.sharded_batches -= 1
                    if self._ladder_action(exc, "sharded") == "chunk":
                        mesh = None
                        packed = _chunk("hbm-fault")
                    else:
                        self._mesh = None
                        mesh = None
                        self._note_rung(RUNG_FLAVOR, retired="sharded")
                        log.warning("retiring solver mesh (runtime failure), falling back to single device: %r", exc)
                        _flight_plain()
                        packed = np.asarray(_plain_dispatch())[:, :B]
                else:
                    # plain flavor: chunk absorbs HBM pressure; everything
                    # else raises through _ladder_action for the host rung
                    self._ladder_action(exc, "plain")
                    packed = _chunk("hbm-fault")
        tstar, feasible = packed[0], packed[2].astype(bool)
        changed = False
        for b, bucket in enumerate(buckets):
            if bool(feasible[b]) != bool(prev_feasible[b]):
                rows, reqs, _ = local[b]
                if not feasible[b]:
                    pack = None
                elif bucket.preset_pack is not None:
                    pack = bucket.preset_pack
                elif refine and not bucket.dedicated:
                    pack = self._best_pack(problem, bucket, reqs, caps_eff, int(tstar[b]))
                else:
                    pack = self._pack_bucket(bucket, reqs, caps_eff[tstar[b]])
                local[b] = (rows, reqs, pack)
                changed = True
            elif refine and not bucket.dedicated:
                # the refined pack already optimized over the type axis; a
                # device argmin tie carries no new information for it.
                # Dedicated buckets did NOT refine (excluded above), so they
                # fall through to the adopt-device-tstar correction below
                continue
            elif feasible[b] and tstar[b] != prev_tstar[b]:
                # TPU f32 division rounds differently by ~1 ulp, and
                # price-proportional catalogs make the cost key near-constant
                # across types — so index disagreements are usually sub-ulp
                # argmin ties, not information (prev_tstar is the argmin of
                # prev_key, so any type the host also scored can only be >=
                # its choice). The one case where the device's answer carries
                # new information: the host preview scored the device's type
                # INFEASIBLE (a boundary f32 fit the TPU rounded the other
                # way). Adopt it when it is genuinely cheaper; the exact
                # f64 audit in _assemble remains the authority either way.
                if np.isfinite(prev_key[b, tstar[b]]):
                    continue  # host scored it: no better than its own argmin
                if problem.prices[tstar[b]] >= problem.prices[prev_tstar[b]]:
                    continue  # not cheaper; keep the speculative pack
                if bucket.preset_pack is not None:
                    continue  # composite zip is type-invariant: nothing to adopt
                rows, reqs, _ = local[b]
                pack = self._pack_bucket(bucket, reqs, caps_eff[tstar[b]])
                local[b] = (rows, reqs, pack)
                changed = True
        if changed:  # genuine disagreement: re-run assembly + preparation
            t_asm = time.perf_counter()
            sol = self._assemble(problem, buckets, local, bucket_extra, caps_eff, reroute_fragments=reroute)
            prep = self._prepare_commit(scheduler, problem, buckets, sol, taken)
            self.stats.assemble_seconds += time.perf_counter() - t_asm
        return prep

    def _sharded_dispatch(self, mesh, catalog, bucket_stats: np.ndarray, allowed: np.ndarray):
        """Dispatch the bucket->type choice over the multi-device mesh.

        Pads the bucket axis to the mesh's pods dimension and the type axis
        to the catalog's padded width, places inputs with the mesh's own
        shardings (parallel/sharded.py:place — never default-device), and
        runs the sharded jit. Result is packed [3, Bp]; the caller trims."""
        FAULTS.check("sharded")
        from jax.sharding import PartitionSpec as P

        from ..parallel.sharded import make_sharded_bucket_cost, place

        caps_dev, prices_dev = catalog
        Tp = caps_dev.shape[0]
        pods_dim = mesh.shape["pods"]
        B = bucket_stats.shape[1]
        Bp = max(-(-B // pods_dim) * pods_dim, pods_dim)
        if getattr(self, "_flight_dispatch", None) is not None:
            self._flight_dispatch.update(flavor="sharded", buckets_padded=int(Bp), types_padded=int(Tp))
        stats_p = np.zeros((2, Bp, bucket_stats.shape[2]), np.float32)
        stats_p[:, :B] = bucket_stats
        allowed_p = np.zeros((Bp, Tp), dtype=bool)
        allowed_p[:B, : allowed.shape[1]] = allowed
        if self.peer_fabric is not None and self.peer_fabric.multiprocess:
            # SPMD broadcast: peers mirror this exact call over the global
            # mesh (parallel/peers.py); result is already replicated numpy
            return self.peer_fabric.dispatch(stats_p, np.asarray(caps_dev), np.asarray(prices_dev), allowed_p)
        fn = make_sharded_bucket_cost(mesh)
        if FLIGHT.enabled:
            # per-mesh wrappers share one {fn} label; registration dedupes
            FLIGHT.register_jit_entry("sharded_bucket_cost", fn)
        return fn(
            place(mesh, stats_p, P(None, "pods", None)),
            caps_dev,
            prices_dev,
            place(mesh, allowed_p, P("pods", "types")),
        )

    _FRAGMENT_MAX_PODS = 3
    # batches up to this many pods refine the per-bucket pack over several
    # candidate types (_best_pack) — a cost polish whose last-bin effect
    # vanishes at scale while its K-packs-per-bucket cost would not
    _PACK_REFINE_MAX_PODS = 2048

    def _assemble(self, problem: DenseProblem, buckets: List[_Bucket], local: List[tuple], bucket_extra: np.ndarray, caps_eff: np.ndarray, reroute_fragments: bool = False) -> dict:
        """Pure assembly + audit of the per-bucket packings: global bin ids,
        per-bin usage/rows, and surviving instance-type masks (same tolerance
        rule as resources.fits so audits can't disagree). Touches no scheduler
        state, so it runs speculatively under the device round trip and is
        recomputed wholesale on (rare) reconciliation.

        reroute_fragments (warm clusters only): a MICRO-COHORT whose whole
        pack is one bin of <=3 pods is handed to the exact host loop instead
        of opening a near-empty fresh node — the host loop mixes such pods
        onto existing capacity (or shares one node across cohorts), which
        bucketed packing cannot. SPREAD fragments reroute too: the host loop
        runs the exact per-pod skew protocol (topologygroup.go:157-184)
        against counts that include every dense-committed bin, so wherever it
        re-places the fragment — warm capacity in a sibling domain, a shared
        node, or a fresh bin in the planned domain — the final skew stays
        legal, and it is precisely the mixed-cohort node sharing the host
        path gets on warm clusters. Deliberately narrow: only single-bin
        packs (bin ordering and spill-donor assumptions stay intact), never
        dedicated/single_bin semantics (one-pod bins ARE their contract),
        and bounded by a per-solve budget so a batch whose NATURAL pattern is
        tiny bins cannot stampede into the O(pods x open-nodes) host loop."""
        bin_of_row = np.full((problem.P,), -1, np.int64)
        bin_bucket_list: List[int] = []
        next_bin = 0
        reroute_budget = max(32, problem.P // 20) if reroute_fragments else 0
        for b, (rows, _reqs, pack) in enumerate(local):
            if pack is None:
                continue  # all pods of this bucket fall back to the host loop
            ids_local, n_local = pack
            if (
                reroute_budget > 0
                and n_local == 1
                and len(rows) <= self._FRAGMENT_MAX_PODS
                and not buckets[b].dedicated
                and not buckets[b].single_bin
            ):
                reroute_budget -= len(rows)
                ids_local = np.full_like(ids_local, -1)  # host loop owns them
                n_local = 0
            bin_of_row[rows] = np.where(ids_local >= 0, ids_local + next_bin, -1)
            bin_bucket_list.extend([b] * n_local)
            next_bin += n_local
        num_bins = next_bin
        bin_bucket = np.asarray(bin_bucket_list, dtype=np.int64)
        sol = {"buckets": buckets, "bin_of_row": bin_of_row, "bin_bucket": bin_bucket, "num_bins": num_bins, "caps_eff": caps_eff}
        if num_bins == 0:
            return sol

        # per-bin aggregates (vectorized over the pod axis)
        usage = np.zeros((num_bins, problem.requests.shape[1]), np.float64)
        placed = bin_of_row >= 0
        np.add.at(usage, bin_of_row[placed], problem.requests[placed])
        placed_rows = np.nonzero(placed)[0]
        order = np.argsort(bin_of_row[placed_rows], kind="stable")
        sorted_rows = placed_rows[order]
        boundaries = np.searchsorted(bin_of_row[sorted_rows], np.arange(num_bins + 1))
        bin_rows: List[np.ndarray] = [sorted_rows[boundaries[i] : boundaries[i + 1]] for i in range(num_bins)]

        # bulk audit: surviving instance-type options for every bin at once.
        # Bins repeat heavily (identical dedicated bins, repeated pack
        # patterns), so the [bins, T, R] compare runs over unique rows only.
        # Per-type daemon overhead folds into the capacity side (same
        # usage + overhead <= caps + tol inequality as before).
        cap_tol_eff = problem.caps + res.tolerance(problem.caps) - problem.daemon_overhead  # [T, R]
        uniq_need, inv_need = np.unique(usage, axis=0, return_inverse=True)
        fit_all = np.all(uniq_need[:, None, :] <= cap_tol_eff[None, :, :], axis=2)[inv_need]  # [num_bins, T]
        group_of_bin = np.asarray([buckets[int(b)].group_index for b in bin_bucket], dtype=np.int64)
        compat_of_bin = problem.compat[group_of_bin]
        # composite buckets (rare) carry an AND-compat row overriding the
        # representative group's; overwrite just those rows
        for bid, b in enumerate(bin_bucket):
            row = buckets[int(b)].compat_row
            if row is not None:
                compat_of_bin[bid] = row
        compat_extra_of_bin = compat_of_bin & bucket_extra[bin_bucket]
        mask_all = fit_all & compat_extra_of_bin
        # fit-free compat per bin: the drain pass (_merge_bins phase 2)
        # moves single PODS between bins, where ANDing the donor's full
        # mask_all would drag the whole-bin fit along and misprice small
        # remainders onto the donor's big types
        sol.update(usage=usage, bin_rows=bin_rows, mask_all=mask_all, bin_compat=compat_extra_of_bin)
        self._attach_bin_members(problem, buckets, sol)
        self._merge_bins(problem, buckets, sol)
        return sol

    @staticmethod
    def _attach_bin_members(problem: DenseProblem, buckets: List[_Bucket], sol) -> None:
        """sol["bin_members"]: per bin, [(group_index, rows, dedicated)] when
        the bin's pods span multiple groups (composite stacked buckets, and
        later any bin _merge_bins coalesces), else None. Commit recording and
        the merge gates both need the true per-group split: recording
        matching_cohort_groups on a single representative would silently drop
        every foreign member group's domain counts (anti-affinity hostnames
        above all), letting the host loop later co-locate a cohort member."""
        num_bins = sol["num_bins"]
        bin_members: List[Optional[list]] = [None] * num_bins
        bin_bucket = sol["bin_bucket"]
        bin_rows = sol.get("bin_rows")
        rmap_cache: Dict[int, dict] = {}
        for bid in range(num_bins):
            bucket = buckets[int(bin_bucket[bid])]
            if bucket.members is None:
                continue
            rmap = rmap_cache.get(id(bucket))
            if rmap is None:
                rmap = {r: g for g, rows in bucket.members for r in rows}
                rmap_cache[id(bucket)] = rmap
            split: Dict[int, List[int]] = {}
            for r in bin_rows[bid]:
                split.setdefault(rmap[int(r)], []).append(int(r))
            bin_members[bid] = [(g, rows, True) for g, rows in split.items()]
        sol["bin_members"] = bin_members

    def _merge_bins(self, problem: DenseProblem, buckets: List[_Bucket], sol) -> None:
        """Cross-bucket node sharing at BIN granularity: first-fit-decreasing
        over the per-bucket packs' bins, coalescing bins that share a
        (template, zone-pin, capacity-type-pin) signature onto one node. This
        is the node sharing the host loop's FFD gets for free and the
        per-bucket pack structurally cannot: at mid scale every small cohort
        opens its own near-empty node (VERDICT r5 weak #3 — 2000-pod sweep,
        dense 482 vs host 51 nodes; still ~250 after dedicated stacking), and
        per-pod spill re-adds cannot close a gap this wide within budget.

        Correct-by-construction gates, all cheap integer/set checks:
          - identical merge key: same template, same zone/ct pins (pods keep
            the exact domains the water-fill planned, so every spread /
            affinity / inverse count records unchanged), and member groups
            carry no node requirements (the merged proto requirement set is
            then content-identical for every member);
          - at most one bin per dedicated group per node (two bins of one
            anti/hostname-spread cohort can never share a host), and no
            dedicated member's selector may match another member's pods in
            the same namespace — the zero-count rule the exact add would
            enforce (same gate as _stack_dedicated_buckets);
          - capacity + price: the joining bin must fit the receiver under
            SOME commonly-surviving type (prefiltered by the elementwise max
            headroom over the receiver's mask — an upper bound; the exact
            sum-usage audit decides), and the merged bin's cheapest price
            must not exceed the two separate bins' cheapest prices summed —
            so total cost never increases while bins coalesce toward the
            roomiest type, which is exactly the host FFD's grow-until-no-
            type-fits discipline (a cheapest-type spare bound instead locks
            every small cohort onto its own small node and leaves the 5x
            node-count divergence in place).

        Commit semantics are preserved exactly: the merged bin's mask is the
        AND of member masks and the sum-usage audit, its rows concatenate,
        and bin_members carries every (group, rows) pair so _prepare_commit
        records topology per member group. Spill still runs after this pass;
        merged bins stay dense (never donors)."""
        num_bins = sol["num_bins"]
        if num_bins < 2:
            return
        usage = sol["usage"]
        bin_rows = sol["bin_rows"]
        mask_all = sol["mask_all"]
        bin_compat = sol["bin_compat"]
        bin_bucket = sol["bin_bucket"]
        bin_members = sol["bin_members"]
        prices = problem.prices
        cap_tol_eff = problem.caps + res.tolerance(problem.caps) - problem.daemon_overhead  # [T, R]

        facts_cache: Dict[int, tuple] = {}

        def group_facts(g: int) -> tuple:
            f = facts_cache.get(g)
            if f is None:
                group = problem.groups[g]
                rep = group.pods[0]
                f = facts_cache[g] = (rep.namespace, dict(rep.metadata.labels), self._dedicated_selector(group))
            return f

        # eligibility + merge key + member view per bin
        keys: List[Optional[tuple]] = []
        membs: List[list] = []
        for bid in range(num_bins):
            bucket = buckets[int(bin_bucket[bid])]
            group = problem.groups[bucket.group_index]
            if bin_members[bid] is not None:
                membs.append(bin_members[bid])
            else:
                membs.append([(bucket.group_index, [int(r) for r in bin_rows[bid]], bucket.dedicated)])
            if (
                bucket.single_bin
                or not mask_all[bid].any()
                or (group.requirements is not None and list(group.requirements.values()))
            ):
                keys.append(None)
            else:
                keys.append((group.template_index, bucket.zone, bucket.capacity_type))

        def gates_ok(s: dict, new_members: List[tuple]) -> bool:
            new_ded = [g for g, _r, d in new_members if d]
            if any(g in s["ded"] for g in new_ded):
                return False
            for g in new_ded:
                ns, _labels, sel = group_facts(g)
                if sel is None:
                    continue
                for g2 in s["groups"]:
                    if g2 == g:
                        continue
                    ns2, labels2, _sel2 = group_facts(g2)
                    if ns == ns2 and sel.matches(labels2):
                        return False
            for g2 in s["ded"]:
                ns2, _labels2, sel2 = group_facts(g2)
                if sel2 is None:
                    continue
                for g, _r, _d in new_members:
                    if g == g2:
                        continue
                    ns, labels, _sel = group_facts(g)
                    if ns2 == ns and sel2.matches(labels):
                        return False
            return True

        # FFD order: dominant capacity fraction, descending
        frac_den = np.maximum(cap_tol_eff.max(axis=0), 1e-12)
        frac = (usage / frac_den[None, :]).max(axis=1)
        order = np.argsort(-frac, kind="stable")
        supers: List[dict] = []
        by_key: Dict[tuple, List[int]] = {}
        for bid0 in order:
            bid = int(bid0)
            key = keys[bid]
            if key is None:
                continue
            bid_price = float(np.min(np.where(mask_all[bid], prices, np.inf)))
            placed = False
            cands = by_key.get(key)
            if cands:
                spare = np.stack([supers[si]["spare"] for si in cands])  # [N, R]
                fits = np.all(usage[bid][None, :] <= spare + 1e-9, axis=1)
                for k in np.flatnonzero(fits):
                    s = supers[cands[int(k)]]
                    if not gates_ok(s, membs[bid]):
                        continue
                    comb_usage = s["usage"] + usage[bid]
                    comb_mask = s["mask"] & mask_all[bid] & np.all(comb_usage[None, :] <= cap_tol_eff, axis=1)
                    if not comb_mask.any():  # exact-tolerance audit disagrees
                        continue
                    comb_price = float(prices[comb_mask].min())
                    if comb_price > s["price"] + bid_price + 1e-9:
                        continue  # one big node would cost more than two small
                    s["bins"].append(bid)
                    s["usage"] = comb_usage
                    s["mask"] = comb_mask
                    s["price"] = comb_price
                    s["spare"] = cap_tol_eff[comb_mask].max(axis=0) - comb_usage
                    s["groups"] |= {g for g, _r, _d in membs[bid]}
                    s["ded"] |= {g for g, _r, d in membs[bid] if d}
                    placed = True
                    break
            if not placed:
                supers.append(
                    {
                        "bins": [bid],
                        "usage": usage[bid].copy(),
                        "mask": mask_all[bid].copy(),
                        "spare": cap_tol_eff[mask_all[bid]].max(axis=0) - usage[bid],
                        "price": bid_price,
                        "groups": {g for g, _r, _d in membs[bid]},
                        "ded": {g for g, _r, d in membs[bid] if d},
                    }
                )
                by_key.setdefault(key, []).append(len(supers) - 1)

        # -- phase 2: sub-bin absorption (PR-2 satellite) --------------------
        # The spot_od shape: anti-affinity skeleton bins open near-empty
        # nodes that whole-bin FFD cannot use — a cpu-full plain bin never
        # fits INTO a skeleton's node, and a skeleton can't join a full
        # plain node. At POD granularity the move is easy: drain a plain
        # super's rows into same-key nodes with spare (skeletons above all)
        # and delete the emptied node, which is exactly the sharing the
        # host FFD gets by packing plain pods around each anti pod. A donor
        # drains all-or-nothing (partial moves shrink no node); receiving
        # masks AND in the donor's surviving-type mask (conservative: any
        # type that held the whole donor holds its pods); the summed
        # cheapest price of every touched node must not increase — the same
        # cost gate as phase 1. Only plain supers donate: moving a
        # dedicated pod could re-pair anti cohort members, while receiving
        # into a dedicated node is selector-gated by gates_ok.
        for key, sids in by_key.items():
            live = [si for si in sids if not supers[si].get("dead")]
            if len(live) < 2:
                continue
            spare_sum = np.sum([supers[si]["spare"] for si in live], axis=0)
            donors = sorted(
                (si for si in live if not supers[si]["ded"]),
                key=lambda si: float((supers[si]["usage"] / frac_den).max()),
            )
            # donors run emptiest-first, so drainability mostly decreases
            # along the list; a streak of failures means the group's spare
            # is exhausted for this shape — stop paying the receiver scans
            # (the anti_spread headline has nothing to drain and must not
            # fund this pass out of its latency budget)
            fail_streak = 0
            for dsi in donors:
                if fail_streak >= 4:
                    break
                d = supers[dsi]
                if d.get("dead") or d.get("extra_rows"):
                    continue  # received rows: draining would churn
                # quick reject: the group's spare outside the donor must
                # cover it elementwise (an upper bound on feasibility)
                if (d["usage"] > spare_sum - d["spare"] + 1e-9).any():
                    continue
                # roomiest receivers first (skeleton nodes above all): a
                # donor then lands whole on one near-empty node instead of
                # splintering across partial bins, which is both what the
                # host FFD produces and what keeps the price gate happy
                receivers = sorted(
                    (si for si in live if si != dsi and not supers[si].get("dead")),
                    key=lambda si: -float((supers[si]["spare"] / frac_den).min()),
                )
                if not receivers:
                    continue
                drows = np.concatenate([np.asarray(bin_rows[b], dtype=np.int64) for b in d["bins"]])
                dreqs = problem.requests[drows]
                order3 = np.argsort(-(dreqs / frac_den[None, :]).max(axis=1), kind="stable")
                drows, dreqs = drows[order3], dreqs[order3]
                donor_membs = [m for b in d["bins"] for m in membs[b]]
                # exact fit-free compat of the donor's pods (bin_compat):
                # using d["mask"] would require every receiving type to fit
                # the WHOLE donor, mispricing small remainders
                d_compat = bin_compat[d["bins"][0]].copy()
                for b in d["bins"][1:]:
                    d_compat &= bin_compat[b]
                tent: Dict[int, dict] = {}
                gate_cache_ok: Dict[int, bool] = {}
                feasible = True
                for row, req in zip(drows, dreqs):
                    placed = False
                    for rsi in receivers:
                        r = supers[rsi]
                        t = tent.get(rsi)
                        u = t["usage"] if t else r["usage"]
                        m = t["mask"] if t else r["mask"]
                        nu = u + req
                        nm = m & d_compat & np.all(nu[None, :] <= cap_tol_eff, axis=1)
                        if not nm.any():
                            continue
                        allowed = gate_cache_ok.get(rsi)
                        if allowed is None:
                            allowed = gate_cache_ok[rsi] = gates_ok(r, donor_membs)
                        if not allowed:
                            continue
                        if t is None:
                            tent[rsi] = {"usage": nu, "mask": nm, "rows": [int(row)]}
                        else:
                            t["usage"] = nu
                            t["mask"] = nm
                            t["rows"].append(int(row))
                        placed = True
                        break
                    if not placed:
                        feasible = False
                        break
                if not feasible or not tent:
                    fail_streak += 1
                    continue
                old_cost = d["price"] + sum(supers[rsi]["price"] for rsi in tent)
                new_prices = {rsi: float(prices[t["mask"]].min()) for rsi, t in tent.items()}
                if sum(new_prices.values()) > old_cost + 1e-9:
                    fail_streak += 1
                    continue  # absorbing would cost more than the two nodes
                # commit: receivers take the rows (with per-group member
                # attribution so topology recording stays per-group exact),
                # the donor's node disappears
                row_group = {int(rr): g for g, rrs, _dd in donor_membs for rr in rrs}
                for rsi, t in tent.items():
                    r = supers[rsi]
                    spare_sum = spare_sum - r["spare"]
                    r["usage"] = t["usage"]
                    r["mask"] = t["mask"]
                    r["price"] = new_prices[rsi]
                    r["spare"] = cap_tol_eff[t["mask"]].max(axis=0) - t["usage"]
                    spare_sum = spare_sum + r["spare"]
                    split2: Dict[int, List[int]] = {}
                    for rr in t["rows"]:
                        split2.setdefault(row_group[rr], []).append(rr)
                    r.setdefault("extra_members", []).extend((g, rrs, False) for g, rrs in split2.items())
                    r.setdefault("extra_rows", []).extend(t["rows"])
                    r["groups"] |= set(split2)
                spare_sum = spare_sum - d["spare"]
                d["dead"] = True
                fail_streak = 0

        dead_bins: set = set()
        for s in supers:
            if s.get("dead"):
                dead_bins.update(s["bins"])
        if all(len(s["bins"]) < 2 and not s.get("extra_rows") for s in supers) and not dead_bins:
            return

        # rebuild sol arrays; each merged super lands at its first bin's slot
        rep_of = list(range(num_bins))
        super_of_rep: Dict[int, dict] = {}
        for s in supers:
            if s.get("dead"):
                continue
            if len(s["bins"]) < 2 and not s.get("extra_rows"):
                continue
            r = min(s["bins"])
            for b in s["bins"]:
                rep_of[b] = r
            super_of_rep[r] = s
        final_reps = sorted({rep_of[b] for b in range(num_bins) if b not in dead_bins})
        nb = len(final_reps)
        new_usage = np.zeros((nb, usage.shape[1]), usage.dtype)
        new_mask = np.zeros((nb, mask_all.shape[1]), bool)
        new_rows: List[np.ndarray] = [None] * nb  # type: ignore[list-item]
        new_members: List[Optional[list]] = [None] * nb
        new_bucket = np.zeros((nb,), np.int64)
        bin_of_row = sol["bin_of_row"]
        for i, r in enumerate(final_reps):
            s = super_of_rep.get(r)
            if s is None:
                new_usage[i] = usage[r]
                new_mask[i] = mask_all[r]
                new_rows[i] = np.asarray(bin_rows[r], dtype=np.int64)
                new_members[i] = bin_members[r]
            else:
                parts = sorted(s["bins"])
                new_usage[i] = s["usage"]
                new_mask[i] = s["mask"]
                rows_parts = [np.asarray(bin_rows[b], dtype=np.int64) for b in parts]
                if s.get("extra_rows"):
                    rows_parts.append(np.asarray(s["extra_rows"], dtype=np.int64))
                new_rows[i] = np.concatenate(rows_parts)
                new_members[i] = [m for b in parts for m in membs[b]] + list(s.get("extra_members", ()))
            new_bucket[i] = bin_bucket[r]
            bin_of_row[new_rows[i]] = i
        sol.update(
            num_bins=nb, usage=new_usage, mask_all=new_mask, bin_rows=new_rows, bin_bucket=new_bucket, bin_members=new_members
        )

    def _best_pack(
        self, problem: DenseProblem, bucket: _Bucket, reqs: np.ndarray, caps_eff: np.ndarray, tstar: int
    ) -> Optional[Tuple[np.ndarray, int]]:
        """Small-batch pack refinement: run the bucket's pack under up to 8
        cheapest capacity-distinct candidate types (plus the argmin choice)
        and keep the pack whose bins PRICE cheapest — each bin priced at its
        cheapest feasible type, which is exactly how the commit prices nodes
        (options = every audited type, node cost = min price). The per-type
        argmin alone prefers few large bins, which strands the last bin's
        slack on mixed-size streams; pricing whole candidate packs captures
        the split-the-remainder-onto-a-smaller-type move the host loop's
        adaptive FFD makes for free. Ties prefer fewer bins (fewer nodes,
        less daemon overhead), then the argmin type's own pack."""
        g = bucket.group_index
        compat_row = problem.compat[g]
        cand = np.nonzero(compat_row)[0]
        if cand.size == 0:
            return None
        max_req = reqs.max(axis=0)
        fits_pod = (max_req[None, :] <= caps_eff[cand] + 1e-9).all(axis=1)
        cand = cand[fits_pod]
        if cand.size == 0:
            return None
        cand = cand[np.argsort(problem.prices[cand], kind="stable")]
        picks: List[int] = []
        seen_caps: set = set()
        for t in cand:
            key = caps_eff[int(t)].tobytes()
            if key in seen_caps:
                continue
            seen_caps.add(key)
            picks.append(int(t))
            if len(picks) >= 8:
                break
        if int(tstar) not in picks and compat_row[int(tstar)]:
            picks.append(int(tstar))
        cap_tol = problem.caps + res.tolerance(problem.caps) - problem.daemon_overhead  # [T, R]
        prices = problem.prices
        if bucket.single_bin:
            pack_of = lambda t: self._pack_bucket(bucket, reqs, caps_eff[t])  # noqa: E731
        else:
            # size dedupe is type-independent at refine scale (the quantum
            # path needs > 4096 pods, refine stops at 2048): one np.unique
            # per bucket instead of one per (bucket, candidate) — the
            # remaining half of the r5 mid-size sweep collapse
            from .pack_counts import dedupe_sizes, pack_and_assign

            unique, counts, inverse = dedupe_sizes(reqs)
            pack_of = lambda t: pack_and_assign(unique, counts, inverse, caps_eff[t])  # noqa: E731
        # pack every candidate first, then price ALL candidates' bins in one
        # stacked [sum(nbins), T] pass — per-candidate pricing paid ~6 small
        # numpy reductions each, and their fixed overhead (not the element
        # count) dominated the r5 mid-size sweep collapse
        packs = [pack_of(t) for t in picks]
        R = reqs.shape[1]
        u_parts: List[np.ndarray] = []
        m_parts: List[np.ndarray] = []
        occ_parts: List[np.ndarray] = []
        offsets = [0]
        for ids, nbins in packs:
            placed_sel = ids >= 0
            u = np.zeros((nbins, R), np.float64)
            m = np.zeros_like(u)
            if placed_sel.any():
                placed_ids = ids[placed_sel]
                placed_reqs = reqs[placed_sel]
                for r in range(R):
                    u[:, r] = np.bincount(placed_ids, weights=placed_reqs[:, r], minlength=nbins)
                np.maximum.at(m, placed_ids, placed_reqs)
                occ = np.bincount(placed_ids, minlength=nbins) > 0
            else:
                occ = np.zeros((nbins,), bool)
            u_parts.append(u)
            m_parts.append(m)
            occ_parts.append(occ)
            offsets.append(offsets[-1] + nbins)
        if offsets[-1]:
            u_all = np.concatenate(u_parts)
            m_all = np.concatenate(m_parts)
            fit_all = (
                compat_row[None, :]
                & np.all(u_all[:, None, :] <= cap_tol[None, :, :] + 1e-9, axis=2)
                & np.all(m_all[:, None, :] <= cap_tol[None, :, :] + 1e-9, axis=2)
            )  # [sum(nbins), T]
            price_all = np.where(fit_all, prices[None, :], np.inf).min(axis=1)
            feas_all = fit_all.any(axis=1)
        best_key = None
        best_pack = None
        for k, (t, pack) in enumerate(zip(picks, packs)):
            ids, nbins = pack
            unplaced = int((ids < 0).sum())
            if nbins == 0:
                cost, feasible = 0.0, True
            else:
                lo, hi = offsets[k], offsets[k + 1]
                occ = occ_parts[k]
                feasible = bool(feas_all[lo:hi][occ].all())
                cost = float(price_all[lo:hi][occ].sum()) if feasible else 0.0
            if not feasible:
                continue
            key = (unplaced, round(cost, 9), nbins)
            if best_key is None or key < best_key:
                best_key = key
                best_pack = pack
        if best_pack is None:
            return self._pack_bucket(bucket, reqs, caps_eff[int(tstar)])
        return best_pack

    def _pack_bucket(self, bucket: _Bucket, reqs: np.ndarray, cap: np.ndarray) -> Tuple[np.ndarray, int]:
        """Pack one bucket's pods into bins of capacity `cap`.

        Returns (local bin id per pod row, -1 unplaced; number of bins)."""
        from .pack_counts import dedupe_sizes, pack_and_assign, pack_dedicated

        n = len(reqs)
        if bucket.dedicated:
            return pack_dedicated(reqs, cap)
        if bucket.single_bin:
            # fill one bin greedily, largest first, exact resource check
            order = np.lexsort((-reqs[:, 1], -reqs[:, 0]))
            free = cap.astype(np.float64).copy()
            taken = []
            for i in order:
                if np.all(reqs[i] <= free + res.tolerance(free)):
                    free -= reqs[i]
                    taken.append(i)
            ids = np.full((n,), -1, np.int64)
            if taken:
                ids[np.asarray(taken)] = 0
                return ids, 1
            return ids, 0
        quantum = None
        # bound the distinct-size count for continuous distributions
        if n > 4096:
            quantum = np.maximum(cap, 1e-9) / 4096.0
        unique, counts, inverse = dedupe_sizes(reqs, quantum)
        return pack_and_assign(unique, counts, inverse, cap)

    # -- step 3.5: cross-bucket spill selection --------------------------------

    # dense may open at most this x the host FLOOR. The floor is an
    # algorithm-independent lower bound that under-approximates the real
    # host loop (measured host/floor: 1.0 on the sweep workload, 1.36 on
    # spot_od, where anti-affinity skeleton hosts don't show in the
    # capacity bound), so the trip point sits at 3x: the r5 pathology this
    # guard exists for was 9.4x the HOST (far above 3x the floor), while a
    # legitimate plan on a cohort-heavy mixed catalog measures ~2.05x the
    # floor and must commit. The differential test asserts the tighter
    # <= 2x bound against the true host oracle (test_warm_fill_vectorized).
    _NODE_GUARD_RATIO = 3.0
    _NODE_GUARD_MIN_NODES = 16  # below this, divergence is noise-cheap

    def _node_guard_tripped(self, problem: DenseProblem, buckets: List[_Bucket], prep: dict, taken: Optional[np.ndarray]) -> bool:
        """Node-count divergence guard (closes VERDICT r5 weak #3's
        "unguarded" half): compare the nodes the dense commit is about to
        open against an algorithm-independent HOST FLOOR — the larger of the
        capacity lower bound (total un-taken demand over the roomiest type)
        and the dedicated lower bound (an anti-affinity cohort needs one
        host per member under ANY algorithm). The floor under-estimates the
        real host loop (fragmentation, topology), so ratio > NODE_GUARD_RATIO
        means the dense plan is structurally fragmented, not merely unlucky
        — fail open BEFORE any commit and let the exact host loop repack.
        Records both counts in stats so bench.py can attribute drifts."""
        n_dense = len(prep["records"])
        cap_tol_eff = problem.caps + res.tolerance(problem.caps) - problem.daemon_overhead  # [T, R]
        rows_mask = np.ones((problem.P,), dtype=bool) if taken is None else ~taken
        total = problem.requests[rows_mask].sum(axis=0)  # [R]
        cap_best = cap_tol_eff.max(axis=0)
        per_axis = np.where(cap_best > 0, np.ceil(total / np.maximum(cap_best, 1e-12)), 0.0)
        floor = int(max(per_axis.max() if per_axis.size else 0.0, 1.0))
        for bucket in buckets:
            if not bucket.dedicated or not bucket.pod_rows:
                continue
            if bucket.preset_pack is not None:
                floor = max(floor, int(bucket.preset_pack[1]))
            elif problem.groups[bucket.group_index].kind == GroupKind.ANTI_HOST:
                floor = max(floor, len(bucket.pod_rows))
        # nodes_opened_dense is recorded by _apply_commit (actual opens);
        # recording the evaluated plan here too would double the counter
        self.stats.nodes_opened_host_floor += floor
        if n_dense < self._NODE_GUARD_MIN_NODES:
            return False
        if n_dense > self._NODE_GUARD_RATIO * floor:
            self.stats.node_guard_failopens += 1
            log.warning(
                "dense node-count guard: %d nodes vs host floor %d (> %.1fx) — failing open to the host loop",
                n_dense,
                floor,
                self._NODE_GUARD_RATIO,
            )
            return True
        return False

    _SPILL_BIN_PODS = 64  # donor bins larger than this stay dense
    _SPILL_TOTAL_PODS = 256  # pass budget: beyond this, host-loop time would bite
    _SPILL_DENSE_BINS = 192  # above this many bins, only whole-bin plain spill runs

    def _select_spill_donors(self, problem: DenseProblem, buckets: List[_Bucket], sol) -> Dict[int, tuple]:
        """Nominate donor bins for cross-bucket packing; returns
        {donor bin -> (receiver bin, full)} where full=True means the whole
        donor bin re-adds directly onto the receiver in _apply_commit and
        full=False (partial, small scale only) routes the donor's pods
        through the exact host loop.

        The per-bucket dense pack cannot share one node between two
        constraint groups, so each bucket's bins may open nodes whose pods
        the host loop would have mixed onto shared capacity — the one
        structural cost gap vs the ILP optimum (measured by
        tests/test_cost_regret.py). A donor's pods are not committed as
        their own bin; _apply_commit re-adds each one directly onto the
        nominated receiver's VirtualNode through the exact add protocol
        (node.py:add — the same per-pod checks the host loop would run),
        and the add itself re-filters the receiver's instance-type options,
        so absorbing a donor can UPGRADE the receiver to a larger type;
        pods the protocol vetoes fall back to the host loop.

        At small scale (<= _SPILL_DENSE_BINS bins) selection is
        agglomerative net-saving CLUSTERING, run to fixpoint: every bin
        starts as its own cluster; each pass, clusters of <= _SPILL_BIN_PODS
        pods (smallest first) merge into the live cluster maximizing
        cheapest(donor) + cheapest(receiver) - cheapest(combined) when that
        saving is positive — combined feasibility evaluated over the full
        type axis. Passes repeat until no merge fires, so two previously
        merged clusters can keep coalescing — which is exactly how the host
        loop's FFD ends up with a few LARGE shared nodes on a cold cluster
        where bucketed packing would open one small bin per cohort, and a
        single-round merge would stop at medium bins. Every non-
        representative bin of a final cluster maps to the representative in
        the returned donor dict. At large scale the scan cost of the type
        axis is not worth the <1% remainder: only whole-bin cost-neutral
        spill of plain remainder bins runs (free capacity under the
        receiver's cheapest type, so the merge can never raise its price).

        Selection must be conservative: a nominated pod the exact re-add
        vetoes leaks to the host loop, which breaks the dense-carries-the-
        batch invariant AND re-prices the pod at host-FFD fidelity. Three
        prescreens make vetoes structurally impossible for the cases the
        estimator prices: (a) a topology-pinned cluster (zone/ct water-fill
        or affinity pin) only merges where the committed domain counts stay
        on plan — the receiver must carry the SAME pin on every axis the
        donor pins, and a pin on an axis the donor leaves free must be a
        domain every donor group allows; (b) every donor group's
        requirement set must be compatible with the receiver cluster's
        accumulated effective requirements (template ∩ group ∩ pins ∩
        previously merged groups — the same algebra node.add will enforce);
        (c) the partial path (donor demoted to the host loop wholesale)
        stays restricted to unmerged remainder/dedicated single bins whose
        group is type-compatible with the receiver's cheapest type — the
        shape it was designed for, where the demoted tail is a few pods,
        never a full pattern bin. Dedicated (anti-affinity / hostname-
        spread) pods additionally require the receiver cluster to hold no
        pod of the same group (the per-host zero-count rule).

        Bounded: donor clusters over _SPILL_BIN_PODS pods stay dense, and
        total donated pods are capped at _SPILL_TOTAL_PODS (each donated
        pod is one exact re-add at apply time).
        """
        num_bins = sol["num_bins"]
        if num_bins < 2:
            return {}
        bin_bucket = sol["bin_bucket"]
        bin_rows = sol["bin_rows"]
        usage_all = sol["usage"]
        masks_all = sol["mask_all"]
        bin_members = sol.get("bin_members", [None] * num_bins)

        prices = problem.prices
        cap_tol_eff = problem.caps + res.tolerance(problem.caps) - problem.daemon_overhead  # [T, R]

        def cheapest(mask_row) -> float:
            hit = np.where(mask_row, prices, np.inf)
            return float(hit.min())

        bucket_of = [buckets[int(b)] for b in bin_bucket]
        dedicated = np.asarray([bk.dedicated for bk in bucket_of])
        group_of = np.asarray([bk.group_index for bk in bucket_of])
        zone_index = {z: i for i, z in enumerate(problem.zones)}
        ct_index = {c: i for i, c in enumerate(problem.capacity_types)}
        # remainder = last bin of each bucket's pack (patterns emit in order,
        # the partial pattern last)
        last_of_bucket: Dict[int, int] = {}
        for bid in range(num_bins):
            last_of_bucket[int(bin_bucket[bid])] = bid
        remainder_bins = set(last_of_bucket.values())

        eff_reqs_cache: Dict[int, Optional[Requirements]] = {}

        def bucket_eff_reqs(bkey: int) -> Optional[Requirements]:
            if bkey not in eff_reqs_cache:
                eff_reqs_cache[bkey] = self._bucket_proto_reqs(problem, buckets[bkey])
            return eff_reqs_cache[bkey]

        if num_bins > self._SPILL_DENSE_BINS:
            # large scale: cost-neutral whole-bin spill of plain remainder
            # bins only (no type upgrades): free capacity under the
            # receiver's cheapest surviving type
            plain = np.asarray(
                [
                    problem.groups[bk.group_index].kind == GroupKind.PLAIN
                    and bk.zone is None
                    and bk.capacity_type is None
                    for bk in bucket_of
                ]
            )
            candidates = [
                bid
                for bid in remainder_bins
                if plain[bid]
                and bin_members[bid] is None
                and masks_all[bid].any()
                and 0 < len(bin_rows[bid]) <= self._SPILL_BIN_PODS
            ]
            candidates.sort(key=lambda bid: len(bin_rows[bid]))
            usage = usage_all.copy()
            receiver_ok = np.asarray(
                [
                    masks_all[r].any()
                    and not dedicated[r]
                    and not (bin_members[r] is not None and any(d for _g, _rr, d in bin_members[r]))
                    and bucket_eff_reqs(int(bin_bucket[r])) is not None
                    for r in range(num_bins)
                ]
            )
            donors: Dict[int, tuple] = {}
            claimed: set = set()
            budget = self._SPILL_TOTAL_PODS
            cheapest_t = np.array([int(np.argmin(np.where(masks_all[b], prices, np.inf))) if masks_all[b].any() else 0 for b in range(num_bins)])
            for bid in candidates:
                rows = bin_rows[bid]
                if len(rows) > budget or bid in claimed:
                    continue
                g = bucket_of[bid].group_index
                donor_reqs = problem.groups[g].requirements
                need = problem.requests[rows].sum(axis=0)
                ok = receiver_ok.copy()
                ok[bid] = False
                ok &= problem.compat[g, cheapest_t]
                for r in np.nonzero(ok)[0]:
                    bk = bucket_of[int(r)]
                    if bk.zone is not None and bk.zone != "__infeasible__":
                        zi = zone_index.get(bk.zone)
                        if zi is None or not problem.group_zone_allowed[g][zi]:
                            ok[r] = False
                            continue
                    if bk.capacity_type is not None:
                        ci = ct_index.get(bk.capacity_type)
                        if ci is None or not problem.group_ct_allowed[g][ci]:
                            ok[r] = False
                            continue
                    # prescreen (b): the exact re-add enforces the donor
                    # group's requirements against the receiver's proto
                    if donor_reqs is not None:
                        eff = bucket_eff_reqs(int(bin_bucket[int(r)]))
                        if eff is None or eff.compatible(donor_reqs) is not None:
                            ok[r] = False
                spare = cap_tol_eff[cheapest_t] - usage
                full_choice = np.nonzero(ok & np.all(need[None, :] <= spare, axis=1))[0]
                if full_choice.size == 0:
                    continue
                receiver = int(full_choice[0])
                usage[receiver] = usage[receiver] + need
                donors[bid] = (receiver, True)
                claimed.add(receiver)
                receiver_ok[bid] = False
                budget -= len(rows)
            return donors

        # -- small scale: agglomerative clustering to fixpoint ---------------
        class _Cluster:
            __slots__ = ("rep", "bins", "pods", "usage", "mask", "price", "zone", "ct", "groups", "ded", "acc", "can_receive", "can_donate")

        clusters: Dict[int, _Cluster] = {}
        for bid in range(num_bins):
            bk = bucket_of[bid]
            c = _Cluster()
            c.rep = bid
            c.bins = [bid]
            c.pods = len(bin_rows[bid])
            c.usage = usage_all[bid].copy()
            c.mask = masks_all[bid].copy()
            c.price = cheapest(c.mask) if c.mask.any() else np.inf
            c.zone = bk.zone
            c.ct = bk.capacity_type
            members = bin_members[bid]
            if members is None:
                c.groups = {bk.group_index}
                c.ded = {bk.group_index} if bk.dedicated else set()
            else:
                # multi-group bin (stacked/merged): the ded-collision and
                # requirement prescreens must see every member group; these
                # bins never donate (their pods are already shared-node
                # dense commits — per-pod re-adds would only re-pay them)
                c.groups = {g for g, _r, _d in members}
                c.ded = {g for g, _r, d in members if d}
            c.acc = None  # lazy: rep bucket proto + merged donor group reqs
            c.can_receive = (
                bool(c.mask.any()) and not bk.dedicated and not c.ded and bucket_eff_reqs(int(bin_bucket[bid])) is not None
            )
            c.can_donate = bool(c.mask.any()) and c.pods > 0 and not bk.single_bin and members is None
            clusters[bid] = c

        def cluster_acc(c: _Cluster) -> Optional[Requirements]:
            if c.acc is None:
                base = bucket_eff_reqs(int(bin_bucket[c.rep]))
                c.acc = base.copy() if base is not None else None
            return c.acc

        def groups_admitted(d: _Cluster, r: _Cluster) -> bool:
            """Prescreens (a)+(b) + the dedicated zero-count rule for merging
            donor cluster d into receiver cluster r."""
            if d.zone is not None and r.zone != d.zone:
                return False
            if d.ct is not None and r.ct != d.ct:
                return False
            if (d.ded & r.groups) or (r.ded & d.groups):
                return False
            acc = cluster_acc(r)
            if acc is None:
                return False
            for g in d.groups:
                if d.zone is None and r.zone is not None:
                    zi = zone_index.get(r.zone)
                    if zi is None or not problem.group_zone_allowed[g][zi]:
                        return False
                if d.ct is None and r.ct is not None:
                    ci = ct_index.get(r.ct)
                    if ci is None or not problem.group_ct_allowed[g][ci]:
                        return False
                greqs = problem.groups[g].requirements
                if greqs is not None and acc.compatible(greqs) is not None:
                    return False
            return True

        donors: Dict[int, tuple] = {}
        budget = self._SPILL_TOTAL_PODS

        def merge(d: _Cluster, r: _Cluster, comb_mask: np.ndarray, comb_price: float) -> None:
            nonlocal budget
            budget -= d.pods
            for bid in d.bins:
                donors[bid] = (r.rep, True)
            r.bins.extend(d.bins)
            r.pods += d.pods
            r.usage = r.usage + d.usage
            r.mask = comb_mask
            r.price = comb_price
            r.groups |= d.groups
            r.ded |= d.ded
            acc = cluster_acc(r)
            for g in d.groups:
                greqs = problem.groups[g].requirements
                if greqs is not None:
                    acc.add(*greqs.values())
            del clusters[d.rep]

        # fixpoint with a pass cap: merges converge in 2-3 passes on real
        # shapes; the cap bounds the worst case (one merge per pass) at
        # O(cap x bins^2) type-axis scans instead of O(bins^3)
        changed = True
        passes = 0
        while changed and passes < 8:
            changed = False
            passes += 1
            for rep in sorted(clusters, key=lambda k: (clusters[k].pods, k)):
                d = clusters.get(rep)
                if d is None or not d.can_donate or d.pods > min(self._SPILL_BIN_PODS, budget):
                    continue
                # donor cluster compat across its groups, AND-combined once
                d_compat = None
                for g in d.groups:
                    row = problem.compat[g]
                    d_compat = row if d_compat is None else (d_compat & row)
                best = None  # (saving, receiver, comb_mask, comb_price)
                for r in clusters.values():
                    if r is d or not r.can_receive or not groups_admitted(d, r):
                        continue
                    comb_fit = ((r.usage + d.usage)[None, :] <= cap_tol_eff).all(axis=1)
                    comb_mask = r.mask & d_compat & comb_fit
                    if not comb_mask.any():
                        continue
                    comb_price = float(np.where(comb_mask, prices, np.inf).min())
                    saving = d.price + r.price - comb_price
                    if saving > 1e-9 and (best is None or saving > best[0]):
                        best = (saving, r, comb_mask, comb_price)
                if best is not None:
                    merge(d, best[1], best[2], best[3])
                    changed = True

        # prescreen (c): cost-neutral partial spill for unmerged remainder/
        # dedicated single bins — the donor's pods take the exact host loop,
        # which fills the committed receivers first and opens a fresh node
        # only for the rest
        for rep in sorted(clusters, key=lambda k: (clusters[k].pods, k)):
            d = clusters.get(rep)
            if (
                d is None
                or len(d.bins) > 1  # merged clusters stay dense
                or d.zone is not None
                or d.ct is not None
                or not d.mask.any()
                or bucket_of[rep].single_bin  # all-or-nothing component contract
                or not (rep in remainder_bins or dedicated[rep])
                or not (0 < d.pods <= min(self._SPILL_BIN_PODS, budget))
            ):
                continue
            g = bucket_of[rep].group_index
            reqs_d = problem.requests[bin_rows[rep]]
            for r in clusters.values():
                if r is d or not r.can_receive or not groups_admitted(d, r):
                    continue
                t = int(np.argmin(np.where(r.mask, prices, np.inf)))
                if not problem.compat[g, t]:
                    continue
                spare = cap_tol_eff[t] - r.usage
                if not np.any(np.all(reqs_d <= spare[None, :], axis=1)):
                    continue
                donors[rep] = (r.rep, False)
                r.usage = cap_tol_eff[t].copy()  # consumed: unknown subset lands on it
                r.groups |= d.groups
                r.ded |= d.ded
                budget -= d.pods
                del clusters[rep]
                break
        return donors

    # -- steps 4+5: verify & commit ------------------------------------------
    # Split into a *pure* preparation half (_prepare_commit — builds every
    # VirtualNode, options list, and the fallback set without touching
    # scheduler state, so it runs speculatively under the device round trip)
    # and a cheap mutation half (_apply_commit — registers hostnames, appends
    # nodes, records topology counts) that runs once the device result is
    # confirmed.

    def _bucket_proto_reqs(self, problem: DenseProblem, bucket: _Bucket) -> Optional[Requirements]:
        """Effective node requirements for a bucket's bins: template ∩ group
        ∩ zone/ct pins — the requirement set every node opened for this
        bucket starts from, shared by commit preparation (bucket_proto) and
        the spill-donor prescreen. None means the bucket's pods are routed
        to the exact host loop at commit: any hostname-keyed pod requirement
        (IN a specific host, but also DoesNotExist/Gt/Lt, which compatible()
        can't veto) is incompatible with the per-bin placeholder-hostname
        protocol, as is a group requirement the template cannot satisfy."""
        group = problem.groups[bucket.group_index]
        reqs = Requirements(*problem.template_of_group(group).requirements.values())
        if group.requirements is not None:
            if group.requirements.has(lbl.LABEL_HOSTNAME):
                return None
            if reqs.compatible(group.requirements) is not None:
                return None
            reqs.add(*group.requirements.values())
        if bucket.zone is not None and bucket.zone != "__infeasible__":
            reqs.add(Requirement(lbl.LABEL_TOPOLOGY_ZONE, OP_IN, bucket.zone))
        if bucket.capacity_type is not None:
            reqs.add(Requirement(lbl.LABEL_CAPACITY_TYPE, OP_IN, bucket.capacity_type))
        return reqs

    def _prepare_commit(
        self, scheduler, problem: DenseProblem, buckets: List[_Bucket], sol, taken: Optional[np.ndarray] = None
    ) -> dict:
        from ..scheduler.node import VirtualNode
        from ..scheduler.scheduler import filter_by_remaining_resources, subtract_max

        bin_of_row = sol["bin_of_row"]
        bin_bucket = sol["bin_bucket"]
        num_bins = sol["num_bins"]

        unplaced = np.nonzero(bin_of_row < 0)[0]
        if taken is not None:  # rows already committed onto existing nodes
            unplaced = unplaced[~taken[unplaced]]
        fallback_rows: List[int] = [int(r) for r in unplaced]

        prep: dict = {
            "fallback_rows": fallback_rows,
            "records": [],
            "remaining": None,
            "committed": 0,
            "inverse_by_uid": {},
            "spill_pods": [],
            "pods": problem.pods,
        }
        if num_bins == 0:
            return prep

        usage = sol["usage"]
        bin_rows = sol["bin_rows"]
        mask_all = sol["mask_all"]
        # Under provisioner limits a receiver can still be knocked out by the
        # limits filter mid-loop; its donors then land in fallback_rows (the
        # record_of_bid guard below), so the pass is safe to run always.
        spill = self._select_spill_donors(problem, buckets, sol)

        # identical dedicated bins share options lists; cache by content
        options_cache: Dict[bytes, list] = {}
        # topology recording caches: bins of one bucket share namespace,
        # labels, and node requirements (up to the per-bin placeholder
        # hostname — hostname-keyed pod requirements are routed to the host
        # loop by bucket_proto below), so which groups count a cohort is a
        # per-bucket fact. The group's *domain* is still read from each bin's
        # own requirements.
        match_cache: Dict[int, list] = {}
        gmatch_cache: Dict[tuple, list] = {}  # (group_index, bucket_key) for multi-group bins
        inverse_by_uid = scheduler.topology.inverse_owner_index()
        prep["inverse_by_uid"] = inverse_by_uid
        # limits simulation runs against a local copy: the sequential
        # filter→subtractMax chain must see earlier bins' pessimism, but
        # scheduler state stays untouched until _apply_commit
        remaining_local = dict(scheduler.remaining_resources)

        # per-bucket prototype requirements: template ∩ group ∩ zone/ct is a
        # bucket-level fact; each bin copies the prototype and adds only its
        # placeholder hostname (inside open_prepared)
        proto_cache: Dict[int, Optional[Requirements]] = {}

        def bucket_proto(bkey: int) -> Optional[Requirements]:
            if bkey not in proto_cache:
                proto_cache[bkey] = self._bucket_proto_reqs(problem, buckets[bkey])
            return proto_cache[bkey]

        committed = 0
        record_of_bid: Dict[int, int] = {}  # receiver bin -> index into records
        spill_pods: List[tuple] = []  # (row, receiver bid)
        for bid in range(num_bins):
            if bid in spill:  # cross-bucket spill
                receiver, full = spill[bid]
                if full:  # whole bin re-adds directly onto the receiver
                    spill_pods.extend((int(r), receiver) for r in bin_rows[bid])
                else:  # partial: the exact host loop re-packs these pods
                    fallback_rows.extend(int(r) for r in bin_rows[bid])
                continue
            bucket_key = int(bin_bucket[bid])
            bucket = buckets[bucket_key]
            group = problem.groups[bucket.group_index]
            template = problem.template_of_group(group)
            mask = mask_all[bid]
            if not mask.any():
                fallback_rows.extend(bin_rows[bid])
                continue

            mask_key = mask.tobytes()
            options = options_cache.get(mask_key)
            if options is None:
                options = [problem.instance_types[t] for t in np.nonzero(mask)[0]]
                options_cache[mask_key] = options
            # provisioner limits: drop types whose capacity alone would
            # breach, then apply the subtractMax pessimism after commit —
            # the exact sequential invariant the host loop keeps per opened
            # node (scheduler.go:263-284), via the host loop's own helpers
            remaining = remaining_local.get(template.provisioner_name)
            if remaining is not None:
                options = filter_by_remaining_resources(options, remaining)
                if not options:
                    fallback_rows.extend(bin_rows[bid])
                    continue
            proto = bucket_proto(bucket_key)
            if proto is None:
                fallback_rows.extend(bin_rows[bid])
                continue
            daemon = scheduler.daemon_overhead.get(template.provisioner_name, {})
            node = VirtualNode.open_prepared(
                template,
                proto.copy(),
                scheduler.topology,
                daemon,
                options,
                register=False,
                filter_cache=scheduler.filter_caches.get(template.provisioner_name),
            )
            reqs = node.template.requirements

            node.pods = [problem.pods[row] for row in bin_rows[bid]]
            node.requests = res.merge(
                node.requests, {name: float(v) for name, v in zip(problem.resource_names, usage[bid]) if v > 0}
            )
            committed += len(node.pods)

            members = sol.get("bin_members", [None] * num_bins)[bid]
            if members is None:
                matching = match_cache.get(bucket_key)
                if matching is None:
                    matching = scheduler.topology.matching_cohort_groups(node.pods[0], reqs)
                    match_cache[bucket_key] = matching
                recs = [(node.pods, matching)]
            else:
                # multi-group bin (stacked dedicated / merged): record each
                # member group with its own matching set — the representative
                # alone cannot stand in for foreign groups' domain counts
                recs = []
                for g, rows_g, _ded in members:
                    m = gmatch_cache.get((g, bucket_key))
                    if m is None:
                        m = scheduler.topology.matching_cohort_groups(problem.groups[g].pods[0], reqs)
                        gmatch_cache[(g, bucket_key)] = m
                    recs.append(([problem.pods[r] for r in rows_g], m))
            record_of_bid[bid] = len(prep["records"])
            prep["records"].append((node, reqs, recs))
            if remaining is not None:
                remaining_local[template.provisioner_name] = subtract_max(remaining, options)
        # spill donors whose receiver never committed (audit/proto drop) have
        # no node to land on — host loop
        for row, rbid in spill_pods:
            if rbid in record_of_bid:
                prep["spill_pods"].append((row, record_of_bid[rbid]))
            else:
                fallback_rows.append(row)
        prep["committed"] = committed
        prep["remaining"] = remaining_local
        return prep

    def _apply_commit(self, scheduler, prep: dict) -> Tuple[int, List[int]]:
        """Make a prepared commit real: per bin (in pack order) register the
        placeholder hostname, append the node, and record topology counts —
        the only scheduler-state mutations of the dense path. Spilled pods
        then re-add directly onto their nominated receiver node through the
        exact protocol; vetoes fall back to the host loop."""
        from ..scheduler.errors import IncompatibleError

        inverse_by_uid = prep["inverse_by_uid"]
        for node, reqs, recs in prep["records"]:
            node.register_hostname()
            scheduler.nodes.append(node)
            for rec_pods, matching in recs:
                scheduler.topology.record_cohort(rec_pods, reqs, matching=matching, inverse_index=inverse_by_uid)
        self.stats.nodes_created += len(prep["records"])
        self.stats.nodes_opened_dense += len(prep["records"])
        if prep["remaining"] is not None:
            scheduler.remaining_resources.clear()
            scheduler.remaining_resources.update(prep["remaining"])
        committed = prep["committed"]
        fallback_rows = prep["fallback_rows"]
        for row, rec_index in prep["spill_pods"]:
            node = prep["records"][rec_index][0]
            try:
                node.add(prep["pods"][row])
                committed += 1
            except IncompatibleError:
                fallback_rows.append(row)
        return committed, fallback_rows
