"""Vectorized warm fill: the existing-capacity phase as array programs.

Through round 5 the repack/consolidation flagship spent ~95% of its wall
clock in `DenseSolver._fill_existing` — a sequential host loop that walks
every pod through per-view Python protocol objects, with zero device work
(VERDICT r5, missing #1). This module replaces that loop for the CERTIFIED
COMMON CASE with a three-phase pipeline:

  1. encode  — ir/encode.py:encode_warm_views builds the [views x resources]
     residual-capacity arrays with the exact f64 expressions of the
     certified fast paths; this module adds per-bucket [views] acceptance
     masks (taints deduped by content signature, zone/ct pins, domain
     allow-lists) and integer topology-count states for every group the
     certificates consult.
  2. device  — ops/warmfill.py dispatches ONE [sizes x views] admission
     kernel (jnp fallback, fused Pallas on TPU): upper-bound closed-form
     counts used to prune views that can never take a size class. The
     device surface is advisory; every placement is re-derived below with
     exact f64 host arithmetic, so f32 boundary rounding costs a probe,
     never a wrong placement.
  3. scan + bulk commit — a host scan over the SAME FFD item stream the
     host loop processes, but against arrays instead of protocol objects:
     plain cohorts commit by closed-form counts, dedicated (anti-affinity /
     hostname-spread) pods by zero-count claims, deferred spread cohorts by
     the pinned-domain skew integers, and deferred zonal affinity by
     populated-domain membership with the host's bootstrap-then-colocate
     rule. The scan's verdict arithmetic is the BucketCert algebra
     (scheduler/existingnode.py) evaluated in bulk, so its placements are
     byte-identical to the host loop's — pinned by the differential suite
     (tests/test_warm_fill_vectorized.py). Commits then mutate view and
     topology state with the same merge/record call sequence the certified
     paths issue, in the same order.

Fail-open: `plan()` returns None whenever any fill item falls outside the
certified common case — IR-inexpressible extras, host-routed buckets,
single-bin components, cohorts with node requirements, non-trivial spread
node filters, groups a foreign selector counts — and `_fill_existing` runs
the exact host loop unchanged. One algorithm is chosen per solve, never a
mix, so the one global FFD order that decides warm-capacity claims is
always preserved.

KARPENTER_TPU_NO_WARMFILL_VECTOR=1 forces the host loop (tests, triage).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import labels as lbl
from ..ir.encode import DenseProblem, GroupKind, WarmViewEncoding, encode_warm_views
from ..utils import resources as res
from .faults import FAULTS, SOLVER_FAULTS, classify

log = logging.getLogger("karpenter_tpu.solver")

NO_VECTOR_ENV = "KARPENTER_TPU_NO_WARMFILL_VECTOR"

# bucket kinds the scan distinguishes (mirrors the host loop's dispatch)
_PLAIN = 0
_DEDICATED = 1
_SPREAD = 2
_AFFINITY = 3

# device surface bounds: past these the [S, V] counts matrix is computed
# lazily per size class on host instead of shipped to the device
_DEVICE_MAX_SIZES = 4096
_DEVICE_MAX_CELLS = 8_000_000


class _GroupState:
    """Integer domain counts for one TopologyGroup, in the axis the scan
    needs: hostname-keyed groups count per VIEW (each view is its own
    domain; non-view hostnames can't affect the hostname rules — the global
    min is 0 and membership checks are per-view); zone/ct groups count over
    the group's full registered domain list (the skew min ranges over
    domains with no usable views too)."""

    __slots__ = ("group", "key", "counts_v", "domains", "counts_d", "dom_of_view")

    def __init__(self, group, enc: WarmViewEncoding):
        self.group = group
        self.key = group.key
        if group.key == lbl.LABEL_HOSTNAME:
            self.counts_v = np.array([group.domains.get(h, 0) for h in enc.hostname], dtype=np.int64)
            self.domains = None
            self.counts_d = None
            self.dom_of_view = None
        else:
            self.counts_v = None
            self.domains = list(group.domains.keys())
            index = {d: i for i, d in enumerate(self.domains)}
            self.counts_d = np.array([group.domains[d] for d in self.domains], dtype=np.int64)
            labels = enc.zone if group.key == lbl.LABEL_TOPOLOGY_ZONE else enc.ct
            self.dom_of_view = np.array([index.get(d, -1) if d is not None else -1 for d in labels], dtype=np.int64)

    def bump(self, v: int, n: int) -> None:
        if self.counts_v is not None:
            self.counts_v[v] += n
        else:
            d = self.dom_of_view[v]
            if d >= 0:
                self.counts_d[d] += n

    def record_domain(self, v: int, enc: WarmViewEncoding) -> Optional[str]:
        """The domain string a commit on view v records for this group —
        None when the view lacks the label (record_cohort's single-value
        rule skips those)."""
        if self.key == lbl.LABEL_HOSTNAME:
            return enc.hostname[v]
        labels = enc.zone if self.key == lbl.LABEL_TOPOLOGY_ZONE else enc.ct
        return labels[v]


class _BucketSpec:
    __slots__ = ("bucket", "kind", "accept", "accept_perpod", "checks", "records", "aff", "group_index")

    def __init__(self, bucket, kind, accept, accept_perpod, checks, records, aff, group_index):
        self.bucket = bucket
        self.kind = kind
        self.accept = accept  # [V] bool: closed-form paths (no volume gate)
        self.accept_perpod = accept_perpod  # [V] bool: per-pod paths
        self.checks = checks  # [(op, state, arg)]
        self.records = records  # [_GroupState] bumped per placement
        self.aff = aff  # _GroupState of the zonal affinity group, if any
        self.group_index = group_index


class WarmFillPlan:
    __slots__ = ("enc", "specs", "runs", "sizes", "size_rows", "views", "P")

    def __init__(self, enc, specs, runs, sizes, size_rows, views, P):
        self.enc = enc
        self.specs = specs  # {id(bucket): _BucketSpec}
        self.runs = runs  # [(bucket, sid, rows)] in FFD order
        self.sizes = sizes  # [S, R] f64 distinct run sizes
        self.size_rows = size_rows  # [S] one representative pod row per size
        self.views = views
        self.P = P


def plan(scheduler, problem: DenseProblem, buckets, extra_pods: Sequence = (), enc: Optional[WarmViewEncoding] = None) -> Optional[WarmFillPlan]:
    """Build the vectorized-fill plan, or None when any item falls outside
    the certified common case (the caller then runs the host loop).

    `enc` is an optional precomputed encoding of scheduler.existing_nodes —
    the incremental engine (solver/incremental.py) passes its resident
    mirror, byte-equal to a fresh encode_warm_views(views) by the engine's
    parity contract, so a delta pass skips the O(cluster) encode here."""
    if os.environ.get(NO_VECTOR_ENV):
        return None
    if extra_pods:
        return None  # IR-inexpressible extras interleave by full adds
    views = scheduler.existing_nodes
    if not views:
        return None
    from ..scheduler.existingnode import ExistingNodeView
    from ..scheduler.queue import ffd_sort_key

    live = [b for b in buckets if b.pod_rows]
    for bucket in live:
        if bucket.zone == "__infeasible__" or bucket.single_bin:
            return None

    if enc is None or len(enc.hostname) != len(views):
        enc = encode_warm_views(views)
    V = len(views)
    topology = scheduler.topology
    shared_inverse = topology.inverse_owner_index()
    zone_index = {z: i for i, z in enumerate(problem.zones)}
    ct_index = {c: i for i, c in enumerate(problem.capacity_types)}

    # volume gate for the per-pod paths: pod-independent for volume-free
    # pods (every dense pod — classify routes volume carriers to HOST), so
    # one evaluation per view stands in for the per-pod validate
    rep_any = problem.pods[live[0].pod_rows[0]] if live else None
    vol_ok = np.ones((V,), dtype=bool)
    for vi, view in enumerate(views):
        if rep_any is not None and view.volume_usage.validate(rep_any).exceeds(view.volume_limits):
            vol_ok[vi] = False

    # taint verdicts deduped by (toleration signature, view taint signature):
    # one tolerates() call per distinct pair, one row per toleration shape
    taint_rows: Dict[tuple, np.ndarray] = {}

    def taint_row(rep) -> np.ndarray:
        from ..ir.encode import _toleration_signature

        tol_sig = _toleration_signature(rep)
        row = taint_rows.get(tol_sig)
        if row is None:
            verdicts: Dict[tuple, bool] = {}
            row = np.zeros((V,), dtype=bool)
            for vi in range(V):
                sig = enc.taint_sig[vi]
                ok = verdicts.get(sig)
                if ok is None:
                    ok = verdicts[sig] = views[vi].taints.tolerates(rep) is None
                row[vi] = ok
            taint_rows[tol_sig] = row
        return row

    group_states: Dict[int, _GroupState] = {}

    def state_of(g) -> _GroupState:
        gs = group_states.get(id(g))
        if gs is None:
            gs = group_states[id(g)] = _GroupState(g, enc)
        return gs

    specs: Dict[int, _BucketSpec] = {}
    for bucket in live:
        group = problem.groups[bucket.group_index]
        if group.requirements is not None and list(group.requirements.values()):
            return None  # CohortCert territory: per-(bucket, view) full adds
        rep = group.pods[0]
        ctx = topology.cohort_context(rep, inverse_index=shared_inverse)
        cert = ExistingNodeView.certify_bucket(rep, ctx)
        if cert is None or not cert.portless:
            return None
        # every group that would COUNT this cohort must be one the model
        # tracks (its own certified groups), with a trivial node filter
        owned_ids = {id(g) for g in ctx.owned}
        for g in ctx.selected:
            if id(g) not in owned_ids or g.node_filter.terms:
                return None
        checks: List[tuple] = []
        aff: Optional[_GroupState] = None
        for g in cert.anti_groups:
            if g.key != lbl.LABEL_HOSTNAME:
                return None
            checks.append(("zero", state_of(g), 0))
        for g, _pod_domains, self_sel in cert.spread_checks:
            if not self_sel or g.node_filter.terms:
                return None
            if g.key == lbl.LABEL_HOSTNAME:
                checks.append(("hskew", state_of(g), int(g.max_skew)))
            elif g.key in (lbl.LABEL_TOPOLOGY_ZONE, lbl.LABEL_CAPACITY_TYPE):
                checks.append(("skew", state_of(g), int(g.max_skew)))
            else:
                return None
        for g in cert.affinity_groups:
            if g.key != lbl.LABEL_TOPOLOGY_ZONE or aff is not None:
                return None
            aff = state_of(g)
            checks.append(("aff", aff, 0))
        for g in cert.inverse_groups:
            if g.key != lbl.LABEL_HOSTNAME:
                return None
            checks.append(("zero", state_of(g), 0))

        if bucket.dedicated:
            kind = _DEDICATED
            if not any(op in ("zero", "hskew") for op, _s, _a in checks):
                return None  # a dedicated bucket must carry its per-host rule
        elif bucket.deferred_spread:
            kind = _AFFINITY if group.kind == GroupKind.AFFINITY else _SPREAD
            if kind == _AFFINITY and aff is None:
                return None
            if kind == _AFFINITY and sum(1 for op, _s, _a in checks if op != "aff") > 1:
                # certified: the aff rule plus AT MOST one extra integer
                # rule. The bootstrap round enforces the extra through the
                # same admit()/room_vector algebra the per-pod scans use
                # (execute()'s bootstrap branch); cohorts stacking several
                # extra rules still fail open to the host loop wholesale
                return None
        elif group.kind == GroupKind.PLAIN:
            kind = _PLAIN
        else:
            return None

        accept = enc.usable & taint_row(rep)
        if bucket.zone is not None:
            accept &= np.array([z == bucket.zone for z in enc.zone], dtype=bool)
        if bucket.capacity_type is not None:
            accept &= np.array([c == bucket.capacity_type for c in enc.ct], dtype=bool)
        if kind in (_SPREAD, _AFFINITY):
            # the deferred host branch admits only views whose domain the
            # group allows (problem.group_zone_allowed / group_ct_allowed)
            gi = bucket.group_index
            if kind == _AFFINITY or group.topology_key == lbl.LABEL_TOPOLOGY_ZONE:
                allowed = problem.group_zone_allowed[gi]
                dom = np.array(
                    [zone_index.get(z, -1) if z is not None else -1 for z in enc.zone], dtype=np.int64
                )
            else:
                allowed = problem.group_ct_allowed[gi]
                dom = np.array([ct_index.get(c, -1) if c is not None else -1 for c in enc.ct], dtype=np.int64)
            ok = (dom >= 0) & allowed[np.clip(dom, 0, None)]
            accept &= ok
        accept_perpod = accept & vol_ok
        specs[id(bucket)] = _BucketSpec(
            bucket, kind, accept, accept_perpod, checks, [state_of(g) for g in ctx.selected]
            + [state_of(g) for g in shared_inverse.get(rep.uid, ())], aff, bucket.group_index
        )

    # -- FFD item stream, segmented into same-bucket same-size runs ----------
    # categorization order mirrors _fill_existing exactly (plain, then
    # dedicated, then deferred) so the stable sort breaks FFD ties the same
    plain_b = [b for b in live if not (b.dedicated or b.single_bin or b.deferred_spread)]
    special_b = [b for b in live if b.dedicated or b.single_bin]
    deferred_b = [b for b in live if b.deferred_spread and not b.dedicated]
    ordered_b = plain_b + special_b + deferred_b

    # FFD order, vectorized: the key is (-cpu, -mem, creation, uid) per
    # queue.ffd_sort_key, and problem.requests IS resource_vector(
    # pod_requests(pod)) (encode_problem's per-pod cache), so the first two
    # components read straight off the dense arrays. uid is unique, so the
    # lexsort is a total order — identical to the host queue's sort. The
    # stream stays in (row, bucket-index) arrays end to end; a P-scale list
    # of Python tuples here was a measurable slice of the 16k plan cost.
    if ordered_b:
        rows_arr = np.concatenate([np.asarray(b.pod_rows, dtype=np.int64) for b in ordered_b])
        bidx0 = np.repeat(
            np.arange(len(ordered_b), dtype=np.int64), [len(b.pod_rows) for b in ordered_b]
        )
    else:
        rows_arr = np.zeros((0,), dtype=np.int64)
        bidx0 = rows_arr

    # distinct size classes over the whole batch in one vectorized pass;
    # run boundaries are where (bucket, size) changes along the sorted stream
    if rows_arr.size:
        pods_list = problem.pods
        req_items = problem.requests[rows_arr]
        try:
            ts = np.asarray([pods_list[r].metadata.creation_timestamp for r in rows_arr], dtype=np.float64)
            uid = np.asarray([pods_list[r].metadata.uid for r in rows_arr])
            order = np.lexsort((uid, ts, -req_items[:, 1], -req_items[:, 0]))
        except (TypeError, ValueError):  # exotic metadata types: exact key
            order = np.asarray(
                sorted(range(rows_arr.size), key=lambda i: ffd_sort_key(pods_list[rows_arr[i]])),
                dtype=np.int64,
            )
        rows_sorted = rows_arr[order]
        bidx = bidx0[order]
        flat = np.ascontiguousarray(problem.requests)
        # byte-view row dedupe: ~5x faster than axis=0 unique, and request
        # vectors are canonical non-negative floats (no -0.0/NaN aliasing)
        void = flat.view(np.dtype((np.void, flat.dtype.itemsize * flat.shape[1]))).reshape(-1)
        _uniq, inverse = np.unique(void, return_inverse=True)
        inverse = inverse.reshape(-1)
        sid_of_item = inverse[rows_sorted]
        change = np.ones(rows_sorted.size, dtype=bool)
        change[1:] = (sid_of_item[1:] != sid_of_item[:-1]) | (bidx[1:] != bidx[:-1])
        bounds = np.flatnonzero(change).tolist() + [rows_sorted.size]
        # compact sids to the ones actually used, first-use order
        sid_map: Dict[int, int] = {}
        sizes: List[np.ndarray] = []
        size_rows: List[int] = []
        runs: List[tuple] = []
        for b0, b1 in zip(bounds[:-1], bounds[1:]):
            raw = int(sid_of_item[b0])
            sid = sid_map.get(raw)
            if sid is None:
                sid = sid_map[raw] = len(sizes)
                sizes.append(problem.requests[rows_sorted[b0]])
                size_rows.append(int(rows_sorted[b0]))
            runs.append((ordered_b[int(bidx[b0])], sid, rows_sorted[b0:b1].tolist()))
        sizes_arr = np.stack(sizes)
    else:
        runs, sizes_arr, size_rows = [], np.zeros((0, problem.requests.shape[1])), []

    return WarmFillPlan(enc, specs, runs, sizes_arr, np.asarray(size_rows, dtype=np.int64), list(views), problem.P)


def _device_counts(plan_: WarmFillPlan, solver) -> Optional[np.ndarray]:
    """One [S, V] admission-surface dispatch (Pallas on TPU, jnp elsewhere);
    None on any failure or when the surface exceeds the device bounds —
    the scan then computes exact rows lazily on host."""
    S = plan_.sizes.shape[0]
    V = len(plan_.views)
    if S == 0 or S > _DEVICE_MAX_SIZES or S * V > _DEVICE_MAX_CELLS:
        return None
    try:
        t0 = time.perf_counter()
        # fault-domain injection seam (solver/faults.py): the warm-fill
        # admission surface is a device dispatch boundary like the bucket
        # solve; a planned fault here exercises the prune-on-host fallback
        FAULTS.check("warmfill")
        sizes32 = plan_.sizes.astype(np.float32)
        head_dev = getattr(plan_.enc, "head_dev", None)
        if solver is not None and solver._pallas_enabled():
            from ..ops.warmfill import warm_fill_counts_pallas

            counts = warm_fill_counts_pallas(sizes32, plan_.enc.head0.astype(np.float32))
        elif head_dev is not None:
            # incremental resident surface (solver/incremental.py): the
            # [Vp, R] f32 headroom buffer is already on device — dispatch
            # against it with NO host->device re-upload and strip the pad
            # columns (head -1.0 → base_ok false → count 0, the same dead-row
            # rule as the pallas pad). Values are bit-identical to the fresh
            # head0.astype(f32) path: the kernel is elementwise per (s, v)
            from ..ops.warmfill import warm_fill_counts

            counts = np.asarray(warm_fill_counts(sizes32, head_dev))[:, : len(plan_.views)]
        else:
            from ..ops.warmfill import warm_fill_counts

            counts = np.asarray(warm_fill_counts(sizes32, plan_.enc.head0.astype(np.float32)))
        if solver is not None:
            dt = time.perf_counter() - t0
            solver.stats.device_seconds += dt
            solver.stats.fill_device_seconds += dt
        return counts
    except Exception as exc:  # pruning is an optimization; never break the fill
        fault = classify(exc)
        if fault is not None:
            # a classified device fault on the admission surface: counted
            # into the taxonomy even though the exact host scan absorbs it
            SOLVER_FAULTS.inc(kind=fault.kind)
        log.warning("warm-fill device surface unavailable, pruning on host: %r", exc)
        return None


def execute(scheduler, problem: DenseProblem, buckets, plan_: WarmFillPlan, solver=None) -> Tuple[int, np.ndarray]:
    """Run the exact scan over the plan and commit in bulk. Returns
    (committed, taken[P]) with bucket.pod_rows filtered like the host loop."""
    enc = plan_.enc
    at = enc.avail_tol
    req_v = enc.requests0.copy()
    V = len(plan_.views)
    S = plan_.sizes.shape[0]

    counts_ub = _device_counts(plan_, solver)
    alive = np.zeros((S, V), dtype=bool)
    if counts_ub is not None:
        alive[:] = (counts_ub > 0) & enc.usable[None, :]
    else:
        alive[:] = enc.usable[None, :]
    fresh = np.zeros((S,), dtype=bool)

    def ensure_alive(sid: int) -> None:
        """Exact host refinement of the device surface at a size class's
        first touch: recompute the closed-form count against the CURRENT
        residuals (at - req_v), killing views other cohorts already filled —
        staleness the initial-headroom device surface cannot see. Monotone-
        safe pruning: req_v only grows during the fill, so a zero count now
        can never become positive, and every placement is still re-derived
        exactly by the scan. Inlined count>0 test (head >= size on the
        positive axes, head >= 0 everywhere — identical set to
        warm_fill_counts_np > 0 without its ratio/floor allocations)."""
        if not fresh[sid]:
            s = plan_.sizes[sid]
            head = at - req_v
            ok = (head >= 0).all(axis=1)
            pos = s > 0
            if pos.any():
                ok &= (head[:, pos] >= s[pos]).all(axis=1)
            alive[sid] &= ok
            fresh[sid] = True

    def closed_form(v: int, s: np.ndarray, positive: np.ndarray) -> int:
        head = at[v] - req_v[v]
        if (head < 0).any():
            return 0
        return int((head[positive] // s[positive]).min())

    def admit(spec: _BucketSpec, v: int) -> bool:
        return admit_checks(spec.checks, v)

    def admit_checks(checks, v: int) -> bool:
        for op, gs, arg in checks:
            if op == "zero":
                if gs.counts_v[v] != 0:
                    return False
            elif op == "hskew":
                if gs.counts_v[v] + 1 > arg:  # hostname global min is 0
                    return False
            elif op == "skew":
                d = gs.dom_of_view[v]
                if d < 0 or gs.counts_d[d] + 1 - gs.counts_d.min() > arg:
                    return False
            else:  # affinity: populated-domain membership
                d = gs.dom_of_view[v]
                if d < 0 or gs.counts_d[d] <= 0:
                    return False
        return True

    _BIG = 1 << 30

    def room_vector(spec: _BucketSpec) -> np.ndarray:
        """[V] int: how many pods of this cohort each view admits before an
        INTEGER check vetoes — the per-pod rules of admit() run forward in
        closed form. Only the cohort's own records evolve during a sub-run
        (runs are sequential), so the bound is exact: skew admits until the
        pinned domain reaches (min over other domains) + maxSkew; zero /
        populated checks are static for non-self-bumping cohorts."""
        n = np.full((V,), _BIG, dtype=np.int64)
        for op, gs, arg in spec.checks:
            if op == "zero":
                n = np.where(gs.counts_v == 0, n, 0)
            elif op == "hskew":
                n = np.minimum(n, np.maximum(arg - gs.counts_v, 0))
            elif op == "skew":
                c = gs.counts_d
                if c.size > 1:
                    m = c.min()
                    # min over the OTHER domains: the second-lowest count
                    # when d is the unique minimum, the minimum otherwise
                    m2 = np.partition(c, 1)[1]
                    unique_min = (c == m).sum() == 1
                    m_excl = np.where((c == m) & unique_min, m2, m)
                    room_d = np.maximum(m_excl + arg - c, 0)
                else:
                    room_d = np.full((c.size,), _BIG, dtype=np.int64)
                dom = gs.dom_of_view
                n = np.minimum(n, np.where(dom >= 0, room_d[np.clip(dom, 0, None)], 0))
            else:  # affinity: populated-domain membership, static per sub-run
                pop = gs.counts_d > 0
                dom = gs.dom_of_view
                n = np.where((dom >= 0) & pop[np.clip(dom, 0, None)], n, 0)
        return n

    events: List[tuple] = []  # ("bulk"|"pod", v, spec, rows)
    taken = np.zeros((plan_.P,), dtype=bool)
    committed = 0

    def place(spec: _BucketSpec, v: int, rows: List[int], s: np.ndarray, bulk: bool) -> None:
        nonlocal committed
        n = len(rows)
        if bulk:
            events.append(("bulk", v, spec, rows))
            req_v[v] = req_v[v] + s * n
        else:
            events.append(("pod", v, spec, rows))
            for _ in rows:
                req_v[v] = req_v[v] + s
        for gs in spec.records:
            gs.bump(v, n)
        taken[rows] = True
        committed += n

    def subrun(spec: _BucketSpec, v: int, rows: List[int], i: int, k_adm: int, s: np.ndarray, positive: np.ndarray, sid: int) -> int:
        """Place pods rows[i:] on view v under the per-pod protocol until a
        veto, in one batch: np.add.accumulate applies the same IEEE addition
        sequence as the per-pod merge loop, so the capacity verdicts (and
        the request vector left behind) are bit-identical to placing one
        pod at a time. Marks the view capacity-dead for this size class
        when the stop reason is a capacity veto. Returns pods placed."""
        nonlocal committed
        R = s.shape[0]
        placed = 0
        budget = min(k_adm, len(rows) - i)
        while budget > 0:
            if budget == 1:
                merged = req_v[v] + s
                if (merged <= at[v]).all():
                    chunk_rows = rows[i + placed : i + placed + 1]
                    events.append(("pod", v, spec, chunk_rows))
                    req_v[v] = merged
                    for gs in spec.records:
                        gs.bump(v, 1)
                    taken[chunk_rows] = True
                    committed += 1
                    placed += 1
                else:
                    alive[sid, v] = False  # capacity veto: persistent per size
                return placed
            # bound the prefix allocation by a cheap estimate; the loop
            # extends it in the (rare) case sequential rounding admits more
            est = closed_form(v, s, positive)
            chunk = min(budget, max(est + 2, 1))
            steps = np.empty((chunk + 1, R), np.float64)
            steps[0] = req_v[v]
            steps[1:] = s
            acc = np.add.accumulate(steps, axis=0)
            ok = np.all(acc[1:] <= at[v], axis=1)
            n = chunk if ok.all() else int(np.argmax(~ok))
            if n:
                chunk_rows = rows[i + placed : i + placed + n]
                events.append(("pod", v, spec, chunk_rows))
                req_v[v] = acc[n]
                for gs in spec.records:
                    gs.bump(v, n)
                taken[chunk_rows] = True
                committed += n
                placed += n
                budget -= n
            if n < chunk:
                alive[sid, v] = False  # capacity veto: persistent per size
                return placed
            if chunk == budget:
                return placed
        return placed

    # -- scan-pointer state, persisted across same-(bucket, size) segments --
    # The FFD stream interleaves buckets along the global size order, so one
    # (bucket, size) pair fragments into many short run segments. Every veto
    # the forward scans act on is PERSISTENT for a fixed size class (capacity
    # death: residuals only grow; zero-count claims: group counts only grow),
    # so the scan position survives segment boundaries — without this the
    # per-segment rescans over already-dead view prefixes dominate the fill
    # (the r5 16k flagship's residual host time).
    scan_state: Dict[tuple, dict] = {}

    # the acceptance-masked candidate lists are built ONCE per spec (they
    # are sid-independent), then narrowed to each size class by one
    # vectorized alive[] take at (spec, sid) first touch — a V-wide
    # flatnonzero per (spec, sid) pair here was a top-5 fill cost at
    # 16k/2400, and leaving dead views for the scalar pointers to skip
    # re-pays the prefix per size class
    shared_lists: Dict[tuple, object] = {}

    def order_state(spec: _BucketSpec, sid: int, perpod: bool) -> dict:
        key = (id(spec), sid)
        st = scan_state.get(key)
        if st is None:
            okey = (id(spec), perpod)
            base = shared_lists.get(okey)
            if base is None:
                accept = spec.accept_perpod if perpod else spec.accept
                base = shared_lists[okey] = np.flatnonzero(accept)
            st = scan_state[key] = {"order": base[alive[sid, base]], "p": 0}
        return st

    def dom_state(spec: _BucketSpec, gs: _GroupState, sid: int) -> dict:
        """Per-domain candidate view lists (view-index order): the restart
        discipline reduces to O(domains) head peeks instead of an O(views)
        room recompute per placement."""
        key = (id(spec), sid)
        st = scan_state.get(key)
        if st is None:
            lkey = (id(spec), "doms")
            base = shared_lists.get(lkey)
            if base is None:
                dom = gs.dom_of_view
                base = shared_lists[lkey] = [
                    np.flatnonzero(spec.accept_perpod & (dom == d)) for d in range(gs.counts_d.size)
                ]
            lists = [l[alive[sid, l]] for l in base]
            st = scan_state[key] = {"lists": lists, "ptrs": [0] * gs.counts_d.size}
        return st

    def head_of(lst: np.ndarray, p: int, sid: int) -> Tuple[int, int]:
        """First still-alive view of `lst` at or past p: (view, p'), view -1
        when exhausted. Skipped (dead) views never resurrect for a size."""
        n = lst.size
        while p < n:
            v = int(lst[p])
            if alive[sid, v]:
                return v, p
            p += 1
        return -1, p

    pos_cache: Dict[int, np.ndarray] = {}
    for bucket, sid, rows in plan_.runs:
        spec = plan_.specs[id(bucket)]
        s = plan_.sizes[sid]
        positive = pos_cache.get(sid)
        if positive is None:
            positive = pos_cache[sid] = s > 0
        ensure_alive(sid)
        if spec.kind == _PLAIN and not spec.checks:
            # certified capacity-only cohort: the closed-form branch of
            # add_certified_view_run, one forward scan, bulk sub-runs. The
            # pointer stays ON a view that still had room when the segment's
            # rows ran out: the next segment re-derives its residual count
            # exactly, so a pathological-rounding leftover is never skipped.
            st = order_state(spec, sid, perpod=False)
            order, p = st["order"], st["p"]
            i = 0
            while i < len(rows) and p < order.size:
                v = int(order[p])
                if not alive[sid, v]:
                    p += 1
                    continue
                n = closed_form(v, s, positive)
                if n <= 0:
                    alive[sid, v] = False
                    p += 1
                    continue
                take = min(n, len(rows) - i)
                place(spec, v, rows[i : i + take], s, bulk=True)
                i += take
            st["p"] = p
        elif spec.kind == _PLAIN:
            # plain cohort vetoed-per-host by an inverse anti-affinity
            # selection: the host runs add_certified_view per pod, forward
            # scan, never restarting (every veto is persistent here)
            st = order_state(spec, sid, perpod=True)
            order, p = st["order"], st["p"]
            i = 0
            while i < len(rows) and p < order.size:
                v = int(order[p])
                if not alive[sid, v]:
                    p += 1
                    continue
                if ((req_v[v] + s) > at[v]).any():
                    alive[sid, v] = False
                    p += 1
                    continue
                if not admit(spec, v):
                    p += 1
                    continue
                place(spec, v, [rows[i]], s, bulk=False)
                i += 1
            st["p"] = p
        elif spec.kind == _DEDICATED:
            st = order_state(spec, sid, perpod=True)
            order, p = st["order"], st["p"]
            for row in rows:
                placed = False
                while p < order.size:
                    v = int(order[p])
                    if not alive[sid, v]:
                        p += 1
                        continue
                    if ((req_v[v] + s) > at[v]).any():
                        alive[sid, v] = False
                        p += 1
                        continue
                    if not admit(spec, v):
                        p += 1
                        continue
                    place(spec, v, [row], s, bulk=False)
                    # advance only once the view stops admitting: a zero-
                    # count claim shuts the host immediately, but hostname
                    # spread with maxSkew >= 2 admits up to maxSkew pods per
                    # host and the host loop would land the next pod right
                    # back here (hskew counts are monotone, so every veto
                    # the pointer acts on stays persistent either way)
                    if not admit(spec, v):
                        p += 1
                    placed = True
                    break
                if not placed:
                    break
            st["p"] = p
        else:  # _SPREAD / _AFFINITY
            i = 0
            if spec.kind == _AFFINITY and not spec.aff.counts_d.any():
                # bootstrap: the full add pins the cohort to the first
                # accepting view's zone, then the certified run sweeps the
                # remainder of the run onto it in closed form. At most once
                # per cohort — populated counts never return to zero.
                # The certified single extra rule (plan() admits at most
                # one non-aff check) gates the boot view through the same
                # admit algebra and caps the sweep by room_vector — both
                # exact closed forms of the per-pod protocol, so a skipped
                # view stays skipped (zero/hskew counts and residuals are
                # monotone) and the remainder falls to the generic scan.
                gs = spec.aff
                extras = [c for c in spec.checks if c[0] != "aff"]
                boot = -1
                for v in np.flatnonzero(spec.accept_perpod & alive[sid]):
                    v = int(v)
                    if gs.dom_of_view[v] < 0:
                        continue  # zone outside the group: full add vetoes
                    if extras and not admit_checks(extras, v):
                        continue  # the extra integer rule vetoes this host
                    if ((req_v[v] + s) > at[v]).any():
                        alive[sid, v] = False
                        continue
                    boot = v
                    break
                if boot < 0:
                    continue  # nothing can host the cohort: rows stay
                place(spec, boot, [rows[i]], s, bulk=False)
                i += 1
                n = min(closed_form(boot, s, positive), len(rows) - i)
                if extras:
                    n = min(n, int(room_vector(spec)[boot]))
                if n > 0:
                    place(spec, boot, rows[i : i + n], s, bulk=True)
                    i += n
            single = spec.checks[0] if len(spec.checks) == 1 else None
            if single is not None and single[0] in ("skew", "aff") and single[1].dom_of_view is not None:
                # deferred spread / post-bootstrap affinity, single domain-
                # keyed rule: the restart-from-view-0 discipline (skew
                # admission is not monotone) via per-domain head pointers.
                # Identical placements to the room_vector scan — the first
                # admitted view is the min-index head among domains with
                # room — but the recurrence runs on PYTHON INTS with each
                # head view's exact capacity prefix computed ONCE per run
                # (np.add.accumulate: the same IEEE addition sequence as the
                # per-pod merge loop, so req_v lands bit-identical). Skew-1
                # spread admits ~1 pod per restart, and a numpy partition +
                # per-pod merge per restart was the dominant scan cost.
                op, gs, arg = single
                st = dom_state(spec, gs, sid)
                lists, ptrs = st["lists"], st["ptrs"]
                D = gs.counts_d.size
                # head-view and capacity caches persist ACROSS run segments
                # (the FFD stream fragments one (bucket, size) pair into
                # thousands of 1-2 pod segments at 16k — per-segment rebuilds
                # were the dominant scan cost). A cached capacity entry is
                # valid only while req_v[v] still equals the acc row we left
                # (another cohort touching the view invalidates it), checked
                # per reuse; cached heads re-verify alive[].
                heads = st.setdefault("heads", [None] * D)
                caps = st.setdefault("caps", {})  # v -> [acc, k, taken_n, cap_hit]

                def view_capacity(v: int, max_n: int) -> list:
                    """acc[n] = req_v[v] after n sequential adds of s; k the
                    max prefix with acc[n] <= at[v] elementwise; cap_hit
                    False when k is the rows bound, not a capacity stop."""
                    R = s.shape[0]
                    n_try = min(max_n, max(closed_form(v, s, positive) + 2, 1))
                    while True:
                        steps = np.empty((n_try + 1, R), np.float64)
                        steps[0] = req_v[v]
                        steps[1:] = s
                        acc = np.add.accumulate(steps, axis=0)
                        ok = np.all(acc[1:] <= at[v], axis=1)
                        if ok.all():
                            if n_try >= max_n:
                                return [acc, n_try, 0, False]
                            n_try = min(max_n, n_try * 2 + 2)  # rare rounding extension
                            continue
                        return [acc, int(np.argmax(~ok)), 0, True]

                while i < len(rows):
                    cvals = gs.counts_d
                    if op == "skew" and D > 1:
                        srt = sorted(int(x) for x in cvals)  # D is small
                        m1, m2 = srt[0], srt[1]
                        unique_min = srt.count(m1) == 1
                    best_v, best_d, best_room = -1, -1, 0
                    for d in range(D):
                        if op == "aff":
                            room = _BIG if int(cvals[d]) > 0 else 0
                        elif D > 1:
                            cd = int(cvals[d])
                            m_excl = m2 if (cd == m1 and unique_min) else m1
                            room = m_excl + arg - cd
                        else:
                            room = _BIG
                        if room <= 0:
                            continue
                        v = heads[d]
                        if v is None or (v >= 0 and not alive[sid, v]):
                            v, ptrs[d] = head_of(lists[d], ptrs[d], sid)
                            heads[d] = v
                        if v < 0:
                            continue
                        if best_v < 0 or v < best_v:
                            best_v, best_d, best_room = v, d, room
                    if best_v < 0:
                        break
                    entry = caps.get(best_v)
                    if entry is not None and not np.array_equal(entry[0][entry[2]], req_v[best_v]):
                        entry = None  # another cohort touched the view: stale
                    if entry is not None and entry[1] - entry[2] <= 0 and not entry[3]:
                        entry = None  # rows-bound entry exhausted: extend fresh
                    if entry is None:
                        entry = caps[best_v] = view_capacity(best_v, len(rows) - i)
                    acc, k, taken_n, cap_hit = entry
                    if k - taken_n <= 0:
                        alive[sid, best_v] = False  # capacity-dead: monotone-safe
                        heads[best_d] = None
                        continue
                    take = min(best_room, k - taken_n, len(rows) - i)
                    chunk_rows = rows[i : i + take]
                    events.append(("pod", best_v, spec, chunk_rows))
                    entry[2] = taken_n + take
                    req_v[best_v] = acc[entry[2]]
                    for gsr in spec.records:
                        gsr.bump(best_v, take)
                    taken[chunk_rows] = True
                    committed += take
                    i += take
                    if entry[2] == k and cap_hit:
                        alive[sid, best_v] = False
                        heads[best_d] = None
            elif single is not None and single[0] == "hskew":
                # hostname spread: per-view counts, monotone room — one
                # forward pointer reproduces the restart discipline exactly
                op, gs, arg = single
                st = order_state(spec, sid, perpod=True)
                order, p = st["order"], st["p"]
                while i < len(rows) and p < order.size:
                    v = int(order[p])
                    if not alive[sid, v] or gs.counts_v[v] >= arg:
                        p += 1
                        continue
                    i += subrun(spec, v, rows, i, int(arg - gs.counts_v[v]), s, positive, sid)
                st["p"] = p
            else:
                # combined constraints (e.g. zonal + hostname spread on one
                # cohort): the generic restart scan
                while i < len(rows):
                    room = room_vector(spec)
                    progressed = False
                    for v in np.flatnonzero(spec.accept_perpod & alive[sid] & (room > 0)):
                        v = int(v)
                        n = subrun(spec, v, rows, i, int(room[v]), s, positive, sid)
                        if n:
                            i += n
                            progressed = True
                            break
                    if not progressed:
                        break

    _apply(problem, plan_, events)
    for bucket in buckets:
        if bucket.pod_rows:
            bucket.pod_rows = [r for r in bucket.pod_rows if not taken[r]]
    return committed, taken


def _apply(problem: DenseProblem, plan_: WarmFillPlan, events: List[tuple]) -> None:
    """Make the scan's placements real with the same mutation sequence the
    certified paths issue: per sub-run one requests merge (closed form) or
    per-pod merges, pods appended in event order, and one record call per
    (group, domain, count)."""
    enc = plan_.enc
    views = plan_.views
    for kind, v, spec, rows in events:
        view = views[v]
        pods = [problem.pods[r] for r in rows]
        n = len(pods)
        if kind == "bulk":
            size = res.pod_requests(pods[0])
            view.pods.extend(pods)
            view.requests = res.merge(view.requests, {name: value * n for name, value in size.items()})
        else:
            # no host_port_usage/volume_usage adds: classify (ir/encode.py)
            # routes every volume- or host-port-carrying pod to the HOST
            # path, so for dense pods both adds are no-ops by construction.
            # The merge is inlined (dict copy + in-place adds) — same float
            # additions in the same order as res.merge, without its
            # rebuild-from-empty overhead at one call per pod.
            for pod in pods:
                view.pods.append(pod)
                nxt = dict(view.requests)
                for name, value in res.pod_requests(pod).items():
                    nxt[name] = nxt.get(name, 0.0) + value
                view.requests = nxt
        for gs in spec.records:
            domain = gs.record_domain(v, enc)
            if domain is not None:
                gs.group.record(domain, count=n)
