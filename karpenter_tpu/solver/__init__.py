from .dense import DenseSolver, DenseSolveStats

__all__ = ["DenseSolver", "DenseSolveStats"]
