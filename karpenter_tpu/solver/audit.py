"""Residency integrity auditor: continuous divergence detection + auto-heal.

PR 16's incremental engine introduced the repo's first LONG-LIVED state: a
host mirror of the warm-view encoding plus a donated device buffer that
survive across provision passes. Every prior fault domain protects a
stateless solve — a missed DeltaJournal record, a donation-aliasing bug, or
a silent device corruption now compounds into every future placement, and
nothing would notice until placements drift. This module is the integrity
domain for that state: on a configurable cadence it re-derives a bounded
sample of the truth (re-encoding view rows straight from cluster state, the
same f64 expressions as the fresh path) and compares it against everything
the engine holds resident.

Four divergence kinds, each a distinct failure shape:

  row-drift       a resident host-mirror row disagrees with a fresh encode
                  of the same view and the world did NOT move under it —
                  the mirror itself was damaged (bit flip, aliasing bug,
                  a splice that copied the wrong row);
  missed-delta    the world moved (the row's truth changed since the last
                  audit) but the DeltaJournal never named the node, so the
                  engine kept serving the stale row — the lost-journal-
                  record shape the double-window rule cannot heal;
  device-corrupt  the resident device buffer's sampled rows disagree with
                  the host mirror's f32 projection (they are byte-equal by
                  construction: _upload writes f32(head0), every rebase
                  scatters f32 recomputes) — the donated buffer rotted;
  cube-stale      the dense solver's cached availability cube no longer
                  matches the host availability array it was built from.

Audit shape discipline: the per-audit sample is a SEEDED bounded draw
(`sample_rows`, deterministic in (seed, audit index)) whose device gather
rides the same pow2 ladder as the rebase kernel (`ops/rebase.pad_dirty`),
so steady-state audits never recompile; every `shadow_every`-th audit
upgrades to a FULL shadow encode when the cluster fits the byte budget
(`shadow_budget_bytes`), which is also the end-state parity witness the
residency chaos scenario settles on.

Divergence ⇒ `karpenter_solver_residency_divergences_total{kind}`, a
`residency-divergence` capsule trigger (detail carries the divergence kinds
and row count — row NAMES are process-relative and would break the
cross-transport fingerprint witness; the full row list rides
/debug/residency and the capsule's journal block), and AUTO-HEAL: the
engine's residency is invalidated with reason 'audit', so the next pass is
the existing byte-equal full re-encode — zero lost pods by construction.
The caller additionally discards the audited pass's encoding (the fresh
path re-derives it), so a corrupted mirror never shapes a placement.

Singleton discipline matches TRACER/FLIGHT: process-wide `AUDITOR`, true
no-op when disabled (one attribute read at the hook), clock-seam timed
stamps, `@guarded_by` under a witnessed `solver.audit` lock, and a
/debug/residency route in routes()/route_descriptions() lockstep.
"""

from __future__ import annotations

import hashlib
import json
import random
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..analysis.guards import guarded_by
from ..analysis.witness import WITNESS
from ..capsule import CAPSULE, TRIGGER_RESIDENCY
from ..logsetup import get_logger
from ..metrics import REGISTRY
from ..utils.clock import Clock

log = get_logger("solver.audit")

# -- divergence taxonomy ------------------------------------------------------

KIND_ROW_DRIFT = "row-drift"
KIND_MISSED_DELTA = "missed-delta"
KIND_CUBE_STALE = "cube-stale"
KIND_DEVICE_CORRUPT = "device-corrupt"
DIVERGENCE_KINDS = (KIND_ROW_DRIFT, KIND_MISSED_DELTA, KIND_CUBE_STALE, KIND_DEVICE_CORRUPT)

# the encoded fields one audited row compares; the digest below covers all
# of them, so ANY damaged field diverges the row
ROW_FIELDS = ("usable", "avail_tol", "requests0", "head0", "zone", "ct", "hostname", "taint_sig")

# approximate bytes one shadow-encoded row costs (three [R] f64 arrays plus
# the identity lists) — the budget arithmetic only needs the right order of
# magnitude to keep a 16k-view shadow from landing on every audit
SHADOW_ROW_BYTES = 256

DEFAULT_SAMPLE_ROWS = 8
DEFAULT_SHADOW_EVERY = 8
DEFAULT_SHADOW_BUDGET_BYTES = 16 * 2**20

# registered at import so gen_docs sees the families without a live auditor
RESIDENCY_DIVERGENCES = REGISTRY.counter(
    "karpenter_solver_residency_divergences_total",
    "Resident-state divergences the residency auditor detected, by kind:"
    " 'row-drift' (host mirror row damaged), 'missed-delta' (truth moved but"
    " the DeltaJournal never named the node), 'cube-stale' (cached"
    " availability cube disagrees with its host source), 'device-corrupt'"
    " (resident device buffer disagrees with the mirror's f32 projection).",
    ("kind",),
)
RESIDENCY_HEALS = REGISTRY.counter(
    "karpenter_solver_residency_heals_total",
    "Auto-heals the residency auditor issued: audits that found at least one"
    " divergence, invalidated the engine's resident state (reason 'audit'),"
    " and discarded the audited pass's encoding so the fresh full re-encode"
    " path owns the next placement.",
)
AUDIT_PASSES = REGISTRY.counter(
    "karpenter_solver_residency_audit_passes_total",
    "Residency audits executed (cadenced provision passes that re-encoded a"
    " seeded row sample — or a full shadow — from cluster truth and compared"
    " it against the engine's resident state).",
)


def divergences_total() -> int:
    """Sum of the divergence counter across kinds (score surface)."""
    return int(sum(RESIDENCY_DIVERGENCES.values().values()))


def heals_total() -> int:
    return int(RESIDENCY_HEALS.value())


def audit_passes_total() -> int:
    return int(AUDIT_PASSES.value())


def _row_digest(enc, i: int) -> str:
    """16-hex digest over every encoded field of row `i` — the unit of
    truth/mirror comparison. f64 bytes are hashed raw, so the digest is
    exact, not tolerance-based (encode_warm_views is deterministic and
    row-independent; byte equality is the pinned contract)."""
    h = hashlib.sha256()
    h.update(b"1" if bool(enc.usable[i]) else b"0")
    h.update(np.ascontiguousarray(enc.avail_tol[i], dtype=np.float64).tobytes())
    h.update(np.ascontiguousarray(enc.requests0[i], dtype=np.float64).tobytes())
    h.update(np.ascontiguousarray(enc.head0[i], dtype=np.float64).tobytes())
    h.update(repr((enc.zone[i], enc.ct[i], enc.hostname[i], tuple(enc.taint_sig[i]))).encode("utf-8"))
    return h.hexdigest()[:16]


def _differing_fields(fresh, j: int, mirror, i: int) -> List[str]:
    """Which encoded fields disagree between fresh row j and mirror row i —
    divergence-detail only, never on the clean path."""
    out = []
    if bool(fresh.usable[j]) != bool(mirror.usable[i]):
        out.append("usable")
    for name in ("avail_tol", "requests0", "head0"):
        if not np.array_equal(getattr(fresh, name)[j], getattr(mirror, name)[i]):
            out.append(name)
    if fresh.zone[j] != mirror.zone[i]:
        out.append("zone")
    if fresh.ct[j] != mirror.ct[i]:
        out.append("ct")
    if fresh.hostname[j] != mirror.hostname[i]:
        out.append("hostname")
    if tuple(fresh.taint_sig[j]) != tuple(mirror.taint_sig[i]):
        out.append("taint_sig")
    return out


@guarded_by(
    "_lock",
    "_passes",
    "_audits",
    "_heals",
    "_divergences",
    "_truth_digest",
    "_last_epoch",
    "_last_divergence",
    "_clean_streak",
)
class ResidencyAuditor:
    """The process-wide resident-state integrity auditor (the TRACER/FLIGHT
    singleton pattern). DenseSolver consults `maybe_audit` once per real
    provision pass, right after the engine advances and before the warm fill
    consumes the encoding — the one point where the resident state, the
    caller's view snapshot, and the journal checkpoint all describe the same
    instant, so an exact byte comparison carries no concurrency false
    positives (views are per-solve snapshots; ExistingNodeView copies its
    state)."""

    def __init__(self):
        self._lock = WITNESS.lock("solver.audit")
        self.enabled = False
        self.interval = 0  # audit every Nth eligible pass; 0 = never
        self.sample_rows = DEFAULT_SAMPLE_ROWS
        self.shadow_every = DEFAULT_SHADOW_EVERY
        self.shadow_budget_bytes = DEFAULT_SHADOW_BUDGET_BYTES
        self.seed = 0
        self.clock: Clock = Clock()
        self._passes = 0
        self._audits = 0
        self._heals = 0
        self._divergences: Dict[str, int] = {}
        # last observed TRUTH digest per audited row: the classifier's
        # memory — a divergent row whose truth moved since its last audit
        # without the journal naming the node is a missed delta, not drift
        self._truth_digest: Dict[str, str] = {}
        # journal epoch at the end of the previous audit: the window
        # `dirty_since` answers the classifier over
        self._last_epoch = 0
        self._last_divergence: Optional[dict] = None
        # consecutive clean audits since the last divergence — >=1 is the
        # end-state parity witness the residency storm settles on (a clean
        # full shadow means any solve from this state is byte-identical to
        # a fresh solver's)
        self._clean_streak = 0

    # -- lifecycle -----------------------------------------------------------

    def enable(
        self,
        interval: Optional[int] = None,
        sample_rows: Optional[int] = None,
        shadow_every: Optional[int] = None,
        shadow_budget_bytes: Optional[int] = None,
        seed: Optional[int] = None,
        clock: Optional[Clock] = None,
    ) -> None:
        """Arm the auditor; None keeps a knob's current value, so a Runtime
        restart re-wiring interval+clock does not clobber a harness's
        shadow cadence (the BREAKER.configure merge discipline)."""
        if WITNESS.enabled and isinstance(self._lock, __import__("threading").Lock().__class__):
            # constructed before the witness came up: adopt a witnessed lock
            # (enable runs at Runtime assembly, before any solve holds it)
            self._lock = WITNESS.lock("solver.audit")
        if interval is not None:
            self.interval = max(0, int(interval))
        if sample_rows is not None:
            self.sample_rows = max(1, int(sample_rows))
        if shadow_every is not None:
            self.shadow_every = max(1, int(shadow_every))
        if shadow_budget_bytes is not None:
            self.shadow_budget_bytes = max(0, int(shadow_budget_bytes))
        if seed is not None:
            self.seed = int(seed)
        if clock is not None:
            self.clock = clock
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        """Drop per-run audit state (cadence counters, digest memory, last
        divergence). The monotonic metric families survive — campaign
        consumers score deltas, the counter discipline every other
        singleton follows."""
        with self._lock:
            self._passes = 0
            self._audits = 0
            self._heals = 0
            self._divergences = {}
            self._truth_digest = {}
            self._last_epoch = 0
            self._last_divergence = None
            self._clean_streak = 0

    # -- the per-pass hook (dense.py) ----------------------------------------

    def maybe_audit(self, engine, views: Sequence, cube_host=None, cube_dev=None) -> Optional[dict]:
        """Cadence gate + audit. Returns None when no audit ran or the audit
        was clean; on divergence returns a report dict (kinds, rows,
        cube_stale) AFTER healing the engine (invalidate reason 'audit') —
        the caller must then discard the pass's encoding and, on
        cube_stale, its cube cache."""
        if not self.enabled or self.interval <= 0:
            return None
        res = getattr(engine, "_resident", None)
        if res is None or not views:
            return None
        with self._lock:
            self._passes += 1
            due = self._passes % self.interval == 0
            audit_index = self._audits
        if not due:
            return None
        # the mirror's row identity must match the caller's snapshot
        # exactly (advance() just committed against these views); anything
        # else means the engine is mid-transition — skip, never guess
        names = [v.node.name for v in views]
        if res.names != names:
            return None
        t0 = time.perf_counter()
        report = self._audit(engine, res, views, names, audit_index, cube_host, cube_dev)
        AUDIT_PASSES.inc()
        dt = time.perf_counter() - t0
        if report is not None:
            log.warning(
                "residency divergence: kinds=%s rows=%s (audit #%d, %.1fms) — healing via full re-encode",
                report["kinds"], report["rows"], audit_index, dt * 1000.0,
            )
        return report

    def _audit(
        self,
        engine,
        res,
        views: Sequence,
        names: List[str],
        audit_index: int,
        cube_host,
        cube_dev,
    ) -> Optional[dict]:
        from ..ir.encode import encode_warm_views

        V = len(views)
        # sample selection: a full shadow when the cadence says so and the
        # cluster fits the byte budget, else the seeded bounded draw (the
        # draw is a pure function of (seed, audit index) — deterministic,
        # and it walks the whole cluster over successive audits)
        shadow = (
            audit_index % self.shadow_every == 0
            and V * SHADOW_ROW_BYTES <= self.shadow_budget_bytes
        )
        if shadow or V <= self.sample_rows:
            idx = list(range(V))
        else:
            rng = random.Random((self.seed, audit_index))
            idx = sorted(rng.sample(range(V), self.sample_rows))

        # truth: re-encode the sampled views with the exact fresh-path
        # expressions (encode_warm_views is row-independent, so sub-row j
        # is byte-identical to full-encode row idx[j])
        fresh = encode_warm_views([views[i] for i in idx])

        findings: List[dict] = []  # {"row": name, "kind": ..., "fields": [...]}
        mirror = res.enc
        fresh_digests: Dict[str, str] = {}
        with self._lock:
            window = engine.journal.dirty_since(self._last_epoch)
            for j, i in enumerate(idx):
                name = names[i]
                truth_digest = _row_digest(fresh, j)
                fresh_digests[name] = truth_digest
                if truth_digest == _row_digest(mirror, i):
                    continue
                prior = self._truth_digest.get(name)
                # classification: the journal window since the previous
                # audit is the engine's only knowledge of motion — truth
                # that moved OUTSIDE it is a record the journal lost
                if prior is not None and prior != truth_digest and window is not None and name not in window:
                    kind = KIND_MISSED_DELTA
                else:
                    kind = KIND_ROW_DRIFT
                findings.append({"row": name, "kind": kind, "fields": _differing_fields(fresh, j, mirror, i)})

        # device residency: the sampled buffer rows must equal the mirror's
        # f32 projection byte-for-byte (inductively true: _upload writes
        # f32(head0) and every rebase scatters f32 recomputes). The gather
        # index pads to the resident buffer's OWN row pad — not the pow2
        # dirty ladder — so sampled audits and full shadows share one
        # compiled gather shape per buffer shape: a fresh gather compile can
        # only coincide with a views-pad change, which the solve signature
        # attributes to a contract-declared varying axis (a row-count pad
        # crossing a pow2 bucket mid-soak would otherwise read as a
        # steady-state recompile on the first transport leg only).
        device_rows: List[str] = []
        if res.head_dev is not None:
            try:
                import jax.numpy as jnp

                from ..ops.rebase import gather_rows, pack_gather

                idx_p = pack_gather(np.asarray(idx, dtype=np.int32), pad=int(res.head_dev.shape[0]))
                got = np.asarray(gather_rows(res.head_dev, jnp.asarray(idx_p)))[: len(idx)]
                want = mirror.head0[idx].astype(np.float32)
                if not np.array_equal(got, want):
                    bad = np.nonzero(~np.all(got == want, axis=1))[0]
                    device_rows = [names[idx[int(b)]] for b in bad]
            except Exception as exc:  # noqa: BLE001 - the audit must never fail a solve
                log.warning("residency device audit unavailable this pass: %r", exc)

        # availability cube: dense's cached device cube vs the host array
        # it was derived from (same reshape+cast the cache performs)
        cube_stale = False
        if cube_host is not None and cube_dev is not None:
            try:
                want_cube = np.ascontiguousarray(cube_host).reshape(cube_host.shape[0], -1).astype(np.float32)
                got_cube = np.asarray(cube_dev)
                cube_stale = got_cube.shape != want_cube.shape or not np.array_equal(got_cube, want_cube)
            except Exception as exc:  # noqa: BLE001
                log.warning("residency cube audit unavailable this pass: %r", exc)

        kinds = [f["kind"] for f in findings] + [KIND_DEVICE_CORRUPT] * len(device_rows)
        if cube_stale:
            kinds.append(KIND_CUBE_STALE)
        row_names = sorted({f["row"] for f in findings} | set(device_rows))

        with self._lock:
            self._audits += 1
            self._truth_digest.update(fresh_digests)
            self._last_epoch = engine.journal.current_epoch()
            if not kinds:
                self._clean_streak += 1
                return None
            self._clean_streak = 0
            for kind in kinds:
                self._divergences[kind] = self._divergences.get(kind, 0) + 1
                RESIDENCY_DIVERGENCES.inc(kind=kind)
            self._heals += 1
            self._last_divergence = {
                "t": self.clock.now(),
                "audit": audit_index,
                "rows": row_names,
                "kinds": sorted(set(kinds)),
                "findings": findings + [{"row": n, "kind": KIND_DEVICE_CORRUPT, "fields": ["head_dev"]} for n in device_rows],
                "cube_stale": cube_stale,
                "journal_window": sorted(window) if window is not None else None,
                "shadow": shadow,
            }
            # capsule detail carries only transport-stable fields (kinds +
            # counts): row names embed process-relative instance counters
            # and would break the byte-identical-fingerprint witness
            if CAPSULE.enabled:
                CAPSULE.trigger(TRIGGER_RESIDENCY, kinds=sorted(set(kinds)), rows=len(row_names))
        # heal OUTSIDE the audit lock: invalidate is two attribute writes on
        # the single-threaded engine, but keeping it out preserves the
        # audit lock as a leaf
        RESIDENCY_HEALS.inc()
        engine.invalidate("audit")
        return {"kinds": sorted(set(kinds)), "rows": row_names, "cube_stale": cube_stale}

    # -- read surfaces -------------------------------------------------------

    def clean_streak(self) -> int:
        with self._lock:
            return self._clean_streak

    def stats(self) -> dict:
        """The /debug/residency index document."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "interval": self.interval,
                "sample_rows": self.sample_rows,
                "shadow_every": self.shadow_every,
                "shadow_budget_bytes": self.shadow_budget_bytes,
                "passes_seen": self._passes,
                "audits": self._audits,
                "divergences": dict(sorted(self._divergences.items())),
                "heals": self._heals,
                "clean_streak": self._clean_streak,
                "rows_tracked": len(self._truth_digest),
                "last_divergence": json.loads(json.dumps(self._last_divergence)),
            }

    def row_detail(self, name: str) -> Optional[dict]:
        """Per-row shadow state for ?row= queries; None when the row was
        never audited."""
        with self._lock:
            digest = self._truth_digest.get(name)
            if digest is None:
                return None
            return {"row": name, "truth_digest": digest, "audits": self._audits}


AUDITOR = ResidencyAuditor()


def enabled() -> bool:
    return AUDITOR.enabled


# -- HTTP route (ObservabilityServer extra routes) ----------------------------


def _json(status, payload) -> tuple:
    return status, "application/json; charset=utf-8", json.dumps(payload) + "\n"


def _residency_route(query: dict) -> tuple:
    raw = (query.get("row") or [None])[0]
    if raw is None:
        return _json(200, AUDITOR.stats())
    detail = AUDITOR.row_detail(raw)
    if detail is None:
        return _json(404, {"error": f"row {raw!r} has never been audited", "status": 404})
    return _json(200, detail)


def routes() -> dict:
    """The residency-auditor read surface, served from the metrics listener
    (cmd/controller.py wires it behind --residency-audit-interval)."""
    return {"/debug/residency": _residency_route}


def route_descriptions() -> dict:
    """/debug-index descriptions, keyed like routes() (see tracing.py)."""
    return {
        "/debug/residency": "residency auditor: audit cadence/counters, divergences by kind, heal count, last divergence detail; ?row= per-row shadow digest",
    }
