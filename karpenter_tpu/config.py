"""Live-tunable global configuration (pkg/config).

The reference watches a `karpenter-global-settings` ConfigMap for batch
window tuning with change-handler fan-out; here the Config object is directly
mutable with the same change-notification contract.
"""

from __future__ import annotations

import threading
from typing import Callable, List

DEFAULT_BATCH_MAX_DURATION = 10.0
DEFAULT_BATCH_IDLE_DURATION = 1.0
DEFAULT_LOG_LEVEL = "info"


class Config:
    def __init__(
        self,
        batch_max_duration: float = DEFAULT_BATCH_MAX_DURATION,
        batch_idle_duration: float = DEFAULT_BATCH_IDLE_DURATION,
        log_level: str = DEFAULT_LOG_LEVEL,
    ):
        self._lock = threading.Lock()
        self._batch_max_duration = batch_max_duration
        self._batch_idle_duration = batch_idle_duration
        self._log_level = log_level
        self._handlers: List[Callable[["Config"], None]] = []

    @property
    def batch_max_duration(self) -> float:
        with self._lock:
            return self._batch_max_duration

    @property
    def batch_idle_duration(self) -> float:
        with self._lock:
            return self._batch_idle_duration

    @property
    def log_level(self) -> str:
        with self._lock:
            return self._log_level

    def on_change(self, handler: Callable[["Config"], None]) -> None:
        with self._lock:
            self._handlers.append(handler)

    def update(self, batch_max_duration=None, batch_idle_duration=None, log_level=None) -> None:
        changed = False
        with self._lock:
            if batch_max_duration is not None and batch_max_duration != self._batch_max_duration:
                self._batch_max_duration = batch_max_duration
                changed = True
            if batch_idle_duration is not None and batch_idle_duration != self._batch_idle_duration:
                self._batch_idle_duration = batch_idle_duration
                changed = True
            if log_level is not None and log_level != self._log_level:
                self._log_level = log_level
                changed = True
            handlers = list(self._handlers)
        if changed:
            for handler in handlers:
                handler(self)
