"""Live-tunable global configuration (pkg/config).

The reference watches a `karpenter-global-settings` ConfigMap for batch
window tuning with change-handler fan-out; here the Config object is directly
mutable with the same change-notification contract.
"""

from __future__ import annotations

import threading
from typing import Callable, List

DEFAULT_BATCH_MAX_DURATION = 10.0
DEFAULT_BATCH_IDLE_DURATION = 1.0


class Config:
    def __init__(self, batch_max_duration: float = DEFAULT_BATCH_MAX_DURATION, batch_idle_duration: float = DEFAULT_BATCH_IDLE_DURATION):
        self._lock = threading.Lock()
        self._batch_max_duration = batch_max_duration
        self._batch_idle_duration = batch_idle_duration
        self._handlers: List[Callable[["Config"], None]] = []

    @property
    def batch_max_duration(self) -> float:
        with self._lock:
            return self._batch_max_duration

    @property
    def batch_idle_duration(self) -> float:
        with self._lock:
            return self._batch_idle_duration

    def on_change(self, handler: Callable[["Config"], None]) -> None:
        with self._lock:
            self._handlers.append(handler)

    def update(self, batch_max_duration=None, batch_idle_duration=None) -> None:
        changed = False
        with self._lock:
            if batch_max_duration is not None and batch_max_duration != self._batch_max_duration:
                self._batch_max_duration = batch_max_duration
                changed = True
            if batch_idle_duration is not None and batch_idle_duration != self._batch_idle_duration:
                self._batch_idle_duration = batch_idle_duration
                changed = True
            handlers = list(self._handlers)
        if changed:
            for handler in handlers:
                handler(self)
