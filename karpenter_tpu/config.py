"""Live-tunable global configuration (pkg/config).

The reference watches a `karpenter-global-settings` ConfigMap for batch
window tuning with change-handler fan-out; here the Config object is directly
mutable with the same change-notification contract.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

DEFAULT_BATCH_MAX_DURATION = 10.0
DEFAULT_BATCH_IDLE_DURATION = 1.0
DEFAULT_LOG_LEVEL = "info"


class Config:
    def __init__(
        self,
        batch_max_duration: float = DEFAULT_BATCH_MAX_DURATION,
        batch_idle_duration: float = DEFAULT_BATCH_IDLE_DURATION,
        log_level: str = DEFAULT_LOG_LEVEL,
    ):
        self._lock = threading.Lock()
        self._batch_max_duration = batch_max_duration
        self._batch_idle_duration = batch_idle_duration
        self._log_level = log_level
        self._handlers: List[Callable[["Config"], None]] = []

    @property
    def batch_max_duration(self) -> float:
        with self._lock:
            return self._batch_max_duration

    @property
    def batch_idle_duration(self) -> float:
        with self._lock:
            return self._batch_idle_duration

    @property
    def log_level(self) -> str:
        with self._lock:
            return self._log_level

    def on_change(self, handler: Callable[["Config"], None]) -> None:
        with self._lock:
            self._handlers.append(handler)

    def update(self, batch_max_duration=None, batch_idle_duration=None, log_level=None) -> None:
        changed = False
        with self._lock:
            if batch_max_duration is not None and batch_max_duration != self._batch_max_duration:
                self._batch_max_duration = batch_max_duration
                changed = True
            if batch_idle_duration is not None and batch_idle_duration != self._batch_idle_duration:
                self._batch_idle_duration = batch_idle_duration
                changed = True
            if log_level is not None and log_level != self._log_level:
                self._log_level = log_level
                changed = True
            handlers = list(self._handlers)
        if changed:
            for handler in handlers:
                handler(self)


# -- live ConfigMap watch (pkg/config/config.go:84-170) ----------------------

CONFIGMAP_NAME = "karpenter-global-settings"
CONFIGMAP_NAMESPACE = "karpenter"  # default system namespace (config.go:85-88)


def system_namespace() -> str:
    """The namespace the settings ConfigMap lives in — $SYSTEM_NAMESPACE,
    injected by the generated Deployment via the downward API, exactly the
    reference's informer wiring (suite_test.go: os.Getenv("SYSTEM_NAMESPACE"))."""
    import os

    return os.environ.get("SYSTEM_NAMESPACE") or CONFIGMAP_NAMESPACE

DEFAULT_CONFIGMAP_DATA = {
    "batchMaxDuration": "10s",
    "batchIdleDuration": "1s",
    "logLevel": DEFAULT_LOG_LEVEL,
}

_DURATION_SUFFIXES = (("ms", 0.001), ("s", 1.0), ("m", 60.0), ("h", 3600.0))


def parse_duration(value: str) -> float:
    """Go-style duration strings ('10s', '500ms', '1.5m') or bare seconds."""
    text = str(value).strip()
    for suffix, scale in _DURATION_SUFFIXES:
        if text.endswith(suffix) and text[: -len(suffix)].replace(".", "", 1).lstrip("-").isdigit():
            return float(text[: -len(suffix)]) * scale
    return float(text)


def watch_config(kube, config: Config, name: str = CONFIGMAP_NAME, namespace: Optional[str] = None):
    """Subscribe the Config to the settings ConfigMap.

    Mirrors the reference watcher (config.go:84-170): a content hash
    suppresses redundant change notifications (hashCM), and a malformed or
    invariant-violating value keeps the previous setting rather than taking
    the controller down. Missing keys fall back to the Config's values at
    watch time — i.e. CLI flags/env stay authoritative until the ConfigMap
    explicitly sets a key (three-tier config: flags < live ConfigMap);
    deleting the ConfigMap restores them.

    Returns an unsubscribe callable: a stopped/crashed Runtime must detach
    its watcher or the dead Config keeps re-leveling logs on every update.
    """
    from .logsetup import get_logger

    log = get_logger("config")
    if namespace is None:
        namespace = system_namespace()
    # the launch-time configuration is the fallback for unset/removed keys
    base = {
        "batchMaxDuration": f"{config.batch_max_duration}s",
        "batchIdleDuration": f"{config.batch_idle_duration}s",
        "logLevel": config.log_level,
    }
    state = {"hash": None}

    def on_event(event) -> None:
        cm = event.obj
        # both name AND namespace must match: a same-named ConfigMap in an
        # unrelated namespace must not drive (or reset) controller settings
        if cm.metadata.name != name or cm.metadata.namespace != namespace:
            return
        if getattr(event, "type", None) == "DELETED":
            data = dict(base)
        else:
            data = {**base, **(cm.data or {})}
        content = tuple(sorted(data.items()))
        digest = hash(content)
        if digest == state["hash"]:
            return
        if state["hash"] is not None:
            log.info("configuration change detected in %s", name)
        state["hash"] = digest
        updates = {}
        for key, field_name in (("batchMaxDuration", "batch_max_duration"), ("batchIdleDuration", "batch_idle_duration")):
            try:
                seconds = parse_duration(data[key])
            except ValueError:
                log.warning("invalid %s %r; keeping previous", key, data[key])
                continue
            if seconds <= 0:
                log.warning("invalid %s %r: must be positive; keeping previous", key, data[key])
                continue
            updates[field_name] = seconds
        # the same invariant Options.validate enforces at boot: idle <= max
        idle = updates.get("batch_idle_duration", config.batch_idle_duration)
        max_ = updates.get("batch_max_duration", config.batch_max_duration)
        if idle > max_:
            log.warning("batchIdleDuration %.3fs > batchMaxDuration %.3fs; keeping previous durations", idle, max_)
            updates.pop("batch_idle_duration", None)
            updates.pop("batch_max_duration", None)
        from .logsetup import is_valid_level

        level = str(data["logLevel"])
        if is_valid_level(level):
            updates["log_level"] = level
        else:
            log.warning("invalid logLevel %r; keeping previous", level)
        config.update(**updates)

    kube.watch("ConfigMap", on_event)
    return lambda: kube.unwatch("ConfigMap", on_event)
