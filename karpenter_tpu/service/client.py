"""Solver-service client: the control plane's side of the packer boundary.

SolverClient turns the local scheduling inputs into a wire request, calls
the sidecar, and maps the launch plan back onto live objects
(LaunchableNode/LaunchableView quack like VirtualNode/ExistingNodeView for
everything ProvisionerController.launch_nodes consumes). On any transport
or remote error the caller falls back to the local scheduler — the sidecar
is an accelerator, never a single point of failure.
"""

from __future__ import annotations

import dataclasses
import pickle
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..logsetup import get_logger
from ..scheduler.scheduler import SchedulingResults
from ..scheduling.nodetemplate import NodeTemplate
from .wire import METHOD_HEALTH, METHOD_SCHEDULE, SERVICE_NAME, SolveRequest, SolveResponse, WireStateNode

log = get_logger("service")


class RemoteSchedulingError(RuntimeError):
    pass


@dataclass
class LaunchableNode:
    """The VirtualNode surface launch_nodes + consolidation consume."""

    template: NodeTemplate
    instance_type_options: List[object]
    pods: List[object]
    requests: Dict[str, float] = field(default_factory=dict)

    @property
    def provisioner_name(self) -> str:
        return self.template.provisioner_name

    @property
    def requirements(self):
        return self.template.requirements


@dataclass
class LaunchableView:
    """The ExistingNodeView surface launch_nodes consumes."""

    node: object
    pods: List[object]


def snapshot_state_node(state) -> WireStateNode:
    """Detach a cluster StateNode into its wire form."""
    volumes, pod_volumes = state.volume_usage.to_wire()
    return WireStateNode(
        node=state.node,
        available=dict(state.available),
        daemonset_requested=dict(state.daemonset_requested),
        host_ports=state.host_port_usage.to_wire(),
        volumes=volumes,
        pod_volumes=pod_volumes,
        volume_limits=dict(state.volume_limits),  # VolumeCount is a dict subclass
    )


class SolverClient:
    def __init__(self, address: str, timeout: float = 10.0):
        import grpc

        self.address = address
        self.timeout = timeout
        self._channel = grpc.insecure_channel(address)
        self._schedule = self._channel.unary_unary(
            f"/{SERVICE_NAME}/{METHOD_SCHEDULE}",
            request_serializer=pickle.dumps,
            response_deserializer=pickle.loads,
        )
        self._health = self._channel.unary_unary(
            f"/{SERVICE_NAME}/{METHOD_HEALTH}",
            request_serializer=pickle.dumps,
            response_deserializer=pickle.loads,
        )

    def health(self) -> dict:
        return self._health(b"", timeout=self.timeout)

    def close(self) -> None:
        self._channel.close()

    def solve(
        self,
        provisioners: Sequence[object],
        instance_types: Dict[str, List[object]],
        pods: Sequence[object],
        daemonset_pods: Sequence[object] = (),
        state_nodes: Sequence[object] = (),
        kube=None,
        simulation_mode: bool = False,
        exclude_nodes: Sequence[str] = (),
    ) -> SchedulingResults:
        """One remote solve; raises RemoteSchedulingError on transport or
        server failure so the caller can fall back to the local path."""
        request = SolveRequest(
            provisioners=list(provisioners),
            instance_types={name: list(universe) for name, universe in instance_types.items()},
            pods=list(pods),
            daemonset_pods=list(daemonset_pods),
            state_nodes=[snapshot_state_node(s) for s in state_nodes],
            cluster_pods=[p for p in kube.list_pods() if p.spec.node_name] if kube is not None else [],
            cluster_nodes=list(kube.list_nodes()) if kube is not None else [],
            pvcs=list(kube.list("PersistentVolumeClaim")) if kube is not None else [],
            pvs=list(kube.list("PersistentVolume")) if kube is not None else [],
            storage_classes=list(kube.list("StorageClass")) if kube is not None else [],
            csi_nodes=list(kube.list("CSINode")) if kube is not None else [],
            simulation_mode=simulation_mode,
            exclude_nodes=list(exclude_nodes),
        )
        try:
            response: SolveResponse = self._schedule(request, timeout=self.timeout)
        except Exception as exc:  # noqa: BLE001 - transport errors become fallback
            raise RemoteSchedulingError(f"solver service unreachable: {exc}") from exc
        if response.error:
            raise RemoteSchedulingError(f"remote solve failed: {response.error}")
        return self._materialize(response, provisioners, instance_types, pods, state_nodes)

    def _materialize(self, response, provisioners, instance_types, pods, state_nodes) -> SchedulingResults:
        pods_by_uid = {p.uid: p for p in pods}
        templates = {p.name: NodeTemplate.from_provisioner(p) for p in provisioners}
        types_by_name = {
            p.name: {it.name(): it for it in instance_types.get(p.name, ())} for p in provisioners
        }
        nodes_by_name = {s.node.name: s.node for s in state_nodes}

        new_nodes: List[LaunchableNode] = []
        for wire_node in response.new_nodes:
            template = templates.get(wire_node.provisioner_name)
            universe = types_by_name.get(wire_node.provisioner_name, {})
            options = [universe[name] for name in wire_node.instance_type_names if name in universe]
            node_pods = [pods_by_uid[uid] for uid in wire_node.pod_uids if uid in pods_by_uid]
            if template is None or not options or len(node_pods) != len(wire_node.pod_uids):
                raise RemoteSchedulingError(
                    f"launch plan references unknown objects (provisioner {wire_node.provisioner_name!r})"
                )
            if wire_node.requirements is not None:
                # honor the scheduler's tightened pins, not the bare template
                template = dataclasses.replace(template, requirements=wire_node.requirements)
            new_nodes.append(
                LaunchableNode(template=template, instance_type_options=options, pods=node_pods, requests=dict(wire_node.requests))
            )
        existing = []
        for node_name, uids in response.existing_placements.items():
            node = nodes_by_name.get(node_name)
            if node is None:
                raise RemoteSchedulingError(f"launch plan references unknown node {node_name!r}")
            existing.append(LaunchableView(node=node, pods=[pods_by_uid[u] for u in uids if u in pods_by_uid]))
        unschedulable = {pods_by_uid[uid]: reason for uid, reason in response.unschedulable.items() if uid in pods_by_uid}
        return SchedulingResults(new_nodes=new_nodes, existing_nodes=existing, unschedulable=unschedulable)
