"""Solver service: the gRPC sidecar hosting the TPU scheduler.

SURVEY.md §7.3 — the solver runs in its own process (owning the TPU and the
compiled XLA programs) and the control plane calls it over gRPC behind the
packer boundary. One long-lived DenseSolver serves every request, so device
catalogs, compiled shapes, and host-side catalog encodings stay warm across
batches exactly as they do in-process.

The server reconstructs a detached scheduling universe per request: an
in-memory kube holding the shipped volume object graph (full-fidelity
PVC→driver resolution), state-node views from the wire snapshots, and the
standard build_scheduler wiring. Solve output is flattened to the launch
plan (wire.SolveResponse); the control plane owns launching.
"""

from __future__ import annotations

import pickle
import threading
from concurrent import futures
from typing import Optional

from ..logsetup import get_logger
from ..scheduler import SchedulerOptions, build_scheduler
from ..scheduling.hostports import HostPortEntry, HostPortUsage
from ..scheduling.volumelimits import VolumeCount, VolumeLimits
from ..solver import DenseSolver
from .wire import METHOD_HEALTH, METHOD_SCHEDULE, SERVICE_NAME, SolveRequest, SolveResponse, WireNewNode, WireStateNode

log = get_logger("service")


class _StateNodeView:
    """Rebuild the minimal StateNode surface from a wire snapshot."""

    def __init__(self, wire: WireStateNode, kube):
        self.node = wire.node
        self.available = dict(wire.available)
        self.daemonset_requested = dict(wire.daemonset_requested)
        self.host_port_usage = HostPortUsage.from_wire(wire.host_ports)
        self.volume_usage = VolumeLimits.from_wire((wire.volumes, wire.pod_volumes), kube)
        self.volume_limits = VolumeCount(dict(wire.volume_limits))


class _ClusterShim:
    """The one Cluster capability Topology consumes server-side: iterating
    bound pods that carry required anti-affinity (state/cluster.py:225)."""

    def __init__(self, kube):
        self.kube = kube

    def for_pods_with_anti_affinity(self, fn):
        from ..utils import pod as podutils

        for pod in self.kube.list_pods():
            if not pod.spec.node_name or podutils.is_terminal(pod):
                continue
            if not podutils.has_required_pod_anti_affinity(pod):
                continue
            if not fn(pod, self.kube.get_node(pod.spec.node_name)):
                return


class _Provider:
    """Serve the request's shipped instance-type universes as a
    CloudProvider (the server-side twin of the control plane's
    _SnapshotProvider fallback shim)."""

    def __init__(self, universes):
        self._universes = universes

    def get_instance_types(self, provisioner):
        return list(self._universes.get(provisioner.name, ()))


class SolverServer:
    """Request handler; transport-agnostic (serve() wires it into gRPC)."""

    def __init__(self, dense_solver: Optional[DenseSolver] = None):
        self.dense_solver = dense_solver if dense_solver is not None else DenseSolver(min_batch=1)
        self._lock = threading.Lock()  # one solve at a time owns the device
        self.solves = 0

    def schedule(self, request: SolveRequest) -> SolveResponse:
        try:
            return self._schedule(request)
        except Exception as exc:  # noqa: BLE001 - the error crosses the wire
            log.exception("remote solve failed")
            return SolveResponse(new_nodes=[], existing_placements={}, unschedulable={}, error=repr(exc))

    def _schedule(self, request: SolveRequest) -> SolveResponse:
        from ..kube.cluster import KubeCluster

        kube = KubeCluster()
        for obj in [
            *request.cluster_nodes,
            *request.cluster_pods,
            *request.pvcs,
            *request.pvs,
            *request.storage_classes,
            *request.csi_nodes,
        ]:
            kube.create(obj)

        state_nodes = [_StateNodeView(w, kube) for w in request.state_nodes]
        opts = SchedulerOptions(simulation_mode=request.simulation_mode, exclude_nodes=list(request.exclude_nodes))
        with self._lock:
            self.solves += 1
            scheduler = build_scheduler(
                request.provisioners,
                _Provider(request.instance_types),
                request.pods,
                kube=kube,
                cluster=_ClusterShim(kube),
                state_nodes=state_nodes,
                daemonset_pods=request.daemonset_pods,
                opts=opts,
                dense_solver=self.dense_solver,
            )
            results = scheduler.solve(request.pods)

        new_nodes = [
            WireNewNode(
                provisioner_name=n.provisioner_name,
                instance_type_names=[it.name() for it in sorted(n.instance_type_options, key=lambda t: t.price())],
                pod_uids=[p.uid for p in n.pods],
                requests=dict(n.requests),
                # post-finalize (placeholder hostname stripped): the pins the
                # launch must honor
                requirements=n.template.requirements,
            )
            for n in results.new_nodes
            if n.pods
        ]
        existing = {v.node.name: [p.uid for p in v.pods] for v in results.existing_nodes if v.pods}
        unschedulable = {pod.uid: err for pod, err in results.unschedulable.items()}
        return SolveResponse(new_nodes=new_nodes, existing_placements=existing, unschedulable=unschedulable)


def serve(address: str = "127.0.0.1:0", dense_solver: Optional[DenseSolver] = None, max_workers: int = 4):
    """Start the gRPC sidecar; returns (grpc server, bound port, handler).

    Pickle-over-gRPC: a same-trust-domain sidecar protocol (see wire.py) —
    bind to loopback / pod-local interfaces only.
    """
    import grpc

    handler = SolverServer(dense_solver)

    def _schedule(request_bytes, context):
        return pickle.dumps(handler.schedule(pickle.loads(request_bytes)))

    def _health(request_bytes, context):
        return pickle.dumps({"ok": True, "solves": handler.solves})

    generic = grpc.method_handlers_generic_handler(
        SERVICE_NAME,
        {
            METHOD_SCHEDULE: grpc.unary_unary_rpc_method_handler(_schedule),
            METHOD_HEALTH: grpc.unary_unary_rpc_method_handler(_health),
        },
    )
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((generic,))
    port = server.add_insecure_port(address)
    if port == 0:
        raise RuntimeError(f"solver service could not bind {address!r}")
    server.start()
    log.info("solver service listening on port %d", port)
    return server, port, handler
