"""Solver service: gRPC sidecar behind the packer boundary (SURVEY §7.3).

Lazy exports: the control plane imports only the client (grpc channel); the
server pulls in the whole solver stack and must not load into client-only
processes.
"""

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .client import RemoteSchedulingError, SolverClient
    from .server import SolverServer, serve

__all__ = ["SolverClient", "SolverServer", "RemoteSchedulingError", "serve"]

_CLIENT = {"SolverClient", "RemoteSchedulingError"}
_SERVER = {"SolverServer", "serve"}


def __getattr__(name):
    if name in _CLIENT:
        from . import client

        return getattr(client, name)
    if name in _SERVER:
        from . import server

        return getattr(server, name)
    raise AttributeError(name)
