from .client import SolverClient, RemoteSchedulingError
from .server import SolverServer, serve

__all__ = ["SolverClient", "SolverServer", "RemoteSchedulingError", "serve"]
