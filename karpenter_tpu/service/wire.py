"""Wire format for the solver service.

The packer plugin boundary of SURVEY.md §7.3 (BASELINE: "a C++/Python gRPC
sidecar hosting the JAX solver; the host control plane calls it behind the
packer plugin boundary — same seam as CloudProvider/SchedulerOptions").

The request carries everything one Scheduler.solve needs — provisioners,
per-provisioner instance-type universes, pods, daemonset pod templates,
existing-node snapshots, and the volume object graph (PVC/PV/StorageClass/
CSINode) so the server-side VolumeLimits resolves drivers with full
fidelity. The response is a launch plan: per new node the provisioner, the
surviving instance-type names (price order), and the pod uids; plus
existing-node placements and unschedulable reasons.

Transport serialization is pickle: the sidecar is a same-trust-domain
process (the reference's packer runs in-process; this is the out-of-process
equivalent), NOT an external API — do not expose the port beyond the pod
boundary.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..api.objects import Node, Pod


@dataclass
class WireStateNode:
    """A cluster-state node snapshot: the minimal StateNode surface
    ExistingNodeView consumes (scheduler/existingnode.py), detached from the
    live Cluster object graph."""

    node: Node
    available: Dict[str, float]
    daemonset_requested: Dict[str, float] = field(default_factory=dict)
    # HostPortUsage internal entries: pod uid -> [(ip, port, protocol)]
    host_ports: Dict[str, List[tuple]] = field(default_factory=dict)
    # VolumeLimits internal state: driver -> mounted volume ids, per pod
    volumes: Dict[str, List[str]] = field(default_factory=dict)
    pod_volumes: Dict[str, Dict[str, List[str]]] = field(default_factory=dict)
    # CSINode-derived mount limits: driver -> count
    volume_limits: Dict[str, int] = field(default_factory=dict)


@dataclass
class SolveRequest:
    provisioners: List[object]
    instance_types: Dict[str, List[object]]  # provisioner name -> universe
    pods: List[Pod]
    daemonset_pods: List[Pod] = field(default_factory=list)
    state_nodes: List[WireStateNode] = field(default_factory=list)
    # bound cluster pods + their nodes: topology domain counting and
    # inverse anti-affinity need them (scheduler/topology.py _count_domains)
    cluster_pods: List[Pod] = field(default_factory=list)
    cluster_nodes: List[Node] = field(default_factory=list)
    # the volume object graph for server-side PVC->driver resolution
    pvcs: List[object] = field(default_factory=list)
    pvs: List[object] = field(default_factory=list)
    storage_classes: List[object] = field(default_factory=list)
    csi_nodes: List[object] = field(default_factory=list)
    simulation_mode: bool = False
    exclude_nodes: List[str] = field(default_factory=list)


@dataclass
class WireNewNode:
    provisioner_name: str
    instance_type_names: List[str]  # surviving options, price order
    pod_uids: List[str]
    requests: Dict[str, float]
    # the scheduler's TIGHTENED requirements (zone/capacity-type/label pins
    # from placement decisions) — the launch must honor these, not the bare
    # provisioner template
    requirements: object = None


@dataclass
class SolveResponse:
    new_nodes: List[WireNewNode]
    existing_placements: Dict[str, List[str]]  # node name -> pod uids
    unschedulable: Dict[str, str]  # pod uid -> reason
    error: Optional[str] = None


SERVICE_NAME = "karpenter_tpu.Solver"
METHOD_SCHEDULE = "Schedule"
METHOD_HEALTH = "Health"
