"""Invariant monitor: the slow-leak witnesses the chaos suites sample over hours.

Every witness built so far answers "did this run break something *now*" —
lock cycles, informer divergence, double launches, budget violations. None
of them catches what leaks *slowly*: a loop thread that outlives its
Runtime by one crash/restart cycle, a watch subscription a dead control
plane left attached, a bounded ring quietly exceeding its declared budget,
heap growth with a positive slope over compressed hours. This module is the
standing census + monitor the soak tier samples every few compressed
minutes:

- **thread census** (`CENSUS`) — every Runtime-spawned thread (control
  loops, the provisioner batcher thread, the lease elector, the
  leader-recovery task) registers under its owning Runtime's identity;
  `stop()`/`crash()` join-with-timeout and then `release()` the owner —
  any thread still alive at release is a *straggler*, logged and counted
  until it dies. A leak is a straggler that never does.
- **watch accounting** — the monitor baselines the cluster backend's
  watch-subscription count when armed; growth above the baseline is a
  leaked subscription (crash/restart cycles are net-zero by contract:
  every successor attaches exactly what its predecessor detached).
- **bounded-budget checks** — the journal's event ring / milestone map /
  completed-waterfall ring / spool bytes and the flight recorder's solve
  ring are each compared against their *declared* budgets — defense in
  depth over the `deque(maxlen=)` guarantees, because a budget that
  silently stopped being enforced is exactly the bug class this catches.
- **memory slope** — with `trace_memory=True` (the soak tier), tracemalloc
  samples traced-heap bytes each round; `rss_growth_slope` is the
  least-squares slope in bytes/second over the run. A flat or negative
  slope over compressed hours is the no-leak witness.
- **folded witnesses** — lock-order cycles (`analysis/witness.py`),
  confirmed informer divergences (`kube/coherence.py`), and client-token
  double launches fold into the same `InvariantReport`, so one document —
  served at `/debug/invariants` and schema-gated into `SCENARIO_*.json` —
  answers "is anything, anywhere, leaking or lying".

Disabled-is-free: nothing samples until `arm()`; the census is a dict
insert per thread spawn (the journal/SLO bar). Violations are recorded
once per (invariant, entity) — a leak that persists across 400 samples is
one violation, not 400 — counted in
`karpenter_invariant_violations_total{invariant}` and journaled as
`kind="chaos"` `invariant-violation` events.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Tuple

from .analysis.guards import guarded_by
from .analysis.witness import WITNESS
from .logsetup import get_logger
from .metrics import REGISTRY
from .utils.clock import Clock

log = get_logger("invariants")

VIOLATIONS = REGISTRY.counter(
    "karpenter_invariant_violations_total",
    "Distinct invariant violations the monitor confirmed, by invariant"
    " (threads.leak, watches.leak, journal.ring/entities/completed/spool,"
    " flight.ring, capsule.ring/spool, locks.cycle, informer.divergence,"
    " cloud.double-launch) — each (invariant, entity) pair counts once,"
    " however long it persists.",
    ("invariant",),
)
SAMPLES = REGISTRY.counter(
    "karpenter_invariant_samples_total",
    "Invariant-monitor sample rounds taken while armed (the soak tier samples"
    " every few compressed minutes).",
)
LEAKED_THREADS = REGISTRY.gauge(
    "karpenter_invariant_leaked_threads",
    "Threads still alive after their owning Runtime released them from the"
    " census (join-with-timeout expired and the thread never exited).",
)
LEAKED_WATCHES = REGISTRY.gauge(
    "karpenter_invariant_leaked_watches",
    "Watch subscriptions on the cluster backend above the armed baseline —"
    " a dead owner's informer still attached, or an undrained chaos watch.",
)


@guarded_by("_lock", "_owners", "_stragglers")
class ThreadCensus:
    """Process-wide registry of Runtime-owned threads (the COHERENCE
    pattern). `register()` at spawn, `release(owner)` after the owner's
    shutdown joins — anything still alive at release is a straggler,
    retained (and counted by the monitor) until it actually dies."""

    def __init__(self):
        self._lock = WITNESS.lock("invariants.census")
        self._owners: Dict[str, List[threading.Thread]] = {}
        self._stragglers: List[Tuple[str, threading.Thread]] = []

    def register(self, owner: str, thread: threading.Thread) -> None:
        with self._lock:
            threads = self._owners.setdefault(owner, [])
            # prune the owner's dead threads here, not only at release: a
            # flapping leader registers a fresh leader-recovery thread per
            # regain, and keeping every dead Thread object until shutdown
            # would make the census itself the slow leak it exists to catch
            threads[:] = [t for t in threads if t.is_alive()]
            threads.append(thread)

    def release(self, owner: str) -> List[str]:
        """The owner has joined its threads: drop them from the census and
        return the names of any STILL-ALIVE stragglers (kept under watch
        until they die — a straggler that never does is the leak)."""
        with self._lock:
            threads = self._owners.pop(owner, [])
            stragglers = [t for t in threads if t.is_alive()]
            self._stragglers.extend((owner, t) for t in stragglers)
            self._prune_locked()
        names = [t.name for t in stragglers]
        if names:
            log.warning("thread census: %s released with straggler(s) still alive: %s", owner, names)
        return names

    def _prune_locked(self) -> None:
        self._stragglers = [(o, t) for o, t in self._stragglers if t.is_alive()]

    def leaked(self) -> List[dict]:
        """Stragglers still alive right now (dead ones age out)."""
        with self._lock:
            self._prune_locked()
            return [{"owner": owner, "thread": t.name} for owner, t in self._stragglers]

    def snapshot(self) -> dict:
        with self._lock:
            self._prune_locked()
            owners = {
                owner: [t.name for t in threads if t.is_alive()] for owner, threads in self._owners.items()
            }
            stragglers = [{"owner": owner, "thread": t.name} for owner, t in self._stragglers]
        return {"owners": owners, "stragglers": stragglers}

    def reset(self) -> None:
        """Test-harness reset; never called by the runtime."""
        with self._lock:
            self._owners.clear()
            self._stragglers.clear()


CENSUS = ThreadCensus()


def _journal_budget_rows() -> List[Tuple[str, str, int, int]]:
    """(invariant, entity, occupancy, budget) rows for the journal's
    declared bounds; empty when the journal never enabled."""
    from . import journal

    stats = journal.JOURNAL.stats()
    if stats["events_stored"] == 0 and stats["entities_tracked"] == 0 and not stats["enabled"]:
        return []
    rows = [
        ("journal.ring", "events", stats["events_stored"], journal.JOURNAL.capacity),
        ("journal.entities", "milestones", stats["entities_tracked"], journal.MAX_ENTITIES),
        ("journal.completed", "waterfalls", stats["waterfalls_completed"], journal.MAX_COMPLETED),
    ]
    if stats.get("spool_bytes") is not None:
        rows.append(("journal.spool", "bytes", stats["spool_bytes"], stats["spool_max_bytes"]))
    return rows


def _flight_budget_rows() -> List[Tuple[str, str, int, int]]:
    from .flight import FLIGHT

    if not FLIGHT.enabled:
        return []
    return [("flight.ring", "records", len(FLIGHT.records()), FLIGHT.capacity)]


def _capsule_budget_rows() -> List[Tuple[str, str, int, int]]:
    """The capsule engine's declared bounds: the in-memory ring and — when
    spooling — the on-disk byte budget (the journal's rotation-budget
    invariant, shared by the capsule spool)."""
    from .capsule import CAPSULE

    if not CAPSULE.enabled:
        return []
    stats = CAPSULE.stats()
    rows = [("capsule.ring", "capsules", stats["capsules_stored"], stats["capacity"])]
    if stats.get("spool_bytes") is not None:
        rows.append(("capsule.spool", "bytes", stats["spool_bytes"], stats["spool_max_bytes"]))
    return rows


@guarded_by(
    "_lock",
    "_armed",
    "_generation",
    "_kube",
    "_backend",
    "_clock",
    "_baseline_watchers",
    "_coherence_baseline",
    "_sample_count",
    "_violations",
    "_memory_series",
    "_trace_memory",
    "_own_tracemalloc",
    "_last",
)
class InvariantMonitor:
    """The process-wide leak monitor (the COHERENCE/FLIGHT singleton
    pattern): `arm()` against a cluster backend captures the baselines,
    `sample()` runs one witness round (the campaign runner calls it on its
    sample cadence — ~one compressed minute at soak compression),
    `report()` is the InvariantReport served at /debug/invariants and
    scored into SCENARIO_*.json."""

    # bound on the memory series the slope regresses over: the whole series
    # lives for the armed window (the PROCESS lifetime in a controller with
    # --invariants-interval), and an unbounded buffer inside the leak
    # monitor would be the joke writing itself. Oldest points age out; a
    # slope over the trailing window is still the trend that matters.
    MEMORY_SERIES_BOUND = 4096

    def __init__(self):
        from collections import deque

        self._lock = WITNESS.lock("invariants.monitor")
        self._armed = False
        self._generation = 0
        self._kube = None
        self._backend = None
        self._clock: Clock = Clock()
        self._baseline_watchers = 0
        self._coherence_baseline = 0
        self._sample_count = 0
        self._violations: Dict[Tuple[str, str], dict] = {}
        self._memory_series = deque(maxlen=self.MEMORY_SERIES_BOUND)
        self._trace_memory = False
        self._own_tracemalloc = False
        self._last: Optional[dict] = None

    # -- lifecycle -----------------------------------------------------------

    def arm(self, kube, backend=None, clock: Optional[Clock] = None, trace_memory: bool = False) -> int:
        """Start a monitoring window: baseline the watch-subscription count
        and the coherence counter NOW (the armed state is the healthy
        state), optionally start tracemalloc for the memory slope. Arming
        replaces any previous window; the returned generation is the arm's
        ownership token — pass it back to disarm() so a stale owner (a
        stopped Runtime whose window was already replaced) cannot tear down
        a successor's live window."""
        from collections import deque

        from .kube.coherence import divergences_total

        own_trace = False
        if trace_memory:
            import tracemalloc

            if not tracemalloc.is_tracing():
                tracemalloc.start()
                own_trace = True
        watcher_count_fn = getattr(kube, "watcher_count", None)
        baseline = int(watcher_count_fn()) if watcher_count_fn is not None else 0
        with self._lock:
            self._armed = True
            self._generation += 1
            generation = self._generation
            self._kube = kube
            self._backend = backend
            self._clock = clock or getattr(kube, "clock", None) or Clock()
            self._baseline_watchers = baseline
            self._coherence_baseline = divergences_total()
            self._sample_count = 0
            self._violations = {}
            self._memory_series = deque(maxlen=self.MEMORY_SERIES_BOUND)
            self._trace_memory = trace_memory
            self._own_tracemalloc = own_trace
            self._last = None
        LEAKED_THREADS.set(0)
        LEAKED_WATCHES.set(0)
        return generation

    def disarm(self, generation: Optional[int] = None) -> None:
        """End the window; the last report stays readable until re-armed.
        With `generation`, only the window that arm() returned it for is
        torn down — a no-op for a stale owner. None disarms whatever is
        live (the campaign runner's per-run teardown, which owns the
        monitor for the whole process)."""
        with self._lock:
            if not self._armed:
                return
            if generation is not None and generation != self._generation:
                return
            self._armed = False
            self._kube = None
            self._backend = None
            own_trace = self._own_tracemalloc
            self._own_tracemalloc = False
        if own_trace:
            import tracemalloc

            tracemalloc.stop()

    def armed(self) -> bool:
        with self._lock:
            return self._armed

    # -- one witness round -----------------------------------------------------

    def _record_locked(self, invariant: str, entity: str, detail: str, t: float) -> None:
        key = (invariant, entity)
        if key in self._violations:
            return
        self._violations[key] = {"invariant": invariant, "entity": entity, "detail": detail, "t": round(t, 3)}
        VIOLATIONS.inc(invariant=invariant)
        log.error("invariant violation [%s] %s: %s", invariant, entity, detail)
        from .journal import JOURNAL

        if JOURNAL.enabled:
            JOURNAL.chaos_event(f"{invariant}/{entity}", "invariant-violation", detail=detail)

    def sample(self) -> Optional[dict]:
        """One witness round across every invariant; returns the sample row
        (None when disarmed). Cheap by design — thread enumeration, a few
        counter reads — so the campaign runner rides its sample cadence."""
        from .analysis.witness import WITNESS as LOCK_WITNESS
        from .kube.coherence import divergences_total

        with self._lock:
            if not self._armed:
                return None
            kube = self._kube
            backend = self._backend
            clock = self._clock
            baseline_watchers = self._baseline_watchers
            coherence_baseline = self._coherence_baseline
            trace_memory = self._trace_memory
        t = clock.now()
        leaked_threads = CENSUS.leaked()
        watcher_count_fn = getattr(kube, "watcher_count", None)
        watchers = int(watcher_count_fn()) if watcher_count_fn is not None else baseline_watchers
        leaked_watches = max(0, watchers - baseline_watchers)
        budget_rows = _journal_budget_rows() + _flight_budget_rows() + _capsule_budget_rows()
        cycles = LOCK_WITNESS.cycles()
        divergence_delta = divergences_total() - coherence_baseline
        double_launches = int(backend.double_launches()) if backend is not None else 0
        traced_bytes = None
        if trace_memory:
            # only when THIS window asked for tracing: something else in the
            # process (the live profiler's heap endpoint) may have started
            # tracemalloc, and a slope nobody requested must not leak into
            # non-soak scores
            import tracemalloc

            if tracemalloc.is_tracing():
                traced_bytes = tracemalloc.get_traced_memory()[0]
        with self._lock:
            if not self._armed:
                return None
            for leak in leaked_threads:
                self._record_locked("threads.leak", leak["thread"], f"owner {leak['owner']} released it alive", t)
            if leaked_watches > 0:
                self._record_locked(
                    "watches.leak", "kube",
                    f"{watchers} watch subscription(s), baseline {baseline_watchers}", t,
                )
            for invariant, entity, occupancy, budget in budget_rows:
                if occupancy > budget:
                    self._record_locked(invariant, entity, f"occupancy {occupancy} > declared budget {budget}", t)
            for cycle in cycles:
                self._record_locked("locks.cycle", "->".join(cycle), "lock acquisition-order cycle", t)
            if divergence_delta > 0:
                self._record_locked(
                    "informer.divergence", "coherence", f"{divergence_delta} confirmed divergence(s) this window", t
                )
            if double_launches > 0:
                self._record_locked("cloud.double-launch", "token-ledger", f"{double_launches} double launch(es)", t)
            if traced_bytes is not None:
                self._memory_series.append((t, traced_bytes))
            row = {
                "t": round(t, 3),
                "threads_leaked": len(leaked_threads),
                "watchers": watchers,
                "watches_leaked": leaked_watches,
                "traced_bytes": traced_bytes,
                "violations": len(self._violations),
            }
            self._sample_count += 1
            self._last = row
        SAMPLES.inc()
        LEAKED_THREADS.set(float(len(leaked_threads)))
        LEAKED_WATCHES.set(float(leaked_watches))
        return row

    # -- the report ------------------------------------------------------------

    def _slope_locked(self) -> Optional[float]:
        """Least-squares slope of traced-heap bytes over the window
        (bytes/second); None below 3 samples — a slope from 2 points is
        noise dressed as a trend."""
        series = list(self._memory_series)
        if len(series) < 3:
            return None
        n = len(series)
        t0 = series[0][0]
        xs = [t - t0 for t, _ in series]
        ys = [float(b) for _, b in series]
        mean_x = sum(xs) / n
        mean_y = sum(ys) / n
        denom = sum((x - mean_x) ** 2 for x in xs)
        if denom <= 0:
            return None
        return round(sum((x - mean_x) * (y - mean_y) for x, y in zip(xs, ys)) / denom, 3)

    def violations(self) -> List[dict]:
        with self._lock:
            return [dict(v) for v in self._violations.values()]

    def report(self) -> dict:
        """The InvariantReport: /debug/invariants payload and the
        SCENARIO_*.json score source."""
        with self._lock:
            armed = self._armed
            samples = self._sample_count
            last = dict(self._last) if self._last is not None else None
            violations = [dict(v) for v in self._violations.values()]
            slope = self._slope_locked()
            baseline_watchers = self._baseline_watchers
        return {
            "armed": armed,
            "samples": samples,
            "leaked_threads": last["threads_leaked"] if last else 0,
            "leaked_watches": last["watches_leaked"] if last else 0,
            "watchers": {"baseline": baseline_watchers, "current": last["watchers"] if last else None},
            "rss_growth_slope": slope,
            "violations": violations,
            "census": CENSUS.snapshot(),
        }


MONITOR = InvariantMonitor()


# -- HTTP routes (ObservabilityServer extra routes) ---------------------------


def _invariants_route(query: dict) -> tuple:
    if MONITOR.armed():
        MONITOR.sample()  # serve a fresh round, not the last loop tick's
    return 200, "application/json; charset=utf-8", json.dumps(MONITOR.report(), indent=1) + "\n"


def routes() -> dict:
    """`/debug/invariants` for the metrics listener (cmd/controller.py wires
    it behind --invariants-interval)."""
    return {"/debug/invariants": _invariants_route}


def route_descriptions() -> dict:
    """/debug-index descriptions, keyed like routes() (see tracing.py)."""
    return {
        "/debug/invariants": "invariant monitor: thread census, watch/ring/heap leak witnesses, confirmed violations",
    }
