"""Admission: defaulting + validation for Provisioner writes.

Equivalent of pkg/webhooks — in the in-memory API the admission chain runs
synchronously inside create/update instead of over an HTTPS webhook, with the
same two phases: defaulting first, then validation (rejection raises).
"""

from __future__ import annotations

from .api.provisioner import Provisioner, validate_provisioner
from .kube.cluster import KubeCluster


class AdmissionError(RuntimeError):
    pass


def default_provisioner(provisioner: Provisioner, cloud_provider=None) -> None:
    """Defaulting webhook: fill canonical defaults in place, then give the
    cloud provider its hook (the DefaultHook seam the reference's AWS
    provider registers, cloudprovider.go:119-120)."""
    spec = provisioner.spec
    if spec.weight is None:
        spec.weight = 0
    for taint in list(spec.taints) + list(spec.startup_taints):
        if not taint.effect:
            taint.effect = "NoSchedule"
    hook = getattr(cloud_provider, "default_provisioner", None)
    if hook is not None:
        hook(provisioner)


def validate_or_raise(provisioner: Provisioner, cloud_provider=None) -> None:
    errs = list(validate_provisioner(provisioner))
    hook = getattr(cloud_provider, "validate_provisioner", None)
    if hook is not None:
        errs.extend(hook(provisioner) or ())
    if errs:
        raise AdmissionError("; ".join(errs))


def register(kube: KubeCluster, cloud_provider=None) -> None:
    """Install the admission chain: Provisioner writes get defaulting then
    validation (core rule set + provider hooks); every other kind is offered
    to the provider's validate_object hook (how provider-owned CRs like the
    simulated NodeClass — the AWSNodeTemplate analog — get admission, same
    seam as the reference's AWSNodeTemplate webhook).

    Idempotent per cluster: a second register (a restarted Runtime over the
    same KubeCluster) swaps the provider in place instead of stacking
    another wrapper around the already-wrapped verbs."""
    if getattr(kube, "_admission_registered", False):
        kube._admission_provider = cloud_provider
        return
    kube._admission_registered = True
    kube._admission_provider = cloud_provider
    original_create, original_update = kube.create, kube.update

    def _admit(obj):
        provider = kube._admission_provider
        if isinstance(obj, Provisioner):
            default_provisioner(obj, provider)
            validate_or_raise(obj, provider)
            return
        hook = getattr(provider, "validate_object", None)
        if hook is not None:
            errs = hook(obj) or ()
            if errs:
                raise AdmissionError("; ".join(errs))

    def admitted_create(obj):
        _admit(obj)
        return original_create(obj)

    def admitted_update(obj):
        _admit(obj)
        return original_update(obj)

    kube.create = admitted_create  # type: ignore[method-assign]
    kube.update = admitted_update  # type: ignore[method-assign]
