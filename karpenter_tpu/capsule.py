"""Incident capsules: triggered cross-subsystem evidence capture.

Every telemetry layer in this repo is a bounded in-process ring (spans,
decisions, flight records, journal events, lock-witness edges): by the time
a human reads `/debug` after a mid-soak breaker trip, the evidence that
explains *why* has been overwritten. This module freezes the rings at the
moment of failure: a process-wide CAPSULE engine subscribes to a typed
trigger bus and, on trigger, snapshots every ring into one cross-linked
`CAPSULE_<trigger>_<seq>.json` bundle — recent traces, decision records, a
journal slice, flight records with recompile attribution, breaker/fault-
domain state, the lock graph, the SLO snapshot, and a full metrics dump,
joined by the trace/decision/flight/journal ids the layers already stamp.

Trigger vocabulary (the bus is typed; unknown kinds are rejected):

- **breaker-open** — the solver circuit breaker transitioned to OPEN
  (solver/faults.py emits from inside the transition).
- **host-rung** — the fault ladder fell all the way to the host fallback
  (solver/dense.py emits once per solve).
- **steady-recompile** — a recompile whose attribution is entirely
  declared-STATIC axes per the committed solver contract (flight.py runs
  the contracts.recompile_violations cross-check per recompile record).
- **conservation-violation** — the journal's waterfall conservation
  invariant failed for a pod (polled).
- **lock-cycle** — the lock witness observed a cyclic acquisition order
  (polled).
- **invariant-breach** — the soak invariant monitor confirmed a violation
  (polled).
- **slo-burn** — the multi-window burn-rate monitor below fired (polled).

**Burn-rate monitor**: fast/slow windows over the pending-latency SLO
(violating-sample fraction over the last N observations, per provisioner,
worst series wins) and a poll-sampled cost-drift series. Burn rate =
violating fraction / error budget, exported as
`karpenter_slo_burn_rate{slo,window}`; the trigger fires only when BOTH
windows burn at or above the threshold (the classic fast-AND-slow
multiwindow rule: fast catches the cliff, slow filters the blip).

Capture discipline (the part that keeps this subsystem honest):

- **disabled == free**: OFF by default; every ring and map allocates on
  `enable()`, never before, and `trigger()` is one attribute read when
  disabled (the tracing overhead bar applies).
- **enqueue-only trigger**: emit sites call `trigger()` while holding
  their own witnessed locks (the breaker emits from `_transition_locked`),
  so `trigger()` only appends to a bounded queue under the capsule lock —
  the breaker->capsule edge stays a leaf. `poll()` drains the queue and
  BUILDS capsule documents with NO capsule lock held (building acquires
  the tracer/journal/flight/breaker locks), then stores the finished
  document under the capsule lock without acquiring anything else. No
  cycle is possible by construction, and the lock witness checks anyway.
- **debounced + deduped**: per-kind debounce through the clock seam, and a
  16-hex fingerprint over the canonical (kind, stable-detail) JSON — the
  same incident re-observed produces the same fingerprint on every
  transport (the cross-transport determinism witness campaigns score) and
  is captured once. Suppressions are counted by reason.
- **size-bounded spool**: one file per capsule under the configured
  directory; the journal's rotation-budget discipline applies (never more
  than the budget on disk — oldest capsule evicted first, evictions
  counted) and a dead disk disables spooling without killing capture (the
  in-memory ring keeps serving `/debug/capsules`).
"""

from __future__ import annotations

import hashlib
import json
import os
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from .analysis.guards import guarded_by
from .analysis.witness import WITNESS
from .logsetup import get_logger
from .metrics import REGISTRY
from .utils.clock import Clock

log = get_logger("capsule")

# -- trigger vocabulary -------------------------------------------------------

TRIGGER_BREAKER_OPEN = "breaker-open"
TRIGGER_HOST_RUNG = "host-rung"
TRIGGER_STEADY_RECOMPILE = "steady-recompile"
TRIGGER_CONSERVATION = "conservation-violation"
TRIGGER_LOCK_CYCLE = "lock-cycle"
TRIGGER_INVARIANT = "invariant-breach"
TRIGGER_SLO_BURN = "slo-burn"
TRIGGER_RESIDENCY = "residency-divergence"

TRIGGERS = (
    TRIGGER_BREAKER_OPEN,
    TRIGGER_HOST_RUNG,
    TRIGGER_STEADY_RECOMPILE,
    TRIGGER_CONSERVATION,
    TRIGGER_LOCK_CYCLE,
    TRIGGER_INVARIANT,
    TRIGGER_SLO_BURN,
    TRIGGER_RESIDENCY,
)

# the capsule document's required top-level blocks (capsule_errors gates
# every document before it lands in the ring or on disk)
CAPSULE_KEYS = (
    "capsule",
    "traces",
    "decisions",
    "journal",
    "flight",
    "fault_domain",
    "locks",
    "slo",
    "burn_rate",
    "invariants",
    "metrics",
)
CAPSULE_META_KEYS = ("id", "seq", "trigger", "fingerprint", "detail", "t")

# capture bounds: a capsule is evidence, not an archive — each block takes
# the newest slice its ring serves, bounded so one capsule stays cheap
CAPTURE_TRACES = 50
CAPTURE_TREES = 10
CAPTURE_DECISIONS = 100
CAPTURE_JOURNAL_EVENTS = 400
CAPTURE_FLIGHT_RECORDS = 50

DEFAULT_RING = 32
DEFAULT_QUEUE = 256
DEFAULT_SPOOL_MAX_BYTES = 32 * 2**20
DEFAULT_DEBOUNCE_SECONDS = 30.0

# burn-rate monitor defaults: objectives sit well above the committed
# healthy-scenario envelope (healthy pending p95 tops out ~3.6s, healthy
# cost drift peaks at 4.5 on diurnal_ramp) so healthy runs never burn
DEFAULT_PENDING_OBJECTIVE_SECONDS = 30.0
DEFAULT_COST_DRIFT_OBJECTIVE = 10.0
DEFAULT_ERROR_BUDGET = 0.10
DEFAULT_BURN_THRESHOLD = 1.0
DEFAULT_FAST_WINDOW = 20
DEFAULT_SLOW_WINDOW = 100
DEFAULT_MIN_SAMPLES = 10

SLO_PENDING = "pending_latency"
SLO_COST_DRIFT = "cost_drift"
BURN_WINDOWS = ("fast", "slow")

# registered at import so gen_docs sees the families without a live engine
CAPTURES = REGISTRY.counter(
    "karpenter_capsule_captures_total",
    "Incident capsules captured, by trigger kind.",
    ("trigger",),
)
SUPPRESSED = REGISTRY.counter(
    "karpenter_capsule_suppressed_total",
    "Capsule triggers suppressed before capture, by reason (debounce, duplicate, queue-full, invalid).",
    ("reason",),
)
SPOOL_EVICTIONS = REGISTRY.counter(
    "karpenter_capsule_spool_evictions_total",
    "Spooled capsule files evicted to stay inside the spool byte budget.",
)
SPOOL_BYTES = REGISTRY.gauge(
    "karpenter_capsule_spool_bytes",
    "Bytes of capsule files currently on disk in the spool directory.",
)
BURN_RATE = REGISTRY.gauge(
    "karpenter_slo_burn_rate",
    "Multi-window SLO burn rate (violating-sample fraction over the error budget; >=1 burns the budget).",
    ("slo", "window"),
)


def fingerprint(kind: str, detail: dict) -> str:
    """16-hex digest over the canonical (kind, detail) JSON. Details carry
    only transport-stable fields, so the same incident fingerprints
    identically across transports — the determinism witness campaigns
    assert."""
    blob = json.dumps({"trigger": kind, "detail": detail}, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def capsule_errors(doc) -> List[str]:
    """All structural problems with one capsule document; empty means
    valid (the self-check every capture passes before it lands)."""
    errs: List[str] = []
    if not isinstance(doc, dict):
        return ["capsule must be a JSON object"]
    for key in CAPSULE_KEYS:
        if key not in doc:
            errs.append(f"missing top-level key {key!r}")
    meta = doc.get("capsule")
    if isinstance(meta, dict):
        for key in CAPSULE_META_KEYS:
            if key not in meta:
                errs.append(f"capsule block missing {key!r}")
        trigger = meta.get("trigger")
        if trigger is not None and trigger not in TRIGGERS:
            errs.append(f"capsule.trigger {trigger!r} is not one of {list(TRIGGERS)}")
        seq = meta.get("seq")
        if seq is not None and (not isinstance(seq, int) or isinstance(seq, bool) or seq < 0):
            errs.append("capsule.seq must be a non-negative int")
        fp = meta.get("fingerprint")
        if fp is not None and (
            not isinstance(fp, str) or len(fp) != 16 or any(c not in "0123456789abcdef" for c in fp)
        ):
            errs.append("capsule.fingerprint must be 16 lowercase hex characters")
        if not isinstance(meta.get("detail"), dict):
            errs.append("capsule.detail must be a dict")
    elif meta is not None:
        errs.append("capsule block must be a dict")
    for key in ("traces", "journal", "flight", "fault_domain", "locks", "slo", "burn_rate", "invariants"):
        block = doc.get(key)
        if block is not None and not isinstance(block, dict):
            errs.append(f"{key} block must be a dict, got {type(block).__name__}")
    decisions = doc.get("decisions")
    if decisions is not None and not isinstance(decisions, list):
        errs.append("decisions block must be a list")
    metrics_dump = doc.get("metrics")
    if metrics_dump is not None and not isinstance(metrics_dump, str):
        errs.append("metrics block must be the registry text dump (a string)")
    journal_block = doc.get("journal")
    if isinstance(journal_block, dict):
        events = journal_block.get("events")
        if not isinstance(events, list):
            errs.append("journal.events must be a list")
        else:
            last = None
            for i, event in enumerate(events):
                t = event.get("t") if isinstance(event, dict) else None
                if isinstance(t, (int, float)):
                    if last is not None and t < last:
                        errs.append(f"journal.events[{i}].t={t} goes backwards: the slice must be ascending")
                        break
                    last = t
    return errs


@guarded_by(
    "_lock",
    "_ring",
    "_queue",
    "_seq",
    "_fingerprints",
    "_last_capture",
    "_cost_samples",
    "_spool_files",
    "_spool_bytes",
    "_spool_dead",
)
class CapsuleEngine:
    """The process-wide capture engine (the TRACER/FLIGHT/JOURNAL singleton
    pattern): emit sites enqueue typed triggers, `poll()` turns them into
    schema-validated capsule documents."""

    def __init__(self, capacity: int = DEFAULT_RING):
        self._lock = WITNESS.lock("capsule.engine")
        self.capacity = capacity
        self.enabled = False
        self.clock: Clock = Clock()
        self.debounce_seconds = DEFAULT_DEBOUNCE_SECONDS
        # burn-rate configuration (overridable per enable() for tests)
        self.pending_objective = DEFAULT_PENDING_OBJECTIVE_SECONDS
        self.cost_objective = DEFAULT_COST_DRIFT_OBJECTIVE
        self.error_budget = DEFAULT_ERROR_BUDGET
        self.burn_threshold = DEFAULT_BURN_THRESHOLD
        self.fast_window = DEFAULT_FAST_WINDOW
        self.slow_window = DEFAULT_SLOW_WINDOW
        self.min_samples = DEFAULT_MIN_SAMPLES
        # spool configuration (directory-per-process, one file per capsule)
        self._spool_dir: Optional[str] = None
        self._spool_max_bytes = DEFAULT_SPOOL_MAX_BYTES
        # allocated on enable(), never before — "disabled is a true no-op"
        self._ring: Optional[OrderedDict] = None  # capsule id -> document
        self._queue: Optional[deque] = None  # enqueued (kind, detail) triggers
        self._seq = 0
        self._fingerprints: Optional[Dict[str, List[str]]] = None  # kind -> fps
        self._last_capture: Optional[Dict[str, float]] = None  # kind -> clock t
        self._cost_samples: Optional[deque] = None  # poll-sampled drift series
        self._spool_files: Optional[OrderedDict] = None  # filename -> bytes
        self._spool_bytes = 0
        self._spool_dead = False

    # -- lifecycle -----------------------------------------------------------

    def enable(
        self,
        spool: Optional[str] = None,
        spool_max_bytes: Optional[int] = None,
        debounce_seconds: Optional[float] = None,
        clock: Optional[Clock] = None,
        pending_objective: Optional[float] = None,
        cost_objective: Optional[float] = None,
        error_budget: Optional[float] = None,
        burn_threshold: Optional[float] = None,
        fast_window: Optional[int] = None,
        slow_window: Optional[int] = None,
        min_samples: Optional[int] = None,
    ) -> None:
        with self._lock:
            first = self._ring is None
            if first:
                self._ring = OrderedDict()
                self._queue = deque(maxlen=DEFAULT_QUEUE)
                self._fingerprints = {}
                self._last_capture = {}
                self._cost_samples = deque(maxlen=max(self.slow_window, slow_window or 0))
                self._spool_files = OrderedDict()
        if first and WITNESS.enabled:
            # first enable happens at Runtime construction, before any emit
            # site holds the lock: adopt a witnessed lock so the engine
            # joins the lock-order graph the chaos suites assert acyclic
            self._lock = WITNESS.lock("capsule.engine")
        if clock is not None:
            self.clock = clock
        if debounce_seconds is not None:
            self.debounce_seconds = max(0.0, float(debounce_seconds))
        if pending_objective is not None:
            self.pending_objective = float(pending_objective)
        if cost_objective is not None:
            self.cost_objective = float(cost_objective)
        if error_budget is not None:
            self.error_budget = max(1e-9, float(error_budget))
        if burn_threshold is not None:
            self.burn_threshold = float(burn_threshold)
        if fast_window is not None:
            self.fast_window = max(1, int(fast_window))
        if slow_window is not None:
            self.slow_window = max(self.fast_window, int(slow_window))
        if min_samples is not None:
            self.min_samples = max(1, int(min_samples))
        if spool_max_bytes is not None:
            self._spool_max_bytes = int(spool_max_bytes)
        if spool:
            self._open_spool(spool)
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        """Drop captured capsules, queued triggers, dedupe/debounce state,
        and this layer's resettable families (per-run harness reset; keeps
        the enabled flag and the spool directory)."""
        with self._lock:
            if self._ring is not None:
                self._ring.clear()
                self._queue.clear()
                self._fingerprints.clear()
                self._last_capture.clear()
                self._cost_samples.clear()
            self._seq = 0
        for family in (CAPTURES, SUPPRESSED, BURN_RATE):
            family.clear()

    def _open_spool(self, path: str) -> None:
        """Adopt `path` as the capsule spool directory, seeding the byte
        accounting (and the sequence counter) from capsules already on disk
        so a restarted process keeps honoring the budget. A dead disk
        disables spooling without killing capture — the ring keeps serving."""
        max_seq = 0
        try:
            os.makedirs(path, exist_ok=True)
            existing: List[Tuple[str, int]] = []
            for name in sorted(os.listdir(path)):
                if name.startswith("CAPSULE_") and name.endswith(".json"):
                    existing.append((name, os.path.getsize(os.path.join(path, name))))
                    stem = name[: -len(".json")]
                    try:
                        max_seq = max(max_seq, int(stem.rsplit("_", 1)[-1]))
                    except ValueError:
                        log.warning("capsule spool: unparseable sequence in %s; ignoring for numbering", name)
        except OSError as exc:
            log.warning("capsule spool unavailable (%s); capturing to memory only", exc)
            with self._lock:
                self._spool_dead = True
            self._spool_dir = None
            return
        self._spool_dir = path
        with self._lock:
            self._spool_dead = False
            self._spool_files = OrderedDict(existing)
            self._spool_bytes = sum(size for _, size in existing)
            self._seq = max(self._seq, max_seq)
            SPOOL_BYTES.set(float(self._spool_bytes))

    # -- the trigger bus -----------------------------------------------------

    def trigger(self, kind: str, **detail) -> None:
        """Enqueue one typed trigger. Cheap by design: emit sites call this
        while holding their own witnessed locks (the breaker emits from its
        transition), so this only appends under the capsule lock — capture
        happens later, in poll(), with no capsule lock held."""
        if not self.enabled:
            return
        if kind not in TRIGGERS:
            SUPPRESSED.inc(reason="invalid")
            log.warning("capsule trigger of unknown kind %r dropped", kind)
            return
        with self._lock:
            if self._queue is None:
                return
            if len(self._queue) == self._queue.maxlen:
                SUPPRESSED.inc(reason="queue-full")
                return
            self._queue.append((kind, dict(detail)))

    # -- the burn-rate monitor -----------------------------------------------

    @staticmethod
    def _window_burn(samples: List[float], objective: float, window: int, min_samples: int, budget: float) -> float:
        tail = samples[-window:]
        if len(tail) < min_samples:
            return 0.0
        violating = sum(1 for s in tail if s > objective)
        return (violating / len(tail)) / budget

    def burn_rates(self) -> Dict[str, Dict[str, float]]:
        """{slo: {window: burn}} over the current sample windows. Pending
        latency reads the SLO summary's per-provisioner observation rings
        (worst series wins); cost drift reads the series poll() samples."""
        from . import slo as _slo

        rates: Dict[str, Dict[str, float]] = {}
        pending = {"fast": 0.0, "slow": 0.0}
        for labels in _slo.PENDING_LATENCY.series():
            obs = _slo.PENDING_LATENCY.observations(**labels)
            for window_name, width in (("fast", self.fast_window), ("slow", self.slow_window)):
                burn = self._window_burn(obs, self.pending_objective, width, self.min_samples, self.error_budget)
                pending[window_name] = max(pending[window_name], burn)
        rates[SLO_PENDING] = pending
        with self._lock:
            drift = list(self._cost_samples) if self._cost_samples is not None else []
        rates[SLO_COST_DRIFT] = {
            window_name: self._window_burn(drift, self.cost_objective, width, self.min_samples, self.error_budget)
            for window_name, width in (("fast", self.fast_window), ("slow", self.slow_window))
        }
        return rates

    def _sample_burn(self) -> List[Tuple[str, dict]]:
        """One burn-monitor round: sample cost drift, export the gauges,
        and return slo-burn triggers for every SLO burning in BOTH windows."""
        from . import slo as _slo

        with self._lock:
            if self._cost_samples is not None:
                self._cost_samples.append(float(_slo.COST_DRIFT.value()))
        fired: List[Tuple[str, dict]] = []
        for slo_name, windows in self.burn_rates().items():
            for window_name in BURN_WINDOWS:
                BURN_RATE.set(round(windows[window_name], 6), slo=slo_name, window=window_name)
            if windows["fast"] >= self.burn_threshold and windows["slow"] >= self.burn_threshold:
                fired.append((TRIGGER_SLO_BURN, {"slo": slo_name}))
        return fired

    # -- polled trigger sources ----------------------------------------------

    def _poll_sources(self) -> List[Tuple[str, dict]]:
        """Evaluate every polled trigger source. Runs with NO capsule lock
        held (each source takes its own subsystem's lock)."""
        from . import invariants as _invariants
        from . import journal as _journal

        found = self._sample_burn()
        if _journal.JOURNAL.enabled:
            for err in _journal.JOURNAL.conservation_errors():
                # "pod <name>: segments sum ..." — the pod is the stable key
                pod = err.split(":", 1)[0].split(" ", 1)[-1]
                found.append((TRIGGER_CONSERVATION, {"pod": pod}))
        for cycle in WITNESS.cycles():
            found.append((TRIGGER_LOCK_CYCLE, {"cycle": "->".join(cycle)}))
        if _invariants.MONITOR.armed():
            for violation in _invariants.MONITOR.violations():
                found.append(
                    (TRIGGER_INVARIANT, {"invariant": violation["invariant"], "entity": violation["entity"]})
                )
        return found

    # -- capture -------------------------------------------------------------

    def poll(self) -> int:
        """Drain the trigger bus into capsules: evaluate polled sources,
        debounce/dedupe under the lock, build each accepted capsule's
        document OUTSIDE the lock, store under the lock. Returns the number
        of capsules captured this round."""
        if not self.enabled:
            return 0
        polled = self._poll_sources()
        now = self.clock.now()
        accepted: List[Tuple[str, dict, str, int]] = []  # (kind, detail, fp, seq)
        suppressed: List[str] = []
        with self._lock:
            if self._queue is None:
                return 0
            candidates = list(self._queue) + polled
            self._queue.clear()
            for kind, detail in candidates:
                fp = fingerprint(kind, detail)
                if fp in self._fingerprints.get(kind, []):
                    suppressed.append("duplicate")
                    continue
                last = self._last_capture.get(kind)
                if last is not None and (now - last) < self.debounce_seconds:
                    suppressed.append("debounce")
                    continue
                self._seq += 1
                self._fingerprints.setdefault(kind, []).append(fp)
                self._last_capture[kind] = now
                accepted.append((kind, detail, fp, self._seq))
        for reason in suppressed:
            SUPPRESSED.inc(reason=reason)
        captured = 0
        for kind, detail, fp, seq in accepted:
            doc = self._build(kind, detail, fp, seq, now)
            errs = capsule_errors(doc)
            if errs:
                # a malformed capture is a bug in THIS module; surface it
                # loudly but never let evidence capture break the caller
                SUPPRESSED.inc(reason="invalid")
                log.error("capsule %s failed self-validation: %s", doc["capsule"]["id"], "; ".join(errs))
                continue
            self._store(doc)
            CAPTURES.inc(trigger=kind)
            captured += 1
            log.warning("incident capsule %s captured (trigger=%s fingerprint=%s)", doc["capsule"]["id"], kind, fp)
        return captured

    def _build(self, kind: str, detail: dict, fp: str, seq: int, now: float) -> dict:
        """Assemble one capsule document. Runs with NO capsule lock held:
        every block acquires its own subsystem's lock (tracer, journal,
        flight, breaker), and the cross-links ride the ids those layers
        already stamp on their records."""
        from . import flight as _flight
        from . import invariants as _invariants
        from . import journal as _journal
        from . import slo as _slo
        from . import tracing as _tracing
        from .solver import faults as _faults

        trace_index = _tracing.TRACER.traces()[:CAPTURE_TRACES]
        trees = {}
        for entry in trace_index[:CAPTURE_TREES]:
            tree = _tracing.TRACER.span_tree(entry["trace_id"])
            if tree is not None:
                trees[entry["trace_id"]] = tree
        # the journal slice is stored ASCENDING so `capsule inspect --replay`
        # can feed it straight into ReplayTrace.from_events
        journal_events = list(reversed(_journal.JOURNAL.events(limit=CAPTURE_JOURNAL_EVENTS)))
        flight_records = [r.to_dict() for r in _flight.FLIGHT.records()[:CAPTURE_FLIGHT_RECORDS]]
        return {
            "capsule": {
                "id": f"{kind}-{seq:04d}",
                "seq": seq,
                "trigger": kind,
                "fingerprint": fp,
                "detail": detail,
                "t": round(now, 6),
            },
            "traces": {"index": trace_index, "trees": trees},
            "decisions": _tracing.DECISIONS.recent(limit=CAPTURE_DECISIONS),
            "journal": {
                "stats": _journal.JOURNAL.stats(),
                "events": journal_events,
                "conservation_errors": _journal.JOURNAL.conservation_errors(),
                "waterfall": _journal.JOURNAL.segment_quantiles(),
            },
            "flight": {
                "records": flight_records,
                "last_record_id": _flight.FLIGHT.last_record_id(),
            },
            "fault_domain": {
                "breaker": _faults.BREAKER.snapshot(),
                "faults_total": _faults.faults_total(),
                "degraded_total": _faults.degraded_total(),
            },
            "locks": WITNESS.snapshot(),
            "slo": _slo.SLO.snapshot(),
            "burn_rate": self.burn_rates(),
            "invariants": {
                "armed": _invariants.MONITOR.armed(),
                "violations": _invariants.MONITOR.violations(),
            },
            "metrics": REGISTRY.export_text(),
        }

    def _store(self, doc: dict) -> None:
        with self._lock:
            if self._ring is None:
                return
            self._ring[doc["capsule"]["id"]] = doc
            while len(self._ring) > self.capacity:
                self._ring.popitem(last=False)
            self._spool_write_locked(doc)

    def _spool_write_locked(self, doc: dict) -> None:
        """One capsule file, then evict oldest files until the directory is
        back inside the byte budget (the journal's rotation-budget
        discipline: never more than the budget on disk). A dead disk stops
        spooling — capture itself survives on the in-memory ring."""
        if self._spool_dir is None or self._spool_dead:
            return
        meta = doc["capsule"]
        name = f"CAPSULE_{meta['trigger']}_{meta['seq']:04d}.json"
        try:
            data = (json.dumps(doc, sort_keys=True) + "\n").encode("utf-8")
            with open(os.path.join(self._spool_dir, name), "wb") as f:
                f.write(data)
            self._spool_files[name] = len(data)
            self._spool_bytes += len(data)
            # oldest-first eviction until the directory is back inside the
            # budget; a single capsule larger than the whole budget evicts
            # itself (the ring still serves it) — the invariant monitor's
            # budget row must NEVER observe spool_bytes > spool_max_bytes
            while self._spool_bytes > self._spool_max_bytes and self._spool_files:
                oldest, size = next(iter(self._spool_files.items()))
                os.remove(os.path.join(self._spool_dir, oldest))
                del self._spool_files[oldest]
                self._spool_bytes -= size
                SPOOL_EVICTIONS.inc()
            SPOOL_BYTES.set(float(self._spool_bytes))
        except (OSError, ValueError) as exc:
            log.warning("capsule spool write failed (%s); spooling disabled, ring capture continues", exc)
            self._spool_dead = True

    # -- read surface --------------------------------------------------------

    def index(self) -> List[dict]:
        """Newest-first capsule index rows (the /debug/capsules listing)."""
        with self._lock:
            docs = list(self._ring.values()) if self._ring is not None else []
        return [dict(doc["capsule"]) for doc in reversed(docs)]

    def capsule_by_id(self, capsule_id: str) -> Optional[dict]:
        with self._lock:
            if self._ring is None:
                return None
            return self._ring.get(capsule_id)

    def captures_total(self) -> int:
        return int(sum(CAPTURES.values().values()))

    def fingerprints(self) -> Dict[str, List[str]]:
        """{trigger: sorted fingerprints} for every capture this run — the
        cross-transport determinism surface SCENARIO artifacts score."""
        with self._lock:
            if self._fingerprints is None:
                return {}
            return {kind: sorted(fps) for kind, fps in self._fingerprints.items() if fps}

    def stats(self) -> dict:
        with self._lock:
            stored = len(self._ring) if self._ring is not None else 0
            queued = len(self._queue) if self._queue is not None else 0
            spool_dir = self._spool_dir if not self._spool_dead else None
            spool_bytes = self._spool_bytes if spool_dir is not None else None
        return {
            "enabled": self.enabled,
            "capsules_stored": stored,
            "capacity": self.capacity,
            "triggers_queued": queued,
            "captures_total": self.captures_total(),
            "suppressed": {reason[0]: int(count) for reason, count in sorted(SUPPRESSED.values().items())},
            "debounce_seconds": self.debounce_seconds,
            # declared-budget surface for the invariant monitor, the same
            # shape the journal spool exposes (None when not spooling)
            "spool": spool_dir,
            "spool_bytes": spool_bytes,
            "spool_max_bytes": self._spool_max_bytes,
        }


CAPSULE = CapsuleEngine()


def enabled() -> bool:
    return CAPSULE.enabled


# -- HTTP routes (ObservabilityServer extra routes) ---------------------------


def _json(status, payload) -> tuple:
    return status, "application/json; charset=utf-8", json.dumps(payload) + "\n"


def _capsules_route(query: dict) -> tuple:
    capsule_id = (query.get("id") or [None])[0]
    if capsule_id is not None:
        doc = CAPSULE.capsule_by_id(capsule_id)
        if doc is None:
            return _json(404, {"error": f"no capsule with id {capsule_id!r}", "status": 404})
        return _json(200, doc)
    payload = CAPSULE.stats()
    payload["capsules"] = CAPSULE.index()
    payload["burn_rate"] = CAPSULE.burn_rates() if CAPSULE.enabled else {}
    return _json(200, payload)


def routes() -> dict:
    """The capsule read surface, served from the metrics listener alongside
    tracing/SLO/flight/journal (cmd/controller.py wires it behind
    --enable-capsules)."""
    return {"/debug/capsules": _capsules_route}


def route_descriptions() -> dict:
    """/debug-index descriptions, keyed like routes() (see tracing.py)."""
    return {
        "/debug/capsules": "incident capsules: triggered cross-subsystem evidence bundles + burn rates; ?id= detail",
    }
