"""Lifecycle journal: the recorded pod/node transition stream + the
end-to-end pending-latency waterfall.

`karpenter_slo_pod_pending_duration_seconds` scores creation->bind as one
opaque number; nobody can say whether a slow p99 was batch wait, solve time,
launch latency, or node initialization. This module records the lifecycle
stream that decomposes it:

- **pod transitions** — created -> queued -> batch-admitted -> solved ->
  nominated -> bound (or failed / deleted), each event cross-linked to the
  trace ID of the controller pass that caused it, the decision record
  (via pod name + trace), and the flight-recorder solve id that placed it.
- **node transitions** — launch-requested -> launched -> registered ->
  ready -> initialized -> terminated.
- **the waterfall** — per pod, the creation->bind interval decomposed into
  consecutive segments (queue_wait / batch_wait / solve / launch /
  node_ready / bind) whose sum equals the observed pending duration BY
  CONSTRUCTION (the conservation invariant every scenario run asserts);
  solve carries encode/fill/device/commit sub-splits joined from the flight
  record. Aggregated per provisioner into p50/p95/p99 per segment, exported
  as `karpenter_waterfall_segment_seconds{segment,provisioner}` and served
  at `/debug/waterfall` (index + `?pod=` detail, 404-shaped JSON).
- **the on-disk trace format** — an optional append-only JSONL spool with a
  size-bounded rotation (never more than the configured budget on disk),
  self-validated by journal_schema.py and replayable through
  scenarios/replay.py `ReplayTrace` — the recorded-arrival-trace seam
  ROADMAP item 3 builds on.

Design constraints match tracing.py exactly:

- **disabled == free**: OFF by default; the ring/milestone maps allocate on
  `enable()`, never before, and every event site is one attribute read when
  disabled (the overhead-guard bar in tests/test_journal.py). The watch
  hooks exist only after `attach()`.
- **zero deps, bounded memory**: bounded event ring (default 8192, eviction
  counted), bounded per-entity milestone map, bounded completed-waterfall
  ring; the spool is size-bounded by rotation.
- **clocked**: every timestamp flows through the `utils/clock.py` seam (the
  kube clock after `attach()`), so a campaign's compressed clock compresses
  the journal identically — which is what makes replay exact.
- **one read surface**: `/debug/journal` + `/debug/waterfall` on the
  metrics listener (wired behind `--enable-journal` in cmd/controller.py).
"""

from __future__ import annotations

import json
import os
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .analysis.guards import guarded_by
from .analysis.witness import WITNESS
from .logsetup import get_logger
from .metrics import REGISTRY
from .utils.clock import Clock

log = get_logger("journal")

DEFAULT_RING = 8192
MAX_ENTITIES = 16384  # per-entity milestone maps retained (oldest evicted)
MAX_COMPLETED = 4096  # completed waterfalls retained for /debug/waterfall
DEFAULT_SPOOL_MAX_BYTES = 16 * 2**20  # total on-disk budget (live + rotated)

KIND_POD = "pod"
KIND_NODE = "node"
KIND_SOLVER = "solver"
KIND_KUBE = "kube"
KIND_CHAOS = "chaos"

# the transition vocabularies; journal_schema.py validates files against them
POD_EVENTS = ("created", "queued", "batch-admitted", "solved", "nominated", "bound", "failed", "deleted")
NODE_EVENTS = ("launch-requested", "launched", "registered", "ready", "initialized", "terminated")
# solver fault-domain events (solver/faults.py + solver/dense.py): unlike
# pod/node milestones these are a STREAM — a solve may hit the same fault
# kind twice, the breaker re-opens — so they bypass the first-occurrence
# dedupe and never participate in the waterfall
SOLVER_EVENTS = ("fault", "degraded", "breaker-opened", "breaker-half-open", "breaker-closed")
# control-plane fault-domain events (kube/chaos.py + kube/leaderelection.py):
# conflict storms, watch gaps, informer relists, and lease transitions —
# also a stream (the same storm fires repeatedly), so replay traces capture
# control-plane weather alongside pod/node/solver events
KUBE_EVENTS = ("conflict-storm", "watch-gap", "relist", "lease-lost", "lease-acquired")
# chaos-orchestrator events (scenarios/chaos_orchestrator.py + invariants.py):
# the schedule arming, every delivered cross-domain event, and every
# confirmed invariant violation — a stream like solver/kube, never deduped,
# so a replayed journal carries the chaos weather next to the load it hit
CHAOS_EVENTS = ("schedule-armed", "injected", "invariant-violation")

# waterfall segments, in chain order: consecutive sub-intervals of
# created->bound, so their sum IS the pending duration (conservation)
SEGMENTS = ("queue_wait", "batch_wait", "solve", "launch", "node_ready", "bind")

# the pod milestones that bound the first four segments, in chain order
_POD_CHAIN = ("created", "queued", "batch-admitted", "solved", "nominated")

QUANTILES = (0.5, 0.95, 0.99)

# registered at import so gen_docs sees the families without a live journal
EVENTS_TOTAL = REGISTRY.counter(
    "karpenter_journal_events_total",
    "Lifecycle transitions recorded by the journal, by entity kind.",
    ("kind",),
)
EVENTS_STORED = REGISTRY.gauge(
    "karpenter_journal_events_stored", "Lifecycle events currently held in the bounded journal ring."
)
EVENTS_DROPPED = REGISTRY.counter(
    "karpenter_journal_events_dropped", "Lifecycle events evicted from the bounded journal ring."
)
SPOOL_ROTATIONS = REGISTRY.counter(
    "karpenter_journal_spool_rotations_total",
    "Journal spool rotations (the JSONL file hit half the on-disk budget and rolled to .1).",
)
WATERFALL_SEGMENT = REGISTRY.summary(
    "karpenter_waterfall_segment_seconds",
    "Per-pod pending-latency decomposition: seconds spent in each waterfall"
    " segment (queue_wait, batch_wait, solve, launch, node_ready, bind), per provisioner.",
    ("segment", "provisioner"),
    objectives=QUANTILES,
)


@dataclass
class JournalEvent:
    """One recorded lifecycle transition."""

    seq: int
    t: float  # clock-seam seconds (the kube clock after attach)
    kind: str  # pod | node
    entity: str  # pod or node name
    event: str  # one of POD_EVENTS / NODE_EVENTS
    attrs: Dict[str, object] = field(default_factory=dict)

    def to_dict(self) -> dict:
        out = {"seq": self.seq, "t": round(self.t, 6), "kind": self.kind, "entity": self.entity, "event": self.event}
        if self.attrs:
            out["attrs"] = self.attrs
        return out


def _clamp(value: float, lo: float, hi: float) -> float:
    return max(lo, min(value, hi))


def _quantile_row(values: List[float], with_sum: bool = False) -> dict:
    """Sorted-index quantile row for one segment's observations (sorts in
    place; callers own the list)."""
    values.sort()
    row = {"count": len(values)}
    if with_sum:
        row["sum_seconds"] = round(sum(values), 6)
    for q in QUANTILES:
        row[f"p{int(q * 100)}"] = round(values[min(len(values) - 1, int(q * len(values)))], 6)
    return row


@guarded_by(
    "_lock",
    "_ring",
    "_seq",
    "_last_t",
    "_milestones",
    "_completed",
    "_spool",
    "_spool_bytes",
    "_spool_path",
    "_spool_max_bytes",
)
class Journal:
    """Bounded lifecycle-event ring + milestone tracking + the waterfall."""

    def __init__(self, capacity: int = DEFAULT_RING):
        self._lock = WITNESS.lock("journal.events")
        self.capacity = capacity
        self.enabled = False
        self.clock: Clock = Clock()
        # allocated on enable(), never before — "disabled is a true no-op"
        self._ring: Optional[deque] = None
        self._seq = 0
        self._last_t = 0.0
        # (kind, entity) -> {milestone -> t}: first-occurrence dedupe + the
        # waterfall's raw material; bounded, oldest entity evicted
        self._milestones: Optional[OrderedDict] = None
        # pod -> completed waterfall entry (set at the bound event); bounded
        self._completed: Optional[OrderedDict] = None
        self._spool = None  # open file object when spooling
        self._spool_bytes = 0
        self._spool_path: Optional[str] = None
        self._spool_max_bytes = DEFAULT_SPOOL_MAX_BYTES

    # -- lifecycle -----------------------------------------------------------

    def enable(self, capacity: Optional[int] = None, clock: Optional[Clock] = None) -> None:
        with self._lock:
            if capacity is not None:
                self.capacity = capacity
            first = self._ring is None
            if first:
                self._ring = deque(maxlen=self.capacity)
                self._milestones = OrderedDict()
                self._completed = OrderedDict()
            elif self._ring.maxlen != self.capacity:
                # re-enabled with a new bound: keep the newest events
                self._ring = deque(self._ring, maxlen=self.capacity)
        if first and WITNESS.enabled:
            # first enable happens at Runtime construction, before any event
            # site holds the lock: adopt a witnessed lock so the journal
            # joins the lock-order graph the chaos suites assert acyclic
            self._lock = WITNESS.lock("journal.events")
        if clock is not None:
            self.clock = clock
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        """Drop events, milestones, completed waterfalls, and this layer's
        resettable families (per-run harness reset; keeps the enabled flag
        and the spool)."""
        with self._lock:
            if self._ring is not None:
                self._ring.clear()
                self._milestones.clear()
                self._completed.clear()
            self._last_t = 0.0  # the next run may use a different clock epoch
        for family in (EVENTS_TOTAL, EVENTS_DROPPED, WATERFALL_SEGMENT):
            family.clear()
        EVENTS_STORED.set(0)

    def attach(self, kube) -> None:
        """Wire the pod/node watch hooks onto a cluster backend and adopt
        its clock (the one timestamp seam). Idempotent per backend; replay
        is skipped so attaching mid-flight only journals entities created
        from here on (same marker discipline as slo.SLOAccountant.attach)."""
        self.clock = kube.clock
        with self._lock:
            if getattr(kube, "_journal_attached", False):
                return
            kube._journal_attached = True
        kube.watch("Pod", lambda event: self._on_pod_event(kube, event), replay=False)
        kube.watch("Node", lambda event: self._on_node_event(kube, event), replay=False)

    # -- the JSONL spool -------------------------------------------------------

    def set_spool(self, path: Optional[str], max_bytes: int = DEFAULT_SPOOL_MAX_BYTES) -> None:
        """(Re)target the append-only JSONL spool; None closes it. The spool
        is size-bounded: before a write would push the live file past half
        of `max_bytes` it rotates to `<path>.1` (replacing the previous
        rotation), so live + rotated never exceed the budget."""
        with self._lock:
            if self._spool is not None:
                try:
                    self._spool.close()
                except OSError as err:
                    log.warning("journal spool close failed: %s", err)
            self._spool = None
            self._spool_path = path
            self._spool_max_bytes = max_bytes
            self._spool_bytes = 0
            if path is not None:
                try:
                    self._spool = open(path, "w", encoding="utf-8")
                except OSError as err:
                    log.warning("journal spool unavailable at %s: %s", path, err)
                    self._spool_path = None

    def _spool_write_locked(self, line: str) -> None:
        if self._spool is None:
            return
        try:
            # rotate BEFORE a write would push the live file past half the
            # budget: live and rotated each stay <= budget/2, so their sum
            # never exceeds the budget at any observable instant (a single
            # line larger than half the budget still lands whole)
            if self._spool_bytes and self._spool_bytes + len(line) > self._spool_max_bytes // 2:
                self._spool.close()
                os.replace(self._spool_path, self._spool_path + ".1")
                self._spool = open(self._spool_path, "w", encoding="utf-8")
                self._spool_bytes = 0
                SPOOL_ROTATIONS.inc()
            self._spool.write(line)
            self._spool_bytes += len(line)
        except (OSError, ValueError) as err:
            # a dead disk (OSError) or a file closed under us (ValueError)
            # must not take the control plane with it: stop spooling, keep
            # journaling in memory
            log.warning("journal spool write failed (spooling disabled): %s", err)
            self._spool = None

    def flush_spool(self) -> None:
        with self._lock:
            if self._spool is not None:
                try:
                    self._spool.flush()
                except OSError as err:
                    log.warning("journal spool flush failed: %s", err)

    # -- recording -------------------------------------------------------------

    def record(
        self, kind: str, entity: str, event: str, t: Optional[float] = None, attrs: Optional[dict] = None, **kwattrs
    ) -> Optional[JournalEvent]:
        """Append one transition. First-occurrence semantics per (entity,
        event): a transition already journaled for this entity is a no-op,
        so watch redeliveries and retry rounds cannot skew the waterfall
        (the FIRST batch admission / solve is the one that decomposes the
        pending time). Returns the event, or None when disabled/deduped.
        Attributes arrive as keywords or — for names that would collide
        with this signature, e.g. the solver events' `kind` — via `attrs`."""
        if not self.enabled:
            return None
        attrs = {**(attrs or {}), **kwattrs}
        if kind == KIND_POD:
            vocab = POD_EVENTS
        elif kind == KIND_NODE:
            vocab = NODE_EVENTS
        elif kind == KIND_SOLVER:
            vocab = SOLVER_EVENTS
        elif kind == KIND_KUBE:
            vocab = KUBE_EVENTS
        elif kind == KIND_CHAOS:
            vocab = CHAOS_EVENTS
        else:
            raise ValueError(f"unknown journal kind {kind!r}")
        if event not in vocab:
            raise ValueError(f"unknown {kind} transition {event!r}; one of {vocab}")
        if t is None:
            t = self.clock.now()
        with self._lock:
            if self._ring is None:
                return None
            # the stream is monotonic BY CONTRACT (journal_schema.py, and
            # replay's inter-arrival reconstruction): two threads can stamp
            # then dispatch out of order by microseconds, so clamp forward.
            # Milestones keep the RAW stamp: the waterfall conserves against
            # authoritative instants (creation_timestamp, the bind verb's
            # startTime), and a cross-entity clamp must not skew a pod's
            # decomposition — the per-pod chain does its own ordering clamp.
            raw_t = t
            t = max(t, self._last_t)
            self._last_t = t
            if kind in (KIND_POD, KIND_NODE):
                # solver/kube fault-domain events are a stream (the same
                # fault kind can legitimately repeat), so only pod/node
                # milestones carry the first-occurrence dedupe + waterfall
                # bookkeeping
                milestones = self._milestones.get((kind, entity))
                if milestones is None:
                    milestones = {}
                    self._milestones[(kind, entity)] = milestones
                    while len(self._milestones) > MAX_ENTITIES:
                        self._milestones.popitem(last=False)
                elif event in milestones:
                    return None  # first occurrence wins
                milestones[event] = raw_t
                if kind == KIND_POD and event == "solved":
                    # the cross-link payload (trace id, flight-record solve
                    # id) survives ring eviction with the milestone map
                    milestones["_solved_attrs"] = dict(attrs)
            record = JournalEvent(seq=self._seq, t=t, kind=kind, entity=entity, event=event, attrs=dict(attrs))
            self._seq += 1
            evicting = len(self._ring) == self._ring.maxlen
            self._ring.append(record)  # deque(maxlen=) evicts the oldest O(1)
            if evicting:
                EVENTS_DROPPED.inc()
            EVENTS_STORED.set(float(len(self._ring)))
            self._spool_write_locked(json.dumps(record.to_dict()) + "\n")
            completed = None
            if kind == KIND_POD and event == "bound":
                completed = self._complete_waterfall_locked(entity, milestones, dict(attrs))
            elif kind == KIND_POD and event == "deleted":
                # a deleted pod's name may be reused (StatefulSet-style): drop
                # its milestones so the next incarnation journals fresh instead
                # of hitting the first-occurrence dedupe — the SLO cross-feed
                # (keyed by name) would otherwise overwrite the dead pod's
                # waterfall with the new pod's observation and fabricate a
                # conservation violation. Completed waterfalls stay: they are
                # history, and a rebind under the reused name replaces them.
                self._milestones.pop((kind, entity), None)
        EVENTS_TOTAL.inc(kind=kind)
        if completed is not None:
            provisioner = completed["provisioner"]
            for segment, seconds in completed["segments"].items():
                WATERFALL_SEGMENT.observe(seconds, segment=segment, provisioner=provisioner)
        return record

    def pod_event(self, name: str, event: str, t: Optional[float] = None, **attrs) -> Optional[JournalEvent]:
        return self.record(KIND_POD, name, event, t=t, **attrs)

    def node_event(self, name: str, event: str, t: Optional[float] = None, **attrs) -> Optional[JournalEvent]:
        return self.record(KIND_NODE, name, event, t=t, **attrs)

    def solver_event(self, entity: str, event: str, t: Optional[float] = None, **attrs) -> Optional[JournalEvent]:
        """One solver fault-domain transition (solver/faults.py): a
        classified fault, a degradation-ladder rung, or a circuit-breaker
        state change. `entity` names the emitting component ('dense',
        'breaker'); unlike pod/node milestones these are never deduped."""
        return self.record(KIND_SOLVER, entity, event, t=t, attrs=attrs)

    def kube_event(self, entity: str, event: str, t: Optional[float] = None, **attrs) -> Optional[JournalEvent]:
        """One control-plane fault-domain transition (kube/chaos.py +
        kube/leaderelection.py): an injected conflict storm, a watch gap,
        an informer relist, or a lease transition. `entity` names the
        emitting component (a verb boundary, a watch loop, an elector
        identity); like solver events these are a stream, never deduped."""
        return self.record(KIND_KUBE, entity, event, t=t, attrs=attrs)

    def chaos_event(self, entity: str, event: str, t: Optional[float] = None, **attrs) -> Optional[JournalEvent]:
        """One chaos-orchestrator transition (scenarios/chaos_orchestrator.py
        + invariants.py): the schedule arming, a delivered cross-domain
        event, or a confirmed invariant violation. `entity` names the action
        or the violated invariant; a stream, never deduped."""
        return self.record(KIND_CHAOS, entity, event, t=t, attrs=attrs)

    def note_observed_pending(self, pod: str, seconds: float) -> None:
        """Cross-feed from the SLO accountant: the independently-measured
        pending duration this pod observed into
        karpenter_slo_pod_pending_duration_seconds. The conservation check
        prefers it over the journal's own bound-created interval — two
        observers, one invariant."""
        if not self.enabled:
            return
        with self._lock:
            if self._completed is None:
                return
            entry = self._completed.get(pod)
            if entry is not None:
                entry["observed_pending_seconds"] = round(seconds, 6)

    # -- watch hooks -----------------------------------------------------------

    def _on_pod_event(self, kube, event) -> None:
        if not self.enabled:
            return
        pod = event.obj
        name = pod.metadata.name
        if event.type == "DELETED":
            self.pod_event(name, "deleted", phase=pod.status.phase)
            return
        if not pod.spec.node_name:
            # creation_timestamp is stamped by the same clock before the
            # watch dispatches, so "created" matches the SLO accountant's
            # pending-start exactly
            self.pod_event(name, "created", t=pod.metadata.creation_timestamp or None)
            return
        node = kube.get_node(pod.spec.node_name)
        provisioner = ""
        if node is not None:
            from .api import labels as lbl

            provisioner = node.metadata.labels.get(lbl.PROVISIONER_NAME_LABEL, "")
        # `bound` at the bind verb's authoritative stamp (PodStatus.startTime,
        # the same instant the SLO accountant measures against) — the node
        # lookup above is a network round trip on the HTTP transport and must
        # not leak into the waterfall's conserved interval
        self.pod_event(
            name, "bound", t=pod.status.start_time or None,
            node=pod.spec.node_name, provisioner=provisioner,
        )

    def _on_node_event(self, kube, event) -> None:
        if not self.enabled:
            return
        node = event.obj
        if event.type == "DELETED":
            # fallback for deletions that bypass the termination controller
            # (first occurrence wins, so the controller's richer record sticks)
            self.node_event(node.name, "terminated")
            return
        if event.type == "ADDED":
            self.node_event(node.name, "registered", t=node.metadata.creation_timestamp or None)
        if node.ready():
            self.node_event(node.name, "ready")

    # -- the waterfall ---------------------------------------------------------

    def _complete_waterfall_locked(self, pod: str, milestones: Dict[str, float], attrs: dict) -> Optional[dict]:
        """Decompose created->bound into consecutive segments. Milestones a
        pod skipped (bound straight onto existing capacity with no solve)
        carry the previous boundary forward, so their segment scores zero
        and the chain stays gapless — which is what makes conservation exact
        by construction."""
        created = milestones.get("created")
        bound = milestones.get("bound")
        if created is None or bound is None:
            return None  # attach-mid-flight: no honest decomposition exists
        bound = max(bound, created)
        boundaries = [created]
        for milestone in _POD_CHAIN[1:]:
            t = milestones.get(milestone)
            boundaries.append(_clamp(t, boundaries[-1], bound) if t is not None else boundaries[-1])
        # the node_ready/bind split: the bound node's ready (or initialized)
        # instant, clamped into [nominated, bound]. A node that was ready
        # long before this pod existed clamps to `nominated` — node_ready 0,
        # the whole tail is bind — the existing-capacity case.
        node_name = str(attrs.get("node") or "")
        node_ms = self._milestones.get((KIND_NODE, node_name), {}) if node_name else {}
        split = node_ms.get("ready", node_ms.get("initialized"))
        boundaries.append(_clamp(split, boundaries[-1], bound) if split is not None else boundaries[-1])
        boundaries.append(bound)
        segments = {
            segment: round(boundaries[i + 1] - boundaries[i], 6) for i, segment in enumerate(SEGMENTS)
        }
        solved_attrs = milestones.get("_solved_attrs", {})
        entry = {
            "pod": pod,
            "provisioner": str(attrs.get("provisioner") or solved_attrs.get("provisioner") or ""),
            "node": node_name,
            "created_t": round(created, 6),
            "bound_t": round(bound, 6),
            "pending_seconds": round(bound - created, 6),
            "observed_pending_seconds": None,  # filled by the SLO cross-feed
            "segments": segments,
            "trace_id": str(solved_attrs.get("trace_id") or ""),
            "flight_record": solved_attrs.get("flight_record"),
        }
        self._completed[pod] = entry
        while len(self._completed) > MAX_COMPLETED:
            self._completed.popitem(last=False)
        return entry

    def completed(self) -> List[dict]:
        with self._lock:
            if self._completed is None:
                return []
            return [dict(entry, segments=dict(entry["segments"])) for entry in self._completed.values()]

    def waterfall_for(self, pod: str) -> Optional[dict]:
        with self._lock:
            if self._completed is None:
                return None
            entry = self._completed.get(pod)
            return dict(entry, segments=dict(entry["segments"])) if entry is not None else None

    def segment_quantiles(self) -> Dict[str, dict]:
        """{segment: {p50, p95, p99, count}} across every completed pod —
        the SCENARIO_*.json `waterfall` score block."""
        by_segment: Dict[str, List[float]] = {segment: [] for segment in SEGMENTS}
        for entry in self.completed():
            for segment, seconds in entry["segments"].items():
                by_segment[segment].append(seconds)
        return {segment: _quantile_row(values, with_sum=True) for segment, values in by_segment.items() if values}

    def conservation_errors(self, tolerance: float = 0.05, completed: Optional[List[dict]] = None) -> List[str]:
        """The invariant: per pod, segments sum to the observed pending
        duration within `tolerance` seconds. `observed` is the SLO
        accountant's independent measurement when it arrived (two observers
        of one interval), else the journal's own bound-created interval.
        `completed` reuses a caller-held snapshot instead of re-copying."""
        errors = []
        for entry in completed if completed is not None else self.completed():
            total = sum(entry["segments"].values())
            observed = entry["observed_pending_seconds"]
            if observed is None:
                observed = entry["pending_seconds"]
            if abs(total - observed) > tolerance:
                errors.append(
                    f"pod {entry['pod']}: segments sum {total:.6f}s != observed pending "
                    f"{observed:.6f}s (delta {abs(total - observed):.6f}s > {tolerance}s)"
                )
        return errors

    # -- read surface ----------------------------------------------------------

    def events(self, limit: int = 200, entity: Optional[str] = None) -> List[dict]:
        """Newest-first events, bounded; `entity` filters before bounding."""
        with self._lock:
            records = list(self._ring) if self._ring is not None else []
        out = []
        for record in reversed(records):
            if entity is not None and record.entity != entity:
                continue
            out.append(record.to_dict())
            if len(out) >= limit:
                break
        return out

    def stats(self) -> dict:
        with self._lock:
            stored = len(self._ring) if self._ring is not None else 0
            entities = len(self._milestones) if self._milestones is not None else 0
            completed = len(self._completed) if self._completed is not None else 0
            seq = self._seq
            spooling = self._spool_path if self._spool is not None else None
            spool_bytes = self._spool_bytes if self._spool is not None else None
            spool_max = self._spool_max_bytes
        return {
            "enabled": self.enabled,
            "events_stored": stored,
            "events_total": seq,
            # evidence-loss surface (mirrors /debug/traces): events already
            # evicted from the ring, and the bound eviction happens at
            "events_dropped": int(EVENTS_DROPPED.value()),
            "capacity": self.capacity,
            "entities_tracked": entities,
            "waterfalls_completed": completed,
            "spool": spooling,
            # declared-budget surface for the invariant monitor: occupancy
            # vs bound for the ring, the milestone map, the completed ring,
            # and the on-disk spool (spool_bytes None when not spooling)
            "spool_bytes": spool_bytes,
            "spool_max_bytes": spool_max,
        }

    def waterfall_index(self) -> dict:
        """The /debug/waterfall index: per-provisioner per-segment quantiles
        plus the conservation verdict over every completed pod."""
        completed = self.completed()
        per_provisioner: Dict[str, Dict[str, List[float]]] = {}
        for entry in completed:
            segments = per_provisioner.setdefault(entry["provisioner"] or "N/A", {s: [] for s in SEGMENTS})
            for segment, seconds in entry["segments"].items():
                segments[segment].append(seconds)
        aggregated = {
            provisioner: {segment: _quantile_row(values) for segment, values in segments.items() if values}
            for provisioner, segments in per_provisioner.items()
        }
        errors = self.conservation_errors(completed=completed)
        return {
            "enabled": self.enabled,
            "segments": list(SEGMENTS),
            "pods_completed": len(completed),
            "per_provisioner": aggregated,
            "conservation": {"violations": len(errors), "errors": errors[:20]},
        }

    def waterfall_detail(self, pod: str) -> Optional[dict]:
        """The ?pod= view: the segment decomposition, the pod's full event
        stream, the solve sub-splits joined from the flight record, and the
        latest decision-log outcome — one page answering 'where did this
        pod's pending time go'."""
        entry = self.waterfall_for(pod)
        if entry is None:
            return None
        detail = dict(entry)
        detail["events"] = list(reversed(self.events(limit=len(POD_EVENTS), entity=pod)))
        solve_phases = None
        if entry["flight_record"] is not None:
            from .flight import FLIGHT

            record = FLIGHT.record_by_id(entry["flight_record"])
            if record is not None:
                solve_phases = {k: round(v, 6) for k, v in record.phases.items()}
        detail["solve_phases"] = solve_phases  # null when the record evicted / host-path solve
        from .tracing import DECISIONS

        detail["decision"] = DECISIONS.latest_outcome_for(pod)
        return detail


# the process-wide instance (the TRACER/SLO/FLIGHT analog): controllers feed
# it, the Runtime enables and attaches it behind --enable-journal, the
# campaign runner enables it per scenario run
JOURNAL = Journal()


def enabled() -> bool:
    return JOURNAL.enabled


# -- HTTP routes (ObservabilityServer extra routes) ---------------------------


def _json(status, payload) -> tuple:
    return status, "application/json; charset=utf-8", json.dumps(payload) + "\n"


_EVENTS_DEFAULT_LIMIT = 200
_EVENTS_MAX_LIMIT = 2000


def _journal_route(query: dict) -> tuple:
    entity = (query.get("entity") or [None])[0]
    raw_limit = (query.get("limit") or [None])[0]
    limit = _EVENTS_DEFAULT_LIMIT
    if raw_limit is not None:
        try:
            limit = int(raw_limit)
        except ValueError:
            return _json(404, {"error": f"limit {raw_limit!r} is not an integer", "status": 404})
        limit = max(1, min(limit, _EVENTS_MAX_LIMIT))
    payload = JOURNAL.stats()
    payload["events"] = JOURNAL.events(limit=limit, entity=entity)
    payload["limit"] = limit
    if entity is not None:
        if not payload["events"]:
            return _json(404, {"error": f"no journal events for entity {entity!r}", "status": 404})
        payload["entity"] = entity
    return _json(200, payload)


def _waterfall_route(query: dict) -> tuple:
    pod = (query.get("pod") or [None])[0]
    if pod is None:
        return _json(200, JOURNAL.waterfall_index())
    detail = JOURNAL.waterfall_detail(pod)
    if detail is None:
        return _json(404, {"error": f"no completed waterfall for pod {pod!r}", "status": 404})
    return _json(200, detail)


def routes() -> dict:
    """The journal read surface, served from the metrics listener alongside
    tracing/SLO/flight (cmd/controller.py wires it behind --enable-journal)."""
    return {"/debug/journal": _journal_route, "/debug/waterfall": _waterfall_route}


def route_descriptions() -> dict:
    """/debug-index descriptions, keyed like routes() (see tracing.py)."""
    return {
        "/debug/journal": "lifecycle journal: pod/node transition stream; ?entity=, ?limit=",
        "/debug/waterfall": "pending-latency waterfall: per-segment quantiles + conservation; ?pod= detail",
    }
