"""Static-analysis core: file walking, findings, baseline, reporting.

The framework half of `python -m karpenter_tpu.cmd.analyze`: rules
(analysis/rules/*) consume parsed modules and emit `Finding`s; the runner
diffs them against the vetted baseline (analysis/baseline.json) and renders
`path:line: rule[key]: message` output, mirroring the exit-code contract of
the existing `gen_docs --check` / `gen_manifests --check` CI gates.

Baseline entries match on (rule, path, scope, key) — never on line numbers,
so an unrelated edit above a vetted exception does not invalidate it. Every
entry must carry a non-empty justification, and an entry that no longer
matches any finding is itself an error: the baseline records debt, and paid
debt must be deleted, not accumulated.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

BASELINE_BASENAME = "baseline.json"


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    scope: str  # "Class.method", "function", or "<module>"
    key: str  # stable detail (attribute/callee name) for baseline matching
    message: str

    def suppression_key(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.path, self.scope, self.key)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}[{self.key}]: {self.message}"


@dataclass
class Module:
    path: str  # repo-relative, forward slashes
    abspath: str
    tree: ast.AST
    source: str


@dataclass
class Baseline:
    suppressions: List[dict] = field(default_factory=list)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        return cls(suppressions=list(doc.get("suppressions", [])))

    def errors(self) -> List[str]:
        """Malformed entries: the baseline only admits justified suppressions
        for rules that exist. The rule-name check matters because split()
        filters by tier — a typo'd or deleted rule name would otherwise be
        invisible to BOTH gates' staleness checks and suppress nothing,
        silently, forever."""
        from .rules import CONTRACT_RULE_NAMES, RULE_NAMES  # runtime: avoids the import cycle

        known_rules = set(RULE_NAMES) | set(CONTRACT_RULE_NAMES)
        out = []
        for i, entry in enumerate(self.suppressions):
            missing = [k for k in ("rule", "path", "scope", "key") if not entry.get(k)]
            if missing:
                out.append(f"baseline entry {i} missing field(s) {missing}: {entry}")
            if entry.get("rule") and entry.get("rule") not in known_rules:
                out.append(
                    f"baseline entry {i} names unknown rule {entry.get('rule')!r} "
                    f"(not in {sorted(known_rules)}) — typo, or the rule was deleted; delete the entry"
                )
            justification = str(entry.get("justification", "")).strip()
            if not justification or justification.lower() == "todo":
                # 'TODO' is the --write-baseline seed: committing it unvetted
                # must fail the gate, same as an empty justification
                out.append(
                    f"baseline entry {i} ({entry.get('rule')}:{entry.get('path')}:{entry.get('scope')}"
                    f"[{entry.get('key')}]) has no justification — every suppression must say why"
                )
        return out

    def split(self, findings: Sequence[Finding], rules: Optional[Sequence[str]] = None):
        """(active findings, suppressed findings, stale baseline entries).

        `rules` scopes the staleness check to one tier: the AST gate and the
        program-contracts gate share this one baseline file, and each must
        judge only its own suppressions stale (an entry for a rule the
        current run never evaluates is the other tier's business)."""
        suppressions = self.suppressions
        if rules is not None:
            wanted = set(rules)
            suppressions = [e for e in suppressions if e.get("rule") in wanted]
        index: Dict[Tuple[str, str, str, str], dict] = {
            (e.get("rule", ""), e.get("path", ""), e.get("scope", ""), e.get("key", "")): e
            for e in suppressions
        }
        matched = set()
        active, suppressed = [], []
        for finding in findings:
            entry = index.get(finding.suppression_key())
            if entry is not None:
                matched.add(finding.suppression_key())
                suppressed.append(finding)
            else:
                active.append(finding)
        stale = [entry for key, entry in index.items() if key not in matched]
        return active, suppressed, stale


def parse_modules(root: str, subdir: str = "karpenter_tpu") -> List[Module]:
    """Parse every .py file under root/subdir into a Module. A file that
    does not parse is itself a finding-shaped error the caller surfaces, so
    we raise with the path attached rather than skipping silently."""
    modules: List[Module] = []
    base = os.path.join(root, subdir)
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            abspath = os.path.join(dirpath, name)
            rel = os.path.relpath(abspath, root).replace(os.sep, "/")
            with open(abspath, encoding="utf-8") as f:
                source = f.read()
            try:
                tree = ast.parse(source, filename=rel)
            except SyntaxError as err:
                raise SyntaxError(f"{rel}: {err}") from err
            modules.append(Module(path=rel, abspath=abspath, tree=tree, source=source))
    return modules


def run_rules(modules: List[Module], rules=None) -> List[Finding]:
    if rules is None:
        from .rules import ALL_RULES

        rules = ALL_RULES
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule(modules))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.key))
    return findings


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), BASELINE_BASENAME)


# -- AST helpers shared by the rules ------------------------------------------


def self_attribute(node: ast.AST) -> Optional[str]:
    """'X' when node is `self.X`, else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target, best effort ('' when dynamic)."""
    return dotted_name(node.func)


def dotted_name(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def decorator_name(node: ast.AST) -> str:
    """Name of a decorator, unwrapping calls: `@guarded_by(...)` -> 'guarded_by',
    `@partial(jax.jit, ...)` -> 'partial'."""
    if isinstance(node, ast.Call):
        node = node.func
    name = dotted_name(node)
    return name.rsplit(".", 1)[-1] if name else ""


class ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor maintaining a Class.method / function scope string, the
    shared spine of the per-rule visitors (findings anchor to scopes, not
    lines, so baselines survive unrelated edits)."""

    def __init__(self):
        self._scopes: List[str] = []

    @property
    def scope(self) -> str:
        return ".".join(self._scopes) if self._scopes else "<module>"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._scopes.append(node.name)
        self.generic_visit(node)
        self._scopes.pop()

    def _visit_function(self, node) -> None:
        self._scopes.append(node.name)
        self.generic_visit(node)
        self._scopes.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function
