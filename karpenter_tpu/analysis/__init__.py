"""Concurrency & hot-path correctness analysis.

Two halves, one discipline:

- **static** (core.py + rules/): the AST framework behind
  `python -m karpenter_tpu.cmd.analyze --check` — guarded-attribute lock
  checking (`@guarded_by`), JIT hygiene for the solver hot path, and the
  swallow/clock/threads hygiene rules, gated against a vetted baseline of
  justified exceptions (baseline.json).
- **dynamic** (witness.py): the opt-in lock-order witness — acquisition-
  order graph, cycle (deadlock) detection, hold-time accounting — that the
  storm/crash/campaign chaos suites run enabled.

The guards module is imported by production code (the declarations live on
the classes); everything else is tooling and stays import-light.
"""

from .guards import guarded_by, requires_lock

__all__ = ["guarded_by", "requires_lock", "WITNESS", "LockWitness"]


def __getattr__(name):
    # lazy: witness pulls in the metrics registry, and metrics.py itself
    # imports the guards — a package-level witness import would cycle
    if name in ("WITNESS", "LockWitness"):
        from . import witness

        return getattr(witness, name)
    raise AttributeError(name)
