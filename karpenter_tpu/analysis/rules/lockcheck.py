"""lockcheck: guarded-attribute access must happen under the declared lock.

For every class carrying `@guarded_by(lock, *attrs, aliases=...)`
(analysis/guards.py), walk each method body and flag:

- any read/write of a guarded attribute (`self.<attr>`) that is not
  lexically inside a `with self.<lock>:` (or declared alias) block;
- any call to a lock-required sibling method (`@requires_lock`, or the
  `*_locked` naming convention) made outside such a block — the callee's
  body is checked as if the lock were held, so the obligation moves to the
  call site.

`__init__` is exempt (the object is unpublished), and nested functions /
lambdas inherit the lock state of their definition point — conservative,
since a closure can escape the block, but closures that stash guarded state
for later are exactly what the rule should surface.
"""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from ..core import Finding, Module, decorator_name, dotted_name, self_attribute

RULE = "lockcheck"


def _guard_decl(cls: ast.ClassDef):
    """(lock, attrs, aliases) from an @guarded_by decorator, or None."""
    for dec in cls.decorator_list:
        if not isinstance(dec, ast.Call) or decorator_name(dec) != "guarded_by":
            continue
        consts = [a.value for a in dec.args if isinstance(a, ast.Constant) and isinstance(a.value, str)]
        if not consts:
            return None
        aliases: Tuple[str, ...] = ()
        for kw in dec.keywords:
            if kw.arg == "aliases" and isinstance(kw.value, (ast.Tuple, ast.List)):
                aliases = tuple(
                    e.value for e in kw.value.elts if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
        return consts[0], tuple(consts[1:]), aliases
    return None


def _requires_lock(fn) -> bool:
    if fn.name.endswith("_locked"):
        return True
    return any(decorator_name(dec) == "requires_lock" for dec in fn.decorator_list)


class _MethodChecker(ast.NodeVisitor):
    def __init__(self, module: Module, cls_name: str, method: str, lock: str,
                 attrs: Set[str], aliases: Set[str], locked_methods: Set[str], held: bool):
        self.module = module
        self.scope = f"{cls_name}.{method}"
        self.lock = lock
        self.attrs = attrs
        self.lock_names = {lock} | aliases
        self.locked_methods = locked_methods
        self.depth = 1 if held else 0
        self.findings: List[Finding] = []

    # -- lock-state tracking ---------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        holds = any(self._is_lock_expr(item.context_expr) for item in node.items)
        if holds:
            self.depth += 1
        self.generic_visit(node)
        if holds:
            self.depth -= 1

    def _is_lock_expr(self, expr: ast.AST) -> bool:
        # `with self._lock:` or `with self._cond:` (alias); also tolerate
        # `self._lock()`-style acquire wrappers should one appear
        if isinstance(expr, ast.Call):
            expr = expr.func
        attr = self_attribute(expr)
        return attr is not None and attr in self.lock_names

    # -- access checks ----------------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = self_attribute(node)
        if attr in self.attrs and self.depth == 0:
            self.findings.append(
                Finding(
                    rule=RULE, path=self.module.path, line=node.lineno, scope=self.scope, key=attr,
                    message=f"access of guarded attribute self.{attr} outside `with self.{self.lock}`",
                )
            )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func)
        if name.startswith("self.") and "." not in name[5:]:
            callee = name[5:]
            if callee in self.locked_methods and self.depth == 0:
                self.findings.append(
                    Finding(
                        rule=RULE, path=self.module.path, line=node.lineno, scope=self.scope, key=callee,
                        message=(
                            f"call to lock-required method self.{callee}() outside "
                            f"`with self.{self.lock}` (callee assumes the lock is held)"
                        ),
                    )
                )
        self.generic_visit(node)


def check(modules: List[Module]) -> List[Finding]:
    findings: List[Finding] = []
    for module in modules:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            decl = _guard_decl(node)
            if decl is None:
                continue
            lock, attrs, aliases = decl
            methods = [
                n for n in node.body if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            locked_methods = {m.name for m in methods if _requires_lock(m)}
            for method in methods:
                if method.name == "__init__":
                    continue
                checker = _MethodChecker(
                    module, node.name, method.name, lock, set(attrs), set(aliases),
                    locked_methods, held=_requires_lock(method),
                )
                for stmt in method.body:
                    checker.visit(stmt)
                findings.extend(checker.findings)
    return findings
