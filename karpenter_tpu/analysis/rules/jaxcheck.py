"""jaxcheck: no host-sync constructs inside jit-reachable code.

The dense solver's <200 ms SLO died once already to an unattributable
host-side stall (ROADMAP "Net state"); CvxCluster-style incremental solving
only pays off while the jitted path stays free of accidental device->host
round trips. This rule finds the silent ones at lint time:

- `.item()` / `.tolist()` / `jax.device_get` / `.block_until_ready()` —
  explicit host syncs;
- `np.asarray` / `np.array` on values flowing through a jitted function —
  a device fetch that disguises itself as a type conversion;
- builtin `float()` / `int()` / `bool()` on non-constant values — forces
  concretization of a traced value;
- wall-clock (`time.*`) and host RNG (`random.*`, `np.random.*`) calls —
  trace-time constants masquerading as runtime values, plus a recompile
  hazard;
- Python `if`/`while` on a traced parameter of a directly-jitted function
  (parameters named in `static_argnames` are exempt) — array truthiness.

Scope: functions REACHABLE from jit entry points in `solver/`, `ops/`, and
`parallel/`. Entry points are functions decorated `@jax.jit` / `@jit` /
`@partial(jax.jit, ...)` / `@pjit` / `@jax.pmap`, plus any function passed
to a jit-wrapper call in ANY of the mesh-wrapper spellings `parallel/`
uses: positional (`jax.jit(fn, in_shardings=...)`,
`shard_map(fn, mesh=...)`), keyword (`shard_map(f=fn, ...)`), applied
partial (`partial(shard_map, mesh=...)(fn)`), nested
(`jax.jit(shard_map(fn, ...))`), and import-aliased
(`from jax.experimental.shard_map import shard_map as shmap`).
Reachability follows plain-name and `self.<name>` references transitively
across the scanned modules — host-side orchestration code (e.g.
solver/dense.py's dispatch loop) that merely CALLS jitted kernels is
deliberately out of scope; it is allowed to sync.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from ..core import Finding, Module, decorator_name, dotted_name

RULE = "jaxcheck"

SCOPE_PREFIXES = ("karpenter_tpu/solver/", "karpenter_tpu/ops/", "karpenter_tpu/parallel/")

_JIT_NAMES = {"jit", "pjit", "pmap", "shard_map"}
# keyword names jit wrappers accept the wrapped function under
# (shard_map(f=...), jax.jit(fun=...))
_FN_KEYWORDS = {"f", "fun", "func"}


def _jit_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to jit wrappers by aliased imports:
    `from jax.experimental.shard_map import shard_map as shmap` makes
    'shmap' a jit spelling for this module."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name in _JIT_NAMES and alias.asname:
                    aliases.add(alias.asname)
    return aliases


_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NP_SYNC = {"np.asarray", "np.array", "onp.asarray", "onp.array", "numpy.asarray", "numpy.array"}
_CONCRETIZERS = {"float", "int", "bool"}


def _is_jit_expr(node: ast.AST, aliases: Set[str] = frozenset()) -> Tuple[bool, Set[str]]:
    """(is this expression a jit wrapper?, static_argnames if readable)."""
    name = dotted_name(node.func) if isinstance(node, ast.Call) else dotted_name(node)
    leaf = name.rsplit(".", 1)[-1]
    if leaf in _JIT_NAMES or leaf in aliases:
        return True, set()
    # partial(jax.jit, static_argnames=(...)) / functools.partial(jit, ...)
    if isinstance(node, ast.Call) and decorator_name(node) == "partial" and node.args:
        inner = dotted_name(node.args[0])
        if inner.rsplit(".", 1)[-1] in _JIT_NAMES or inner.rsplit(".", 1)[-1] in aliases:
            static: Set[str] = set()
            for kw in node.keywords:
                if kw.arg == "static_argnames":
                    if isinstance(kw.value, (ast.Tuple, ast.List)):
                        static = {e.value for e in kw.value.elts if isinstance(e, ast.Constant)}
                    elif isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, str):
                        static = {kw.value.value}
            return True, static
    return False, set()


class _FunctionIndexer(ast.NodeVisitor):
    """Collects every function definition (by simple name) plus the jit
    entry set for one module."""

    def __init__(self, module: Module):
        self.module = module
        self.functions: Dict[str, ast.AST] = {}
        self.entries: Dict[str, Set[str]] = {}  # name -> static_argnames
        self._jit_wrapped_names: Set[str] = set()
        self.aliases = _jit_aliases(module.tree)

    def _visit_function(self, node) -> None:
        self.functions.setdefault(node.name, node)
        for dec in node.decorator_list:
            jitted, static = _is_jit_expr(dec, self.aliases)
            if jitted:
                self.entries.setdefault(node.name, set()).update(static)
        self.generic_visit(node)

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def _collect_wrapped(self, node: ast.Call) -> None:
        """Record every function handed to a jit-wrapper call, positionally
        or under a known fn-keyword (`shard_map(f=impl, mesh=...)`)."""
        candidates = list(node.args) + [kw.value for kw in node.keywords if kw.arg in _FN_KEYWORDS]
        for arg in candidates:
            name = dotted_name(arg)
            if name.rsplit(".", 1)[-1] in _JIT_NAMES or name in self.aliases:
                continue  # partial(shard_map, ...): the wrapper is not the wrapped fn
            if name and "." not in name:
                self._jit_wrapped_names.add(name)
            elif name.startswith("self."):
                self._jit_wrapped_names.add(name[5:])

    def visit_Call(self, node: ast.Call) -> None:
        # fn = jax.jit(impl) / dispatch = pjit(impl, ...) / shard_map(f=impl)
        jitted, _ = _is_jit_expr(node, self.aliases)
        if jitted:
            self._collect_wrapped(node)
        elif (
            isinstance(node.func, ast.Call)
            and decorator_name(node.func) == "partial"
            and _is_jit_expr(node.func, self.aliases)[0]
        ):
            # applied partial ONLY: partial(shard_map, mesh=...)(impl) — the
            # outer call's operands are the wrapped functions. A direct
            # immediate invocation like jax.jit(impl)(batch) must NOT land
            # here: its outer operands are runtime arguments, not functions
            # (the inner jit call is visited separately and collects impl)
            self._collect_wrapped(node)
        self.generic_visit(node)

    def finish(self) -> None:
        for name in self._jit_wrapped_names:
            if name in self.functions:
                self.entries.setdefault(name, set())


def _referenced_functions(fn: ast.AST, known: Set[str]) -> Set[str]:
    """Simple names referenced in a function body that name known functions
    (call targets AND bare references like a kernel handed to pallas_call)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id in known:
            out.add(node.id)
        elif isinstance(node, ast.Attribute) and node.attr in known:
            base = dotted_name(node.value)
            if base in ("self", "cls"):
                out.add(node.attr)
    return out


class _HostSyncChecker(ast.NodeVisitor):
    def __init__(self, module: Module, fn, scope: str, traced_params: Set[str]):
        self.module = module
        self.fn = fn
        self.scope = scope
        self.traced_params = traced_params
        self.findings: List[Finding] = []

    def _flag(self, node: ast.AST, key: str, message: str) -> None:
        self.findings.append(
            Finding(rule=RULE, path=self.module.path, line=node.lineno, scope=self.scope, key=key, message=message)
        )

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func)
        leaf = name.rsplit(".", 1)[-1] if name else ""
        if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_METHODS:
            self._flag(node, node.func.attr, f".{node.func.attr}() forces a device->host sync inside a jitted path")
        elif name in _NP_SYNC:
            self._flag(node, name, f"{name}() on a traced value is a hidden device->host transfer")
        elif name == "jax.device_get":
            self._flag(node, name, "jax.device_get inside a jitted path is an explicit host sync")
        elif name in _CONCRETIZERS and node.args and not isinstance(node.args[0], ast.Constant):
            self._flag(node, name, f"builtin {name}() concretizes a traced value (host sync) inside a jitted path")
        elif name.startswith("time.") or (leaf in ("time", "monotonic", "perf_counter", "sleep") and name.split(".")[0] == "time"):
            self._flag(node, "wall-clock", f"{name}() inside a jitted path is a trace-time constant, not a runtime clock")
        elif (name.split(".", 1)[0] == "random" or ".random." in f".{name}") and name.split(".", 1)[0] != "jax":
            # jax.random.* is the CORRECT in-jit RNG; stdlib random and
            # np.random are the host-side hazards
            self._flag(node, "host-rng", f"{name}() is host RNG inside a jitted path; use jax.random with an explicit key")
        self.generic_visit(node)

    def _check_truthiness(self, node) -> None:
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Name) and sub.id in self.traced_params:
                self._flag(
                    node, "truthiness",
                    f"Python branch on traced parameter {sub.id!r} (array truthiness); "
                    f"use lax.cond/jnp.where or mark it static",
                )
                return

    def visit_If(self, node: ast.If) -> None:
        self._check_truthiness(node)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_truthiness(node)
        self.generic_visit(node)


def check(modules: List[Module]) -> List[Finding]:
    scanned = [m for m in modules if m.path.startswith(SCOPE_PREFIXES)]
    indexers: List[_FunctionIndexer] = []
    known: Set[str] = set()
    for module in scanned:
        indexer = _FunctionIndexer(module)
        indexer.visit(module.tree)
        indexer.finish()
        indexers.append(indexer)
        known.update(indexer.functions)

    # reachability: entry functions, then every known function they reference
    reachable: Dict[Tuple[str, str], Tuple[Module, ast.AST, Set[str], bool]] = {}
    worklist: List[Tuple[str, bool, Set[str]]] = []  # (name, is_entry, static_argnames)
    for indexer in indexers:
        for name, static in indexer.entries.items():
            worklist.append((name, True, static))
    seen: Set[str] = set()
    while worklist:
        name, is_entry, static = worklist.pop()
        if name in seen and not is_entry:
            continue
        seen.add(name)
        for indexer in indexers:
            fn = indexer.functions.get(name)
            if fn is None:
                continue
            key = (indexer.module.path, name)
            if key not in reachable or is_entry:
                reachable[key] = (indexer.module, fn, static, is_entry)
            for ref in _referenced_functions(fn, known):
                if ref not in seen:
                    worklist.append((ref, False, set()))

    findings: List[Finding] = []
    for (path, name), (module, fn, static, is_entry) in sorted(reachable.items()):
        traced: Set[str] = set()
        if is_entry:
            args = fn.args
            params = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
            traced = {p for p in params if p not in static and p not in ("self", "cls")}
        checker = _HostSyncChecker(module, fn, scope=name, traced_params=traced)
        for stmt in fn.body:
            checker.visit(stmt)
        findings.extend(checker.findings)
    return findings
