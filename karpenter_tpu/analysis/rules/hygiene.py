"""Hygiene rules: swallowed exceptions, stray wall-clock calls, wild threads.

Three small rules that each encode an existing repo-wide discipline:

- **swallow** — a broad `except Exception:` / bare `except:` handler that
  neither re-raises nor leaves evidence (a logging call or a metrics
  counter increment) hides failures on self-healing controller loops; the
  fix is `log.<level>` + a counter, the baseline records the few handlers
  whose silence is the contract (e.g. typed-fallback returns).
- **clock** — direct `time.sleep` / `time.monotonic` outside
  `utils/clock.py` bypasses the Clock seam, so FakeClock suites cannot
  drive that code path deterministically.
- **threads** — `threading.Thread(...)` without BOTH `name=` and `daemon=`
  makes stack dumps unreadable and shutdown behavior accidental; every
  loop thread in the runtime is named and explicitly daemonized.
"""

from __future__ import annotations

import ast
import hashlib
from typing import List, Set

from ..core import Finding, Module, ScopedVisitor, dotted_name

SWALLOW_RULE = "swallow"
CLOCK_RULE = "clock"
THREADS_RULE = "threads"

_CLOCK_EXEMPT = ("karpenter_tpu/utils/clock.py",)
_LOG_LEVELS = {"exception", "warning", "error", "info", "debug", "critical", "log"}
_BROAD = {"Exception", "BaseException"}


# -- swallow -------------------------------------------------------------------


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    types = handler.type.elts if isinstance(handler.type, ast.Tuple) else [handler.type]
    for t in types:
        name = dotted_name(t)
        if name.rsplit(".", 1)[-1] in _BROAD:
            return True
    return False


def _leaves_evidence(handler: ast.ExceptHandler) -> bool:
    """True when the handler re-raises or records the failure somewhere a
    human or a metric scrape can see it."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            leaf = name.rsplit(".", 1)[-1]
            root = name.split(".", 1)[0]
            if leaf in _LOG_LEVELS and ("log" in root.lower() or "log" in name.lower()):
                return True
            if leaf == "inc":  # metrics counter
                return True
    return False


def _handler_key(handler: ast.ExceptHandler) -> str:
    """Content-derived key: a hash of the handler's (position-independent)
    AST dump. An ordinal key (except#0) would let a vetted suppression
    silently migrate to a NEW handler added earlier in the same scope; a
    content key pins the suppression to this handler's exact type+body —
    editing the handler invalidates it, which forces a re-vet (intended)."""
    return f"except:{hashlib.md5(ast.dump(handler).encode()).hexdigest()[:8]}"


class _SwallowVisitor(ScopedVisitor):
    def __init__(self, module: Module):
        super().__init__()
        self.module = module
        self.findings: List[Finding] = []

    def visit_Try(self, node: ast.Try) -> None:
        for handler in node.handlers:
            if _is_broad(handler) and not _leaves_evidence(handler):
                self.findings.append(
                    Finding(
                        rule=SWALLOW_RULE, path=self.module.path, line=handler.lineno,
                        scope=self.scope, key=_handler_key(handler),
                        message="broad except swallows the exception without logging or counting it",
                    )
                )
        self.generic_visit(node)


# -- clock ---------------------------------------------------------------------

_CLOCK_FNS = {"sleep", "monotonic", "monotonic_ns"}


class _ClockVisitor(ScopedVisitor):
    def __init__(self, module: Module, time_aliases: Set[str], from_imports: Set[str]):
        super().__init__()
        self.module = module
        self.time_aliases = time_aliases
        self.from_imports = from_imports
        self.findings: List[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func)
        flagged = None
        if "." in name:
            root, leaf = name.split(".", 1)
            if root in self.time_aliases and leaf in _CLOCK_FNS:
                flagged = leaf
        elif name in self.from_imports:
            flagged = name
        if flagged is not None:
            self.findings.append(
                Finding(
                    rule=CLOCK_RULE, path=self.module.path, line=node.lineno, scope=self.scope,
                    key=flagged,
                    message=f"direct time.{flagged}() bypasses utils/clock.Clock (FakeClock cannot cover this path)",
                )
            )
        self.generic_visit(node)


def _time_imports(tree: ast.AST):
    aliases: Set[str] = set()
    from_imports: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time":
                    aliases.add(alias.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name in _CLOCK_FNS:
                    from_imports.add(alias.asname or alias.name)
    return aliases, from_imports


# -- threads -------------------------------------------------------------------


class _ThreadVisitor(ScopedVisitor):
    def __init__(self, module: Module, threading_aliases: Set[str]):
        super().__init__()
        self.module = module
        self.threading_aliases = threading_aliases
        self.findings: List[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func)
        is_thread = name in {f"{alias}.Thread" for alias in self.threading_aliases} or name == "Thread"
        if is_thread:
            kwargs = {kw.arg for kw in node.keywords}
            for required in ("name", "daemon"):
                if required not in kwargs:
                    self.findings.append(
                        Finding(
                            rule=THREADS_RULE, path=self.module.path, line=node.lineno, scope=self.scope,
                            key=required,
                            message=f"threading.Thread(...) without {required}=: loop threads must be "
                                    f"named and explicitly daemonized",
                        )
                    )
        self.generic_visit(node)


def _threading_aliases(tree: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "threading":
                    out.add(alias.asname or "threading")
    return out


# -- entry points --------------------------------------------------------------


def check_swallow(modules: List[Module]) -> List[Finding]:
    findings: List[Finding] = []
    for module in modules:
        visitor = _SwallowVisitor(module)
        visitor.visit(module.tree)
        findings.extend(visitor.findings)
    return findings


def check_clock(modules: List[Module]) -> List[Finding]:
    findings: List[Finding] = []
    for module in modules:
        if module.path in _CLOCK_EXEMPT:
            continue
        aliases, from_imports = _time_imports(module.tree)
        if not aliases and not from_imports:
            continue
        visitor = _ClockVisitor(module, aliases, from_imports)
        visitor.visit(module.tree)
        findings.extend(visitor.findings)
    return findings


def check_threads(modules: List[Module]) -> List[Finding]:
    findings: List[Finding] = []
    for module in modules:
        aliases = _threading_aliases(module.tree)
        visitor = _ThreadVisitor(module, aliases or {"threading"})
        visitor.visit(module.tree)
        findings.extend(visitor.findings)
    return findings
