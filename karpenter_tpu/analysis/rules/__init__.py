"""Rule registry for the static-analysis framework (analysis/core.py).

Two tiers share one baseline:

- **AST tier** (ALL_RULES): callables `List[Module] -> List[Finding]` over
  parsed source — import-light, runs on jax-free CI stages. Adding a rule
  here is the ONLY registration step: the CLI, the baseline machinery, and
  the fixture-test harness all iterate ALL_RULES.
- **program tier** (programcheck / CONTRACT_RULE_NAMES): findings over the
  jaxpr-level contracts (analysis/contracts.py) — needs jax, runs behind
  `analyze --contracts`. Listed here by NAME ONLY so the shared baseline
  machinery can split suppressions by tier without importing jax.
"""

from __future__ import annotations

from . import hygiene, jaxcheck, lockcheck
from .programcheck import CONTRACT_RULE_NAMES

ALL_RULES = (
    lockcheck.check,
    jaxcheck.check,
    hygiene.check_swallow,
    hygiene.check_clock,
    hygiene.check_threads,
)

RULE_NAMES = (
    lockcheck.RULE,
    jaxcheck.RULE,
    hygiene.SWALLOW_RULE,
    hygiene.CLOCK_RULE,
    hygiene.THREADS_RULE,
)

__all__ = ["ALL_RULES", "RULE_NAMES", "CONTRACT_RULE_NAMES", "lockcheck", "jaxcheck", "hygiene"]
