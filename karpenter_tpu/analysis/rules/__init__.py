"""Rule registry for the static-analysis framework (analysis/core.py).

Each rule is a callable `List[Module] -> List[Finding]`. Adding a rule here
is the ONLY registration step: the CLI, the baseline machinery, and the
fixture-test harness all iterate ALL_RULES.
"""

from __future__ import annotations

from . import hygiene, jaxcheck, lockcheck

ALL_RULES = (
    lockcheck.check,
    jaxcheck.check,
    hygiene.check_swallow,
    hygiene.check_clock,
    hygiene.check_threads,
)

RULE_NAMES = (
    lockcheck.RULE,
    jaxcheck.RULE,
    hygiene.SWALLOW_RULE,
    hygiene.CLOCK_RULE,
    hygiene.THREADS_RULE,
)

__all__ = ["ALL_RULES", "RULE_NAMES", "lockcheck", "jaxcheck", "hygiene"]
