"""programcheck: findings over the jaxpr-level program contracts.

The second analysis tier. Where the AST rules (jaxcheck & co.) read the
solver's *source*, these rules read its *traced programs* — the contract
dicts analysis/contracts.py extracts with `jax.make_jaxpr` over the bench
shape grid — and emit `Finding`s through the exact same justified-baseline
machinery (one baseline.json, one (rule, path, scope, key) shape). Scope is
the jit entry's registered {fn} name (the flight recorder's label), never a
line number, so suppressions survive unrelated edits — same anchoring
discipline as the AST tier.

Three rule classes:

- **program-donation** — a device-resident input large enough to matter
  (>= DONATION_MIN_BYTES at the base grid point) has a byte-size-matched
  output buffer free to alias at EVERY grid point but is not donated
  (`donate_argnums` debt the incremental steady-state solve needs paid);
  or a donation is declared that XLA would reject (no matching output — a
  warning-per-compile in production, and a false sense of reuse).
- **program-promotion** — a 64-bit intermediate appears when the entry is
  re-traced under enable_x64 with the same pinned 32-bit inputs (dtype
  discipline leaning on the global flag: the program doubles its HBM and
  recompiles differently depending on process config), or an output leaks
  weak_type=True (a retrace hazard for any downstream consumer).
- **program-constant** — concrete arrays closed over and baked into the
  jaxpr above CONST_MIN_BYTES: every compiled executable carries them, and
  a refactor that captures a catalog by accident ships it to the device
  once per shape bucket. The current solver surface is pinned at ZERO
  captured bytes — this rule keeps it there.

Unlike the AST tier these rules import jax and the solver modules (the
programs must be traced), so they are NOT in ALL_RULES; `analyze
--contracts` is their entry point and tier-1 runs it as a subprocess gate.
"""

from __future__ import annotations

from typing import Dict, List

from ..core import Finding

DONATION_RULE = "program-donation"
PROMOTION_RULE = "program-promotion"
CONSTANT_RULE = "program-constant"

CONTRACT_RULE_NAMES = (DONATION_RULE, PROMOTION_RULE, CONSTANT_RULE)

# inputs below this (base grid point) aren't worth a donation finding: the
# aliasing saves an allocation the size of the buffer, and sub-512B buffers
# are noise next to the [P, T] surfaces
DONATION_MIN_BYTES = 512

# scalars traced into literals are free; a captured array above this is a
# baked-in per-executable payload worth a finding
CONST_MIN_BYTES = 64


def findings_from_contracts(doc: dict) -> List[Finding]:
    """Contract dict (analysis/contracts.py build_contracts) -> Findings,
    sorted with the same key as the AST runner so output interleaves
    deterministically."""
    findings: List[Finding] = []
    for name, entry in sorted(doc.get("entries", {}).items()):
        path = entry.get("module", "")
        donation: Dict[str, list] = entry.get("donation", {})
        for arg in donation.get("candidates", ()):
            findings.append(
                Finding(
                    rule=DONATION_RULE,
                    path=path,
                    line=1,
                    scope=name,
                    key=arg,
                    message=(
                        f"input {arg!r} has a byte-size-matched output buffer at every grid point "
                        f"but is not donated — add donate_argnums (device-buffer reuse the "
                        f"incremental solve depends on) or baseline with why the caller must "
                        f"keep the input alive"
                    ),
                )
            )
        for arg in donation.get("rejected", ()):
            findings.append(
                Finding(
                    rule=DONATION_RULE,
                    path=path,
                    line=1,
                    scope=name,
                    key=f"{arg}:rejected",
                    message=(
                        f"input {arg!r} is donated but no output of equal byte size exists to "
                        f"alias — XLA rejects the donation (warning per compile, no reuse)"
                    ),
                )
            )
        for promo in entry.get("promotions", ()):
            findings.append(
                Finding(
                    rule=PROMOTION_RULE,
                    path=path,
                    line=1,
                    scope=name,
                    key=promo,
                    message=(
                        f"{promo}: 64-bit/weak-typed value appears under enable_x64 with pinned "
                        f"32-bit inputs — pin the dtype (e.g. lax.argmin index_dtype, explicit "
                        f".astype) so the program is identical regardless of the global flag"
                    ),
                )
            )
        for const in entry.get("captured_consts", ()):
            if const.get("bytes", 0) < CONST_MIN_BYTES:
                continue
            shape = "x".join(str(d) for d in const.get("shape", ()))
            findings.append(
                Finding(
                    rule=CONSTANT_RULE,
                    path=path,
                    line=1,
                    scope=name,
                    key=f"const:{const.get('dtype')}[{shape}]",
                    message=(
                        f"captured constant {const.get('dtype')}[{shape}] "
                        f"({const.get('bytes')} bytes) is baked into the compiled program — "
                        f"pass it as an argument or baseline with why baking it in is right"
                    ),
                )
            )
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.key))
    return findings
