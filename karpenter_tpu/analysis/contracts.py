"""Solver program contracts: the compile-free jaxpr audit behind
`analyze --contracts`.

PR 8's AST tier (rules/*.py) reads *source*; this second tier reads the
*programs*: every registered jit entry of the solver pipeline (the same
registry flight.py attributes compile churn to) is abstractly interpreted
with `jax.make_jaxpr` over the bench shape grid — no XLA compile, no
device — and the facts that govern the incremental steady-state solve
(ROADMAP item 1) are extracted into a committed machine-readable contract,
`SOLVER_CONTRACTS.json`:

- **recompile axes** — per entry, which named shape dimensions (the flight
  recorder's signature vocabulary) are *declared varying* (a change is an
  expected retrace) vs *declared static* (a change recompiling this entry
  is a contract violation). The flight recorder's runtime recompile
  attribution is cross-checked against this declaration by the bench smoke
  gate (`recompile_violations`).
- **dtype surface** — every input/output dtype, weak-type leaks on
  outputs, and x64-sensitivity: the entry is re-traced under
  `jax.experimental.enable_x64()` with the SAME pinned f32/i32 inputs, and
  any 64-bit intermediate that appears means the program's dtype
  discipline depends on the global flag instead of pinned dtypes — the
  silent f64/i64 promotion class.
- **donation coverage** — which inputs are donated (`donated_invars` read
  straight off the traced pjit equation), which donations XLA would reject
  (no byte-size-matched output buffer to alias), and which large inputs
  are donation *candidates* left undonated (an unclaimed output of equal
  byte size exists at every grid point — the `donate_argnums` debt the
  incremental solve needs paid).
- **captured-constant bytes** — concrete arrays closed over and baked into
  the jaxpr (every nested sub-jaxpr is walked). Baked constants ride along
  with every compiled executable; the current solver surface is pinned at
  zero bytes.

Violations become `Finding`s (rules/programcheck.py) and flow through the
SAME justified-baseline machinery as the AST tier — one baseline.json, one
(rule, path, scope, key) suppression shape, one workflow.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..flight import _SIGNATURE_DIMS

CONTRACTS_BASENAME = "SOLVER_CONTRACTS.json"
SCHEMA_VERSION = 1

# the flight recorder's recompile-attribution vocabulary, imported (not
# duplicated) so contracts declare varying/static in exactly the terms the
# runtime cross-check compares — a dimension added to flight.py can never
# silently read as declared-static here
FLIGHT_DIMS = tuple(_SIGNATURE_DIMS)

# The bench shape grid the entries are audited over: BASE mirrors the smoke
# configs' scaled shapes, ALT perturbs every runtime-varying dimension so
# (a) re-tracing across the varying surface is proven and (b) donation
# byte-size matches are structural, not a numeric coincidence of one point.
# "resources" is deliberately identical in both: it is the canonical
# declared-STATIC axis (the encode's resource arity never changes within a
# deployment), and the grid embodies that.
GRID_BASE: Dict[str, int] = {
    "pods": 304,
    "groups": 8,
    "buckets": 24,
    "types": 56,
    "zones": 3,
    "capacity_types": 2,
    "resources": 3,
    "segments": 41,
    "sizes": 16,
    "views": 48,
    "bins": 40,
    "offerings": 6,  # zones x capacity_types, flattened
    "buckets_padded": 24,
    "types_padded": 128,
    "sizes_padded": 16,
    "views_padded": 128,
    "dirty_padded": 8,  # rebase delta axis, pow2 ladder (ops/rebase.py)
}
GRID_ALT: Dict[str, int] = {
    **GRID_BASE,
    "pods": 712,
    "groups": 12,
    "buckets": 40,
    "types": 104,
    "zones": 4,
    "capacity_types": 3,
    "segments": 57,
    "sizes": 24,
    "views": 160,
    "bins": 56,
    "offerings": 12,
    "buckets_padded": 40,
    "types_padded": 256,
    "sizes_padded": 24,
    "views_padded": 256,
    "dirty_padded": 16,
}


@dataclass(frozen=True)
class ArgSpec:
    """One array argument: named axes (grid dims or literal ints) + dtype."""

    name: str
    axes: Tuple[object, ...]  # str grid-dim names or int literals
    dtype: str  # numpy dtype name

    def shape(self, dims: Dict[str, int]) -> Tuple[int, ...]:
        return tuple(dims[a] if isinstance(a, str) else int(a) for a in self.axes)


@dataclass(frozen=True)
class EntrySpec:
    """One registered jit entry: how to build it, its abstract argument
    surface, and its declared recompile contract."""

    name: str  # MUST match the flight recorder's register_jit_entry label
    module: str  # repo-relative path, forward slashes
    resolve: Callable[[Dict[str, int]], object]  # dims -> jitted callable
    args: Tuple[ArgSpec, ...]
    varying: Tuple[str, ...]  # FLIGHT_DIMS declared runtime-varying
    # trailing static (hashed) arguments: (name, grid-dim name or literal)
    static_args: Tuple[Tuple[str, object], ...] = ()

    def static_values(self, dims: Dict[str, int]) -> Tuple[object, ...]:
        return tuple(dims[v] if isinstance(v, str) and v in dims else v for _, v in self.static_args)


def _audit_mesh():
    """The deterministic 1-device CPU mesh the per-mesh wrappers are audited
    on: the contract facts (avals, donation, consts) are mesh-shape
    independent, and pinning CPU keeps the committed JSON identical across
    hosts with and without accelerators."""
    from ..parallel.mesh import solver_mesh

    return solver_mesh(1, types_parallel=1, prefer_cpu=True)


def _resolve_plain(module_name: str, attr: str):
    def resolve(dims: Dict[str, int]):
        import importlib

        return getattr(importlib.import_module(module_name), attr)

    return resolve


def _resolve_sharded_step(dims: Dict[str, int]):
    from ..parallel.sharded import make_sharded_solve_step

    return make_sharded_solve_step(_audit_mesh(), dims["bins"])


def _resolve_sharded_bucket_cost(dims: Dict[str, int]):
    from ..parallel.sharded import make_sharded_bucket_cost

    return make_sharded_bucket_cost(_audit_mesh())


def default_entries() -> Tuple[EntrySpec, ...]:
    """The audited program surface. Names match flight.py's registered
    {fn} labels exactly — the runtime cross-check joins on them."""
    f32, i32, b8, i8 = "float32", "int32", "bool", "int8"
    ops = "karpenter_tpu.ops."
    return (
        EntrySpec(
            name="resource_fit",
            module="karpenter_tpu/ops/feasibility.py",
            resolve=_resolve_plain(ops + "feasibility", "resource_fit"),
            args=(ArgSpec("requests", ("pods", "resources"), f32), ArgSpec("caps", ("types", "resources"), f32)),
            varying=("pods", "types"),
        ),
        EntrySpec(
            name="feasibility_mask",
            module="karpenter_tpu/ops/feasibility.py",
            resolve=_resolve_plain(ops + "feasibility", "feasibility_mask"),
            args=(
                ArgSpec("requests", ("pods", "resources"), f32),
                ArgSpec("caps", ("types", "resources"), f32),
                ArgSpec("compat", ("groups", "types"), b8),
                ArgSpec("group_ids", ("pods",), i32),
            ),
            varying=("pods", "types", "groups"),
        ),
        EntrySpec(
            name="availability_counts",
            module="karpenter_tpu/ops/feasibility.py",
            resolve=_resolve_plain(ops + "feasibility", "availability_counts"),
            args=(
                ArgSpec("pair", ("buckets", "offerings"), f32),
                ArgSpec("cube", ("types", "offerings"), f32),
            ),
            varying=("buckets", "types", "zones", "capacity_types"),
        ),
        EntrySpec(
            name="bucket_type_cost",
            module="karpenter_tpu/ops/feasibility.py",
            resolve=_resolve_plain(ops + "feasibility", "bucket_type_cost"),
            args=(
                ArgSpec("sum_requests", ("buckets", "resources"), f32),
                ArgSpec("max_requests", ("buckets", "resources"), f32),
                ArgSpec("caps", ("types", "resources"), f32),
                ArgSpec("prices", ("types",), f32),
                ArgSpec("allowed", ("buckets", "types"), b8),
            ),
            varying=("buckets", "types"),
        ),
        EntrySpec(
            name="bucket_type_cost_packed",
            module="karpenter_tpu/ops/feasibility.py",
            resolve=_resolve_plain(ops + "feasibility", "bucket_type_cost_packed"),
            args=(
                ArgSpec("bucket_stats", (2, "buckets", "resources"), f32),
                ArgSpec("caps", ("types", "resources"), f32),
                ArgSpec("prices", ("types",), f32),
                ArgSpec("allowed", ("buckets", "types"), b8),
            ),
            varying=("buckets", "types"),
        ),
        EntrySpec(
            name="segment_usage",
            module="karpenter_tpu/ops/packing.py",
            resolve=_resolve_plain(ops + "packing", "segment_usage"),
            args=(ArgSpec("requests", ("pods", "resources"), f32), ArgSpec("bin_ids", ("pods",), i32)),
            static_args=(("num_segments", "segments"),),
            varying=("pods", "buckets"),
        ),
        EntrySpec(
            name="audit_layout",
            module="karpenter_tpu/ops/packing.py",
            resolve=_resolve_plain(ops + "packing", "audit_layout"),
            args=(ArgSpec("usage", ("buckets", "resources"), f32), ArgSpec("caps_of_bin", ("buckets", "resources"), f32)),
            varying=("buckets",),
        ),
        EntrySpec(
            name="warm_fill_counts",
            module="karpenter_tpu/ops/warmfill.py",
            resolve=_resolve_plain(ops + "warmfill", "warm_fill_counts"),
            args=(ArgSpec("sizes", ("sizes", "resources"), f32), ArgSpec("head", ("views", "resources"), f32)),
            varying=("pods",),
        ),
        EntrySpec(
            name="warm_fill_counts_pallas",
            module="karpenter_tpu/ops/warmfill.py",
            resolve=_resolve_plain(ops + "warmfill", "_warm_fill_counts_pallas_padded"),
            args=(
                ArgSpec("sizes_p", ("sizes_padded", "resources"), f32),
                ArgSpec("head_t", ("resources", "views_padded"), f32),
            ),
            static_args=(("interpret", True),),
            varying=("pods",),
        ),
        EntrySpec(
            name="rebase_view_state",
            module="karpenter_tpu/ops/rebase.py",
            resolve=_resolve_plain(ops + "rebase", "rebase_view_state"),
            args=(
                ArgSpec("buf", ("views_padded", "resources"), f32),
                ArgSpec("perm", ("views_padded",), i32),
                ArgSpec("rows", ("dirty_padded", "resources"), f32),
                ArgSpec("idx", ("dirty_padded",), i32),
            ),
            # the delta axes (views_padded lane pad, dirty_padded pow2
            # ladder) are padded-stable by construction; like
            # warm_fill_counts, their rare regrowth re-traces on shapes the
            # signature can only express through the batch axis
            varying=("pods",),
        ),
        EntrySpec(
            name="bucket_type_cost_pallas",
            module="karpenter_tpu/ops/pallas_kernels.py",
            resolve=_resolve_plain(ops + "pallas_kernels", "_bucket_type_cost_padded"),
            args=(
                ArgSpec("sum_requests", ("buckets_padded", "resources"), f32),
                ArgSpec("max_requests", ("buckets_padded", "resources"), f32),
                ArgSpec("caps_t", ("resources", "types_padded"), f32),
                ArgSpec("prices", (1, "types_padded"), f32),
                ArgSpec("allowed", ("buckets_padded", "types_padded"), i8),
            ),
            static_args=(("interpret", True),),
            varying=("buckets", "buckets_padded", "types", "types_padded"),
        ),
        EntrySpec(
            name="sharded_solve_step",
            module="karpenter_tpu/parallel/sharded.py",
            resolve=_resolve_sharded_step,
            args=(
                ArgSpec("requests", ("pods", "resources"), f32),
                ArgSpec("group_ids", ("pods",), i32),
                ArgSpec("compat", ("groups", "types"), b8),
                ArgSpec("caps", ("types", "resources"), f32),
                ArgSpec("prices", ("types",), f32),
                ArgSpec("allowed", ("buckets", "types"), b8),
                ArgSpec("bucket_sum", ("buckets", "resources"), f32),
                ArgSpec("bucket_max", ("buckets", "resources"), f32),
                ArgSpec("bin_ids", ("pods",), i32),
            ),
            varying=("pods", "groups", "buckets", "types", "buckets_padded", "types_padded"),
        ),
        EntrySpec(
            name="sharded_bucket_cost",
            module="karpenter_tpu/parallel/sharded.py",
            resolve=_resolve_sharded_bucket_cost,
            args=(
                ArgSpec("bucket_stats", (2, "buckets", "resources"), f32),
                ArgSpec("caps", ("types", "resources"), f32),
                ArgSpec("prices", ("types",), f32),
                ArgSpec("allowed", ("buckets", "types"), b8),
            ),
            varying=("buckets", "types", "buckets_padded", "types_padded"),
        ),
    )


# -- the abstract interpretation ----------------------------------------------


_64BIT = ("float64", "int64", "uint64", "complex128")


def _abstract_args(spec: EntrySpec, dims: Dict[str, int]):
    import jax
    import numpy as np

    return tuple(jax.ShapeDtypeStruct(a.shape(dims), np.dtype(a.dtype)) for a in spec.args)


def _trace(spec: EntrySpec, dims: Dict[str, int]):
    """make_jaxpr the entry at one grid point; returns (closed_jaxpr,
    donated_invars, inner_closed_jaxpr). Tracing only — no XLA compile."""
    import jax

    fn = spec.resolve(dims)
    n_array = len(spec.args)
    static_argnums = tuple(range(n_array, n_array + len(spec.static_args)))
    closed = jax.make_jaxpr(fn, static_argnums=static_argnums or None)(
        *_abstract_args(spec, dims), *spec.static_values(dims)
    )
    donated = None
    inner = closed
    for eqn in closed.jaxpr.eqns:
        if eqn.primitive.name == "pjit" and "jaxpr" in eqn.params:
            donated = eqn.params.get("donated_invars")
            inner = eqn.params["jaxpr"]
            break
    return closed, donated, inner


def _walk_nested(closed, visit) -> None:
    """visit(closed_jaxpr) on a closed jaxpr and every nested sub-jaxpr
    reachable through equation params (pjit bodies, scan/cond branches,
    pallas kernels)."""
    seen = set()
    stack = [closed]
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        visit(node)
        jaxpr = getattr(node, "jaxpr", node)
        for eqn in getattr(jaxpr, "eqns", ()):
            for value in eqn.params.values():
                candidates = value if isinstance(value, (list, tuple)) else (value,)
                for cand in candidates:
                    if hasattr(cand, "eqns") or hasattr(cand, "jaxpr"):
                        stack.append(cand)


def _captured_consts(closed) -> List[dict]:
    out: List[dict] = []

    def visit(node):
        for const in getattr(node, "consts", ()):
            shape = getattr(const, "shape", None)
            if shape is None or getattr(const, "size", 0) == 0:
                continue
            out.append(
                {
                    "shape": [int(d) for d in shape],
                    "dtype": str(getattr(const, "dtype", "?")),
                    "bytes": int(getattr(const, "nbytes", 0)),
                }
            )

    _walk_nested(closed, visit)
    out.sort(key=lambda c: (-c["bytes"], c["dtype"], c["shape"]))
    return out


def _x64_sensitive(spec: EntrySpec, dims: Dict[str, int]) -> List[str]:
    """Re-trace under enable_x64 with the SAME pinned 32-bit inputs; any
    64-bit aval that appears is dtype discipline leaning on the global flag."""
    import jax

    with jax.experimental.enable_x64():
        closed, _, _ = _trace(spec, dims)
    hits = set()

    def visit(node):
        jaxpr = getattr(node, "jaxpr", node)
        for eqn in getattr(jaxpr, "eqns", ()):
            if eqn.primitive.name == "pjit":
                # the wrapper eqn's outvars restate its inner jaxpr's outputs;
                # the nested walk visits the inner program and names the
                # actually-promoting primitive instead
                continue
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                dtype = str(getattr(aval, "dtype", ""))
                if dtype in _64BIT:
                    hits.add(f"{eqn.primitive.name}:{dtype}")

    _walk_nested(closed, visit)
    return sorted(hits)


def _donation_audit(spec: EntrySpec, traces: Sequence[tuple]) -> dict:
    """Greedy byte-size matching of inputs to outputs at EVERY grid point:
    a donated input must find an unclaimed output of equal byte size at all
    points or XLA would reject the aliasing; an undonated input that finds
    one (and is large enough to matter) is a candidate left on the table."""
    donated_names: List[str] = []
    rejected: List[str] = []
    candidates: List[str] = []
    per_point = []
    for closed, donated, inner in traces:
        jaxpr = inner.jaxpr if hasattr(inner, "jaxpr") else inner
        in_bytes = [
            int(v.aval.size) * v.aval.dtype.itemsize for v in jaxpr.invars
        ]
        out_bytes = [int(v.aval.size) * v.aval.dtype.itemsize for v in jaxpr.outvars]
        per_point.append((in_bytes, out_bytes))
    donated_flags = traces[0][1] or (False,) * len(spec.args)

    def match_all_points(arg_idx: int, claimed: List[set]) -> Optional[List[int]]:
        """Output index per point aliasable by this input, or None."""
        picks = []
        for point, (in_bytes, out_bytes) in enumerate(per_point):
            pick = next(
                (o for o, ob in enumerate(out_bytes) if o not in claimed[point] and ob == in_bytes[arg_idx]),
                None,
            )
            if pick is None:
                return None
            picks.append(pick)
        return picks

    claimed: List[set] = [set() for _ in per_point]
    for i, arg in enumerate(spec.args):
        if i < len(donated_flags) and donated_flags[i]:
            picks = match_all_points(i, claimed)
            if picks is None:
                rejected.append(arg.name)
            else:
                donated_names.append(arg.name)
                for point, pick in enumerate(picks):
                    claimed[point].add(pick)
    from .rules.programcheck import DONATION_MIN_BYTES

    for i, arg in enumerate(spec.args):
        if i < len(donated_flags) and donated_flags[i]:
            continue
        if per_point[0][0][i] < DONATION_MIN_BYTES:
            continue
        if match_all_points(i, claimed) is not None:
            candidates.append(arg.name)
    return {"donated": donated_names, "rejected": rejected, "candidates": candidates}


def audit_entry(spec: EntrySpec, grid_points: Sequence[Dict[str, int]] = (GRID_BASE, GRID_ALT)) -> dict:
    """Audit one entry over the grid; returns its contract dict."""
    traces = [_trace(spec, dims) for dims in grid_points]
    closed, donated, inner = traces[0]
    jaxpr = inner.jaxpr if hasattr(inner, "jaxpr") else inner
    base = grid_points[0]
    outputs = []
    for i, var in enumerate(jaxpr.outvars):
        aval = var.aval
        outputs.append(
            {
                "shape": [int(d) for d in aval.shape],
                "dtype": str(aval.dtype),
                "weak_type": bool(getattr(aval, "weak_type", False)),
            }
        )
    consts = _captured_consts(traces[0][0])
    promotions = list(_x64_sensitive(spec, base))
    promotions.extend(f"out[{i}]:weak_type" for i, o in enumerate(outputs) if o["weak_type"])
    varying = sorted(spec.varying)
    donation = _donation_audit(spec, traces)
    return {
        "module": spec.module,
        "args": [
            {
                "name": a.name,
                "axes": [ax if isinstance(ax, str) else int(ax) for ax in a.axes],
                "dtype": a.dtype,
                "donated": a.name in donation["donated"],
            }
            for a in spec.args
        ],
        "static_args": [name for name, _ in spec.static_args],
        "outputs": outputs,
        "varying_axes": varying,
        "static_axes": sorted(set(FLIGHT_DIMS) - set(varying)),
        "donation": donation,
        "promotions": sorted(set(promotions)),
        "captured_consts": consts,
        "captured_const_bytes": sum(c["bytes"] for c in consts),
    }


def build_contracts(entries: Optional[Sequence[EntrySpec]] = None) -> dict:
    """The full contract document (deterministic: sorted entries, no
    timestamps; the digest keys the staleness gate)."""
    specs = list(entries if entries is not None else default_entries())
    doc_entries = {spec.name: audit_entry(spec) for spec in specs}
    body = {
        "schema_version": SCHEMA_VERSION,
        "grid": {"base": GRID_BASE, "alt": GRID_ALT},
        "entries": {name: doc_entries[name] for name in sorted(doc_entries)},
    }
    digest = hashlib.sha256(json.dumps(body, sort_keys=True).encode("utf-8")).hexdigest()[:16]
    return {
        "comment": (
            "Solver program contracts — generated by `python -m karpenter_tpu.cmd.analyze "
            "--contracts --write`, gated by `--contracts --check`. Per jit entry: declared "
            "varying/static recompile axes (cross-checked against the flight recorder's "
            "runtime attribution by the bench smoke gate), dtype surface with x64-sensitive "
            "promotions, donation coverage, and captured-constant bytes. Do not edit by hand."
        ),
        **body,
        "digest": digest,
    }


def default_contracts_path(root: str) -> str:
    return os.path.join(root, CONTRACTS_BASENAME)


def load_committed(root: str, path: Optional[str] = None) -> Optional[dict]:
    path = path or default_contracts_path(root)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def write_contracts(root: str, path: Optional[str] = None, entries: Optional[Sequence[EntrySpec]] = None) -> dict:
    doc = build_contracts(entries)
    path = path or default_contracts_path(root)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    return doc


def staleness_errors(committed: Optional[dict], current: dict) -> List[str]:
    """Staleness gate: the committed contract must equal the recomputed one.
    Equality is judged on CONTENT (schema/grid/entries), never on the
    committed file's own digest field — a hand-edited file keeps its old
    digest, and trusting it would wave the tamper through."""
    if committed is None:
        return [f"{CONTRACTS_BASENAME} missing — run `analyze --contracts --write` and commit it"]

    def body(doc: dict) -> dict:
        return {k: doc.get(k) for k in ("schema_version", "grid", "entries")}

    if body(committed) == body(current) and committed.get("digest") == current.get("digest"):
        return []
    errors = [f"{CONTRACTS_BASENAME} is stale — run `analyze --contracts --write` and commit the diff"]
    old_entries = committed.get("entries", {})
    new_entries = current.get("entries", {})
    for name in sorted(set(old_entries) | set(new_entries)):
        old, new = old_entries.get(name), new_entries.get(name)
        if old is None:
            errors.append(f"  entry {name}: new (no committed contract)")
        elif new is None:
            errors.append(f"  entry {name}: removed (committed contract is orphaned)")
        elif json.dumps(old, sort_keys=True) != json.dumps(new, sort_keys=True):
            changed = [k for k in sorted(set(old) | set(new)) if old.get(k) != new.get(k)]
            errors.append(f"  entry {name}: changed field(s) {changed}")
    return errors


# -- the runtime cross-check (flight recorder <-> static contract) ------------


def recompile_violations(records: Sequence[object], doc: Optional[dict]) -> List[str]:
    """Cross-validate observed recompiles against the declared contract.

    A recompile of entry E attributed to changed shape axes D is
    *contract-explained* when at least one axis in D is declared varying
    for E; it is a violation when every changed axis is declared static —
    the program retraced on an axis the contract promises never moves.
    Out of scope: process-wide cold starts, unattributed ('other')
    compiles, and per-fn FIRST compiles (record.first_compiles — an entry
    whose executable cache was empty when the solve started is a path
    engaging for the first time, not a retrace; the solve-level shape
    delta says nothing about it). An entry with no contract at all is
    itself a violation (the registry and the contract must stay in
    lockstep)."""
    if doc is None:
        return [f"{CONTRACTS_BASENAME} missing — the recompile cross-check has no contract to check against"]
    entries = doc.get("entries", {})
    violations: List[str] = []
    for rec in records:
        recompile = rec.recompile if hasattr(rec, "recompile") else rec.get("recompile")
        attribution = list(
            rec.recompile_attribution if hasattr(rec, "recompile_attribution") else rec.get("recompile_attribution", [])
        )
        compiled = dict(rec.compiled_fns if hasattr(rec, "compiled_fns") else rec.get("compiled_fns", {}))
        first = set(rec.first_compiles if hasattr(rec, "first_compiles") else rec.get("first_compiles", ()))
        signature = dict(rec.signature if hasattr(rec, "signature") else rec.get("signature", {}))
        rec_id = rec.id if hasattr(rec, "id") else rec.get("id")
        if not recompile or not attribution or attribution == ["cold-start"]:
            continue
        for fn_name in sorted(compiled):
            if fn_name == "other" or fn_name in first:
                continue
            entry = entries.get(fn_name)
            if entry is None:
                violations.append(
                    f"solve {rec_id}: recompile of {fn_name!r} but no contract entry exists — "
                    f"add it to analysis/contracts.py default_entries()"
                )
                continue
            varying = set(entry.get("varying_axes", ()))
            observed = set(attribution)
            if observed & varying:
                continue
            observed_sig = {dim: signature.get(dim) for dim in sorted(observed)}
            violations.append(
                f"solve {rec_id}: recompile of {fn_name!r} attributed to declared-STATIC axis(es) "
                f"{sorted(observed)} — contract declares varying={sorted(varying)}, "
                f"static={entry.get('static_axes')}; observed signature change: {observed_sig}"
            )
    return violations
