"""Lock-discipline declarations: the `@guarded_by` / `@requires_lock` seam.

Go-Karpenter gets its concurrency discipline checked for free (`go vet`,
the race detector, lint conventions like `mu` guarding the fields below it).
This module is the declaration half of the Python analog: shared-state
classes declare WHICH lock guards WHICH attributes, and the AST checker
(analysis/rules/lockcheck.py) verifies every method-body access happens
under `with self.<lock>`.

The decorators are deliberately inert at runtime — they attach metadata and
return the class/function unchanged, so declaring a contract costs nothing
on any hot path. The checker never imports the code; it reads the decorator
syntactically, which is what lets it run on a file with unimportable
dependencies (e.g. jax-free CI stages).

Conventions the checker understands:

- `@guarded_by("_lock", "_attr_a", "_attr_b", aliases=("_cond",))` on a
  class: `_attr_a`/`_attr_b` may only be read or written inside a
  `with self._lock:` block (or `with self._cond:` for declared aliases —
  a Condition constructed over the same lock).
- `@requires_lock` on a method: the CALLER must hold the class's declared
  lock; the method body is checked as if the lock were held, and every
  call site of the method outside a lock block is flagged instead.
- a method whose name ends in `_locked` is treated exactly like
  `@requires_lock` (the Go `fooLocked` convention).
- `__init__` is exempt: the object is not yet published to other threads.
"""

from __future__ import annotations

from typing import Tuple

GUARDED_ATTR = "__guarded_by__"
REQUIRES_LOCK_ATTR = "__requires_lock__"


def guarded_by(lock: str, *attrs: str, aliases: Tuple[str, ...] = ()):
    """Class decorator declaring that `attrs` are guarded by `self.<lock>`.

    `aliases` names attributes whose `with` block also proves the lock is
    held — e.g. a `threading.Condition` constructed over the same lock.
    """

    def decorate(cls):
        setattr(cls, GUARDED_ATTR, {"lock": lock, "attrs": tuple(attrs), "aliases": tuple(aliases)})
        return cls

    return decorate


def requires_lock(fn):
    """Marks a method whose caller must already hold the class's declared
    lock (the `fooLocked` convention, spelled as a decorator)."""
    setattr(fn, REQUIRES_LOCK_ATTR, True)
    return fn
