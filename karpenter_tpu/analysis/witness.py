"""Runtime lock-order witness: the dynamic half of the concurrency tooling.

Go-Karpenter leans on the race detector to catch what `go vet` cannot; this
is the Python analog for LOCK ORDERING. Shared-state classes create their
locks through `WITNESS.lock/rlock/condition(name)`; while the witness is
enabled, every acquisition records the per-thread held-set so the witness
maintains the global acquisition-order graph (edge A->B = "some thread
acquired B while holding A"). A cycle in that graph is a potential deadlock
— two threads interleaving the two orders WILL deadlock eventually, even if
no run has hung yet. The storm/crash/campaign chaos suites run with the
witness on and assert zero cycles, so every chaos scenario doubles as a
deadlock hunt.

Also recorded, per lock: acquisition and contention counts, hold times
(with a long-hold counter above LONG_HOLD_SECONDS — a lock held across a
network call is a latency bug even when ordering is clean), all exported as
`karpenter_lockwitness_*` metrics and served as JSON from `/debug/locks`.

Disabled is the default and is a TRUE no-op: `WITNESS.lock()` returns a
plain `threading.Lock` — not a wrapper with a dead branch — so production
hot paths pay nothing, the same bar tracing and SLO accounting meet.
Wrappers created while enabled keep working after `disable()` (they
short-circuit on the enabled flag), so a teardown cannot strand a lock.

Reentrant acquisition of the same RLock adds no edge and no duplicate held
entry; ordering is judged on first acquisition only. The witness's own
bookkeeping runs under one internal leaf lock that is never held while
acquiring a witnessed lock, so the witness cannot deadlock the program it
watches.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..metrics import REGISTRY

LONG_HOLD_SECONDS = 0.1

ACQUISITIONS = REGISTRY.counter(
    "karpenter_lockwitness_acquisitions_total",
    "Acquisitions of witnessed locks while the lock-order witness is enabled",
    ("lock",),
)
CONTENDED = REGISTRY.counter(
    "karpenter_lockwitness_contended_total",
    "Witnessed acquisitions that had to wait for another holder",
    ("lock",),
)
LONG_HOLDS = REGISTRY.counter(
    "karpenter_lockwitness_long_holds_total",
    f"Witnessed lock holds longer than {LONG_HOLD_SECONDS}s",
    ("lock",),
)
EDGES = REGISTRY.gauge(
    "karpenter_lockwitness_edges",
    "Distinct ordered pairs (A held while acquiring B) in the acquisition-order graph",
)
CYCLES = REGISTRY.gauge(
    "karpenter_lockwitness_cycles",
    "Cycles (potential deadlocks) detected in the lock acquisition-order graph",
)
LOCKS_REGISTERED = REGISTRY.gauge(
    "karpenter_lockwitness_locks", "Witnessed locks created since the witness was enabled"
)


class _WitnessedLock:
    """Lock/RLock wrapper that reports to the owning witness. Supports the
    full acquire(blocking, timeout) protocol plus the context manager, so a
    threading.Condition built over it works unmodified."""

    __slots__ = ("_witness", "_inner", "name")

    def __init__(self, witness: "LockWitness", inner, name: str):
        self._witness = witness
        self._inner = inner
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        witness = self._witness
        if not witness.enabled:
            return self._inner.acquire(blocking, timeout)
        contended = False
        acquired = self._inner.acquire(False)
        if not acquired:
            if not blocking:
                # a failed non-blocking acquire is a PROBE, not a wait —
                # Condition._is_owned() probes exactly this way on every
                # wait()/notify(), so counting it would drown the metric
                return False
            contended = True
            acquired = self._inner.acquire(True, timeout)
            if not acquired:
                CONTENDED.inc(lock=self.name)  # waited the full timeout
                return False
        witness._on_acquired(self.name, contended)
        return True

    def release(self) -> None:
        # ALWAYS run the held-stack bookkeeping: a disable() landing between
        # acquire and release must not strand a phantom entry that poisons
        # the edge graph after the next enable (metrics are gated inside)
        self._witness._on_released(self.name)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner.locked() if hasattr(self._inner, "locked") else False


class LockWitness:
    def __init__(self):
        self.enabled = False
        self._meta = threading.Lock()  # leaf lock: guards everything below
        self._local = threading.local()
        self._names: Dict[str, str] = {}  # name -> kind
        self._edges: Dict[Tuple[str, str], int] = {}
        self._cycles: List[List[str]] = []
        self._cycle_keys: set = set()
        self._max_hold: Dict[str, float] = {}

    # -- lifecycle -------------------------------------------------------------

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        """Drop the recorded graph and stats (test teardown). Call with no
        witnessed locks held; per-thread held stacks are rebuilt naturally."""
        with self._meta:
            self._names.clear()
            self._edges.clear()
            self._cycles.clear()
            self._cycle_keys.clear()
            self._max_hold.clear()
        EDGES.set(0)
        CYCLES.set(0)
        LOCKS_REGISTERED.set(0)

    # -- factories -------------------------------------------------------------

    def lock(self, name: str):
        """A mutex for `name`. Plain threading.Lock when disabled."""
        if not self.enabled:
            return threading.Lock()
        self._register(name, "lock")
        return _WitnessedLock(self, threading.Lock(), name)

    def rlock(self, name: str):
        if not self.enabled:
            return threading.RLock()
        self._register(name, "rlock")
        return _WitnessedLock(self, threading.RLock(), name)

    def condition(self, name: str):
        """A Condition whose underlying mutex is witnessed. The Condition's
        wait() releases and reacquires through the wrapper, so held-set
        bookkeeping stays correct across waits."""
        if not self.enabled:
            return threading.Condition()
        self._register(name, "condition")
        return threading.Condition(_WitnessedLock(self, threading.Lock(), name))

    def _register(self, name: str, kind: str) -> None:
        with self._meta:
            self._names[name] = kind
            LOCKS_REGISTERED.set(float(len(self._names)))

    # -- acquisition bookkeeping -----------------------------------------------

    def _held(self) -> list:
        held = getattr(self._local, "held", None)
        if held is None:
            held = self._local.held = []  # [name, depth, acquired_at]
        return held

    def _on_acquired(self, name: str, contended: bool) -> None:
        ACQUISITIONS.inc(lock=name)
        if contended:
            CONTENDED.inc(lock=name)
        held = self._held()
        for entry in held:
            if entry[0] == name:  # reentrant: deeper, no new edge
                entry[1] += 1
                return
        new_edges = []
        for entry in held:
            new_edges.append((entry[0], name))
        held.append([name, 1, time.perf_counter()])
        if new_edges:
            self._record_edges(new_edges)

    def _on_released(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name:
                held[i][1] -= 1
                if held[i][1] == 0:
                    duration = time.perf_counter() - held[i][2]
                    del held[i]
                    if not self.enabled:
                        return  # bookkeeping only: no stats while disabled
                    if duration > LONG_HOLD_SECONDS:
                        LONG_HOLDS.inc(lock=name)
                    with self._meta:
                        if duration > self._max_hold.get(name, 0.0):
                            self._max_hold[name] = duration
                return

    def _record_edges(self, edges: List[Tuple[str, str]]) -> None:
        with self._meta:
            fresh = []
            for edge in edges:
                if edge[0] == edge[1]:
                    continue
                if edge in self._edges:
                    self._edges[edge] += 1
                else:
                    self._edges[edge] = 1
                    fresh.append(edge)
            for a, b in fresh:
                cycle = self._find_path(b, a)
                if cycle is not None:
                    key = frozenset(cycle)
                    if key not in self._cycle_keys:
                        self._cycle_keys.add(key)
                        self._cycles.append(cycle)
            EDGES.set(float(len(self._edges)))
            CYCLES.set(float(len(self._cycles)))

    def _find_path(self, start: str, target: str) -> Optional[List[str]]:
        """DFS for a path start -> ... -> target over the edge graph; with
        the new edge target->start already inserted, such a path closes a
        cycle. Returns the cycle's node list (target first) or None.
        Caller holds self._meta."""
        adjacency: Dict[str, List[str]] = {}
        for a, b in self._edges:
            adjacency.setdefault(a, []).append(b)
        stack = [(start, [target, start])]
        seen = {start}
        while stack:
            node, path = stack.pop()
            if node == target:
                return path[:-1]
            for nxt in adjacency.get(node, ()):
                if nxt == target:
                    return path
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- read surface ----------------------------------------------------------

    def cycles(self) -> List[List[str]]:
        with self._meta:
            return [list(c) for c in self._cycles]

    def edges(self) -> Dict[Tuple[str, str], int]:
        with self._meta:
            return dict(self._edges)

    def locks(self) -> Dict[str, str]:
        with self._meta:
            return dict(self._names)

    def snapshot(self) -> dict:
        """The /debug/locks payload."""
        with self._meta:
            return {
                "enabled": self.enabled,
                "locks": dict(self._names),
                "edges": [
                    {"from": a, "to": b, "count": count} for (a, b), count in sorted(self._edges.items())
                ],
                "cycles": [list(c) for c in self._cycles],
                "max_hold_seconds": {k: round(v, 6) for k, v in sorted(self._max_hold.items())},
                "long_hold_threshold_seconds": LONG_HOLD_SECONDS,
            }


# the process-wide witness (the TRACER/REGISTRY analog): shared classes
# create their locks through it; chaos suites enable it around a run
WITNESS = LockWitness()


def _locks_route(query: dict) -> tuple:
    return 200, "application/json; charset=utf-8", json.dumps(WITNESS.snapshot(), indent=1) + "\n"


def routes() -> dict:
    """`/debug/locks` for the metrics listener (cmd/controller.py wires it
    behind --enable-lock-witness)."""
    return {"/debug/locks": _locks_route}


def route_descriptions() -> dict:
    """/debug-index descriptions, keyed like routes() (see tracing.py)."""
    return {
        "/debug/locks": "lock-order witness: acquisition graph, cycles (potential deadlocks), contention/hold times",
    }
