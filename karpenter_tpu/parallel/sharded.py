"""The sharded solve step: one jitted program over the (pods x types) mesh.

This is the multi-chip formulation of the dense solve's device portion:
feasibility masks sharded [pods x types], per-pod cheapest-feasible-type
argmin reduced over the types axis (XLA inserts the cross-shard argmin
combine over ICI), the bucket->instance-type cost choice reduced likewise,
and per-bin segment reductions sharded over pods. Everything is expressed
with sharding annotations on a single jit — no hand-written collectives —
per the standard mesh/pjit recipe: annotate in/out shardings, let XLA place
psum/all-gather where the math demands them.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import pod_sharding, replicated, type_sharding


@lru_cache(maxsize=16)
def make_sharded_solve_step(mesh: Mesh, num_bins: int):
    """Build the jitted sharded solve step for a given mesh and bin budget.

    Signature of the returned fn:
      (requests [P, R], group_ids [P], compat [G, T], caps [T, R],
       prices [T], allowed [B, T], bucket_sum [B, R], bucket_max [B, R],
       bin_ids [P], num_bins static)
        -> (feasible_any [P], best_type [P], tstar [B], bins [B],
            bin_usage [num_bins, R], bin_counts [num_bins])
    """
    in_shardings = (
        pod_sharding(mesh),  # requests
        pod_sharding(mesh),  # group_ids
        replicated(mesh),  # compat (G is tiny)
        type_sharding(mesh),  # caps
        type_sharding(mesh),  # prices
        NamedSharding(mesh, P(None, "types")),  # allowed [B, T]
        replicated(mesh),  # bucket_sum
        replicated(mesh),  # bucket_max
        pod_sharding(mesh),  # bin_ids
    )
    out_shardings = (
        pod_sharding(mesh),
        pod_sharding(mesh),
        replicated(mesh),
        replicated(mesh),
        replicated(mesh),
        replicated(mesh),
    )

    # bin_ids is donated: it is a per-solve [P] i32 scratch input whose buffer
    # XLA aliases onto the equal-sized best_type output (the program-donation
    # contract; callers pass freshly placed arrays and never reuse the input)
    @partial(jax.jit, in_shardings=in_shardings, out_shardings=out_shardings, donate_argnums=(8,))
    def solve_step(requests, group_ids, compat, caps, prices, allowed, bucket_sum, bucket_max, bin_ids):
        # --- [P, T] feasibility: resource fit x compat row. 2D-sharded
        # compute; XLA broadcasts pod shards against type shards over ICI.
        fit = jnp.all(requests[:, None, :] <= caps[None, :, :] + 1e-6, axis=-1)
        rows = jnp.take(compat, group_ids, axis=0)
        feasible = fit & rows  # [P, T] sharded (pods, types)

        feasible_any = jnp.any(feasible, axis=1)  # reduction over types axis
        cost = jnp.where(feasible, prices[None, :], jnp.inf)
        # explicit index_dtype: jnp.argmin follows jax_enable_x64 (int64 under
        # the flag) — the program-promotion contract pins the surface to i32
        best_type = jax.lax.argmin(cost, 1, jnp.int32)  # types-axis argmin

        # --- bucket -> type choice (ops/feasibility.py:bucket_type_cost
        # inlined so the whole step is one program): types axis sharded.
        eps = 1e-9
        safe_caps = jnp.maximum(caps, eps)
        ratio = bucket_sum[:, None, :] / safe_caps[None, :, :]  # [B, T, R]
        impossible = (caps[None, :, :] <= eps) & (bucket_sum[:, None, :] > eps)
        frac = jnp.max(jnp.where(impossible, jnp.inf, ratio), axis=-1)
        bins = jnp.ceil(jnp.maximum(frac, eps))
        pod_fits = jnp.all(bucket_max[:, None, :] <= caps[None, :, :] + 1e-6, axis=-1)
        ok = allowed & pod_fits & jnp.isfinite(frac)
        key = jnp.where(ok, frac * prices[None, :] + bins * 1e-4 + prices[None, :] * 1e-7, jnp.inf)
        tstar = jax.lax.argmin(key, 1, jnp.int32)
        chosen_bins = jnp.take_along_axis(bins, tstar[:, None], axis=1)[:, 0].astype(jnp.int32)

        # --- audit reductions over the pod shards
        safe_ids = jnp.where(bin_ids < 0, num_bins, bin_ids)
        usage = jax.ops.segment_sum(requests, safe_ids, num_segments=num_bins + 1)[:num_bins]
        counts = jax.ops.segment_sum(jnp.ones_like(bin_ids), safe_ids, num_segments=num_bins + 1)[:num_bins]
        return feasible_any, best_type, tstar, chosen_bins, usage, counts

    return solve_step


def sharded_solve_step(mesh: Mesh, requests, group_ids, compat, caps, prices, allowed, bucket_sum, bucket_max, bin_ids, num_bins: int):
    fn = make_sharded_solve_step(mesh, num_bins)
    from ..flight import FLIGHT

    if FLIGHT.enabled:
        # per-mesh wrappers share one {fn} label so compile attribution and
        # the program contract join on the same name; registration dedupes
        FLIGHT.register_jit_entry("sharded_solve_step", fn)
    return fn(requests, group_ids, compat, caps, prices, allowed, bucket_sum, bucket_max, bin_ids)


def place(mesh: Mesh, array, spec: P):
    """device_put onto the mesh's own devices.

    Never use default-device jnp.asarray for mesh inputs: when the mesh is a
    CPU fallback (virtual multi-device dryrun) the default backend may be a
    single — or broken — TPU client, and a default placement either lands on
    the wrong device set or fails outright before the sharded program runs.
    """
    return jax.device_put(array, NamedSharding(mesh, spec))


@lru_cache(maxsize=8)
def make_sharded_bucket_cost(mesh: Mesh):
    """The PRODUCTION multi-chip dispatch: bucket->type cost choice sharded
    over the (pods x types) mesh.

    Same math and packed [3, B] result as ops/feasibility.py:
    bucket_type_cost_packed — the bucket axis rides the "pods" mesh axis
    (data parallel), the instance-type axis rides "types" (model parallel),
    and the per-bucket argmin over types becomes an XLA cross-shard argmin
    combine over ICI. DenseSolver routes its device dispatch here whenever
    more than one device is visible; shapes are padded by the caller to mesh
    divisibility (padded types carry allowed=False and zero caps, so they can
    never win the argmin; padded buckets report infeasible and are trimmed).
    """
    from ..ops.feasibility import bucket_type_cost_packed

    in_shardings = (
        NamedSharding(mesh, P(None, "pods", None)),  # bucket_stats [2, B, R]
        type_sharding(mesh),  # caps [T, R]
        type_sharding(mesh),  # prices [T]
        NamedSharding(mesh, P("pods", "types")),  # allowed [B, T]
    )

    # the body IS the single-device program (one definition of the cost
    # formula — ops/feasibility.py); only the shardings are new here
    @partial(jax.jit, in_shardings=in_shardings, out_shardings=replicated(mesh))
    def bucket_cost(bucket_stats, caps, prices, allowed):
        return bucket_type_cost_packed(bucket_stats, caps, prices, allowed)

    return bucket_cost
