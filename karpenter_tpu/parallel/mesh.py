"""Device mesh + sharding layout for the distributed solver.

The problem's parallel structure (SURVEY.md section 2.10): the pods x types
feasibility/packing surface is embarrassingly parallel over pods and
reducible over types. The mesh maps that directly:

  axis "pods"  — data-parallel shards of the pod axis (requests, group ids,
                 per-pod outputs). Scales with batch size over ICI.
  axis "types" — model-parallel shards of the instance-type axis (caps,
                 prices, compat columns). Reductions over types (argmin cost,
                 any-feasible) become XLA collectives over this axis.

Multi-host: the same mesh spans hosts; XLA routes the "types" reductions and
"pods" all-gathers over ICI within a host and DCN across hosts, which is the
right locality because types-axis traffic (argmin combines) is tiny compared
to pods-axis activations.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def solver_mesh(n_devices: Optional[int] = None, types_parallel: int = 1) -> Mesh:
    """Build a (pods x types) mesh over the first n devices.

    types_parallel devices shard the type axis; the rest shard pods.
    """
    devices = jax.devices()
    n = n_devices or len(devices)
    devices = np.asarray(devices[:n])
    if n % types_parallel != 0:
        raise ValueError(f"{n} devices not divisible by types_parallel={types_parallel}")
    grid = devices.reshape(n // types_parallel, types_parallel)
    return Mesh(grid, axis_names=("pods", "types"))


def pod_sharding(mesh: Mesh) -> NamedSharding:
    """[P, ...] arrays: shard the leading pod axis."""
    return NamedSharding(mesh, P("pods"))


def type_sharding(mesh: Mesh) -> NamedSharding:
    """[T, ...] arrays: shard the leading type axis."""
    return NamedSharding(mesh, P("types"))


def pod_by_type_sharding(mesh: Mesh) -> NamedSharding:
    """[P, T] arrays: 2D-sharded over both mesh axes."""
    return NamedSharding(mesh, P("pods", "types"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
