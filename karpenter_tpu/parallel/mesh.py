"""Device mesh + sharding layout for the distributed solver.

The problem's parallel structure (SURVEY.md section 2.10): the pods x types
feasibility/packing surface is embarrassingly parallel over pods and
reducible over types. The mesh maps that directly:

  axis "pods"  — data-parallel shards of the pod axis (requests, group ids,
                 per-pod outputs). Scales with batch size over ICI.
  axis "types" — model-parallel shards of the instance-type axis (caps,
                 prices, compat columns). Reductions over types (argmin cost,
                 any-feasible) become XLA collectives over this axis.

Multi-host: the same mesh spans hosts; XLA routes the "types" reductions and
"pods" all-gathers over ICI within a host and DCN across hosts, which is the
right locality because types-axis traffic (argmin combines) is tiny compared
to pods-axis activations.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def solver_mesh(n_devices: Optional[int] = None, types_parallel: int = 1, prefer_cpu: bool = False, devices=None) -> Mesh:
    """Build a (pods x types) mesh over the first n devices.

    types_parallel devices shard the type axis; the rest shard pods.

    `devices` pins an explicit device list (e.g. jax.local_devices() — the
    only safe choice for a single-process caller once jax.distributed makes
    jax.devices() span other hosts). prefer_cpu checks the host CPU backend
    FIRST — the virtual-multi-device dryrun path, where the default backend
    may be a single tunneled TPU chip that is slow (or broken) to initialize
    and must not be touched when the forced CPU device count already
    satisfies the request.
    """
    if devices is not None and prefer_cpu:
        raise ValueError("pass either devices or prefer_cpu, not both")
    pinned = devices is not None  # caller-pinned, not the prefer_cpu pick
    if prefer_cpu and n_devices:
        try:
            cpu_devices = jax.devices("cpu")
            if len(cpu_devices) >= n_devices:
                devices = cpu_devices
        except RuntimeError:
            devices = None
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = n_devices or len(devices)
    if pinned and len(devices) < n:
        # an explicitly pinned list must never be silently swapped for the
        # CPU fallback — that would mask a config error
        raise ValueError(f"need {n} devices but the pinned list has {len(devices)}")
    if len(devices) < n:
        # The default backend (e.g. a single tunneled TPU chip) may have fewer
        # devices than requested while the host CPU backend carries the forced
        # virtual-device count (--xla_force_host_platform_device_count).
        try:
            cpu_devices = jax.devices("cpu")
        except RuntimeError:
            cpu_devices = []
        if len(cpu_devices) >= n:
            devices = cpu_devices
        else:
            raise ValueError(
                f"need {n} devices; have {len(devices)} on the default backend "
                f"and {len(cpu_devices)} on cpu"
            )
    devices = np.asarray(devices[:n])
    if n % types_parallel != 0:
        raise ValueError(f"{n} devices not divisible by types_parallel={types_parallel}")
    grid = devices.reshape(n // types_parallel, types_parallel)
    return Mesh(grid, axis_names=("pods", "types"))


def default_mesh(n_devices: int, prefer_cpu: bool = False, types_parallel: Optional[int] = None, devices=None) -> Mesh:
    """The production mesh shape for n devices: 2-way types-parallel when the
    count allows (argmin-combine traffic over the types axis is tiny), the
    rest pods-parallel — or an explicit types_parallel from the host-aware
    factorization (parallel/multihost.py host_mesh_axes). Both the solver
    auto-detect and the driver dryrun use this, so the dryrun always
    validates the shape production runs."""
    if types_parallel is None:
        types_parallel = 2 if n_devices % 2 == 0 and n_devices >= 4 else 1
    return solver_mesh(n_devices, types_parallel=types_parallel, prefer_cpu=prefer_cpu, devices=devices)


def pod_sharding(mesh: Mesh) -> NamedSharding:
    """[P, ...] arrays: shard the leading pod axis."""
    return NamedSharding(mesh, P("pods"))


def type_sharding(mesh: Mesh) -> NamedSharding:
    """[T, ...] arrays: shard the leading type axis."""
    return NamedSharding(mesh, P("types"))


def pod_by_type_sharding(mesh: Mesh) -> NamedSharding:
    """[P, T] arrays: 2D-sharded over both mesh axes."""
    return NamedSharding(mesh, P("pods", "types"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
