"""Multi-host distributed initialization for the solver service.

The distributed-communication backend of SURVEY.md §5: the reference's
"fabric" is kube watches + cloud APIs; the TPU build adds a real device
fabric — XLA collectives over ICI within a host and DCN across hosts — and
this module is the seam that brings additional hosts into one solver.

Deployment model (mirrors standard JAX multi-host):

- every host runs the solver service (cmd/solver_service.py) with the same
  coordinator address; process 0 hosts the coordination service;
- :func:`initialize` wires ``jax.distributed`` from explicit arguments or
  the standard env (``KARPENTER_TPU_COORDINATOR``, ``..._NUM_PROCESSES``,
  ``..._PROCESS_ID``), after which ``jax.devices()`` spans every host and
  ``solver_mesh`` / ``make_sharded_*`` transparently build global meshes;
- :func:`host_mesh_axes` picks the (pods × types) factorization that keeps
  the types axis — whose reductions (argmin combines, any-feasible) are the
  chatty ones — INSIDE each host's ICI domain, so only the cheap pods-axis
  concatenations ride DCN. This is the scaling-book recipe: put the
  low-volume collective on the slow fabric.

Single-process fallback: with no coordinator configured, initialize() is a
no-op and everything runs on the local devices — the same code path the
8-virtual-device CPU tests and the driver dryrun exercise.

Cross-host execution: a solve over a multi-process mesh is SPMD — every
process must enter the same jitted program. parallel/peers.py provides that
loop: the coordinator broadcasts each solve request, peers mirror the
sharded call, and cmd/solver_service.py routes every non-zero process into
PeerFabric.serve(). DenseSolver's AUTO-detected mesh still spans only
addressable devices (a solver constructed without a fabric must never build
a mesh it cannot drive alone); constructing it with peer_fabric=PeerFabric()
opts into the global mesh.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from ..logsetup import get_logger

log = get_logger("parallel")

ENV_COORDINATOR = "KARPENTER_TPU_COORDINATOR"
ENV_NUM_PROCESSES = "KARPENTER_TPU_NUM_PROCESSES"
ENV_PROCESS_ID = "KARPENTER_TPU_PROCESS_ID"

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-host solver fabric; returns True when distributed mode
    is active.

    Arguments default to the KARPENTER_TPU_* env; with no coordinator
    configured anywhere this is a single-process no-op (False). Safe to call
    more than once.
    """
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or os.environ.get(ENV_COORDINATOR) or None
    if not coordinator_address:
        return False
    # leave unset values as None so jax.distributed auto-detects the
    # process topology on TPU pods (forcing 1/0 would make every host claim
    # process 0 of a one-process 'fabric')
    env_np = os.environ.get(ENV_NUM_PROCESSES)
    env_pid = os.environ.get(ENV_PROCESS_ID)
    if num_processes is None and env_np is not None:
        num_processes = int(env_np)
    if process_id is None and env_pid is not None:
        process_id = int(env_pid)

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    log.info(
        "joined solver fabric: coordinator=%s process %s/%s, %d global devices",
        coordinator_address,
        jax.process_index(),
        jax.process_count(),
        len(jax.devices()),
    )
    return True


def host_mesh_axes(n_global: int, n_local: int) -> Tuple[int, int]:
    """(pods, types) axis sizes that keep types-axis collectives on ICI.

    The types axis carries the argmin-combine traffic, so it must not span
    hosts: its size divides the per-host device count. Pods-axis shards
    (independent bucket rows, concatenated once per solve) span hosts over
    DCN. Examples: 2 hosts × 4 chips (8 global) → (pods=2, types=4);
    4 hosts × 8 chips (32 global) → (pods=8, types=4).
    """
    if n_local <= 0 or n_global <= 0 or n_global % max(n_local, 1):
        return (max(n_global, 1), 1)
    types = 1
    # largest power-of-two types axis that DIVIDES the per-host device count
    # (a non-dividing axis would either fail mesh construction or span
    # hosts), capped at 4: types reductions saturate quickly; pods
    # parallelism is the scaler
    while types * 2 <= 4 and n_local % (types * 2) == 0:
        types *= 2
    return (n_global // types, types)


def distributed_solver_mesh():
    """A global (pods × types) mesh spanning every process's devices, with
    the types axis confined to per-host ICI (host_mesh_axes)."""
    import jax

    from .mesh import solver_mesh

    n_global = len(jax.devices())
    n_local = len(jax.local_devices())
    pods_dim, types_dim = host_mesh_axes(n_global, n_local)
    return solver_mesh(n_devices=pods_dim * types_dim, types_parallel=types_dim)
