from .mesh import solver_mesh, pod_sharding, type_sharding, replicated
from .sharded import sharded_solve_step

__all__ = ["solver_mesh", "pod_sharding", "type_sharding", "replicated", "sharded_solve_step"]
